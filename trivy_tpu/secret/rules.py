"""Builtin secret rules — capability parity with the reference's 86-rule
set (pkg/fanal/secret/builtin-rules.go; rule IDs/titles/severities/keyword
gates match so findings diff cleanly). The token formats are the public,
vendor-documented shapes. Patterns are authored table-driven: most rules
are either a bare prefixed-token regex or a "key-assignment" shape
(`<service-ish key> <assign op> "<secret>"`).

Global allow rules mirror builtin-allow-rules.go (test/example/vendor
paths etc.)."""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

# shared grammar fragments
QUOTE = r"""["']?"""
CONNECT = r"\s*(:|=>|=)?\s*"
START = r"(^|\s+)"
END = r"[.,]?(\s+|$)"
UUID = r"[0-9A-F]{8}-[0-9A-F]{4}-[0-9A-F]{4}-[0-9A-F]{4}-[0-9A-F]{12}"


@dataclass
class AllowRule:
    id: str
    description: str = ""
    regex: Optional[re.Pattern] = None
    path: Optional[re.Pattern] = None


@dataclass
class Rule:
    id: str
    category: str
    title: str
    severity: str
    regex: re.Pattern
    keywords: list
    secret_group: str = ""
    # duplicate-name aliases of secret_group (Go regexps may bind one
    # name twice; each occurrence yields its own finding)
    secret_aliases: tuple = ()
    path: Optional[re.Pattern] = None
    allow_rules: list = field(default_factory=list)
    exclude_regexes: list = field(default_factory=list)

    def match_path(self, path: str) -> bool:
        return self.path is None or bool(self.path.search(path))

    def allow_path(self, path: str) -> bool:
        return any(a.path and a.path.search(path) for a in self.allow_rules)

    def allow_match(self, match: str) -> bool:
        return any(a.regex and a.regex.search(match)
                   for a in self.allow_rules)

    def match_keywords(self, lower_content: bytes) -> bool:
        if not self.keywords:
            return True
        return any(k.lower().encode() in lower_content
                   for k in self.keywords)


GLOBAL_ALLOW_RULES = [
    AllowRule("tests", "Avoid test files and paths",
              path=re.compile(r"(^test|\/test|-test|_test|\.test)")),
    AllowRule("examples", "Avoid example files and paths",
              path=re.compile(r"example"),
              regex=re.compile(r"(?i)example")),
    AllowRule("vendor", "Vendor dirs", path=re.compile(r"\/vendor\/")),
    AllowRule("usr-dirs", "System dirs",
              path=re.compile(r"^usr\/(?:share|include|lib)\/")),
    AllowRule("locale-dir", "Locales directory",
              path=re.compile(r"\/locales?\/")),
    AllowRule("markdown", "Markdown files", path=re.compile(r"\.md$")),
    AllowRule("node.js", "Node container images",
              path=re.compile(r"^opt\/yarn-v[\d.]+\/")),
    AllowRule("golang", "Go container images",
              path=re.compile(r"^usr\/local\/go\/")),
    AllowRule("python", "Python container images",
              path=re.compile(r"^usr\/local\/lib\/python[\d.]+\/")),
    AllowRule("rubygems", "Ruby container images",
              path=re.compile(r"^usr\/lib\/gems\/")),
    AllowRule("wordpress", "Wordpress container images",
              path=re.compile(r"^usr\/src\/wordpress\/")),
    AllowRule("anaconda-log", "Anaconda CI logs",
              path=re.compile(r"^var\/log\/anaconda\/")),
]


def _assign(key_prefix: str, secret_pat: str) -> str:
    """Key-assignment rule shape: `<key>... = "<secret>"`."""
    return (rf""" (?i)(?P<key>{key_prefix}[a-z0-9_ .\-,]{{0,25}})"""
            rf"""(=|>|:=|\|\|:|<=|=>|:).{{0,5}}['\"]"""
            rf"""(?P<secret>{secret_pat})['\"]""")


def _quoted(pat: str) -> str:
    return rf"""['\"]{pat}['\"]"""


# (id, category, title, severity, regex, keywords, secret_group)
_TABLE = [
    ("aws-access-key-id", "AWS", "AWS Access Key ID", "CRITICAL",
     QUOTE + r"(?P<secret>(A3T[A-Z0-9]|AKIA|AGPA|AIDA|AROA|AIPA|ANPA|ANVA|"
     r"ASIA)[A-Z0-9]{16})" + QUOTE + END,
     ["AKIA", "AGPA", "AIDA", "AROA", "AIPA", "ANPA", "ANVA", "ASIA"],
     "secret"),
    ("aws-secret-access-key", "AWS", "AWS Secret Access Key", "CRITICAL",
     r"(?i)" + START + QUOTE + r"aws_?" + r"(sec(ret)?)?_?(access)?_?key" +
     QUOTE + CONNECT + QUOTE + r"(?P<secret>[A-Za-z0-9\/\+=]{40})" + QUOTE +
     END,
     ["key"], "secret"),
    ("github-pat", "GitHub", "GitHub Personal Access Token", "CRITICAL",
     r"ghp_[0-9a-zA-Z]{36}", ["ghp_"], ""),
    ("github-oauth", "GitHub", "GitHub OAuth Access Token", "CRITICAL",
     r"gho_[0-9a-zA-Z]{36}", ["gho_"], ""),
    ("github-app-token", "GitHub", "GitHub App Token", "CRITICAL",
     r"(ghu|ghs)_[0-9a-zA-Z]{36}", ["ghu_", "ghs_"], ""),
    ("github-refresh-token", "GitHub", "GitHub Refresh Token", "CRITICAL",
     r"ghr_[0-9a-zA-Z]{76}", ["ghr_"], ""),
    ("github-fine-grained-pat", "GitHub",
     "GitHub Fine-grained personal access tokens", "CRITICAL",
     r"github_pat_[0-9a-zA-Z_]{82}", ["github_pat_"], ""),
    ("gitlab-pat", "GitLab", "GitLab Personal Access Token", "CRITICAL",
     r"glpat-[0-9a-zA-Z\-\_]{20}", ["glpat-"], ""),
    ("hugging-face-access-token", "HuggingFace", "Hugging Face Access Token",
     "CRITICAL", r"hf_[A-Za-z0-9]{34,40}", ["hf_"], ""),
    ("private-key", "AsymmetricPrivateKey", "Asymmetric Private Key", "HIGH",
     r"(?i)-----\s*?BEGIN[ A-Z0-9_-]*?PRIVATE KEY( BLOCK)?\s*?-----[\s]*?"
     r"(?P<secret>[\sA-Za-z0-9=+/\\\r\n]+)[\s]*?-----\s*?END[ A-Z0-9_-]*? ?"
     r"PRIVATE KEY( BLOCK)?\s*?-----", ["-----"], "secret"),
    ("shopify-token", "Shopify", "Shopify token", "HIGH",
     r"shp(ss|at|ca|pa)_[a-fA-F0-9]{32}",
     ["shpss_", "shpat_", "shpca_", "shppa_"], ""),
    ("slack-access-token", "Slack", "Slack token", "HIGH",
     r"xox[baprs]-([0-9a-zA-Z]{10,48})?",
     ["xoxb-", "xoxa-", "xoxp-", "xoxr-", "xoxs-"], ""),
    ("stripe-publishable-token", "Stripe", "Stripe Publishable Key", "LOW",
     r"(?i)pk_(test|live)_[0-9a-z]{10,32}", ["pk_test_", "pk_live_"], ""),
    ("stripe-secret-token", "Stripe", "Stripe Secret Key", "CRITICAL",
     r"(?i)sk_(test|live)_[0-9a-z]{10,32}", ["sk_test_", "sk_live_"], ""),
    ("pypi-upload-token", "PyPI", "PyPI upload token", "HIGH",
     r"pypi-AgEIcHlwaS5vcmc[A-Za-z0-9\-_]{50,1000}",
     ["pypi-AgEIcHlwaS5vcmc"], ""),
    ("gcp-service-account", "Google", "Google (GCP) Service-account",
     "CRITICAL", r"\"type\": \"service_account\"",
     ['"type": "service_account"'], ""),
    ("heroku-api-key", "Heroku", "Heroku API Key", "HIGH",
     _assign("heroku", UUID), ["heroku"], "secret"),
    ("slack-web-hook", "Slack", "Slack Webhook", "MEDIUM",
     r"https:\/\/hooks.slack.com\/services\/T[a-zA-Z0-9_]{8}\/"
     r"B[a-zA-Z0-9_]{8,12}\/[a-zA-Z0-9_]{24}", ["hooks.slack.com"], ""),
    ("twilio-api-key", "Twilio", "Twilio API Key", "MEDIUM",
     r"SK[0-9a-fA-F]{32}", ["SK"], ""),
    ("age-secret-key", "Age", "Age secret key", "MEDIUM",
     r"AGE-SECRET-KEY-1[QPZRY9X8GF2TVDW0S3JN54KHCE6MUA7L]{58}",
     ["AGE-SECRET-KEY-1"], ""),
    ("facebook-token", "Facebook", "Facebook token", "LOW",
     _assign("facebook", r"[a-f0-9]{32}"), ["facebook"], "secret"),
    ("twitter-token", "Twitter", "Twitter token", "LOW",
     _assign("twitter", r"[a-f0-9]{35,44}"), ["twitter"], "secret"),
    ("adobe-client-id", "Adobe", "Adobe Client ID (Oauth Web)", "LOW",
     _assign("adobe", r"[a-f0-9]{32}"), ["adobe"], "secret"),
    ("adobe-client-secret", "Adobe", "Adobe Client Secret", "LOW",
     r"(p8e-)(?i)[a-z0-9]{32}", ["p8e-"], ""),
    ("alibaba-access-key-id", "Alibaba", "Alibaba AccessKey ID", "HIGH",
     r"([^0-9A-Za-z]|^)(?P<secret>(LTAI)(?i)[a-z0-9]{20})"
     r"([^0-9A-Za-z]|$)",
     ["LTAI"], "secret"),
    ("alibaba-secret-key", "Alibaba", "Alibaba Secret Key", "HIGH",
     _assign("alibaba", r"[a-z0-9]{30}"), ["alibaba"], "secret"),
    ("asana-client-id", "Asana", "Asana Client ID", "MEDIUM",
     _assign("asana", r"[0-9]{16}"), ["asana"], "secret"),
    ("asana-client-secret", "Asana", "Asana Client Secret", "MEDIUM",
     _assign("asana", r"[a-z0-9]{32}"), ["asana"], "secret"),
    ("atlassian-api-token", "Atlassian", "Atlassian API token", "HIGH",
     _assign("atlassian", r"[a-z0-9]{24}"), ["atlassian"], "secret"),
    ("bitbucket-client-id", "Bitbucket", "Bitbucket client ID", "HIGH",
     _assign("bitbucket", r"[a-z0-9]{32}"), ["bitbucket"], "secret"),
    ("bitbucket-client-secret", "Bitbucket", "Bitbucket client secret",
     "HIGH", _assign("bitbucket", r"[a-z0-9_\-]{64}"), ["bitbucket"],
     "secret"),
    ("beamer-api-token", "Beamer", "Beamer API token", "LOW",
     _assign("beamer", r"b_[a-z0-9=_\-]{44}"), ["beamer"], "secret"),
    ("clojars-api-token", "Clojars", "Clojars API token", "MEDIUM",
     r"(?i)(CLOJARS_)[a-z0-9]{60}", ["clojars"], ""),
    ("contentful-delivery-api-token", "Contentful",
     "Contentful delivery API token", "LOW",
     _assign("contentful", r"[a-z0-9\-=_]{43}"), ["contentful"], "secret"),
    ("databricks-api-token", "Databricks", "Databricks API token", "MEDIUM",
     r"dapi[a-h0-9]{32}", ["dapi"], ""),
    ("discord-api-token", "Discord", "Discord API key", "MEDIUM",
     _assign("discord", r"[a-h0-9]{64}"), ["discord"], "secret"),
    ("discord-client-id", "Discord", "Discord client ID", "MEDIUM",
     _assign("discord", r"[0-9]{18}"), ["discord"], "secret"),
    ("discord-client-secret", "Discord", "Discord client secret", "MEDIUM",
     _assign("discord", r"[a-z0-9=_\-]{32}"), ["discord"], "secret"),
    ("doppler-api-token", "Doppler", "Doppler API token", "MEDIUM",
     _quoted(r"(dp\.pt\.)(?i)[a-z0-9]{43}"), ["doppler"], ""),
    ("dropbox-api-secret", "Dropbox", "Dropbox API secret/key", "HIGH",
     _assign("dropbox", r"[a-z0-9]{15}"), ["dropbox"], "secret"),
    ("dropbox-short-lived-api-token", "Dropbox",
     "Dropbox short lived API token", "HIGH",
     _assign("dropbox", r"sl\.[a-z0-9\-=_]{135}"), ["dropbox"], "secret"),
    ("dropbox-long-lived-api-token", "Dropbox",
     "Dropbox long lived API token", "HIGH",
     _assign("dropbox", r"[a-z0-9]{11}(AAAAAAAAAA)[a-z0-9\-_=]{43}"),
     ["dropbox"], "secret"),
    ("duffel-api-token", "Duffel", "Duffel API token", "LOW",
     _quoted(r"duffel_(test|live)_(?i)[a-z0-9_-]{43}"), ["duffel"], ""),
    ("dynatrace-api-token", "Dynatrace", "Dynatrace API token", "MEDIUM",
     _quoted(r"dt0c01\.(?i)[a-z0-9]{24}\.[a-z0-9]{64}"), ["dynatrace"], ""),
    ("easypost-api-token", "EasyPost", "EasyPost API token", "LOW",
     _quoted(r"EZ[AT]K(?i)[a-z0-9]{54}"), ["EZAK", "EZTK"], ""),
    ("fastly-api-token", "Fastly", "Fastly API token", "MEDIUM",
     _assign("fastly", r"[a-z0-9\-=_]{32}"), ["fastly"], "secret"),
    ("finicity-client-secret", "Finicity", "Finicity client secret",
     "MEDIUM", _assign("finicity", r"[a-z0-9]{20}"), ["finicity"], "secret"),
    ("finicity-api-token", "Finicity", "Finicity API token", "MEDIUM",
     _assign("finicity", r"[a-f0-9]{32}"), ["finicity"], "secret"),
    ("flutterwave-public-key", "Flutterwave", "Flutterwave public/secret key",
     "MEDIUM", r"FLW(PUB|SEC)K_TEST-(?i)[a-h0-9]{32}-X", ["FLWPUBK_TEST",
                                                          "FLWSECK_TEST"],
     ""),
    ("flutterwave-enc-key", "Flutterwave", "Flutterwave encrypted key",
     "MEDIUM", r"FLWSECK_TEST[a-h0-9]{12}", ["FLWSECK_TEST"], ""),
    ("frameio-api-token", "FrameIO", "Frame.io API token", "LOW",
     r"fio-u-(?i)[a-z0-9\-_=]{64}", ["fio-u-"], ""),
    ("gocardless-api-token", "GoCardless", "GoCardless API token", "MEDIUM",
     _quoted(r"live_(?i)[a-z0-9\-_=]{40}"), ["gocardless"], ""),
    ("grafana-api-token", "Grafana", "Grafana API token", "MEDIUM",
     _quoted(r"eyJrIjoi(?i)[a-z0-9\-_=]{72,92}"), ["grafana"], ""),
    ("hashicorp-tf-api-token", "HashiCorp",
     "HashiCorp Terraform user/org API token", "MEDIUM",
     _quoted(r"(?i)[a-z0-9]{14}\.atlasv1\.[a-z0-9\-_=]{60,70}"),
     ["atlasv1"], ""),
    ("hubspot-api-token", "HubSpot", "HubSpot API token", "LOW",
     _assign("hubspot", UUID.lower().replace("a-f", "a-f")), ["hubspot"],
     "secret"),
    ("intercom-api-token", "Intercom", "Intercom API token", "LOW",
     _assign("intercom", r"[a-z0-9=_]{60}"), ["intercom"], "secret"),
    ("intercom-client-secret", "Intercom", "Intercom client secret/ID",
     "LOW", _assign("intercom", UUID), ["intercom"], "secret"),
    ("ionic-api-token", "Ionic", "Ionic API token", "MEDIUM",
     _assign("ionic", r"ion_[a-z0-9]{42}"), ["ion_"], "secret"),
    ("jwt-token", "JWT", "JWT token", "MEDIUM",
     r"ey[a-zA-Z0-9]{17,}\.ey[a-zA-Z0-9\/\\_-]{17,}\."
     r"(?:[a-zA-Z0-9\/\\_-]{10,}={0,2})?", ["jwt"], ""),
    ("linear-api-token", "Linear", "Linear API token", "MEDIUM",
     r"lin_api_(?i)[a-z0-9]{40}", ["lin_api_"], ""),
    ("linear-client-secret", "Linear", "Linear client secret/ID", "MEDIUM",
     _assign("linear", r"[a-f0-9]{32}"), ["linear"], "secret"),
    ("lob-api-key", "Lob", "Lob API Key", "LOW",
     _assign("lob", r"(live|test)_[a-f0-9]{35}"), ["lob"], "secret"),
    ("lob-pub-api-key", "Lob", "Lob Publishable API Key", "LOW",
     _assign("lob", r"(test|live)_pub_[a-f0-9]{31}"), ["lob"], "secret"),
    ("mailchimp-api-key", "Mailchimp", "Mailchimp API key", "MEDIUM",
     _assign("mailchimp", r"[a-f0-9]{32}-us[0-9]{1,2}"), ["mailchimp"],
     "secret"),
    ("mailgun-token", "Mailgun", "Mailgun private API token", "MEDIUM",
     _assign("mailgun", r"key-[a-f0-9]{32}"), ["mailgun"], "secret"),
    ("mailgun-signing-key", "Mailgun", "Mailgun webhook signing key",
     "MEDIUM",
     _assign("mailgun", r"[a-h0-9]{32}-[a-h0-9]{8}-[a-h0-9]{8}"),
     ["mailgun"], "secret"),
    ("mapbox-api-token", "Mapbox", "Mapbox API token", "MEDIUM",
     r"(?i)(pk\.[a-z0-9]{60}\.[a-z0-9]{22})", ["mapbox"], ""),
    ("messagebird-api-token", "MessageBird", "MessageBird API token",
     "MEDIUM", _assign("messagebird", r"[a-z0-9]{25}"), ["messagebird"],
     "secret"),
    ("messagebird-client-id", "MessageBird", "MessageBird API client ID",
     "MEDIUM", _assign("messagebird", UUID), ["messagebird"], "secret"),
    ("new-relic-user-api-key", "NewRelic", "New Relic user API Key",
     "MEDIUM", _quoted(r"NRAK-[A-Z0-9]{27}"), ["NRAK-"], ""),
    ("new-relic-user-api-id", "NewRelic", "New Relic user API ID", "MEDIUM",
     _assign("newrelic", r"[A-Z0-9]{64}"), ["newrelic"], "secret"),
    ("new-relic-browser-api-token", "NewRelic",
     "New Relic ingest browser API token", "MEDIUM",
     _quoted(r"NRJS-[a-f0-9]{19}"), ["NRJS-"], ""),
    ("npm-access-token", "Npm", "npm access token", "CRITICAL",
     r"(?i)" + _quoted(r"npm_[a-z0-9]{36}"), ["npm_"], ""),
    ("planetscale-password", "PlanetScale", "PlanetScale password", "MEDIUM",
     r"pscale_pw_(?i)[a-z0-9\-_\.]{43}", ["pscale_pw_"], ""),
    ("planetscale-api-token", "PlanetScale", "PlanetScale API token",
     "MEDIUM", r"pscale_tkn_(?i)[a-z0-9\-_\.]{43}", ["pscale_tkn_"], ""),
    ("postman-api-token", "Postman", "Postman API token", "MEDIUM",
     r"PMAK-(?i)[a-f0-9]{24}\-[a-f0-9]{34}", ["PMAK-"], ""),
    ("pulumi-api-token", "Pulumi", "Pulumi API token", "HIGH",
     r"pul-[a-f0-9]{40}", ["pul-"], ""),
    ("rubygems-api-token", "Rubygems", "Rubygem API token", "MEDIUM",
     r"rubygems_[a-f0-9]{48}", ["rubygems_"], ""),
    ("sendgrid-api-token", "SendGrid", "SendGrid API token", "MEDIUM",
     r"SG\.(?i)[a-z0-9_\-\.]{66}", ["SG."], ""),
    ("sendinblue-api-token", "SendinBlue", "Sendinblue API token", "LOW",
     r"xkeysib-[a-f0-9]{64}\-(?i)[a-z0-9]{16}", ["xkeysib-"], ""),
    ("shippo-api-token", "Shippo", "Shippo API token", "LOW",
     r"shippo_(live|test)_[a-f0-9]{40}", ["shippo_live_", "shippo_test_"],
     ""),
    ("linkedin-client-secret", "LinkedIn", "LinkedIn Client secret", "LOW",
     _assign("linkedin", r"[a-z]{16}"), ["linkedin"], "secret"),
    ("linkedin-client-id", "LinkedIn", "LinkedIn Client ID", "LOW",
     _assign("linkedin", r"[a-z0-9]{14}"), ["linkedin"], "secret"),
    ("twitch-api-token", "Twitch", "Twitch API token", "LOW",
     _assign("twitch", r"[a-z0-9]{30}"), ["twitch"], "secret"),
    ("typeform-api-token", "Typeform", "Typeform API token", "LOW",
     _assign("typeform", r"tfp_[a-z0-9\-_\.=]{59}"), ["typeform"], "secret"),
    ("dockerconfig-secret", "Docker", "Dockerconfig secret exposed", "HIGH",
     r"(?i)(\.(dockerconfigjson|dockercfg):\s*\|*\s*"
     r"(?P<secret>(ey|ew)+[A-Za-z0-9\/\+=]+))", ["dockerc"], "secret"),
]


def _goflags(pattern: str, top: bool = True) -> str:
    """Translate Go's mid-pattern `(?i)` into Python syntax.

    In Go a bare flag group applies from its position to the END OF THE
    ENCLOSING GROUP (e.g. `(?P<secret>(LTAI)(?i)[a-z0-9]{20})` leaves
    `LTAI` case-sensitive). Python only allows bare flags at position 0,
    so the scoped remainder is wrapped in `(?i:...)`."""
    i = pattern.find("(?i)")
    if i == -1 or (top and i == 0):
        return pattern
    j = i + 4
    # scan to the end of the enclosing group (unmatched ')') honoring
    # escapes and character classes
    depth = 0
    in_class = False
    k = j
    while k < len(pattern):
        c = pattern[k]
        if c == "\\":
            k += 2
            continue
        if in_class:
            if c == "]":
                in_class = False
        elif c == "[":
            in_class = True
        elif c == "(":
            depth += 1
        elif c == ")":
            if depth == 0:
                break
            depth -= 1
        k += 1
    inner = _goflags(pattern[j:k], top=False)
    rest = _goflags(pattern[k:], top=False)
    return pattern[:i] + "(?i:" + inner + ")" + rest


_NAMED_GROUP = re.compile(r"\(\?P<([A-Za-z_]\w*)>")


def _dedup_groups(pattern: str):
    """Go regexps may reuse a group name; Python forbids it. Rename
    later occurrences name → name__N and report the alias map so
    secret-group extraction can follow every occurrence."""
    seen: dict[str, int] = {}
    aliases: dict[str, list[str]] = {}
    out = []
    last = 0
    for m in _NAMED_GROUP.finditer(pattern):
        name = m.group(1)
        n = seen.get(name, 0) + 1
        seen[name] = n
        if n > 1:
            new = f"{name}__{n}"
            out.append(pattern[last:m.start()] + f"(?P<{new}>")
            last = m.end()
            aliases.setdefault(name, []).append(new)
    out.append(pattern[last:])
    return "".join(out), aliases


def compile_rule_regex(pattern: str):
    """→ (compiled regex, group alias map) with Go-compat fixups."""
    pattern, aliases = _dedup_groups(pattern)
    return re.compile(_goflags(pattern)), aliases


def _build() -> list[Rule]:
    rules = []
    for rid, cat, title, sev, pattern, keywords, group in _TABLE:
        rx, aliases = compile_rule_regex(pattern)
        rules.append(Rule(
            id=rid, category=cat, title=title, severity=sev,
            regex=rx, keywords=list(keywords),
            secret_group=group,
            secret_aliases=tuple(aliases.get(group, ()))))
    return rules


BUILTIN_RULES: list[Rule] = _build()


def load_secret_config(path: str):
    """trivy-secret.yaml → (rules, global_allow_rules,
    global_exclude_regexes). Schema mirrors the reference secret.Config
    (pkg/fanal/secret/scanner.go:27-41): enable-builtin-rules restricts
    the builtin set, disable-rules and disable-allow-rules remove by id
    (from the global AND per-rule allow sets), `rules` / `allow-rules`
    append custom entries, `exclude-block` (global and per-rule) strips
    matching text regions before reporting."""
    import dataclasses

    import yaml
    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    rules = list(BUILTIN_RULES)
    enable = doc.get("enable-builtin-rules") or []
    if enable:
        keep = set(enable)
        rules = [r for r in rules if r.id in keep]
    for rd in doc.get("rules") or []:
        rx, aliases = compile_rule_regex(rd.get("regex", ""))
        rules.append(Rule(
            id=rd.get("id", ""), category=rd.get("category", ""),
            title=rd.get("title", ""), severity=rd.get("severity", ""),
            regex=rx,
            keywords=list(rd.get("keywords") or []),
            secret_group=rd.get("secret-group-name", ""),
            secret_aliases=tuple(
                aliases.get(rd.get("secret-group-name", ""), ())),
            path=re.compile(rd["path"]) if rd.get("path") else None,
            allow_rules=[_allow_from_dict(a)
                         for a in rd.get("allow-rules") or []],
            exclude_regexes=[
                re.compile(rx) for rx in
                (rd.get("exclude-block") or {}).get("regexes") or []],
        ))
    # disable-rules applies to builtin AND custom ids (the reference
    # filters after merging, scanner.go NewScanner)
    disable = set(doc.get("disable-rules") or [])
    rules = [r for r in rules if r.id not in disable]
    disable_allow = set(doc.get("disable-allow-rules") or [])
    if disable_allow:
        # applies to per-rule allow sets too (scanner.go NewScanner)
        rules = [
            dataclasses.replace(r, allow_rules=[
                a for a in r.allow_rules if a.id not in disable_allow])
            if any(a.id in disable_allow for a in r.allow_rules) else r
            for r in rules
        ]
    allow = [a for a in GLOBAL_ALLOW_RULES if a.id not in disable_allow]
    allow.extend(_allow_from_dict(a) for a in doc.get("allow-rules") or [])
    exclude = [re.compile(rx) for rx in
               (doc.get("exclude-block") or {}).get("regexes") or []]
    return rules, allow, exclude


def _allow_from_dict(a: dict) -> AllowRule:
    return AllowRule(
        a.get("id", ""), a.get("description", ""),
        regex=re.compile(a["regex"]) if a.get("regex") else None,
        path=re.compile(a["path"]) if a.get("path") else None)
