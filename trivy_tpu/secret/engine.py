"""SecretScanner v2: exact multi-pattern keyword matching on device,
regex confirmation on host.

Parity contract with the reference scanner (pkg/fanal/secret/scanner.go
Scan:341-418): per file — global allow paths, per-rule path gates, keyword
prefilter (here: one device shift-or pass over all files × all rules
instead of bytes.Contains per rule per file), regex locations with optional
secret-group submatch, allow regexes, exclude blocks, censoring, line/
context extraction (findLocation:447-504), finding sort.

Engine v1 ran a 4-byte-prefix SUPERSET filter on device and re-confirmed
every candidate with a host substring pass; v2's device bitmask is exact
(ops/ac.py has the shift-or derivation), so the host stage is "run the
regex for gated rules" and nothing else. Three prefilter paths, counted
by `trivy_tpu_secret_prefilter_path_total{path=}`:

  pallas  the ops/shiftor_pallas VMEM kernel (TPU backends)
  jnp     ops/ac.shiftor_scan — the CPU path, and the mesh path via
          parallel.mesh.sharded_shiftor_scan (chunk rows sharded over
          every device, so the secrets lane rides meshguard's fault
          domains exactly like the join). Known cost: the meshed lane
          pays the jnp scan's n_keywords × state_words HBM passes per
          shard — dispatching the Pallas kernel per shard under
          shard_map is the open follow-up, deferred until a live-TPU
          round can validate it
  host    bytes.find per keyword — small batches (the device cannot
          amortize dispatch latency under `small_batch_bytes`), the
          graftguard fallback while the detect breaker is open, and
          the parity oracle tier-1 gates the device paths against

`scan_files_many` is the coalesced entry: fanald's pipelined layer walk
hands EVERY missing layer's secret files to one call, so one device
launch serves many concurrent layers the way detectd coalesces joins —
per-layer calls rarely cross the small-batch floor, coalesced ones do.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Optional

import numpy as np

from .. import types as T
from ..metrics import METRICS
from ..obs import cost as _cost
from ..obs import span
from ..ops import ac
from .rules import BUILTIN_RULES, GLOBAL_ALLOW_RULES, Rule

CHUNK_LEN = 16384
# Max chunk rows per shiftor_scan call. Large on purpose: the dominant
# cost of a device call is per-call (tunnel/dispatch) latency, so rows
# are batched up to 4096 (64 MiB of chunk bytes) and padded to a power
# of two so each bucket shape compiles exactly once.
DEVICE_ROWS = 4096
# Below this many total bytes the device path cannot amortize its
# dispatch+transfer latency and plain bytes.find wins — route small
# batches to the host scan so the default is never slower than host.
SMALL_BATCH_BYTES = 2 << 20


class SecretScanner:
    def __init__(self, rules: Optional[list[Rule]] = None,
                 allow_rules: Optional[list] = None,
                 use_device: bool = True,
                 exclude_regexes: Optional[list] = None,
                 mesh=None,
                 small_batch_bytes: Optional[int] = None):
        self.rules = rules if rules is not None else BUILTIN_RULES
        self.global_allow = (allow_rules if allow_rules is not None
                             else GLOBAL_ALLOW_RULES)
        # global exclude-block regexes (scanner.go:27-41 Config)
        self.global_exclude = exclude_regexes or []
        self.use_device = use_device
        # when set, the keyword prefilter shards chunk rows over every
        # device of the dp×db mesh (parallel.mesh.sharded_shiftor_scan)
        self.mesh = mesh
        # instance knob so coalesced callers (storm drills, bench) can
        # force the device path on small fixtures
        self.small_batch_bytes = SMALL_BATCH_BYTES \
            if small_batch_bytes is None else small_batch_bytes
        # keyword → rule bitset mapping for the shared automaton
        self._keywords: list[bytes] = []
        self._kw_rules: list[list[int]] = []
        kw_index: dict[bytes, int] = {}
        self._no_keyword_rules = []
        for ri, rule in enumerate(self.rules):
            if not rule.keywords:
                self._no_keyword_rules.append(ri)
                continue
            for kw in rule.keywords:
                k = kw.lower().encode()
                if k not in kw_index:
                    kw_index[k] = len(self._keywords)
                    self._keywords.append(k)
                    self._kw_rules.append([])
                self._kw_rules[kw_index[k]].append(ri)
        self._bank = ac.build_literal_bank(self._keywords) \
            if self._keywords else None
        self._device_arrays = None
        self._pallas_arrays = None
        self._pallas_lock = threading.Lock()
        # tri-state: None = untried, True = compiled fine, False =
        # failed once (don't pay the compile attempt again)
        self._pallas_ok: Optional[bool] = None
        # graftprof: shapes this scanner has dispatched (a new
        # (rows, chunk_len) bucket is a fresh compile) + the bank's
        # host-resident footprint
        self._seen_shapes: set = set()
        if self._bank is not None:
            from ..obs.perf import LEDGER, ndarray_bytes
            LEDGER.note_resident(
                "secret_bank", ndarray_bytes(self._bank.kw_words,
                                             self._bank.kw_masks))

    # --- device prefilter ---

    def _keyword_masks(self, files: list[bytes]) -> list[set[int]]:
        """→ per-file set of rule indices whose keywords appear (exact:
        the device bitmask IS keyword presence, no host re-confirm).

        graftguard: the device prefilter shares the detect breaker —
        while it is open the host scan runs directly (identical rule
        sets, both paths are exact), and device failures here count
        toward opening it. The whole device pass runs under
        GUARD.watch: its dispatch+gets are synchronous, so a clean
        exit is real execution success, a wedge arms the watchdog
        (trips the breaker for everyone else), and errors are recorded
        exactly once by the watch. The `secret.prefilter` failpoint
        fires inside the watch, so chaos drills exercise exactly the
        degradation a real device fault takes."""
        from ..resilience import GUARD, DeviceError
        from ..resilience.failpoints import failpoint
        if self._bank is None:
            return [set() for _ in files]
        total = sum(len(f) for f in files)
        if self.use_device and total >= self.small_batch_bytes and \
                GUARD.allow_device():
            try:
                with GUARD.watch("detect.device_get"):
                    failpoint("secret.prefilter")
                    out, path = self._keyword_masks_device(files)
                self._note_path(path, total)
                return out
            except DeviceError:
                # logged, not just swallowed: a DETERMINISTIC host-side
                # bug landing here would open the shared breaker after
                # fail_threshold scans, and the operator needs the
                # traceback to tell it apart from a real device outage
                from ..log import get as _get_logger
                _get_logger("secret").warning(
                    "device keyword prefilter failed; falling back to "
                    "host scan (counted against the detect breaker)",
                    exc_info=True)
        self._note_path("host", total)
        return self._keyword_masks_host(files)

    @staticmethod
    def _note_path(path: str, n_bytes: int) -> None:
        METRICS.inc("trivy_tpu_secret_prefilter_path_total", path=path)
        METRICS.inc("trivy_tpu_secret_scan_bytes_total", n_bytes,
                    path=path)
        # graftcost: scanned bytes billed to the requesting tenant by
        # the serving path that actually ran them
        _cost.charge_secret_bytes(path, float(n_bytes))

    def _keyword_masks_host(self, files: list[bytes]) -> list[set[int]]:
        out = []
        for data in files:
            low = bytes(ac.lower_bytes(data)) if data else b""
            hit = set()
            for ki, kw in enumerate(self._keywords):
                if kw in low:
                    hit.update(self._kw_rules[ki])
            out.append(hit)
        return out

    def _keyword_masks_device(self, files: list[bytes]
                              ) -> tuple[list[set[int]], str]:
        """→ (per-file exact rule sets, the path that served the
        launch: \"pallas\" | \"jnp\"). The path rides the return value,
        not instance state — one scanner serves many request threads
        (the storm topology and any server process), and a shared
        last-path attribute would mislabel the per-path counters under
        concurrency."""
        import jax
        bank = self._bank
        overlap = bank.max_kw_len - 1
        chunks, owner = ac.pack_chunks(files, CHUNK_LEN, overlap)
        out: list[set[int]] = [set() for _ in files]
        if chunks.shape[0] == 0:
            return out, "jnp"
        if self._device_arrays is None:
            if self.mesh is not None:
                # replicate the (tiny) bank across the mesh once
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(self.mesh, PartitionSpec())
                self._device_arrays = (
                    jax.device_put(bank.kw_words, rep),
                    jax.device_put(bank.kw_masks, rep))
            else:
                self._device_arrays = (jax.device_put(bank.kw_words),
                                       jax.device_put(bank.kw_masks))
        kw_words, kw_masks = self._device_arrays
        # content-addressed dedup: container filesystems repeat whole
        # blocks across files/layers (vendored code, copied configs,
        # near-identical images), and the host→device link is the scan
        # bottleneck — ship each distinct 16 KiB chunk once and fan the
        # result back out. Hashing is ~2 GB/s, pure win.
        import hashlib
        seen: dict[bytes, int] = {}
        remap = np.empty(chunks.shape[0], np.int64)
        uniq_rows: list[int] = []
        for i in range(chunks.shape[0]):
            h = hashlib.blake2b(chunks[i], digest_size=16).digest()
            j = seen.get(h)
            if j is None:
                j = seen[h] = len(uniq_rows)
                uniq_rows.append(i)
            remap[i] = j
        uniq = chunks[np.asarray(uniq_rows)] \
            if len(uniq_rows) < chunks.shape[0] else chunks
        # bounded rows per device call (O(B·L) working set), padded to a
        # power of two so each bucket shape compiles once; calls pipeline
        from ..ops import next_pow2
        use_pallas = (self.mesh is None and self._pallas_ok is not False
                      and bank.n_keywords <= 128 and _tpu_backend())
        from ..obs.perf import LEDGER
        from ..resilience import GUARD
        # ledger contract: blameless background work (a redetectd-style
        # sweep) re-tags its launches so it never muddies the live
        # occupancy story
        site = "redetect" if GUARD.blameless_active() else "secret"
        futures = []
        for off in range(0, uniq.shape[0], DEVICE_ROWS):
            piece = uniq[off:off + DEVICE_ROWS]
            real_rows = int(piece.shape[0])
            row_len = int(piece.shape[1])
            b = next_pow2(real_rows, floor=64)
            if real_rows < b:
                pad = np.zeros((b, row_len), np.uint8)
                pad[:real_rows] = piece
                piece = pad
            # graftprof: a (rows, chunk_len, path) bucket this scanner
            # has not dispatched is a fresh trace+compile — the
            # dispatch call below pays it synchronously, so its wall
            # time is the compile estimate the ledger records
            shape_key = (b, row_len, use_pallas,
                         self.mesh is not None)
            with self._pallas_lock:
                new_shape = shape_key not in self._seen_shapes
                if new_shape:
                    self._seen_shapes.add(shape_key)
            t0 = time.perf_counter()
            # device_put, not jnp.asarray — the latter is an order of
            # magnitude slower for large host arrays on remote backends
            if self.mesh is not None:
                from ..parallel.mesh import sharded_shiftor_scan
                futures.append(sharded_shiftor_scan(
                    self.mesh, kw_words, kw_masks, piece,
                    n_words=bank.words))
            elif use_pallas:
                try:
                    futures.append(self._pallas_scan(piece))
                except Exception:
                    # a silent downgrade here used to cost every later
                    # scan its kernel with no signal — now it logs once
                    # and shows up as path="jnp" in the path counter
                    self._note_pallas_failure()
                    use_pallas = False
                    # the jnp shape this fallback compiles is ALSO
                    # seen now — without this, the next chunk of the
                    # same geometry re-keys (use_pallas=False), reads
                    # as a fresh "compile", and lands a near-zero
                    # sample in the compile_ms histogram
                    with self._pallas_lock:
                        self._seen_shapes.add(
                            (b, row_len, False, self.mesh is not None))
                    futures.append(ac.shiftor_scan(
                        kw_words, kw_masks, jax.device_put(piece),
                        n_words=bank.words))
            else:
                futures.append(ac.shiftor_scan(
                    kw_words, kw_masks, jax.device_put(piece),
                    n_words=bank.words))
            if new_shape:
                LEDGER.note_compile(
                    site, b, 0,
                    (time.perf_counter() - t0) * 1e3)
            LEDGER.note_dispatch(site, real_rows, b,
                                 row_bytes=row_len)
            # graftcost: the dispatch call's wall time (compile
            # included on a fresh shape) is this piece's device ms
            _cost.charge_device_ms(site,
                                   (time.perf_counter() - t0) * 1e3)
        try:
            fetched = []
            for f in futures:
                t_get = time.perf_counter()
                arr = jax.device_get(f)
                _cost.charge_device_ms(
                    site, (time.perf_counter() - t_get) * 1e3)
                _cost.ledgered_transfer("dense", float(arr.nbytes))
                fetched.append(arr)
            masks = np.concatenate(
                fetched, axis=0)[:uniq.shape[0]][remap]
        except Exception:
            # async pallas failures surface here, not at dispatch —
            # record them so later batches skip straight to the
            # shiftor_scan path instead of re-failing every scan
            if use_pallas:
                self._note_pallas_failure()
            raise
        if use_pallas:
            # monotonic, under the lock: never re-arm a failed kernel.
            # A scan that dispatched via pallas BEFORE a concurrent
            # thread recorded a deterministic failure must not flip
            # the flag back and re-pay the failing compile (and the
            # downgrade log) on every later scan — only None→True.
            with self._pallas_lock:
                if self._pallas_ok is None:
                    self._pallas_ok = True
        # decode the EXACT bitmask: a set bit means the full keyword
        # occurs in that chunk row, so file hits are direct unions —
        # the v1 substring re-confirm is gone. Bit decode is
        # vectorized (unpackbits + nonzero): the per-word Python bit
        # loop was ~1 s on a 64 MiB corpus.
        u8 = np.ascontiguousarray(
            masks.astype(np.uint32)).view(np.uint8)
        bits = np.unpackbits(u8, axis=1, bitorder="little")
        hit_ci, hit_ki = np.nonzero(bits[:, :bank.n_keywords])
        owner_l = owner.tolist()
        for ci, ki in zip(hit_ci.tolist(), hit_ki.tolist()):
            out[owner_l[ci]].update(self._kw_rules[ki])
        return out, ("pallas" if use_pallas else "jnp")

    def _note_pallas_failure(self) -> None:
        with self._pallas_lock:
            self._pallas_ok = False
        from ..log import get as _get_logger
        _get_logger("secret").warning(
            "pallas shiftor kernel failed; this process downgrades the "
            "secret prefilter to the jnp scan (path=\"jnp\" in "
            "trivy_tpu_secret_prefilter_path_total)", exc_info=True)

    def _pallas_scan(self, piece: np.ndarray):
        """One padded [B, CHUNK_LEN] batch through the Pallas TPU
        kernel (ops.shiftor_pallas) — single-VMEM-pass exact keyword
        matching; the jnp scan re-reads HBM once per (keyword, state
        word)."""
        import jax

        from ..ops import shiftor_pallas as sp
        if self._pallas_arrays is None:
            self._pallas_arrays = tuple(
                jax.device_put(a) for a in sp.pack_bank(self._bank))
        kww, kwm, bit = self._pallas_arrays
        return sp.shiftor(kww, kwm, bit, jax.device_put(piece),
                          n_words=self._bank.words)

    # --- host confirmation (exact reference semantics) ---

    def scan_files(self, files: list[tuple[str, bytes]]) -> list[T.Secret]:
        """files: [(path, content)] → per-file Secret results (empty
        findings omitted)."""
        return self.scan_files_many([files])[0]

    def scan_files_many(self, batches: list[list[tuple[str, bytes]]]
                        ) -> list[list[T.Secret]]:
        """Coalesced entry: ONE keyword-prefilter launch over every
        batch's files (fanald hands each missing layer as one batch),
        then per-file regex confirmation. Results are per batch, in
        batch/file order — bit-identical to per-batch scan_files calls
        by construction (the prefilter is exact either way; only the
        device launch is shared)."""
        files = [fc for batch in batches for fc in batch]
        contents = [c for _, c in files]
        with span("secret.prefilter", files=len(files),
                  batches=len(batches),
                  bytes=sum(len(c) for c in contents)) as sp:
            masks = self._keyword_masks(contents)
            flagged = sum(len(m) for m in masks)
            sp.attrs["candidates"] = flagged
        results: list[list[T.Secret]] = []
        confirmed = 0
        it = iter(zip(files, masks))
        with span("secret.confirm", files=len(files)) as sp:
            for batch in batches:
                out = []
                for _ in batch:
                    (path, content), rule_idx = next(it)
                    gated = set(rule_idx)
                    sec = self.scan_file(
                        path, content,
                        candidate_rules=gated
                        | set(self._no_keyword_rules))
                    if sec.findings:
                        out.append(sec)
                        hit_ids = {f.rule_id for f in sec.findings}
                        confirmed += sum(
                            1 for ri in gated
                            if self.rules[ri].id in hit_ids)
                results.append(out)
            sp.attrs["findings"] = sum(len(s.findings)
                                       for out in results for s in out)
        if flagged:
            # regex yield of the keyword gate: how many gated
            # (file, rule) candidates actually produced a finding
            METRICS.observe("trivy_tpu_secret_candidate_precision",
                            confirmed / flagged)
        METRICS.inc("trivy_tpu_secret_files_total", len(files))
        METRICS.inc("trivy_tpu_secret_bytes_total",
                    sum(len(c) for c in contents))
        METRICS.inc("trivy_tpu_secret_findings_total",
                    sum(len(s.findings) for out in results for s in out))
        return results

    def scan_file(self, path: str, content: bytes,
                  candidate_rules: Optional[set] = None) -> T.Secret:
        if any(a.path and a.path.search(path) for a in self.global_allow):
            return T.Secret(file_path=path)
        text = content.decode("utf-8", errors="surrogateescape")
        censored = None
        matched = []
        global_exb = _blocks(text, self.global_exclude) \
            if self.global_exclude else []
        if candidate_rules is None:
            low = bytes(ac.lower_bytes(content)) if content else b""
        for ri, rule in enumerate(self.rules):
            if candidate_rules is not None and ri not in candidate_rules:
                continue
            if not rule.match_path(path):
                continue
            if rule.allow_path(path):
                continue
            if candidate_rules is None and not rule.match_keywords(low):
                continue
            locs = self._find_locations(rule, text)
            if not locs:
                continue
            exb = _blocks(text, rule.exclude_regexes) + global_exb
            for start, end in locs:
                if _in_blocks(start, end, exb):
                    continue
                matched.append((rule, start, end))
                if censored is None:
                    censored = list(text)
                for i in range(start, end):
                    censored[i] = "*"
        if not matched:
            return T.Secret(file_path=path)
        censored_text = "".join(censored)
        findings = [self._to_finding(rule, s, e, censored_text)
                    for rule, s, e in matched]
        findings.sort(key=lambda f: (f.rule_id, f.match))
        return T.Secret(file_path=path, findings=findings)

    def _find_locations(self, rule: Rule, text: str):
        locs = []
        if rule.secret_group:
            # a Go regex may bind the group name more than once
            # (renamed name__N at compile); each occurrence is a finding
            groups = (rule.secret_group,) + tuple(
                getattr(rule, "secret_aliases", ()))
            for m in rule.regex.finditer(text):
                if self._allowed(rule, m.group(0)):
                    continue
                for g in groups:
                    try:
                        s, e = m.span(g)
                    except (IndexError, re.error):
                        continue
                    if s >= 0:
                        locs.append((s, e))
        else:
            for m in rule.regex.finditer(text):
                if self._allowed(rule, m.group(0)):
                    continue
                locs.append(m.span())
        return locs

    def _allowed(self, rule: Rule, match: str) -> bool:
        if any(a.regex and a.regex.search(match) for a in self.global_allow):
            return True
        return rule.allow_match(match)

    @staticmethod
    def _to_finding(rule: Rule, start: int, end: int,
                    content: str) -> T.SecretFinding:
        start_line, end_line, code, match_line = _find_location(
            start, end, content)
        return T.SecretFinding(
            rule_id=rule.id,
            category=rule.category,
            severity=rule.severity or "UNKNOWN",
            title=rule.title,
            start_line=start_line,
            end_line=end_line,
            code=code,
            match=match_line,
        )


def _tpu_backend() -> bool:
    """True when the default JAX device is a TPU (incl. the tunneled
    axon platform, whose device_kind reads 'TPU v5 ...')."""
    try:
        import jax
        dev = jax.devices()[0]
        return "tpu" in (getattr(dev, "platform", "") or "").lower() \
            or "tpu" in (getattr(dev, "device_kind", "") or "").lower()
    except Exception:
        return False


def _blocks(text: str, regexes) -> list[tuple[int, int]]:
    out = []
    for rx in regexes:
        for m in rx.finditer(text):
            out.append(m.span())
    return out


def _in_blocks(start: int, end: int, blocks) -> bool:
    return any(bs <= start and end <= be for bs, be in blocks)


_RADIUS = 2  # context lines above/below (scanner.go secretHighlightRadius)


def _find_location(start: int, end: int, content: str):
    """Line numbers, context code window, and the censored match line —
    reference findLocation (scanner.go:447-504)."""
    start_line_num = content.count("\n", 0, start)
    line_start = content.rfind("\n", 0, start)
    line_start = 0 if line_start == -1 else line_start + 1
    line_end = content.find("\n", start)
    line_end = len(content) if line_end == -1 else line_end
    if line_end - line_start > 100:
        line_start = max(start - 30, 0)
        line_end = min(end + 20, len(content))
    match_line = content[line_start:line_end]
    end_line_num = start_line_num + content.count("\n", start, end)

    lines = content.split("\n")
    code_start = max(start_line_num - _RADIUS, 0)
    code_end = min(end_line_num + _RADIUS, len(lines))
    code_lines = []
    found_first = False
    for i, raw in enumerate(lines[code_start:code_end]):
        real = code_start + i
        in_cause = start_line_num <= real <= end_line_num
        code_lines.append(T.CodeLine(
            number=code_start + i + 1,
            content=raw,
            is_cause=in_cause,
            highlighted=raw,
            first_cause=in_cause and not found_first,
            last_cause=False,
        ))
        found_first = found_first or in_cause
    for cl in reversed(code_lines):
        if cl.is_cause:
            cl.last_cause = True
            break
    return (start_line_num + 1, end_line_num + 1,
            T.Code(lines=code_lines), match_line)
