"""SecretScanner: batched keyword prefilter on device, exact rule
confirmation on host.

Parity contract with the reference scanner (pkg/fanal/secret/scanner.go
Scan:341-418): per file — global allow paths, per-rule path gates, keyword
prefilter (here: one device Aho-Corasick pass over all files × all rules
instead of bytes.Contains per rule per file), regex locations with optional
secret-group submatch, allow regexes, exclude blocks, censoring, line/
context extraction (findLocation:447-504), finding sort.
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from .. import types as T
from ..obs import span
from ..ops import ac
from .rules import BUILTIN_RULES, GLOBAL_ALLOW_RULES, Rule

CHUNK_LEN = 16384
# Max chunk rows per prefix_scan call. Large on purpose: the dominant
# cost of a device call is per-call (tunnel/dispatch) latency, so rows
# are batched up to 4096 (64 MiB of chunk bytes) and padded to a power
# of two so each bucket shape compiles exactly once.
DEVICE_ROWS = 4096
# Below this many total bytes the device path cannot amortize its
# dispatch+transfer latency and plain bytes.find wins — route small
# batches to the host scan so the default is never slower than host.
SMALL_BATCH_BYTES = 2 << 20


class SecretScanner:
    def __init__(self, rules: Optional[list[Rule]] = None,
                 allow_rules: Optional[list] = None,
                 use_device: bool = True,
                 exclude_regexes: Optional[list] = None,
                 mesh=None):
        self.rules = rules if rules is not None else BUILTIN_RULES
        self.global_allow = (allow_rules if allow_rules is not None
                             else GLOBAL_ALLOW_RULES)
        # global exclude-block regexes (scanner.go:27-41 Config)
        self.global_exclude = exclude_regexes or []
        self.use_device = use_device
        # when set, the keyword prefilter shards chunk rows over every
        # device of the dp×db mesh (parallel.mesh.sharded_prefix_scan)
        self.mesh = mesh
        # keyword → rule bitset mapping for the shared automaton
        self._keywords: list[bytes] = []
        self._kw_rules: list[list[int]] = []
        kw_index: dict[bytes, int] = {}
        self._no_keyword_rules = []
        for ri, rule in enumerate(self.rules):
            if not rule.keywords:
                self._no_keyword_rules.append(ri)
                continue
            for kw in rule.keywords:
                k = kw.lower().encode()
                if k not in kw_index:
                    kw_index[k] = len(self._keywords)
                    self._keywords.append(k)
                    self._kw_rules.append([])
                self._kw_rules[kw_index[k]].append(ri)
        self._bank = ac.build_literal_bank(self._keywords) \
            if self._keywords else None
        self._device_arrays = None
        self._pallas_arrays = None
        # tri-state: None = untried, True = compiled fine, False =
        # failed once (don't pay the compile attempt again)
        self._pallas_ok: Optional[bool] = None

    # --- device prefilter ---

    def _keyword_masks(self, files: list[bytes]) -> list[set[int]]:
        """→ per-file set of rule indices whose keywords appear.

        graftguard: the device prefilter shares the detect breaker —
        while it is open the host scan runs directly (same candidate
        sets, the prefilter is exact either way), and device failures
        here count toward opening it. The whole device pass runs under
        GUARD.watch: its dispatch+gets are synchronous, so a clean
        exit is real execution success, a wedge arms the watchdog
        (trips the breaker for everyone else), and errors are recorded
        exactly once by the watch."""
        from ..resilience import GUARD, DeviceError
        if self._bank is None:
            return [set() for _ in files]
        if self.use_device and \
                sum(len(f) for f in files) >= SMALL_BATCH_BYTES and \
                GUARD.allow_device():
            try:
                with GUARD.watch("detect.device_get"):
                    return self._keyword_masks_device(files)
            except DeviceError:
                # logged, not just swallowed: a DETERMINISTIC host-side
                # bug landing here would open the shared breaker after
                # fail_threshold scans, and the operator needs the
                # traceback to tell it apart from a real device outage
                from ..log import get as _get_logger
                _get_logger("secret").warning(
                    "device keyword prefilter failed; falling back to "
                    "host scan (counted against the detect breaker)",
                    exc_info=True)
        return self._keyword_masks_host(files)

    def _keyword_masks_host(self, files: list[bytes]) -> list[set[int]]:
        out = []
        for data in files:
            low = bytes(ac.lower_bytes(data)) if data else b""
            hit = set()
            for ki, kw in enumerate(self._keywords):
                if kw in low:
                    hit.update(self._kw_rules[ki])
            out.append(hit)
        return out

    def _keyword_masks_device(self, files: list[bytes]) -> list[set[int]]:
        import jax
        bank = self._bank
        overlap = bank.max_kw_len - 1
        chunks, owner = ac.pack_chunks(files, CHUNK_LEN, overlap)
        out: list[set[int]] = [set() for _ in files]
        if chunks.shape[0] == 0:
            return out
        if self._device_arrays is None:
            if self.mesh is not None:
                # replicate the (tiny) bank across the mesh once
                from jax.sharding import NamedSharding, PartitionSpec
                rep = NamedSharding(self.mesh, PartitionSpec())
                self._device_arrays = (
                    jax.device_put(bank.kw_word4, rep),
                    jax.device_put(bank.kw_mask4, rep))
            else:
                self._device_arrays = (jax.device_put(bank.kw_word4),
                                       jax.device_put(bank.kw_mask4))
        kw_word4, kw_mask4 = self._device_arrays
        # content-addressed dedup: container filesystems repeat whole
        # blocks across files/layers (vendored code, copied configs,
        # near-identical images), and the host→device link is the scan
        # bottleneck — ship each distinct 16 KiB chunk once and fan the
        # result back out. Hashing is ~2 GB/s, pure win.
        import hashlib
        seen: dict[bytes, int] = {}
        remap = np.empty(chunks.shape[0], np.int64)
        uniq_rows: list[int] = []
        for i in range(chunks.shape[0]):
            h = hashlib.blake2b(chunks[i], digest_size=16).digest()
            j = seen.get(h)
            if j is None:
                j = seen[h] = len(uniq_rows)
                uniq_rows.append(i)
            remap[i] = j
        uniq = chunks[np.asarray(uniq_rows)] \
            if len(uniq_rows) < chunks.shape[0] else chunks
        # bounded rows per device call (O(B·L) working set), padded to a
        # power of two so each bucket shape compiles once; calls pipeline
        from ..ops import next_pow2
        use_pallas = (self.mesh is None and self._pallas_ok is not False
                      and bank.n_keywords <= 128 and _tpu_backend())
        futures = []
        for off in range(0, uniq.shape[0], DEVICE_ROWS):
            piece = uniq[off:off + DEVICE_ROWS]
            b = next_pow2(piece.shape[0], floor=64)
            if piece.shape[0] < b:
                pad = np.zeros((b, piece.shape[1]), np.uint8)
                pad[:piece.shape[0]] = piece
                piece = pad
            # device_put, not jnp.asarray — the latter is an order of
            # magnitude slower for large host arrays on remote backends
            if self.mesh is not None:
                from ..parallel.mesh import sharded_prefix_scan
                futures.append(sharded_prefix_scan(
                    self.mesh, kw_word4, kw_mask4, piece,
                    n_words=bank.words))
            elif use_pallas:
                try:
                    futures.append(self._pallas_scan(piece))
                except Exception:
                    self._pallas_ok = use_pallas = False
                    futures.append(ac.prefix_scan(
                        kw_word4, kw_mask4, jax.device_put(piece),
                        n_words=bank.words))
            else:
                futures.append(ac.prefix_scan(
                    kw_word4, kw_mask4, jax.device_put(piece),
                    n_words=bank.words))
        try:
            masks = np.concatenate(
                [jax.device_get(f) for f in futures],
                axis=0)[:uniq.shape[0]][remap]
        except Exception:
            # async pallas failures surface here, not at dispatch —
            # record them so later batches skip straight to the
            # lax.scan path instead of re-failing every scan
            if use_pallas:
                self._pallas_ok = False
            raise
        if use_pallas:
            self._pallas_ok = True
        # confirm the (rare) device candidates exactly: the device tests
        # only the packed 4-byte keyword prefix, so confirm the full
        # keyword in the chunk's (lowercased, overlap-including) bytes
        # before gating any rule — parity with bytes.Contains. Bit
        # decode is vectorized (unpackbits + nonzero): the per-word
        # Python bit loop was ~1 s on a 64 MiB corpus.
        u8 = np.ascontiguousarray(
            masks.astype(np.uint32)).view(np.uint8)
        bits = np.unpackbits(u8, axis=1, bitorder="little")
        cand_ci, cand_ki = np.nonzero(bits[:, :bank.n_keywords])
        owner_l = owner.tolist()
        confirmed: set[tuple[int, int]] = set()
        row_cache: dict[int, bytes] = {}
        for ci, ki in zip(cand_ci.tolist(), cand_ki.tolist()):
            fi = owner_l[ci]
            ck = (fi, ki)
            if ck in confirmed:
                continue
            row_bytes = row_cache.get(ci)
            if row_bytes is None:
                row_bytes = row_cache[ci] = chunks[ci].tobytes()
            if bank.kw_bytes[ki] in row_bytes:
                confirmed.add(ck)
                out[fi].update(self._kw_rules[ki])
        return out

    def _pallas_scan(self, piece: np.ndarray):
        """One padded [B, CHUNK_LEN] batch through the Pallas TPU
        kernel (ops.prefilter_pallas) — single-VMEM-pass keyword
        matching, ~16× the lax.scan path on a v5e."""
        import jax

        from ..ops import prefilter_pallas as pp
        if self._pallas_arrays is None:
            self._pallas_arrays = tuple(
                jax.device_put(a) for a in pp.pack_bank(self._bank))
        kww, kwm, bit = self._pallas_arrays
        return pp.prefilter(kww, kwm, bit, jax.device_put(piece),
                            n_words=self._bank.words)

    # --- host confirmation (exact reference semantics) ---

    def scan_files(self, files: list[tuple[str, bytes]]) -> list[T.Secret]:
        """files: [(path, content)] → per-file Secret results (empty
        findings omitted)."""
        from ..metrics import METRICS
        contents = [c for _, c in files]
        with span("secret.prefilter", files=len(files),
                  bytes=sum(len(c) for c in contents)) as sp:
            masks = self._keyword_masks(contents)
            sp.attrs["candidates"] = sum(len(m) for m in masks)
        results = []
        with span("secret.confirm", files=len(files)) as sp:
            for (path, content), rule_idx in zip(files, masks):
                rule_idx = set(rule_idx) | set(self._no_keyword_rules)
                sec = self.scan_file(path, content,
                                     candidate_rules=rule_idx)
                if sec.findings:
                    results.append(sec)
            sp.attrs["findings"] = sum(len(s.findings) for s in results)
        METRICS.inc("trivy_tpu_secret_files_total", len(files))
        METRICS.inc("trivy_tpu_secret_bytes_total",
                    sum(len(c) for c in contents))
        METRICS.inc("trivy_tpu_secret_findings_total",
                    sum(len(s.findings) for s in results))
        return results

    def scan_file(self, path: str, content: bytes,
                  candidate_rules: Optional[set] = None) -> T.Secret:
        if any(a.path and a.path.search(path) for a in self.global_allow):
            return T.Secret(file_path=path)
        text = content.decode("utf-8", errors="surrogateescape")
        censored = None
        matched = []
        global_exb = _blocks(text, self.global_exclude) \
            if self.global_exclude else []
        if candidate_rules is None:
            low = bytes(ac.lower_bytes(content)) if content else b""
        for ri, rule in enumerate(self.rules):
            if candidate_rules is not None and ri not in candidate_rules:
                continue
            if not rule.match_path(path):
                continue
            if rule.allow_path(path):
                continue
            if candidate_rules is None and not rule.match_keywords(low):
                continue
            locs = self._find_locations(rule, text)
            if not locs:
                continue
            exb = _blocks(text, rule.exclude_regexes) + global_exb
            for start, end in locs:
                if _in_blocks(start, end, exb):
                    continue
                matched.append((rule, start, end))
                if censored is None:
                    censored = list(text)
                for i in range(start, end):
                    censored[i] = "*"
        if not matched:
            return T.Secret(file_path=path)
        censored_text = "".join(censored)
        findings = [self._to_finding(rule, s, e, censored_text)
                    for rule, s, e in matched]
        findings.sort(key=lambda f: (f.rule_id, f.match))
        return T.Secret(file_path=path, findings=findings)

    def _find_locations(self, rule: Rule, text: str):
        locs = []
        if rule.secret_group:
            # a Go regex may bind the group name more than once
            # (renamed name__N at compile); each occurrence is a finding
            groups = (rule.secret_group,) + tuple(
                getattr(rule, "secret_aliases", ()))
            for m in rule.regex.finditer(text):
                if self._allowed(rule, m.group(0)):
                    continue
                for g in groups:
                    try:
                        s, e = m.span(g)
                    except (IndexError, re.error):
                        continue
                    if s >= 0:
                        locs.append((s, e))
        else:
            for m in rule.regex.finditer(text):
                if self._allowed(rule, m.group(0)):
                    continue
                locs.append(m.span())
        return locs

    def _allowed(self, rule: Rule, match: str) -> bool:
        if any(a.regex and a.regex.search(match) for a in self.global_allow):
            return True
        return rule.allow_match(match)

    @staticmethod
    def _to_finding(rule: Rule, start: int, end: int,
                    content: str) -> T.SecretFinding:
        start_line, end_line, code, match_line = _find_location(
            start, end, content)
        return T.SecretFinding(
            rule_id=rule.id,
            category=rule.category,
            severity=rule.severity or "UNKNOWN",
            title=rule.title,
            start_line=start_line,
            end_line=end_line,
            code=code,
            match=match_line,
        )


def _tpu_backend() -> bool:
    """True when the default JAX device is a TPU (incl. the tunneled
    axon platform, whose device_kind reads 'TPU v5 ...')."""
    try:
        import jax
        dev = jax.devices()[0]
        return "tpu" in (getattr(dev, "platform", "") or "").lower() \
            or "tpu" in (getattr(dev, "device_kind", "") or "").lower()
    except Exception:
        return False


def _blocks(text: str, regexes) -> list[tuple[int, int]]:
    out = []
    for rx in regexes:
        for m in rx.finditer(text):
            out.append(m.span())
    return out


def _in_blocks(start: int, end: int, blocks) -> bool:
    return any(bs <= start and end <= be for bs, be in blocks)


_RADIUS = 2  # context lines above/below (scanner.go secretHighlightRadius)


def _find_location(start: int, end: int, content: str):
    """Line numbers, context code window, and the censored match line —
    reference findLocation (scanner.go:447-504)."""
    start_line_num = content.count("\n", 0, start)
    line_start = content.rfind("\n", 0, start)
    line_start = 0 if line_start == -1 else line_start + 1
    line_end = content.find("\n", start)
    line_end = len(content) if line_end == -1 else line_end
    if line_end - line_start > 100:
        line_start = max(start - 30, 0)
        line_end = min(end + 20, len(content))
    match_line = content[line_start:line_end]
    end_line_num = start_line_num + content.count("\n", start, end)

    lines = content.split("\n")
    code_start = max(start_line_num - _RADIUS, 0)
    code_end = min(end_line_num + _RADIUS, len(lines))
    code_lines = []
    found_first = False
    for i, raw in enumerate(lines[code_start:code_end]):
        real = code_start + i
        in_cause = start_line_num <= real <= end_line_num
        code_lines.append(T.CodeLine(
            number=code_start + i + 1,
            content=raw,
            is_cause=in_cause,
            highlighted=raw,
            first_cause=in_cause and not found_first,
            last_cause=False,
        ))
        found_first = found_first or in_cause
    for cl in reversed(code_lines):
        if cl.is_cause:
            cl.last_cause = True
            break
    return (start_line_num + 1, end_line_num + 1,
            T.Code(lines=code_lines), match_line)
