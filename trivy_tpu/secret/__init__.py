"""Secret scanning: exact device shift-or keyword matching + host
regex confirmation with the reference's rule semantics."""

from .engine import SecretScanner  # noqa: F401
from .rules import BUILTIN_RULES  # noqa: F401
