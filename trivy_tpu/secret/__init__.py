"""Secret scanning: device Aho-Corasick keyword prefilter + host regex
confirmation with the reference's rule semantics."""

from .engine import SecretScanner  # noqa: F401
from .rules import BUILTIN_RULES  # noqa: F401
