"""Rekor transparency-log client (reference pkg/rekor/client.go).

Speaks the two REST endpoints the reference uses:
- POST /api/v1/index/retrieve  {"hash": "sha256:..."} → [entry ids]
- POST /api/v1/log/entries/retrieve {"entryUUIDs": [...]}
  → [{id: {"attestation": {"data": b64}, ...}}]

Entry IDs are TreeID(16 hex) + UUID(64 hex) (client.go NewEntryID:37).
Used by the remote-SBOM image shortcut and the unpackaged handler.
"""

from __future__ import annotations

import base64
import json
import urllib.request

MAX_GET_ENTRIES = 10  # client.go MaxGetEntriesLimit


class RekorError(Exception):
    pass


class EntryID:
    def __init__(self, raw: str):
        if len(raw) == 80:
            self.tree_id, self.uuid = raw[:16], raw[16:]
        elif len(raw) == 64:
            self.tree_id, self.uuid = "", raw
        else:
            raise RekorError(f"invalid entry UUID: {raw!r}")

    def __str__(self):
        return self.tree_id + self.uuid


class Client:
    def __init__(self, rekor_url: str, timeout: float = 15.0):
        self.base = rekor_url.rstrip("/")
        self.timeout = timeout

    def _post(self, path: str, payload: dict):
        req = urllib.request.Request(
            f"{self.base}{path}", data=json.dumps(payload).encode(),
            method="POST", headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as r:
                return json.loads(r.read() or b"[]")
        except Exception as e:
            raise RekorError(f"rekor request failed: {e}") from e

    def search(self, hash_: str) -> list[EntryID]:
        """Entry IDs whose subjects include this digest
        (client.go Search:73)."""
        ids = self._post("/api/v1/index/retrieve", {"hash": hash_})
        return [EntryID(i) for i in ids or []]

    def get_entries(self, entry_ids: list[EntryID]) -> list[bytes]:
        """Attestation statements for the entries
        (client.go GetEntries:92); entries without attestations are
        skipped."""
        if len(entry_ids) > MAX_GET_ENTRIES:
            raise RekorError(
                f"over get entries limit ({MAX_GET_ENTRIES})")
        if not entry_ids:
            return []
        payload = self._post("/api/v1/log/entries/retrieve",
                             {"entryUUIDs": [str(e) for e in entry_ids]})
        uuids = {e.uuid for e in entry_ids}
        out = []
        for bundle in payload or []:
            for raw_id, entry in bundle.items():
                try:
                    eid = EntryID(raw_id)
                except RekorError:
                    continue
                if eid.uuid not in uuids:
                    continue
                att = (entry or {}).get("attestation") or {}
                data = att.get("data")
                if not data:
                    continue
                try:
                    out.append(base64.b64decode(data))
                except ValueError:
                    continue
        return out


def fetch_sbom_statement(rekor_url: str, digest: str):
    """digest (sha256:...) → decoded in-toto Statement with an SBOM
    predicate, or None (remote_sbom.go inspectSBOMAttestation flow)."""
    from .attestation import decode_any
    client = Client(rekor_url)
    ids = client.search(digest)
    if not ids:
        return None
    for raw in client.get_entries(ids[:MAX_GET_ENTRIES]):
        try:
            doc = json.loads(raw)
        except json.JSONDecodeError:
            continue
        try:
            return decode_any(doc)
        except Exception:
            continue
    return None
