"""Native (C++) host helpers, compiled on first use and loaded via
ctypes; every entry point has a pure-numpy/Python fallback so the
framework works without a toolchain.

Source: native/trivy_native.cpp at the repo root. The compiled object is
cached next to the source keyed by its content hash."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "trivy_native.cpp")

_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SRC):
        return None
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
        cache_dir = os.path.join(tempfile.gettempdir(), "trivy_tpu_native")
        os.makedirs(cache_dir, exist_ok=True)
        so_path = os.path.join(cache_dir, f"trivy_native_{digest}.so")
        if not os.path.exists(so_path):
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                 _SRC, "-o", so_path + ".tmp"],
                check=True, capture_output=True)
            os.replace(so_path + ".tmp", so_path)
        lib = ctypes.CDLL(so_path)
        lib.fnv1a64_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p]
        lib.lower_pack_chunks.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32,
            ctypes.c_void_p]
        lib.contains_lower.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int64]
        lib.contains_lower.restype = ctypes.c_int32
        _lib = lib
    except (OSError, subprocess.CalledProcessError):
        _lib = None
    return _lib


def available() -> bool:
    return _build_and_load() is not None


def fnv1a64_batch(keys: list[bytes]) -> np.ndarray:
    """Hash a batch of byte strings → uint64[N]."""
    lib = _build_and_load()
    if lib is None or not keys:
        from ..ops.hashing import fnv1a64
        return np.asarray([fnv1a64(k) for k in keys], dtype=np.uint64)
    data = b"".join(keys)
    offsets = np.zeros(len(keys) + 1, dtype=np.int64)
    np.cumsum([len(k) for k in keys], out=offsets[1:])
    buf = np.frombuffer(data, dtype=np.uint8) if data else \
        np.zeros(1, np.uint8)
    out = np.empty(len(keys), dtype=np.uint64)
    lib.fnv1a64_batch(
        buf.ctypes.data, offsets.ctypes.data,
        ctypes.c_int64(len(keys)), out.ctypes.data)
    return out


def lower_pack_chunks(data: bytes, chunk_len: int,
                      overlap: int) -> Optional[np.ndarray]:
    """Lowercase + chunk one file → uint8[n_chunks, chunk_len]; None if
    the native library is unavailable (caller falls back)."""
    lib = _build_and_load()
    if lib is None:
        return None
    if not data:
        return np.zeros((0, chunk_len), np.uint8)
    stride = max(1, chunk_len - overlap)
    max_chunks = (len(data) + stride - 1) // stride + 1
    out = np.zeros((max_chunks, chunk_len), dtype=np.uint8)
    n = ctypes.c_int32(0)
    buf = np.frombuffer(data, dtype=np.uint8)
    lib.lower_pack_chunks(
        buf.ctypes.data, ctypes.c_int64(len(data)),
        ctypes.c_int32(chunk_len), ctypes.c_int32(overlap),
        out.ctypes.data, ctypes.c_int32(max_chunks),
        ctypes.byref(n))
    return out[:n.value]
