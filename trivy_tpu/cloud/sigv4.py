"""AWS Signature Version 4 request signing (reference relies on
aws-sdk-go-v2 for this; we sign by hand — no SDK in this image).

Standard algorithm: canonical request → string-to-sign →
HMAC-SHA256 chain keyed on the secret — identical output to the SDK so
the command works against real AWS or any sigv4-checking emulator
(LocalStack, the reference's integration setup)."""

from __future__ import annotations

import datetime as dt
import hashlib
import hmac
from urllib.parse import quote


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sign(method: str, url_host: str, path: str, query: dict,
         headers: dict, body: bytes, service: str, region: str,
         access_key: str, secret_key: str, session_token: str = "",
         now: dt.datetime | None = None) -> dict:
    """→ headers dict including Authorization for the request."""
    t = now or dt.datetime.now(dt.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    datestamp = t.strftime("%Y%m%d")

    payload_hash = hashlib.sha256(body or b"").hexdigest()
    all_headers = dict(headers)
    all_headers["host"] = url_host
    all_headers["x-amz-date"] = amz_date
    all_headers["x-amz-content-sha256"] = payload_hash
    if session_token:
        all_headers["x-amz-security-token"] = session_token

    canon_headers = "".join(
        f"{k.lower()}:{str(v).strip()}\n"
        for k, v in sorted(all_headers.items(),
                           key=lambda kv: kv[0].lower()))
    signed_headers = ";".join(sorted(k.lower() for k in all_headers))
    canon_query = "&".join(
        f"{quote(str(k), safe='-_.~')}={quote(str(v), safe='-_.~')}"
        for k, v in sorted(query.items()))
    canon_path = quote(path or "/", safe="/-_.~")
    canonical = "\n".join([method, canon_path, canon_query,
                           canon_headers, signed_headers, payload_hash])

    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()

    all_headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_headers}, Signature={signature}")
    return all_headers
