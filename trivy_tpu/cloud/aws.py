"""AWS account scanning (reference pkg/cloud/aws).

Walks live AWS APIs (sigv4-signed; endpoint overridable for
LocalStack-style emulators), adapts the responses into the shared
cloud-state model, caches the adapted state per account/region
(pkg/cloud/aws/cache/cache.go), and evaluates the AVD-AWS check set —
the same checks the terraform/cloudformation scanners use, which is
exactly how the reference reuses its iac rules over live accounts
(pkg/cloud/aws/scanner/scanner.go:29).

Services covered: s3, ec2 (security groups, instances, VPC flow
logs), ebs, rds, cloudtrail, efs, elb (v2), iam (customer-managed
policies + account password policy, root summary, per-user credential
hygiene), cloudfront, dynamodb, ecr, ecs, eks, kms, lambda, sns, sqs,
elasticache, redshift, api-gateway, and sts (account discovery).
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from .. import types as T
from ..iac.cloud import Attr, AWS_CHECKS, CloudResource
from ..iac.core import build_misconf
from ..log import logger
from .sigv4 import sign

SUPPORTED_SERVICES = ["s3", "ec2", "ebs", "rds", "cloudtrail",
                      "efs", "elb", "iam", "cloudfront", "dynamodb",
                      "ecr", "ecs", "eks", "kms", "lambda", "sns",
                      "sqs", "elasticache", "redshift", "api-gateway"]
# v2: cloudtrail carries cloud_watch_logs_group_arn; ec2 emits
# aws_vpc + security-group is_default — older caches must not load
CACHE_VERSION = 2


class AWSError(Exception):
    pass


class AWSClient:
    def __init__(self, region: str = "us-east-1", endpoint: str = "",
                 access_key: str = "", secret_key: str = "",
                 session_token: str = "", timeout: float = 30.0):
        self.region = region or "us-east-1"
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key or os.environ.get(
            "AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        self.session_token = session_token or os.environ.get(
            "AWS_SESSION_TOKEN", "")
        self.timeout = timeout
        if not self.access_key or not self.secret_key:
            raise AWSError(
                "AWS credentials not found (AWS_ACCESS_KEY_ID / "
                "AWS_SECRET_ACCESS_KEY)")

    def _service_url(self, service: str) -> str:
        if self.endpoint:
            return self.endpoint
        if service == "s3":
            return f"https://s3.{self.region}.amazonaws.com"
        return f"https://{service}.{self.region}.amazonaws.com"

    def request(self, service: str, method: str = "GET", path: str = "/",
                query: dict | None = None, body: bytes = b"",
                headers: dict | None = None) -> bytes:
        query = query or {}
        url = self._service_url(service)
        parsed = urllib.parse.urlparse(url)
        signed = sign(method, parsed.netloc, path, query,
                      headers or {}, body, service, self.region,
                      self.access_key, self.secret_key,
                      self.session_token)
        qs = urllib.parse.urlencode(sorted(query.items()))
        full = f"{url}{path}" + (f"?{qs}" if qs else "")
        # throttling / transient server errors retry with backoff the
        # way the reference's SDK does — an account walk hitting rate
        # limits must not cache partial state
        last: Exception | None = None
        for attempt in range(3):
            req = urllib.request.Request(full, data=body or None,
                                         method=method, headers=signed)
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                detail = e.read()[:200]
                last = AWSError(
                    f"{service} {path}: HTTP {e.code}: {detail!r}")
                last.__cause__ = e
                if e.code not in (429, 500, 502, 503) and \
                        b"Throttling" not in detail:
                    raise last
            except Exception as e:
                raise AWSError(
                    f"{service} request failed: {e}") from e
            if attempt < 2:
                time.sleep(0.2 * (attempt + 1))
        raise last


def _xml(data: bytes) -> ET.Element:
    root = ET.fromstring(data)
    # strip namespaces for painless findall
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def _txt(el, path, default=""):
    found = el.find(path)
    if found is None or not found.text:
        return default
    return found.text.strip() or default


# ---- service walkers → CloudResource state ---------------------------

def walk_s3(client: AWSClient) -> list[CloudResource]:
    out = []
    root = _xml(client.request("s3"))
    for b in root.findall(".//Bucket"):
        name = _txt(b, "Name")
        if not name:
            continue
        r = CloudResource("aws_s3_bucket", name)
        r.attrs["arn"] = Attr(f"arn:aws:s3:::{name}")
        for call, key in (
                ("versioning", "versioning"),
                ("logging", "logging"),
                ("encryption", "encryption"),
                ("publicAccessBlock", "public_access_block"),
                ("acl", "acl")):
            try:
                data = client.request("s3", path=f"/{name}",
                                      query={call: ""})
            except AWSError:
                continue
            doc = _xml(data)
            if call == "versioning":
                r.attrs["versioning_enabled"] = Attr(
                    _txt(doc, "Status") == "Enabled")
            elif call == "logging":
                r.attrs["logging_enabled"] = Attr(
                    doc.find(".//LoggingEnabled") is not None)
            elif call == "encryption":
                algo = _txt(doc, ".//SSEAlgorithm")
                r.attrs["encryption_enabled"] = Attr(bool(algo))
                r.attrs["sse_algorithm"] = Attr(algo)
            elif call == "publicAccessBlock":
                r.attrs["public_access_block"] = Attr({
                    "block_public_acls":
                        _txt(doc, ".//BlockPublicAcls") == "true",
                    "block_public_policy":
                        _txt(doc, ".//BlockPublicPolicy") == "true",
                    "ignore_public_acls":
                        _txt(doc, ".//IgnorePublicAcls") == "true",
                    "restrict_public_buckets":
                        _txt(doc, ".//RestrictPublicBuckets") == "true",
                })
            elif call == "acl":
                grants = []
                for g in doc.findall(".//Grant"):
                    uri = _txt(g, ".//URI")
                    perm = _txt(g, "Permission")
                    grants.append({"uri": uri, "permission": perm})
                public = any("AllUsers" in g["uri"] for g in grants)
                r.attrs["acl"] = Attr(
                    "public-read" if public else "private")
        out.append(r)
    return out


def walk_ec2(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "ec2", "DescribeSecurityGroups",
                            "2016-11-15"):
        out += _parse_sgs(doc)
    return out


def _parse_sgs(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//securityGroupInfo/item"):
        name = _txt(item, "groupName")
        r = CloudResource("aws_security_group", name)
        r.attrs["is_default"] = Attr(name == "default")
        r.attrs["description"] = Attr(_txt(item, "groupDescription"))
        ingress = []
        for perm in item.findall("ipPermissions/item"):
            for ip in perm.findall("ipRanges/item"):
                ingress.append({
                    "cidrs": [_txt(ip, "cidrIp")],
                    "description": _txt(ip, "description"),
                    "from_port": int(_txt(perm, "fromPort", "0") or 0),
                    "to_port": int(_txt(perm, "toPort", "0") or 0),
                })
        egress = []
        for perm in item.findall("ipPermissionsEgress/item"):
            for ip in perm.findall("ipRanges/item"):
                egress.append({
                    "cidrs": [_txt(ip, "cidrIp")],
                    "description": _txt(ip, "description"),
                })
        r.attrs["ingress"] = Attr(ingress)
        r.attrs["egress"] = Attr(egress)
        out.append(r)
    return out


def _query_api(client: AWSClient, service: str, action: str,
               version: str, extra: dict | None = None) -> ET.Element:
    """AWS query-protocol POST (ec2/rds/elbv2/iam style) → XML root."""
    fields = {"Action": action, "Version": version}
    fields.update(extra or {})
    body = urllib.parse.urlencode(fields).encode()
    return _xml(client.request(
        service, method="POST", body=body,
        headers={"content-type":
                 "application/x-www-form-urlencoded; charset=utf-8"}))


_MAX_PAGES = 100


def _paged_query(client: AWSClient, service: str, action: str,
                 version: str, extra: dict | None = None,
                 req_token: str = "NextToken",
                 resp_paths: tuple = (".//nextToken",)):
    """Yield every page of a query-protocol listing. Resources beyond
    the first page would otherwise be silently dropped — and then
    cached as complete account state for the TTL."""
    fields = dict(extra or {})
    for _ in range(_MAX_PAGES):
        doc = _query_api(client, service, action, version, fields)
        yield doc
        token = ""
        for p in resp_paths:
            token = _txt(doc, p)
            if token:
                break
        if not token:
            return
        fields[req_token] = token
    # a silent stop here would cache a truncated listing as complete
    logger.warning("aws %s %s: pagination stopped after %d pages; "
                   "listing may be incomplete", service, action,
                   _MAX_PAGES)


def walk_ec2_instances(client: AWSClient) -> list[CloudResource]:
    """DescribeInstances → aws_instance state (IMDSv2, root/EBS
    encryption feed the shared AVD-AWS checks)."""
    out = []
    for doc in _paged_query(client, "ec2", "DescribeInstances",
                            "2016-11-15"):
        out += _parse_instances(doc)
    return out


def _parse_instances(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//reservationSet/item/instancesSet/item"):
        iid = _txt(item, "instanceId")
        r = CloudResource("aws_instance", iid)
        mo = item.find("metadataOptions")
        if mo is not None:
            r.attrs["metadata_options"] = Attr({
                "http_tokens": _txt(mo, "httpTokens", "optional"),
                "http_endpoint": _txt(mo, "httpEndpoint", "enabled"),
            })
        out.append(r)
    return out


def walk_ebs(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "ec2", "DescribeVolumes",
                            "2016-11-15"):
        out += _parse_volumes(doc)
    return out


def _parse_volumes(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//volumeSet/item"):
        r = CloudResource("aws_ebs_volume", _txt(item, "volumeId"))
        r.attrs["encrypted"] = Attr(_txt(item, "encrypted") == "true")
        out.append(r)
    return out


def walk_rds(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "rds", "DescribeDBInstances",
                            "2014-10-31", req_token="Marker",
                            resp_paths=(".//Marker",)):
        out += _parse_dbs(doc)
    return out


def _parse_dbs(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//DBInstances/DBInstance"):
        name = _txt(item, "DBInstanceIdentifier")
        r = CloudResource("aws_db_instance", name)
        r.attrs["storage_encrypted"] = Attr(
            _txt(item, "StorageEncrypted") == "true")
        r.attrs["backup_retention_period"] = Attr(
            int(_txt(item, "BackupRetentionPeriod", "0") or 0))
        r.attrs["publicly_accessible"] = Attr(
            _txt(item, "PubliclyAccessible") == "true")
        if _txt(item, "ReadReplicaSourceDBInstanceIdentifier"):
            r.attrs["replicate_source_db"] = Attr(True)
        out.append(r)
    return out


def walk_cloudtrail(client: AWSClient) -> list[CloudResource]:
    """JSON API (x-amz-json-1.1): DescribeTrails."""
    raw = client.request(
        "cloudtrail", method="POST", body=b"{}",
        headers={"Content-Type": "application/x-amz-json-1.1",
                 "X-Amz-Target":
                     "com.amazonaws.cloudtrail.v20131101."
                     "CloudTrail_20131101.DescribeTrails"})
    out = []
    for t in json.loads(raw).get("trailList", []):
        r = CloudResource("aws_cloudtrail", t.get("Name", ""))
        r.attrs["is_multi_region_trail"] = Attr(
            bool(t.get("IsMultiRegionTrail")))
        r.attrs["enable_log_file_validation"] = Attr(
            bool(t.get("LogFileValidationEnabled")))
        if t.get("KmsKeyId"):
            r.attrs["kms_key_id"] = Attr(t["KmsKeyId"])
        r.attrs["cloud_watch_logs_group_arn"] = Attr(
            t.get("CloudWatchLogsLogGroupArn", ""))
        out.append(r)
    return out


def walk_efs(client: AWSClient) -> list[CloudResource]:
    """REST API: GET /2015-02-01/file-systems (Marker-paginated)."""
    out = []
    query = {}
    for _ in range(_MAX_PAGES):
        raw = client.request("elasticfilesystem",
                             path="/2015-02-01/file-systems",
                             query=query)
        body = json.loads(raw)
        for fs in body.get("FileSystems", []):
            r = CloudResource("aws_efs_file_system",
                              fs.get("FileSystemId", ""))
            r.attrs["encrypted"] = Attr(bool(fs.get("Encrypted")))
            out.append(r)
        marker = body.get("NextMarker")
        if not marker:
            break
        query = {"Marker": marker}
    else:
        logger.warning("aws efs: pagination stopped after %d pages; "
                       "listing may be incomplete", _MAX_PAGES)
    return out


def walk_elb(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "elasticloadbalancing",
                            "DescribeLoadBalancers", "2015-12-01",
                            req_token="Marker",
                            resp_paths=(".//NextMarker",)):
        out += _parse_lbs(client, doc)
    return out


def _parse_lbs(client: AWSClient, doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//LoadBalancers/member"):
        name = _txt(item, "LoadBalancerName")
        arn = _txt(item, "LoadBalancerArn")
        r = CloudResource("aws_lb", name)
        r.attrs["internal"] = Attr(
            _txt(item, "Scheme") == "internal")
        r.attrs["load_balancer_type"] = Attr(
            _txt(item, "Type", "application"))
        try:
            attrs = _query_api(
                client, "elasticloadbalancing",
                "DescribeLoadBalancerAttributes", "2015-12-01",
                {"LoadBalancerArn": arn})
            for a in attrs.findall(".//Attributes/member"):
                if _txt(a, "Key") == \
                        "routing.http.drop_invalid_header_fields.enabled":
                    r.attrs["drop_invalid_header_fields"] = Attr(
                        _txt(a, "Value") == "true")
        except AWSError:
            pass
        out.append(r)
    return out


def walk_iam(client: AWSClient) -> list[CloudResource]:
    """Customer-managed policies (wildcard check) + account password
    policy, root-account summary, and per-user credential hygiene
    (CIS 1.x controls)."""
    out = []
    for doc in _paged_query(client, "iam", "ListPolicies",
                            "2010-05-08", {"Scope": "Local"},
                            req_token="Marker",
                            resp_paths=(".//Marker",)):
        out += _parse_policies(client, doc)
    out += _walk_iam_password_policy(client)
    out += _walk_iam_root(client)
    out += _walk_iam_users(client)
    return out


def _walk_iam_password_policy(client: AWSClient) -> list[CloudResource]:
    r = CloudResource("aws_iam_password_policy", "account")
    try:
        doc = _query_api(client, "iam", "GetAccountPasswordPolicy",
                         "2010-05-08")
    except AWSError as e:
        if "NoSuchEntity" in str(e):
            # no policy set at all: every requirement check fires
            r.attrs["reuse_prevention"] = Attr(0)
            r.attrs["require_lowercase"] = Attr(False)
            r.attrs["require_numbers"] = Attr(False)
            r.attrs["require_symbols"] = Attr(False)
            r.attrs["require_uppercase"] = Attr(False)
            r.attrs["max_age_days"] = Attr(0)
            r.attrs["minimum_length"] = Attr(0)
            return [r]
        raise
    p = doc.find(".//PasswordPolicy")
    if p is None:
        return []
    r.attrs["reuse_prevention"] = Attr(
        int(_txt(p, "PasswordReusePrevention", "0") or 0))
    r.attrs["require_lowercase"] = Attr(
        _txt(p, "RequireLowercaseCharacters") == "true")
    r.attrs["require_numbers"] = Attr(
        _txt(p, "RequireNumbers") == "true")
    r.attrs["require_symbols"] = Attr(
        _txt(p, "RequireSymbols") == "true")
    r.attrs["require_uppercase"] = Attr(
        _txt(p, "RequireUppercaseCharacters") == "true")
    r.attrs["max_age_days"] = Attr(
        int(_txt(p, "MaxPasswordAge", "0") or 0))
    r.attrs["minimum_length"] = Attr(
        int(_txt(p, "MinimumPasswordLength", "0") or 0))
    return [r]


def _walk_iam_root(client: AWSClient) -> list[CloudResource]:
    try:
        doc = _query_api(client, "iam", "GetAccountSummary",
                         "2010-05-08")
    except AWSError:
        return []
    summary = {}
    for e in doc.findall(".//SummaryMap/entry"):
        summary[_txt(e, "key")] = int(_txt(e, "value", "0") or 0)
    r = CloudResource("aws_iam_root", "root")
    r.attrs["access_keys_present"] = Attr(
        summary.get("AccountAccessKeysPresent", 0) > 0)
    r.attrs["mfa_enabled"] = Attr(
        summary.get("AccountMFAEnabled", 0) > 0)
    return [r]


def _days_since(iso: str) -> int | None:
    import datetime as dt
    if not iso:
        return None
    try:
        then = dt.datetime.fromisoformat(iso.replace("Z", "+00:00"))
    except ValueError:
        return None
    now = dt.datetime.now(dt.timezone.utc)
    return max(0, int((now - then).total_seconds() // 86400))


def _walk_iam_users(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "iam", "ListUsers", "2010-05-08",
                            req_token="Marker",
                            resp_paths=(".//Marker",)):
        for u in doc.findall(".//Users/member"):
            name = _txt(u, "UserName")
            r = CloudResource("aws_iam_user", name)
            pw_used = _days_since(_txt(u, "PasswordLastUsed"))
            if pw_used is not None:
                r.attrs["password_last_used_days"] = Attr(pw_used)
            try:
                _query_api(client, "iam", "GetLoginProfile",
                           "2010-05-08", {"UserName": name})
                r.attrs["has_console_password"] = Attr(True)
            except AWSError as e:
                if "NoSuchEntity" in str(e):
                    r.attrs["has_console_password"] = Attr(False)
            try:
                mfa = _query_api(client, "iam", "ListMFADevices",
                                 "2010-05-08", {"UserName": name})
                r.attrs["mfa_active"] = Attr(
                    mfa.find(".//MFADevices/member") is not None)
            except AWSError:
                pass
            try:
                keys = _query_api(client, "iam", "ListAccessKeys",
                                  "2010-05-08", {"UserName": name})
                ages, unused = [], []
                for k in keys.findall(
                        ".//AccessKeyMetadata/member"):
                    if _txt(k, "Status") != "Active":
                        continue
                    age = _days_since(_txt(k, "CreateDate"))
                    if age is not None:
                        ages.append(age)
                    kid = _txt(k, "AccessKeyId")
                    try:
                        lu = _query_api(
                            client, "iam", "GetAccessKeyLastUsed",
                            "2010-05-08", {"AccessKeyId": kid})
                        d = _days_since(_txt(
                            lu, ".//AccessKeyLastUsed/LastUsedDate"))
                        unused.append(d if d is not None
                                      else (age or 0))
                    except AWSError:
                        pass
                r.attrs["access_key_ages_days"] = Attr(ages)
                r.attrs["key_unused_days"] = Attr(unused)
            except AWSError:
                pass
            try:
                att = _query_api(client, "iam",
                                 "ListAttachedUserPolicies",
                                 "2010-05-08", {"UserName": name})
                r.attrs["attached_policies"] = Attr([
                    _txt(m, "PolicyName") for m in att.findall(
                        ".//AttachedPolicies/member")])
            except AWSError:
                pass
            out.append(r)
    return out


def _parse_policies(client: AWSClient, doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//Policies/member"):
        arn = _txt(item, "Arn")
        name = _txt(item, "PolicyName")
        version = _txt(item, "DefaultVersionId", "v1")
        r = CloudResource("aws_iam_policy", name)
        try:
            vdoc = _query_api(client, "iam", "GetPolicyVersion",
                              "2010-05-08",
                              {"PolicyArn": arn, "VersionId": version})
            enc = _txt(vdoc, ".//Document")
            if enc:
                r.attrs["policy_document"] = Attr(
                    urllib.parse.unquote(enc))
        except AWSError:
            pass
        out.append(r)
    return out


def _json_api(client: AWSClient, service: str, target: str,
              payload: dict, version: str = "1.1") -> dict:
    """AWS JSON-protocol POST (cloudtrail/dynamodb/ecr/kms/ecs
    style)."""
    raw = client.request(
        service, method="POST",
        body=json.dumps(payload).encode(),
        headers={"Content-Type": f"application/x-amz-json-{version}",
                 "X-Amz-Target": target})
    return json.loads(raw or b"{}")


def walk_cloudfront(client: AWSClient) -> list[CloudResource]:
    """REST XML: ListDistributions + per-distribution config."""
    out = []
    marker = ""
    for _ in range(_MAX_PAGES):
        query = {"Marker": marker} if marker else {}
        doc = _xml(client.request("cloudfront",
                                  path="/2020-05-31/distribution",
                                  query=query))
        for item in doc.findall(".//DistributionSummary"):
            did = _txt(item, "Id")
            r = CloudResource("aws_cloudfront_distribution", did)
            r.attrs["minimum_protocol_version"] = Attr(_txt(
                item, ".//ViewerCertificate/MinimumProtocolVersion",
                "TLSv1"))
            policies = []
            for beh in ([item.find("DefaultCacheBehavior")]
                        + item.findall(".//CacheBehaviors/Items"
                                       "/CacheBehavior")):
                if beh is not None:
                    policies.append({"policy": _txt(
                        beh, "ViewerProtocolPolicy", "allow-all")})
            r.attrs["viewer_policies"] = Attr(policies)
            try:
                cfg = _xml(client.request(
                    "cloudfront",
                    path=f"/2020-05-31/distribution/{did}/config"))
                r.attrs["logging_enabled"] = Attr(
                    _txt(cfg, ".//Logging/Enabled") == "true")
            except AWSError:
                pass
            out.append(r)
        if _txt(doc, ".//IsTruncated") != "true":
            break
        marker = _txt(doc, ".//NextMarker")
        if not marker:
            break
    else:
        logger.warning("aws cloudfront: pagination stopped after %d "
                       "pages; listing may be incomplete", _MAX_PAGES)
    return out


def walk_dynamodb(client: AWSClient) -> list[CloudResource]:
    """JSON 1.0: ListTables → DescribeTable + ContinuousBackups."""
    out = []
    start = {}
    tgt = "DynamoDB_20120810"
    for _ in range(_MAX_PAGES):
        body = _json_api(client, "dynamodb", f"{tgt}.ListTables",
                         start, version="1.0")
        for name in body.get("TableNames", []):
            r = CloudResource("aws_dynamodb_table", name)
            try:
                t = _json_api(client, "dynamodb",
                              f"{tgt}.DescribeTable",
                              {"TableName": name}, version="1.0")
                sse = (t.get("Table") or {}).get("SSEDescription") or {}
                r.attrs["sse_kms_key"] = Attr(
                    sse.get("KMSMasterKeyArn", ""))
            except AWSError:
                pass
            try:
                b = _json_api(client, "dynamodb",
                              f"{tgt}.DescribeContinuousBackups",
                              {"TableName": name}, version="1.0")
                pitr = ((b.get("ContinuousBackupsDescription") or {})
                        .get("PointInTimeRecoveryDescription") or {})
                r.attrs["pitr_enabled"] = Attr(
                    pitr.get("PointInTimeRecoveryStatus") == "ENABLED")
            except AWSError:
                pass
            out.append(r)
        last = body.get("LastEvaluatedTableName")
        if not last:
            break
        start = {"ExclusiveStartTableName": last}
    else:
        logger.warning("aws dynamodb: pagination stopped after %d "
                       "pages; listing may be incomplete", _MAX_PAGES)
    return out


def walk_ecr(client: AWSClient) -> list[CloudResource]:
    out = []
    payload: dict = {}
    tgt = "AmazonEC2ContainerRegistry_V20150921.DescribeRepositories"
    for _ in range(_MAX_PAGES):
        body = _json_api(client, "ecr", tgt, payload)
        for repo in body.get("repositories", []):
            r = CloudResource("aws_ecr_repository",
                              repo.get("repositoryName", ""))
            scan = repo.get("imageScanningConfiguration") or {}
            r.attrs["scan_on_push"] = Attr(bool(scan.get("scanOnPush")))
            r.attrs["image_tag_mutability"] = Attr(
                repo.get("imageTagMutability", "MUTABLE"))
            out.append(r)
        token = body.get("nextToken")
        if not token:
            break
        payload = {"nextToken": token}
    else:
        logger.warning("aws ecr: pagination stopped after %d pages; "
                       "listing may be incomplete", _MAX_PAGES)
    return out


def walk_ecs(client: AWSClient) -> list[CloudResource]:
    out = []
    payload: dict = {}
    ns = "AmazonEC2ContainerServiceV20141113"
    for _ in range(_MAX_PAGES):
        body = _json_api(client, "ecs", f"{ns}.ListClusters", payload)
        arns = body.get("clusterArns", [])
        if arns:
            desc = _json_api(
                client, "ecs", f"{ns}.DescribeClusters",
                {"clusters": arns, "include": ["SETTINGS"]})
            for c in desc.get("clusters", []):
                r = CloudResource("aws_ecs_cluster",
                                  c.get("clusterName", ""))
                ci = next((s.get("value") for s in
                           c.get("settings", [])
                           if s.get("name") == "containerInsights"),
                          "disabled")
                r.attrs["container_insights"] = Attr(ci == "enabled")
                out.append(r)
        token = body.get("nextToken")
        if not token:
            break
        payload = {"nextToken": token}
    else:
        logger.warning("aws ecs: pagination stopped after %d pages; "
                       "listing may be incomplete", _MAX_PAGES)
    return out


def walk_eks(client: AWSClient) -> list[CloudResource]:
    """REST JSON: GET /clusters + GET /clusters/{name}."""
    out = []
    query: dict = {}
    for _ in range(_MAX_PAGES):
        body = json.loads(client.request("eks", path="/clusters",
                                         query=query))
        for name in body.get("clusters", []):
            r = CloudResource("aws_eks_cluster", name)
            try:
                c = json.loads(client.request(
                    "eks", path=f"/clusters/{name}")).get("cluster", {})
            except AWSError:
                out.append(r)
                continue
            types_on = [t for lg in (c.get("logging") or {})
                        .get("clusterLogging", [])
                        if lg.get("enabled")
                        for t in lg.get("types", [])]
            r.attrs["enabled_log_types"] = Attr(types_on)
            r.attrs["secrets_encrypted"] = Attr(
                bool(c.get("encryptionConfig")))
            vpc = c.get("resourcesVpcConfig") or {}
            r.attrs["endpoint_public_access"] = Attr(
                bool(vpc.get("endpointPublicAccess", True)))
            r.attrs["public_access_cidrs"] = Attr(
                vpc.get("publicAccessCidrs") or ["0.0.0.0/0"])
            out.append(r)
        token = body.get("nextToken")
        if not token:
            break
        query = {"nextToken": token}
    else:
        logger.warning("aws eks: pagination stopped after %d pages; "
                       "listing may be incomplete", _MAX_PAGES)
    return out


def walk_kms(client: AWSClient) -> list[CloudResource]:
    """JSON 1.1 TrentService: customer-managed keys + rotation."""
    out = []
    payload: dict = {}
    for _ in range(_MAX_PAGES):
        body = _json_api(client, "kms", "TrentService.ListKeys",
                         payload)
        for k in body.get("Keys", []):
            kid = k.get("KeyId", "")
            try:
                meta = _json_api(client, "kms",
                                 "TrentService.DescribeKey",
                                 {"KeyId": kid}).get("KeyMetadata", {})
            except AWSError:
                continue
            if meta.get("KeyManager") != "CUSTOMER":
                continue  # AWS-managed keys rotate automatically
            r = CloudResource("aws_kms_key", kid)
            r.attrs["key_usage"] = Attr(
                meta.get("KeyUsage", "ENCRYPT_DECRYPT"))
            try:
                rot = _json_api(client, "kms",
                                "TrentService.GetKeyRotationStatus",
                                {"KeyId": kid})
                r.attrs["enable_key_rotation"] = Attr(
                    bool(rot.get("KeyRotationEnabled")))
            except AWSError:
                pass
            out.append(r)
        if not body.get("Truncated"):
            break
        payload = {"Marker": body.get("NextMarker", "")}
    else:
        logger.warning("aws kms: pagination stopped after %d pages; "
                       "listing may be incomplete", _MAX_PAGES)
    return out


def walk_lambda(client: AWSClient) -> list[CloudResource]:
    out = []
    query: dict = {}
    for _ in range(_MAX_PAGES):
        body = json.loads(client.request(
            "lambda", path="/2015-03-31/functions/", query=query))
        for fn in body.get("Functions", []):
            r = CloudResource("aws_lambda_function",
                              fn.get("FunctionName", ""))
            r.attrs["tracing_mode"] = Attr(
                (fn.get("TracingConfig") or {})
                .get("Mode", "PassThrough"))
            out.append(r)
        marker = body.get("NextMarker")
        if not marker:
            break
        query = {"Marker": marker}
    else:
        logger.warning("aws lambda: pagination stopped after %d "
                       "pages; listing may be incomplete", _MAX_PAGES)
    return out


def walk_sns(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "sns", "ListTopics", "2010-03-31",
                            resp_paths=(".//NextToken",)):
        for t in doc.findall(".//Topics/member"):
            arn = _txt(t, "TopicArn")
            r = CloudResource("aws_sns_topic",
                              arn.rsplit(":", 1)[-1] or arn)
            try:
                attrs = _query_api(client, "sns", "GetTopicAttributes",
                                   "2010-03-31", {"TopicArn": arn})
                for e in attrs.findall(".//Attributes/entry"):
                    if _txt(e, "key") == "KmsMasterKeyId":
                        r.attrs["kms_master_key_id"] = Attr(
                            _txt(e, "value"))
            except AWSError:
                pass
            out.append(r)
    return out


def walk_sqs(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "sqs", "ListQueues", "2012-11-05",
                            resp_paths=(".//NextToken",)):
        for q in doc.findall(".//QueueUrl"):
            url = q.text or ""
            name = url.rstrip("/").rsplit("/", 1)[-1]
            r = CloudResource("aws_sqs_queue", name)
            try:
                attrs = _query_api(
                    client, "sqs", "GetQueueAttributes", "2012-11-05",
                    {"QueueUrl": url, "AttributeName.1": "All"})
                for e in attrs.findall(".//Attribute"):
                    k, v = _txt(e, "Name"), _txt(e, "Value")
                    if k == "KmsMasterKeyId":
                        r.attrs["kms_master_key_id"] = Attr(v)
                    elif k == "SqsManagedSseEnabled":
                        r.attrs["sqs_managed_sse_enabled"] = Attr(
                            v == "true")
            except AWSError:
                pass
            out.append(r)
    return out


def walk_elasticache(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "elasticache",
                            "DescribeReplicationGroups", "2015-02-02",
                            req_token="Marker",
                            resp_paths=(".//Marker",)):
        for g in doc.findall(".//ReplicationGroups/ReplicationGroup"):
            r = CloudResource("aws_elasticache_replication_group",
                              _txt(g, "ReplicationGroupId"))
            r.attrs["at_rest_encryption_enabled"] = Attr(
                _txt(g, "AtRestEncryptionEnabled") == "true")
            r.attrs["transit_encryption_enabled"] = Attr(
                _txt(g, "TransitEncryptionEnabled") == "true")
            out.append(r)
    return out


def walk_redshift(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "redshift", "DescribeClusters",
                            "2012-12-01", req_token="Marker",
                            resp_paths=(".//Marker",)):
        for c in doc.findall(".//Clusters/Cluster"):
            r = CloudResource("aws_redshift_cluster",
                              _txt(c, "ClusterIdentifier"))
            r.attrs["encrypted"] = Attr(_txt(c, "Encrypted") == "true")
            r.attrs["subnet_group"] = Attr(
                _txt(c, "ClusterSubnetGroupName"))
            out.append(r)
    return out


def walk_apigateway(client: AWSClient) -> list[CloudResource]:
    """REST JSON: GET /restapis + per-API stages."""
    out = []
    query: dict = {}
    for _ in range(_MAX_PAGES):
        body = json.loads(client.request("apigateway",
                                         path="/restapis",
                                         query=query))
        for api in body.get("item", []):
            api_id = api.get("id", "")
            try:
                stages = json.loads(client.request(
                    "apigateway",
                    path=f"/restapis/{api_id}/stages"))
            except AWSError:
                continue
            for st in stages.get("item", []):
                name = f"{api.get('name', api_id)}/" \
                       f"{st.get('stageName', '')}"
                r = CloudResource("aws_api_gateway_stage", name)
                r.attrs["access_log_arn"] = Attr(
                    (st.get("accessLogSettings") or {})
                    .get("destinationArn", ""))
                r.attrs["xray_tracing_enabled"] = Attr(
                    bool(st.get("tracingEnabled")))
                out.append(r)
        pos = body.get("position")
        if not pos:
            break
        query = {"position": pos}
    else:
        logger.warning("aws api-gateway: pagination stopped after %d "
                       "pages; listing may be incomplete", _MAX_PAGES)
    return out


def walk_vpcs(client: AWSClient) -> list[CloudResource]:
    """DescribeVpcs + DescribeFlowLogs → per-VPC flow-log state."""
    logged = set()
    for doc in _paged_query(client, "ec2", "DescribeFlowLogs",
                            "2016-11-15"):
        for fl in doc.findall(".//flowLogSet/item"):
            logged.add(_txt(fl, "resourceId"))
    out = []
    for doc in _paged_query(client, "ec2", "DescribeVpcs",
                            "2016-11-15"):
        for v in doc.findall(".//vpcSet/item"):
            vid = _txt(v, "vpcId")
            r = CloudResource("aws_vpc", vid)
            r.attrs["is_default"] = Attr(
                _txt(v, "isDefault") == "true")
            r.attrs["flow_logs_enabled"] = Attr(vid in logged)
            out.append(r)
    return out


def _walk_ec2_all(client: AWSClient) -> list[CloudResource]:
    """ec2 service = security groups + instances + VPC flow-log
    state."""
    return walk_ec2(client) + walk_ec2_instances(client) + \
        walk_vpcs(client)


def get_account_id(client: AWSClient) -> str:
    try:
        doc = _query_api(client, "sts", "GetCallerIdentity",
                         "2011-06-15")
        return _txt(doc, ".//Account", "unknown")
    except AWSError:
        return "unknown"


WALKERS = {"s3": walk_s3, "ec2": _walk_ec2_all, "ebs": walk_ebs,
           "rds": walk_rds, "cloudtrail": walk_cloudtrail,
           "efs": walk_efs, "elb": walk_elb, "iam": walk_iam,
           "cloudfront": walk_cloudfront, "dynamodb": walk_dynamodb,
           "ecr": walk_ecr, "ecs": walk_ecs, "eks": walk_eks,
           "kms": walk_kms, "lambda": walk_lambda, "sns": walk_sns,
           "sqs": walk_sqs, "elasticache": walk_elasticache,
           "redshift": walk_redshift, "api-gateway": walk_apigateway}



# ---- account-state cache (pkg/cloud/aws/cache) ------------------------

def cache_path(cache_dir: str, provider: str, account: str,
               region: str) -> str:
    return os.path.join(cache_dir, "cloud", provider, account, region,
                        "data.json")


def save_state(path: str, resources: list[CloudResource]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {"schema_version": CACHE_VERSION, "updated": time.time(),
           "resources": [{
               "kind": r.kind, "name": r.name,
               "attrs": {k: a.value for k, a in r.attrs.items()},
           } for r in resources]}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)


def load_state(path: str, max_age_s: float) -> list[CloudResource] | None:
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if doc.get("schema_version") != CACHE_VERSION:
        return None
    if max_age_s > 0 and time.time() - doc.get("updated", 0) > max_age_s:
        return None
    out = []
    for rj in doc.get("resources", []):
        r = CloudResource(rj.get("kind", ""), rj.get("name", ""))
        for k, v in (rj.get("attrs") or {}).items():
            r.attrs[k] = Attr(v)
        out.append(r)
    return out


# ---- scan entry -------------------------------------------------------

def scan_account(services: list[str], region: str = "us-east-1",
                 endpoint: str = "", cache_dir: str = "",
                 account: str = "", update_cache: bool = False,
                 max_cache_age_s: float = 24 * 3600,
                 ) -> tuple[list[T.Result], str]:
    """→ (results grouped per service, account_id)."""
    services = services or list(SUPPORTED_SERVICES)
    for s in services:
        if s not in WALKERS:
            raise AWSError(
                f"unsupported service {s!r} "
                f"(supported: {', '.join(SUPPORTED_SERVICES)})")
    client = AWSClient(region=region, endpoint=endpoint)
    if not account:
        account = get_account_id(client)
    cpath = cache_path(cache_dir or ".", "aws", account, region)
    resources = None
    if not update_cache:
        resources = load_state(cpath, max_cache_age_s)
    if resources is None:
        resources = []
        failed = False
        for s in services:
            try:
                resources.extend(WALKERS[s](client))
            except AWSError as e:
                failed = True
                logger.warning("aws %s walk failed: %s", s, e)
        # caching a partial walk would silently report no findings for the
        # failed service until the TTL expires — only cache complete state
        if not failed:
            save_state(cpath, resources)

    results: list[T.Result] = []
    by_service: dict[str, list] = {}
    for check in AWS_CHECKS:
        for item in check.fn(resources):
            msg, _rng = item
            m = build_misconf(check, "cloud", msg, (0, 0), [])
            by_service.setdefault(check.service, []).append(m)
    for svc in sorted(by_service):
        results.append(T.Result(
            target=f"arn:aws:{svc}:{region}:{account}",
            clazz=T.ResultClass.CONFIG, type="cloud",
            misconf_summary=T.MisconfSummary(
                failures=len(by_service[svc])),
            misconfigurations=sorted(by_service[svc],
                                     key=lambda m: (m.id, m.message)),
        ))
    return results, account
