"""AWS account scanning (reference pkg/cloud/aws).

Walks live AWS APIs (sigv4-signed; endpoint overridable for
LocalStack-style emulators), adapts the responses into the shared
cloud-state model, caches the adapted state per account/region
(pkg/cloud/aws/cache/cache.go), and evaluates the AVD-AWS check set —
the same checks the terraform/cloudformation scanners use, which is
exactly how the reference reuses its iac rules over live accounts
(pkg/cloud/aws/scanner/scanner.go:29).

Services covered: s3, ec2 (security groups + instances), ebs, rds,
cloudtrail, efs, elb (v2), iam (customer-managed policies), and sts
(account discovery).
"""

from __future__ import annotations

import json
import os
import time
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from .. import types as T
from ..iac.cloud import Attr, AWS_CHECKS, CloudResource
from ..iac.core import build_misconf
from ..log import logger
from .sigv4 import sign

SUPPORTED_SERVICES = ["s3", "ec2", "ebs", "rds", "cloudtrail",
                      "efs", "elb", "iam"]
CACHE_VERSION = 1


class AWSError(Exception):
    pass


class AWSClient:
    def __init__(self, region: str = "us-east-1", endpoint: str = "",
                 access_key: str = "", secret_key: str = "",
                 session_token: str = "", timeout: float = 30.0):
        self.region = region or "us-east-1"
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key or os.environ.get(
            "AWS_ACCESS_KEY_ID", "")
        self.secret_key = secret_key or os.environ.get(
            "AWS_SECRET_ACCESS_KEY", "")
        self.session_token = session_token or os.environ.get(
            "AWS_SESSION_TOKEN", "")
        self.timeout = timeout
        if not self.access_key or not self.secret_key:
            raise AWSError(
                "AWS credentials not found (AWS_ACCESS_KEY_ID / "
                "AWS_SECRET_ACCESS_KEY)")

    def _service_url(self, service: str) -> str:
        if self.endpoint:
            return self.endpoint
        if service == "s3":
            return f"https://s3.{self.region}.amazonaws.com"
        return f"https://{service}.{self.region}.amazonaws.com"

    def request(self, service: str, method: str = "GET", path: str = "/",
                query: dict | None = None, body: bytes = b"",
                headers: dict | None = None) -> bytes:
        query = query or {}
        url = self._service_url(service)
        parsed = urllib.parse.urlparse(url)
        signed = sign(method, parsed.netloc, path, query,
                      headers or {}, body, service, self.region,
                      self.access_key, self.secret_key,
                      self.session_token)
        qs = urllib.parse.urlencode(sorted(query.items()))
        full = f"{url}{path}" + (f"?{qs}" if qs else "")
        req = urllib.request.Request(full, data=body or None,
                                     method=method, headers=signed)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            raise AWSError(
                f"{service} {path}: HTTP {e.code}: "
                f"{e.read()[:200]!r}") from e
        except Exception as e:
            raise AWSError(f"{service} request failed: {e}") from e


def _xml(data: bytes) -> ET.Element:
    root = ET.fromstring(data)
    # strip namespaces for painless findall
    for el in root.iter():
        if "}" in el.tag:
            el.tag = el.tag.split("}", 1)[1]
    return root


def _txt(el, path, default=""):
    found = el.find(path)
    return found.text if found is not None and found.text else default


# ---- service walkers → CloudResource state ---------------------------

def walk_s3(client: AWSClient) -> list[CloudResource]:
    out = []
    root = _xml(client.request("s3"))
    for b in root.findall(".//Bucket"):
        name = _txt(b, "Name")
        if not name:
            continue
        r = CloudResource("aws_s3_bucket", name)
        r.attrs["arn"] = Attr(f"arn:aws:s3:::{name}")
        for call, key in (
                ("versioning", "versioning"),
                ("logging", "logging"),
                ("encryption", "encryption"),
                ("publicAccessBlock", "public_access_block"),
                ("acl", "acl")):
            try:
                data = client.request("s3", path=f"/{name}",
                                      query={call: ""})
            except AWSError:
                continue
            doc = _xml(data)
            if call == "versioning":
                r.attrs["versioning_enabled"] = Attr(
                    _txt(doc, "Status") == "Enabled")
            elif call == "logging":
                r.attrs["logging_enabled"] = Attr(
                    doc.find(".//LoggingEnabled") is not None)
            elif call == "encryption":
                algo = _txt(doc, ".//SSEAlgorithm")
                r.attrs["encryption_enabled"] = Attr(bool(algo))
                r.attrs["sse_algorithm"] = Attr(algo)
            elif call == "publicAccessBlock":
                r.attrs["public_access_block"] = Attr({
                    "block_public_acls":
                        _txt(doc, ".//BlockPublicAcls") == "true",
                    "block_public_policy":
                        _txt(doc, ".//BlockPublicPolicy") == "true",
                    "ignore_public_acls":
                        _txt(doc, ".//IgnorePublicAcls") == "true",
                    "restrict_public_buckets":
                        _txt(doc, ".//RestrictPublicBuckets") == "true",
                })
            elif call == "acl":
                grants = []
                for g in doc.findall(".//Grant"):
                    uri = _txt(g, ".//URI")
                    perm = _txt(g, "Permission")
                    grants.append({"uri": uri, "permission": perm})
                public = any("AllUsers" in g["uri"] for g in grants)
                r.attrs["acl"] = Attr(
                    "public-read" if public else "private")
        out.append(r)
    return out


def walk_ec2(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "ec2", "DescribeSecurityGroups",
                            "2016-11-15"):
        out += _parse_sgs(doc)
    return out


def _parse_sgs(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//securityGroupInfo/item"):
        name = _txt(item, "groupName")
        r = CloudResource("aws_security_group", name)
        r.attrs["description"] = Attr(_txt(item, "groupDescription"))
        ingress = []
        for perm in item.findall("ipPermissions/item"):
            for ip in perm.findall("ipRanges/item"):
                ingress.append({
                    "cidrs": [_txt(ip, "cidrIp")],
                    "description": _txt(ip, "description"),
                    "from_port": int(_txt(perm, "fromPort", "0") or 0),
                    "to_port": int(_txt(perm, "toPort", "0") or 0),
                })
        egress = []
        for perm in item.findall("ipPermissionsEgress/item"):
            for ip in perm.findall("ipRanges/item"):
                egress.append({
                    "cidrs": [_txt(ip, "cidrIp")],
                    "description": _txt(ip, "description"),
                })
        r.attrs["ingress"] = Attr(ingress)
        r.attrs["egress"] = Attr(egress)
        out.append(r)
    return out


def _query_api(client: AWSClient, service: str, action: str,
               version: str, extra: dict | None = None) -> ET.Element:
    """AWS query-protocol POST (ec2/rds/elbv2/iam style) → XML root."""
    fields = {"Action": action, "Version": version}
    fields.update(extra or {})
    body = urllib.parse.urlencode(fields).encode()
    return _xml(client.request(
        service, method="POST", body=body,
        headers={"content-type":
                 "application/x-www-form-urlencoded; charset=utf-8"}))


_MAX_PAGES = 100


def _paged_query(client: AWSClient, service: str, action: str,
                 version: str, extra: dict | None = None,
                 req_token: str = "NextToken",
                 resp_paths: tuple = (".//nextToken",)):
    """Yield every page of a query-protocol listing. Resources beyond
    the first page would otherwise be silently dropped — and then
    cached as complete account state for the TTL."""
    fields = dict(extra or {})
    for _ in range(_MAX_PAGES):
        doc = _query_api(client, service, action, version, fields)
        yield doc
        token = ""
        for p in resp_paths:
            token = _txt(doc, p)
            if token:
                break
        if not token:
            return
        fields[req_token] = token
    # a silent stop here would cache a truncated listing as complete
    logger.warning("aws %s %s: pagination stopped after %d pages; "
                   "listing may be incomplete", service, action,
                   _MAX_PAGES)


def walk_ec2_instances(client: AWSClient) -> list[CloudResource]:
    """DescribeInstances → aws_instance state (IMDSv2, root/EBS
    encryption feed the shared AVD-AWS checks)."""
    out = []
    for doc in _paged_query(client, "ec2", "DescribeInstances",
                            "2016-11-15"):
        out += _parse_instances(doc)
    return out


def _parse_instances(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//reservationSet/item/instancesSet/item"):
        iid = _txt(item, "instanceId")
        r = CloudResource("aws_instance", iid)
        mo = item.find("metadataOptions")
        if mo is not None:
            r.attrs["metadata_options"] = Attr({
                "http_tokens": _txt(mo, "httpTokens", "optional"),
                "http_endpoint": _txt(mo, "httpEndpoint", "enabled"),
            })
        out.append(r)
    return out


def walk_ebs(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "ec2", "DescribeVolumes",
                            "2016-11-15"):
        out += _parse_volumes(doc)
    return out


def _parse_volumes(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//volumeSet/item"):
        r = CloudResource("aws_ebs_volume", _txt(item, "volumeId"))
        r.attrs["encrypted"] = Attr(_txt(item, "encrypted") == "true")
        out.append(r)
    return out


def walk_rds(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "rds", "DescribeDBInstances",
                            "2014-10-31", req_token="Marker",
                            resp_paths=(".//Marker",)):
        out += _parse_dbs(doc)
    return out


def _parse_dbs(doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//DBInstances/DBInstance"):
        name = _txt(item, "DBInstanceIdentifier")
        r = CloudResource("aws_db_instance", name)
        r.attrs["storage_encrypted"] = Attr(
            _txt(item, "StorageEncrypted") == "true")
        r.attrs["backup_retention_period"] = Attr(
            int(_txt(item, "BackupRetentionPeriod", "0") or 0))
        r.attrs["publicly_accessible"] = Attr(
            _txt(item, "PubliclyAccessible") == "true")
        if _txt(item, "ReadReplicaSourceDBInstanceIdentifier"):
            r.attrs["replicate_source_db"] = Attr(True)
        out.append(r)
    return out


def walk_cloudtrail(client: AWSClient) -> list[CloudResource]:
    """JSON API (x-amz-json-1.1): DescribeTrails."""
    raw = client.request(
        "cloudtrail", method="POST", body=b"{}",
        headers={"Content-Type": "application/x-amz-json-1.1",
                 "X-Amz-Target":
                     "com.amazonaws.cloudtrail.v20131101."
                     "CloudTrail_20131101.DescribeTrails"})
    out = []
    for t in json.loads(raw).get("trailList", []):
        r = CloudResource("aws_cloudtrail", t.get("Name", ""))
        r.attrs["is_multi_region_trail"] = Attr(
            bool(t.get("IsMultiRegionTrail")))
        r.attrs["enable_log_file_validation"] = Attr(
            bool(t.get("LogFileValidationEnabled")))
        if t.get("KmsKeyId"):
            r.attrs["kms_key_id"] = Attr(t["KmsKeyId"])
        out.append(r)
    return out


def walk_efs(client: AWSClient) -> list[CloudResource]:
    """REST API: GET /2015-02-01/file-systems (Marker-paginated)."""
    out = []
    query = {}
    for _ in range(_MAX_PAGES):
        raw = client.request("elasticfilesystem",
                             path="/2015-02-01/file-systems",
                             query=query)
        body = json.loads(raw)
        for fs in body.get("FileSystems", []):
            r = CloudResource("aws_efs_file_system",
                              fs.get("FileSystemId", ""))
            r.attrs["encrypted"] = Attr(bool(fs.get("Encrypted")))
            out.append(r)
        marker = body.get("NextMarker")
        if not marker:
            break
        query = {"Marker": marker}
    else:
        logger.warning("aws efs: pagination stopped after %d pages; "
                       "listing may be incomplete", _MAX_PAGES)
    return out


def walk_elb(client: AWSClient) -> list[CloudResource]:
    out = []
    for doc in _paged_query(client, "elasticloadbalancing",
                            "DescribeLoadBalancers", "2015-12-01",
                            req_token="Marker",
                            resp_paths=(".//NextMarker",)):
        out += _parse_lbs(client, doc)
    return out


def _parse_lbs(client: AWSClient, doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//LoadBalancers/member"):
        name = _txt(item, "LoadBalancerName")
        arn = _txt(item, "LoadBalancerArn")
        r = CloudResource("aws_lb", name)
        r.attrs["internal"] = Attr(
            _txt(item, "Scheme") == "internal")
        r.attrs["load_balancer_type"] = Attr(
            _txt(item, "Type", "application"))
        try:
            attrs = _query_api(
                client, "elasticloadbalancing",
                "DescribeLoadBalancerAttributes", "2015-12-01",
                {"LoadBalancerArn": arn})
            for a in attrs.findall(".//Attributes/member"):
                if _txt(a, "Key") == \
                        "routing.http.drop_invalid_header_fields.enabled":
                    r.attrs["drop_invalid_header_fields"] = Attr(
                        _txt(a, "Value") == "true")
        except AWSError:
            pass
        out.append(r)
    return out


def walk_iam(client: AWSClient) -> list[CloudResource]:
    """Customer-managed policies: ListPolicies(Scope=Local) +
    GetPolicyVersion → policy documents for the wildcard check."""
    out = []
    for doc in _paged_query(client, "iam", "ListPolicies",
                            "2010-05-08", {"Scope": "Local"},
                            req_token="Marker",
                            resp_paths=(".//Marker",)):
        out += _parse_policies(client, doc)
    return out


def _parse_policies(client: AWSClient, doc) -> list[CloudResource]:
    out = []
    for item in doc.findall(".//Policies/member"):
        arn = _txt(item, "Arn")
        name = _txt(item, "PolicyName")
        version = _txt(item, "DefaultVersionId", "v1")
        r = CloudResource("aws_iam_policy", name)
        try:
            vdoc = _query_api(client, "iam", "GetPolicyVersion",
                              "2010-05-08",
                              {"PolicyArn": arn, "VersionId": version})
            enc = _txt(vdoc, ".//Document")
            if enc:
                r.attrs["policy_document"] = Attr(
                    urllib.parse.unquote(enc))
        except AWSError:
            pass
        out.append(r)
    return out


def _walk_ec2_all(client: AWSClient) -> list[CloudResource]:
    """ec2 service = security groups + instances."""
    return walk_ec2(client) + walk_ec2_instances(client)


def get_account_id(client: AWSClient) -> str:
    try:
        doc = _query_api(client, "sts", "GetCallerIdentity",
                         "2011-06-15")
        return _txt(doc, ".//Account", "unknown")
    except AWSError:
        return "unknown"


WALKERS = {"s3": walk_s3, "ec2": _walk_ec2_all, "ebs": walk_ebs,
           "rds": walk_rds, "cloudtrail": walk_cloudtrail,
           "efs": walk_efs, "elb": walk_elb, "iam": walk_iam}



# ---- account-state cache (pkg/cloud/aws/cache) ------------------------

def cache_path(cache_dir: str, provider: str, account: str,
               region: str) -> str:
    return os.path.join(cache_dir, "cloud", provider, account, region,
                        "data.json")


def save_state(path: str, resources: list[CloudResource]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {"schema_version": CACHE_VERSION, "updated": time.time(),
           "resources": [{
               "kind": r.kind, "name": r.name,
               "attrs": {k: a.value for k, a in r.attrs.items()},
           } for r in resources]}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f)


def load_state(path: str, max_age_s: float) -> list[CloudResource] | None:
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if doc.get("schema_version") != CACHE_VERSION:
        return None
    if max_age_s > 0 and time.time() - doc.get("updated", 0) > max_age_s:
        return None
    out = []
    for rj in doc.get("resources", []):
        r = CloudResource(rj.get("kind", ""), rj.get("name", ""))
        for k, v in (rj.get("attrs") or {}).items():
            r.attrs[k] = Attr(v)
        out.append(r)
    return out


# ---- scan entry -------------------------------------------------------

def scan_account(services: list[str], region: str = "us-east-1",
                 endpoint: str = "", cache_dir: str = "",
                 account: str = "", update_cache: bool = False,
                 max_cache_age_s: float = 24 * 3600,
                 ) -> tuple[list[T.Result], str]:
    """→ (results grouped per service, account_id)."""
    services = services or list(SUPPORTED_SERVICES)
    for s in services:
        if s not in WALKERS:
            raise AWSError(
                f"unsupported service {s!r} "
                f"(supported: {', '.join(SUPPORTED_SERVICES)})")
    client = AWSClient(region=region, endpoint=endpoint)
    if not account:
        account = get_account_id(client)
    cpath = cache_path(cache_dir or ".", "aws", account, region)
    resources = None
    if not update_cache:
        resources = load_state(cpath, max_cache_age_s)
    if resources is None:
        resources = []
        failed = False
        for s in services:
            try:
                resources.extend(WALKERS[s](client))
            except AWSError as e:
                failed = True
                logger.warning("aws %s walk failed: %s", s, e)
        # caching a partial walk would silently report no findings for the
        # failed service until the TTL expires — only cache complete state
        if not failed:
            save_state(cpath, resources)

    results: list[T.Result] = []
    by_service: dict[str, list] = {}
    for check in AWS_CHECKS:
        for item in check.fn(resources):
            msg, _rng = item
            m = build_misconf(check, "cloud", msg, (0, 0), [])
            by_service.setdefault(check.service, []).append(m)
    for svc in sorted(by_service):
        results.append(T.Result(
            target=f"arn:aws:{svc}:{region}:{account}",
            clazz=T.ResultClass.CONFIG, type="cloud",
            misconf_summary=T.MisconfSummary(
                failures=len(by_service[svc])),
            misconfigurations=sorted(by_service[svc],
                                     key=lambda m: (m.id, m.message)),
        ))
    return results, account
