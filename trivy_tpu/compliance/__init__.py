"""Compliance reports (reference pkg/compliance).

The reference embeds compliance specs (docker-cis-1.6.0, k8s-cis,
k8s-nsa, k8s-pss-*, aws-cis — pkg/compliance/spec/compliance.go) that
map framework controls to individual check IDs, filters scan results
down to the checks a spec references, and renders either a summary
table (per-control pass/fail counts) or a full per-control report
(pkg/compliance/report).  Same model here: specs are data, controls
match results by check ID (AVD ID or scanner-local ID), and the report
builder consumes the standard types.Report."""

from .report import (ComplianceReport, build_compliance_report,  # noqa: F401
                     write_compliance)
from .spec import SPECS, Control, Spec, get_spec  # noqa: F401
