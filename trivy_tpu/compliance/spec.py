"""Built-in compliance specs.

Control numbering follows the public CIS/NSA framework documents the
reference's embedded specs encode (trivy-checks specs/ compliance
bundle); each control lists the check IDs our scanners emit
(AVD-KSV-*/AVD-DS-*/AVD-AWS-*), so coverage maps 1:1 onto the misconf
engine.  Controls whose framework requirement has no automated check
carry default_status MANUAL, the way the reference surfaces them."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Control:
    id: str
    name: str
    description: str = ""
    severity: str = "MEDIUM"
    checks: list = field(default_factory=list)   # check IDs
    default_status: str = ""                     # "" | MANUAL | FAIL


@dataclass
class Spec:
    id: str
    title: str
    description: str
    version: str
    related_resources: list = field(default_factory=list)
    controls: list = field(default_factory=list)


_K8S_CIS = Spec(
    id="k8s-cis", title="CIS Kubernetes Benchmarks",
    description="CIS Kubernetes Benchmarks",
    version="1.23",
    related_resources=["https://www.cisecurity.org/benchmark/kubernetes"],
    controls=[
        Control("5.1.1", "Ensure that the cluster-admin role is only "
                "used where required", severity="HIGH",
                default_status="MANUAL"),
        Control("5.2.1", "Minimize the admission of privileged "
                "containers",
                "Do not generally permit containers to be run with "
                "the securityContext.privileged flag set to true.",
                "HIGH", ["AVD-KSV-0017"]),
        Control("5.2.2", "Minimize the admission of containers wishing "
                "to share the host process ID namespace",
                "Do not generally permit containers to be run with the "
                "hostPID flag set to true.",
                "HIGH", ["AVD-KSV-0010"]),
        Control("5.2.3", "Minimize the admission of containers wishing "
                "to share the host IPC namespace",
                "Do not generally permit containers to be run with the "
                "hostIPC flag set to true.",
                "HIGH", ["AVD-KSV-0008"]),
        Control("5.2.4", "Minimize the admission of containers wishing "
                "to share the host network namespace",
                "Do not generally permit containers to be run with the "
                "hostNetwork flag set to true.",
                "HIGH", ["AVD-KSV-0009"]),
        Control("5.2.5", "Minimize the admission of containers with "
                "allowPrivilegeEscalation",
                "Do not generally permit containers to be run with the "
                "allowPrivilegeEscalation flag set to true.",
                "HIGH", ["AVD-KSV-0001"]),
        Control("5.2.6", "Minimize the admission of root containers",
                "Do not generally permit containers to be run as the "
                "root user.",
                "MEDIUM", ["AVD-KSV-0012"]),
        Control("5.2.7", "Minimize the admission of containers with "
                "added capabilities",
                "Do not generally permit containers with capabilities "
                "assigned beyond the default set.",
                "LOW", ["AVD-KSV-0022"]),
        Control("5.2.8", "Minimize the admission of containers with "
                "capabilities assigned",
                "Do not generally permit containers with capabilities.",
                "LOW", ["AVD-KSV-0003"]),
        Control("5.7.3", "Apply Security Context to Your Pods and "
                "Containers",
                "Apply Security Context to Your Pods and Containers.",
                "HIGH", ["AVD-KSV-0021", "AVD-KSV-0020",
                         "AVD-KSV-0030", "AVD-KSV-0104",
                         "AVD-KSV-0014"]),
    ])

_K8S_NSA = Spec(
    id="k8s-nsa", title="National Security Agency - Kubernetes "
    "Hardening Guidance v1.0",
    description="National Security Agency - Kubernetes Hardening "
    "Guidance",
    version="1.0",
    related_resources=[
        "https://www.nsa.gov/Press-Room/News-Highlights/Article/"
        "Article/2716980/nsa-cisa-release-kubernetes-hardening-"
        "guidance/"],
    controls=[
        Control("1.0", "Non-root containers",
                "Check that container is not running as root",
                "MEDIUM", ["AVD-KSV-0012"]),
        Control("1.1", "Immutable container file systems",
                "Check that container root file system is immutable",
                "LOW", ["AVD-KSV-0014"]),
        Control("1.2", "Preventing privileged containers",
                "Controls whether Pods can run privileged containers",
                "HIGH", ["AVD-KSV-0017"]),
        Control("1.3", "Share containers process namespaces",
                "Controls whether containers can share process "
                "namespaces",
                "HIGH", ["AVD-KSV-0010"]),
        Control("1.4", "Share host process namespaces",
                "Controls whether share host process namespaces",
                "HIGH", ["AVD-KSV-0008"]),
        Control("1.5", "Use the host network",
                "Controls whether containers can use the host network",
                "HIGH", ["AVD-KSV-0009"]),
        Control("1.6", "Run with root privileges or with root group "
                "membership",
                "Controls whether container applications can run with "
                "root privileges or with root group membership",
                "LOW", ["AVD-KSV-0029"]),
        Control("1.7", "Restricts escalation to root privileges",
                "Control check restrictions escalation to root "
                "privileges",
                "MEDIUM", ["AVD-KSV-0001"]),
        Control("1.8", "Sets the SELinux context of the container",
                "Control checks if pod sets the SELinux context of "
                "the container",
                "MEDIUM", ["AVD-KSV-0025"]),
        Control("1.9", "Restrict a container's access to resources "
                "with AppArmor",
                "Control checks the restriction of containers access "
                "to resources with AppArmor",
                "MEDIUM", ["AVD-KSV-0002"]),
        Control("1.10", "Sets the seccomp profile used to sandbox "
                "containers",
                "Control checks the sets the seccomp profile used to "
                "sandbox containers",
                "LOW", ["AVD-KSV-0030"]),
        Control("1.11", "Protecting Pod service account tokens",
                "Control check whether disable secret token been "
                "mount, automountServiceAccountToken: false",
                "MEDIUM", ["AVD-KSV-0036"]),
    ])

_K8S_PSS_BASELINE = Spec(
    id="k8s-pss-baseline", title="Kubernetes Pod Security Standards - "
    "Baseline",
    description="Kubernetes Pod Security Standards - Baseline",
    version="0.1",
    related_resources=[
        "https://kubernetes.io/docs/concepts/security/"
        "pod-security-standards/#baseline"],
    controls=[
        Control("1", "HostProcess",
                "Windows pods offer the ability to run HostProcess "
                "containers which enables privileged access to the "
                "Windows node.",
                "HIGH", ["AVD-KSV-0103"]),
        Control("2", "Host Namespaces",
                "Sharing the host namespaces must be disallowed.",
                "HIGH", ["AVD-KSV-0008", "AVD-KSV-0009",
                         "AVD-KSV-0010"]),
        Control("3", "Privileged Containers",
                "Privileged Pods disable most security mechanisms and "
                "must be disallowed.",
                "HIGH", ["AVD-KSV-0017"]),
        Control("4", "Capabilities",
                "Adding additional capabilities beyond the default set "
                "must be disallowed.",
                "MEDIUM", ["AVD-KSV-0022"]),
        Control("5", "HostPath Volumes",
                "HostPath volumes must be forbidden.",
                "MEDIUM", ["AVD-KSV-0023"]),
        Control("7", "SELinux",
                "Setting the SELinux type is restricted, and setting a "
                "custom SELinux user or role option is forbidden.",
                "MEDIUM", ["AVD-KSV-0025"]),
        Control("10", "Seccomp",
                "Seccomp profile must not be explicitly set to "
                "Unconfined.",
                "MEDIUM", ["AVD-KSV-0104"]),
    ])

_K8S_PSS_RESTRICTED = Spec(
    id="k8s-pss-restricted", title="Kubernetes Pod Security Standards "
    "- Restricted",
    description="Kubernetes Pod Security Standards - Restricted",
    version="0.1",
    related_resources=[
        "https://kubernetes.io/docs/concepts/security/"
        "pod-security-standards/#restricted"],
    controls=list(_K8S_PSS_BASELINE.controls) + [
        Control("11", "Volume Types",
                "The restricted policy only permits specific volume "
                "types.",
                "LOW", ["AVD-KSV-0028"]),
        Control("12", "Privilege Escalation",
                "Privilege escalation (such as via set-user-ID or "
                "set-group-ID file mode) should not be allowed.",
                "MEDIUM", ["AVD-KSV-0001"]),
        Control("13", "Running as Non-root",
                "Containers must be required to run as non-root users.",
                "MEDIUM", ["AVD-KSV-0012"]),
        Control("14", "Seccomp v2",
                "Seccomp profile must be explicitly set to one of the "
                "allowed values.",
                "LOW", ["AVD-KSV-0030"]),
        Control("15", "Capabilities v2",
                "Containers must drop ALL capabilities, and are only "
                "permitted to add back the NET_BIND_SERVICE "
                "capability.",
                "LOW", ["AVD-KSV-0003"]),
    ])

_DOCKER_CIS = Spec(
    id="docker-cis-1.6.0", title="CIS Docker Community Edition "
    "Benchmark v1.6.0",
    description="CIS Docker Community Edition Benchmark",
    version="1.6.0",
    related_resources=["https://www.cisecurity.org/benchmark/docker"],
    controls=[
        Control("4.1", "Ensure that a user for the container has been "
                "created",
                "Create a non-root user for the container in the "
                "Dockerfile for the container image.",
                "HIGH", ["AVD-DS-0002"]),
        Control("4.2", "Ensure that containers use only trusted base "
                "images", severity="MEDIUM", default_status="MANUAL"),
        Control("4.3", "Ensure that unnecessary packages are not "
                "installed in the container",
                severity="MEDIUM", default_status="MANUAL"),
        Control("4.4", "Ensure images are scanned and rebuilt to "
                "include security patches",
                "Images should be scanned frequently for any "
                "vulnerabilities.",
                "CRITICAL", ["VULN-CRITICAL"]),
        Control("4.6", "Ensure that HEALTHCHECK instructions have been "
                "added to container images",
                "Add the HEALTHCHECK instruction to your docker "
                "container images.",
                "LOW", ["AVD-DS-0026"]),
        Control("4.7", "Ensure update instructions are not used alone "
                "in the Dockerfile",
                "Do not use update instructions such as apt-get "
                "update alone or in a single line in the Dockerfile.",
                "HIGH", ["AVD-DS-0017"]),
        Control("4.8", "Ensure setuid and setgid permissions are "
                "removed",
                severity="MEDIUM", default_status="MANUAL"),
        Control("4.9", "Ensure that COPY is used instead of ADD",
                "Use COPY instruction instead of ADD instruction in "
                "the Dockerfile.",
                "LOW", ["AVD-DS-0005"]),
        Control("4.10", "Ensure secrets are not stored in Dockerfiles",
                "Do not store any kind of secrets within Dockerfiles.",
                "CRITICAL", ["SECRET-CRITICAL"]),
    ])

_AWS_CIS_14 = Spec(
    id="aws-cis-1.4", title="AWS CIS Foundations v1.4",
    description="AWS CIS Foundations",
    version="1.4",
    related_resources=["https://www.cisecurity.org/benchmark/"
                       "amazon_web_services"],
    controls=[
        Control("2.1.1", "Ensure all S3 buckets employ "
                "encryption-at-rest",
                severity="MEDIUM", checks=["AVD-AWS-0088"]),
        Control("2.1.3", "Ensure MFA Delete is enabled on S3 buckets",
                severity="MEDIUM", default_status="MANUAL"),
        Control("2.1.5", "Ensure that S3 Buckets are configured with "
                "'Block public access'",
                severity="HIGH",
                checks=["AVD-AWS-0086", "AVD-AWS-0087",
                        "AVD-AWS-0091", "AVD-AWS-0093"]),
        Control("2.2.1", "Ensure EBS volume encryption is enabled",
                severity="HIGH", checks=["AVD-AWS-0026"]),
        Control("2.3.1", "Ensure that encryption is enabled for RDS "
                "Instances",
                severity="HIGH", checks=["AVD-AWS-0080"]),
        Control("3.1", "Ensure CloudTrail is enabled in all regions",
                severity="MEDIUM", checks=["AVD-AWS-0014"]),
        Control("3.2", "Ensure CloudTrail log file validation is "
                "enabled",
                severity="MEDIUM", checks=["AVD-AWS-0016"]),
        Control("3.7", "Ensure CloudTrail logs are encrypted at rest "
                "using KMS CMKs",
                severity="HIGH", checks=["AVD-AWS-0015"]),
        Control("5.2", "Ensure no security groups allow ingress from "
                "0.0.0.0/0 to remote server administration ports",
                severity="HIGH", checks=["AVD-AWS-0107"]),
    ])

_AWS_CIS_12 = Spec(
    id="aws-cis-1.2", title="AWS CIS Foundations v1.2",
    description="AWS CIS Foundations",
    version="1.2",
    related_resources=["https://www.cisecurity.org/benchmark/"
                       "amazon_web_services"],
    controls=[
        # 1. Identity and Access Management
        Control("1.1", "Avoid the use of the root account",
                severity="LOW", default_status="MANUAL"),
        Control("1.2", "Ensure MFA is enabled for all IAM users with "
                "a console password",
                severity="HIGH", checks=["AVD-AWS-0145"]),
        Control("1.3", "Ensure credentials unused for 90 days or "
                "greater are disabled",
                severity="MEDIUM", checks=["AVD-AWS-0144"]),
        Control("1.4", "Ensure access keys are rotated every 90 days "
                "or less",
                severity="MEDIUM", checks=["AVD-AWS-0146"]),
        Control("1.5", "Ensure IAM password policy requires at least "
                "one uppercase letter",
                severity="MEDIUM", checks=["AVD-AWS-0061"]),
        Control("1.6", "Ensure IAM password policy requires at least "
                "one lowercase letter",
                severity="MEDIUM", checks=["AVD-AWS-0058"]),
        Control("1.7", "Ensure IAM password policy requires at least "
                "one symbol",
                severity="MEDIUM", checks=["AVD-AWS-0060"]),
        Control("1.8", "Ensure IAM password policy requires at least "
                "one number",
                severity="MEDIUM", checks=["AVD-AWS-0059"]),
        Control("1.9", "Ensure IAM password policy requires a minimum "
                "length of 14 or greater",
                severity="MEDIUM", checks=["AVD-AWS-0063"]),
        Control("1.10", "Ensure IAM password policy prevents password "
                "reuse",
                severity="MEDIUM", checks=["AVD-AWS-0056"]),
        Control("1.11", "Ensure IAM password policy expires passwords "
                "within 90 days or less",
                severity="MEDIUM", checks=["AVD-AWS-0062"]),
        Control("1.12", "Ensure no root account access key exists",
                severity="CRITICAL", checks=["AVD-AWS-0141"]),
        Control("1.13", "Ensure MFA is enabled for the root account",
                severity="CRITICAL", checks=["AVD-AWS-0142"]),
        Control("1.14", "Ensure hardware MFA is enabled for the root "
                "account",
                severity="CRITICAL", default_status="MANUAL"),
        Control("1.16", "Ensure IAM policies are attached only to "
                "groups or roles",
                severity="LOW", checks=["AVD-AWS-0143"]),
        # 2. Logging
        Control("2.1", "Ensure CloudTrail is enabled in all regions",
                severity="MEDIUM", checks=["AVD-AWS-0014"]),
        Control("2.2", "Ensure CloudTrail log file validation is "
                "enabled",
                severity="MEDIUM", checks=["AVD-AWS-0016"]),
        Control("2.3", "Ensure the S3 bucket used to store CloudTrail "
                "logs is not publicly accessible",
                severity="CRITICAL",
                checks=["AVD-AWS-0086", "AVD-AWS-0087"]),
        Control("2.4", "Ensure CloudTrail trails are integrated with "
                "CloudWatch Logs",
                severity="LOW", checks=["AVD-AWS-0162"]),
        Control("2.6", "Ensure S3 bucket access logging is enabled on "
                "the CloudTrail S3 bucket",
                severity="LOW", checks=["AVD-AWS-0089"]),
        Control("2.7", "Ensure CloudTrail logs are encrypted at rest "
                "using KMS CMKs",
                severity="HIGH", checks=["AVD-AWS-0015"]),
        Control("2.8", "Ensure rotation for customer created CMKs is "
                "enabled",
                severity="MEDIUM", checks=["AVD-AWS-0065"]),
        Control("2.9", "Ensure VPC flow logging is enabled in all "
                "VPCs",
                severity="MEDIUM", checks=["AVD-AWS-0178"]),
        # 3. Monitoring (metric filters require account inspection)
        Control("3.1", "Ensure a log metric filter and alarm exist "
                "for unauthorized API calls",
                severity="LOW", default_status="MANUAL"),
        Control("3.2", "Ensure a log metric filter and alarm exist "
                "for console sign-in without MFA",
                severity="LOW", default_status="MANUAL"),
        Control("3.3", "Ensure a log metric filter and alarm exist "
                "for usage of root account",
                severity="LOW", default_status="MANUAL"),
        # 4. Networking
        Control("4.1", "Ensure no security groups allow ingress from "
                "0.0.0.0/0 to port 22",
                severity="HIGH", checks=["AVD-AWS-0107"]),
        Control("4.2", "Ensure no security groups allow ingress from "
                "0.0.0.0/0 to port 3389",
                severity="HIGH", checks=["AVD-AWS-0107"]),
        Control("4.3", "Ensure the default security group of every "
                "VPC restricts all traffic",
                severity="LOW", checks=["AVD-AWS-0173"]),
    ])

SPECS = {s.id: s for s in (_K8S_CIS, _K8S_NSA, _K8S_PSS_BASELINE,
                           _K8S_PSS_RESTRICTED, _DOCKER_CIS,
                           _AWS_CIS_12, _AWS_CIS_14)}


def get_spec(name: str) -> Spec:
    """Accepts a builtin id ('@'-prefixed paths load YAML specs the way
    the reference accepts --compliance @spec.yaml)."""
    if name.startswith("@"):
        return load_spec_file(name[1:])
    spec = SPECS.get(name)
    if spec is None:
        raise KeyError(
            f"unknown compliance spec {name!r}; builtin: "
            f"{', '.join(sorted(SPECS))}")
    return spec


def load_spec_file(path: str) -> Spec:
    """Custom spec YAML, same document shape the reference accepts."""
    import yaml
    with open(path, encoding="utf-8") as f:
        doc = yaml.safe_load(f)
    body = doc.get("spec", doc)
    controls = []
    for c in body.get("controls", []):
        controls.append(Control(
            id=str(c.get("id", "")), name=c.get("name", ""),
            description=c.get("description", ""),
            severity=c.get("severity", "MEDIUM"),
            checks=[chk["id"] if isinstance(chk, dict) else str(chk)
                    for chk in c.get("checks") or []],
            default_status=c.get("defaultStatus", "")))
    return Spec(
        id=body.get("id", path), title=body.get("title", ""),
        description=body.get("description", ""),
        version=str(body.get("version", "")),
        related_resources=body.get("relatedResources", []) or [],
        controls=controls)
