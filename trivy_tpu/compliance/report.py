"""Compliance report assembly + writers (reference
pkg/compliance/report/report.go — BuildComplianceReport, summary and
all writers).

A control PASSes when the scan produced no matching failure, FAILs on
any matching misconfiguration failure / vulnerability / secret, and is
MANUAL when it has no automated checks."""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from .. import types as T
from .spec import Control, Spec

_SEV_ORDER = {s: i for i, s in enumerate(T.SEVERITIES)}


@dataclass
class ControlResult:
    control: Control
    status: str = "PASS"     # PASS | FAIL | MANUAL
    failures: list = field(default_factory=list)  # misconf/vuln/secret


@dataclass
class ComplianceReport:
    spec: Spec
    results: list = field(default_factory=list)   # [ControlResult]


def _check_index(results):
    """check-id → [(result, finding)] over misconfigurations, plus
    severity buckets for VULN-*/SECRET-* pseudo-checks."""
    by_check: dict[str, list] = {}
    for res in results:
        for m in res.misconfigurations:
            if m.status != "FAIL":
                continue
            for key in (m.id, m.avd_id):
                if key:
                    by_check.setdefault(key.upper(), []).append((res, m))
        for v in res.vulnerabilities:
            sev = (v.vulnerability.severity or "UNKNOWN").upper()
            by_check.setdefault(f"VULN-{sev}", []).append((res, v))
        for s in res.secrets:
            sev = (s.severity or "UNKNOWN").upper()
            by_check.setdefault(f"SECRET-{sev}", []).append((res, s))
    return by_check


def build_compliance_report(spec: Spec,
                            results: list) -> ComplianceReport:
    by_check = _check_index(results)
    out = ComplianceReport(spec=spec)
    for control in spec.controls:
        cr = ControlResult(control=control)
        if not control.checks:
            cr.status = control.default_status or "MANUAL"
        else:
            for check_id in control.checks:
                for _res, finding in by_check.get(check_id.upper(), []):
                    cr.failures.append(finding)
            cr.status = "FAIL" if cr.failures else "PASS"
        out.results.append(cr)
    return out


def to_summary_table(report: ComplianceReport) -> str:
    from ..report.tables import render_table
    head = ["ID", "Name", "Status", "Issues"]
    rows = [[cr.control.id, cr.control.name[:60], cr.status,
             str(len(cr.failures))] for cr in report.results]
    return render_table(
        "Summary Report for compliance: " + report.spec.title,
        head, rows)


def _finding_json(f):
    if isinstance(f, T.DetectedMisconfiguration):
        return {"Type": "misconfiguration", "ID": f.id,
                "AVDID": f.avd_id, "Title": f.title,
                "Severity": f.severity, "Message": f.message}
    if isinstance(f, T.DetectedVulnerability):
        return {"Type": "vulnerability",
                "VulnerabilityID": f.vulnerability_id,
                "PkgName": f.pkg_name,
                "InstalledVersion": f.installed_version,
                "Severity": f.vulnerability.severity}
    if isinstance(f, T.SecretFinding):
        return {"Type": "secret", "RuleID": f.rule_id,
                "Severity": f.severity, "Title": f.title}
    return {"Type": "unknown"}


def to_json_report(report: ComplianceReport) -> str:
    doc = {
        "ID": report.spec.id,
        "Title": report.spec.title,
        "Description": report.spec.description,
        "Version": report.spec.version,
        "RelatedResources": report.spec.related_resources,
        "SummaryControls": [
            {"ID": cr.control.id, "Name": cr.control.name,
             "Severity": cr.control.severity,
             "Status": cr.status, "TotalFail": len(cr.failures)}
            for cr in report.results],
        "Results": [
            {"ID": cr.control.id, "Name": cr.control.name,
             "Description": cr.control.description,
             "Severity": cr.control.severity, "Status": cr.status,
             "Findings": sorted(
                 (_finding_json(f) for f in cr.failures),
                 key=lambda d: (-_SEV_ORDER.get(
                     d.get("Severity") or "UNKNOWN", 0), str(d)))}
            for cr in report.results],
    }
    return json.dumps(doc, indent=2)


def write_compliance(report: ComplianceReport, mode: str = "summary",
                     fmt: str = "table", output=None) -> None:
    import sys
    out = output or sys.stdout
    if fmt == "json" or mode == "all":
        out.write(to_json_report(report) + "\n")
    else:
        out.write(to_summary_table(report))
