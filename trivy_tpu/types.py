"""Core scan/report types.

Mirrors the reference report schema so JSON output is comparable
byte-for-byte after normalization:
- pkg/types/report.go (Report/Result), pkg/types/vulnerability.go
  (DetectedVulnerability), pkg/fanal/types/artifact.go (Package, BlobInfo,
  ArtifactDetail, OS, Layer), pkg/fanal/types/secret.go (SecretFinding).

Go's `json:",omitempty"` semantics are reproduced by `_strip_empty`:
zero values (empty string, 0, False, empty list/dict, None) are omitted.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Optional


# --- enums (string constants, reference pkg/fanal/types/const.go) ---

class OSFamily:
    NONE = "none"  # packages without a detected OS (scan.go:70)
    ALPINE = "alpine"
    DEBIAN = "debian"
    UBUNTU = "ubuntu"
    REDHAT = "redhat"
    CENTOS = "centos"
    ROCKY = "rocky"
    ALMA = "alma"
    AMAZON = "amazon"
    ORACLE = "oracle"
    FEDORA = "fedora"
    SUSE = "suse"  # family umbrella; concrete: opensuse/sles
    OPENSUSE = "opensuse"
    OPENSUSE_LEAP = "opensuse.leap"
    OPENSUSE_TUMBLEWEED = "opensuse.tumbleweed"
    SLES = "suse linux enterprise server"
    PHOTON = "photon"
    WOLFI = "wolfi"
    CHAINGUARD = "chainguard"
    MARINER = "cbl-mariner"


class ResultClass:
    OS_PKGS = "os-pkgs"
    LANG_PKGS = "lang-pkgs"
    CONFIG = "config"
    SECRET = "secret"
    LICENSE = "license"
    LICENSE_FILE = "license-file"
    CUSTOM = "custom"
    INGEST = "ingest"  # fanald degradation annotations (partial scans)


class ArtifactType:
    CONTAINER_IMAGE = "container_image"
    FILESYSTEM = "filesystem"
    REPOSITORY = "repository"
    CYCLONEDX = "cyclonedx"
    SPDX = "spdx"
    VM = "vm"


class Scanner:
    VULN = "vuln"
    SECRET = "secret"
    MISCONF = "misconfig"
    LICENSE = "license"
    NONE = "none"


SEVERITIES = ["UNKNOWN", "LOW", "MEDIUM", "HIGH", "CRITICAL"]


class Status:
    """Advisory status (trivy-db pkg/types/status.go ordering)."""
    UNKNOWN = "unknown"
    NOT_AFFECTED = "not_affected"
    AFFECTED = "affected"
    FIXED = "fixed"
    UNDER_INVESTIGATION = "under_investigation"
    WILL_NOT_FIX = "will_not_fix"
    FIX_DEFERRED = "fix_deferred"
    END_OF_LIFE = "end_of_life"


# --- helpers ---

def _strip_empty(v: Any) -> Any:
    """Drop Go-zero values recursively (json omitempty emulation)."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return v.to_json()
    if isinstance(v, dict):
        out = {}
        for k, val in v.items():
            sv = _strip_empty(val)
            if sv not in ("", None, [], {}, 0, False) or sv is True:
                out[k] = sv
        return out
    if isinstance(v, (list, tuple)):
        return [_strip_empty(x) for x in v]
    if isinstance(v, float) and v.is_integer():
        return int(v)  # Go marshals float64(5) as "5", not "5.0"
    return v


class JsonMixin:
    _json_names: dict = {}
    _keep_zero: tuple = ()  # fields serialized even when zero (no omitempty)
    _json_skip: tuple = ()  # fields never serialized (Go `json:"-"`)
    _json_raw: tuple = ()   # fields emitted verbatim (no zero-stripping)

    def to_json(self) -> dict:
        out = {}
        for f in dataclasses.fields(self):
            if f.name in self._json_skip:
                continue
            v = getattr(self, f.name)
            name = self._json_names.get(f.name, _pascal(f.name))
            sv = v if f.name in self._json_raw else _strip_empty(v)
            if f.name in self._keep_zero:
                out[name] = sv
                continue
            if sv in ("", None, [], {}, 0, False):
                continue
            out[name] = sv
        return out


def _pascal(name: str) -> str:
    return "".join(p.capitalize() if not p[0].isupper() else p
                   for p in name.split("_")) if "_" in name else (name[0].upper() + name[1:])


# --- fanal types ---

@dataclass
class Layer(JsonMixin):
    digest: str = ""
    diff_id: str = ""
    created_by: str = ""
    _json_names = {"diff_id": "DiffID"}

    def __bool__(self):
        return bool(self.digest or self.diff_id or self.created_by)


@dataclass
class OS(JsonMixin):
    family: str = ""
    name: str = ""
    eosl: bool = False
    extended: bool = False
    _json_names = {"eosl": "EOSL", "extended": "extended"}

    @property
    def detected(self) -> bool:
        return self.family != ""

    def merge(self, other: "OS") -> None:
        """Reference OS.Merge (pkg/fanal/types/artifact.go:30-55):
        a previously detected family is KEPT unless it is redhat or
        debian — Oracle ships /etc/redhat-release (detected as RHEL by
        mistake) and Ubuntu ships debian files, so only those two get
        overwritten by a later, more specific detection."""
        if not other.detected:
            return
        if self.family in (OSFamily.REDHAT, OSFamily.DEBIAN):
            self.family = other.family
            self.name = other.name
            self.extended = other.extended
            return
        if not self.family:
            self.family = other.family
        if not self.name:
            self.name = other.name
        self.extended = self.extended or other.extended


@dataclass
class Repository(JsonMixin):
    family: str = ""
    release: str = ""


@dataclass
class Location(JsonMixin):
    start_line: int = 0
    end_line: int = 0


@dataclass
class PkgIdentifier(JsonMixin):
    purl: str = ""
    bom_ref: str = ""
    uid: str = ""
    _json_names = {"purl": "PURL", "bom_ref": "BOMRef", "uid": "UID"}


@dataclass
class Package(JsonMixin):
    """Installed package row (reference pkg/fanal/types/artifact.go:68)."""
    id: str = ""
    name: str = ""
    identifier: PkgIdentifier = field(default_factory=PkgIdentifier)
    version: str = ""
    release: str = ""
    epoch: int = 0
    arch: str = ""
    dev: bool = False
    src_name: str = ""
    src_version: str = ""
    src_release: str = ""
    src_epoch: int = 0
    licenses: list = field(default_factory=list)
    maintainer: str = ""
    modularitylabel: str = ""
    indirect: bool = False
    depends_on: list = field(default_factory=list)
    layer: Layer = field(default_factory=Layer)
    file_path: str = ""
    digest: str = ""
    locations: list = field(default_factory=list)
    installed_files: list = field(default_factory=list)
    # attached by the applier from the origin layer's Red Hat build
    # metadata (docker.go lookupBuildInfo); never serialized to reports
    # (reference Package has BuildInfo `json:"-"`)
    build_info: Optional["BuildInfo"] = None
    _json_skip = ("build_info",)
    _json_names = {"id": "ID", "src_name": "SrcName", "src_version": "SrcVersion",
                   "src_release": "SrcRelease", "src_epoch": "SrcEpoch"}
    # non-pointer structs: always marshaled by Go (see DetectedVulnerability)
    _keep_zero = ("identifier", "layer")

    def format_version(self) -> str:
        """epoch:version-release (reference pkg/scanner/utils/util.go FormatVersion)."""
        return _format_ver(self.epoch, self.version, self.release)

    def format_src_version(self) -> str:
        return _format_ver(self.src_epoch, self.src_version, self.src_release)


def _format_ver(epoch: int, version: str, release: str) -> str:
    if version == "":
        return ""
    v = version
    if release != "":
        v = f"{v}-{release}"
    if epoch:
        v = f"{epoch}:{v}"
    return v


@dataclass
class PackageInfo(JsonMixin):
    file_path: str = ""
    packages: list = field(default_factory=list)  # [Package]


@dataclass
class Application(JsonMixin):
    """A language-ecosystem application (lockfile etc.),
    reference pkg/fanal/types/artifact.go Application."""
    type: str = ""          # ecosystem, e.g. "python-pkg", "npm"
    file_path: str = ""
    packages: list = field(default_factory=list)  # [Package]


@dataclass
class Code(JsonMixin):
    lines: list = field(default_factory=list)


@dataclass
class CodeLine(JsonMixin):
    number: int = 0
    content: str = ""
    is_cause: bool = False
    annotation: str = ""
    truncated: bool = False
    highlighted: str = ""
    first_cause: bool = False
    last_cause: bool = False
    _json_names = {"is_cause": "IsCause", "first_cause": "FirstCause",
                   "last_cause": "LastCause"}
    _keep_zero = ("number", "content", "is_cause", "annotation",
                  "truncated", "first_cause", "last_cause")


@dataclass
class SecretFinding(JsonMixin):
    rule_id: str = ""
    category: str = ""
    severity: str = ""
    title: str = ""
    start_line: int = 0
    end_line: int = 0
    code: Code = field(default_factory=Code)
    match: str = ""
    layer: Layer = field(default_factory=Layer)
    _json_names = {"rule_id": "RuleID"}
    _keep_zero = ("rule_id", "category", "severity", "title",
                  "start_line", "end_line", "code", "match", "layer")


@dataclass
class Secret(JsonMixin):
    file_path: str = ""
    findings: list = field(default_factory=list)  # [SecretFinding]


@dataclass
class Misconfiguration(JsonMixin):
    """Per-file misconfiguration record inside a blob
    (reference pkg/fanal/types/misconf.go)."""
    file_type: str = ""
    file_path: str = ""
    successes: int = 0
    exceptions: int = 0
    failures: list = field(default_factory=list)  # [DetectedMisconfiguration]
    layer: "Layer" = field(default_factory=lambda: Layer())


@dataclass
class BuildInfo(JsonMixin):
    """Red Hat build metadata (reference pkg/fanal/types/artifact.go
    BuildInfo): content sets scope which advisories apply."""
    content_sets: list = field(default_factory=list)
    nvr: str = ""
    arch: str = ""
    _json_names = {"nvr": "Nvr"}


@dataclass
class BlobInfo(JsonMixin):
    """Per-layer analysis result (reference pkg/fanal/types/artifact.go:311)."""
    schema_version: int = 2
    digest: str = ""
    diff_id: str = ""
    created_by: str = ""
    opaque_dirs: list = field(default_factory=list)
    whiteout_files: list = field(default_factory=list)
    os: OS = field(default_factory=OS)
    repository: Optional[Repository] = None
    package_infos: list = field(default_factory=list)   # [PackageInfo]
    applications: list = field(default_factory=list)    # [Application]
    misconfigurations: list = field(default_factory=list)  # [Misconfiguration]
    secrets: list = field(default_factory=list)         # [Secret]
    licenses: list = field(default_factory=list)
    custom_resources: list = field(default_factory=list)
    build_info: Optional[BuildInfo] = None
    # fanald (fanal/pipeline.py) per-stage degradation annotations; a
    # non-empty list marks this blob a PARTIAL analysis (cached only
    # under a salted partial id, surfaced in the report)
    ingest_errors: list = field(default_factory=list)
    _json_names = {"diff_id": "DiffID", "os": "OS",
                   "ingest_errors": "IngestErrors"}
    _json_raw = ("ingest_errors",)


@dataclass
class ArtifactInfo(JsonMixin):
    schema_version: int = 2
    architecture: str = ""
    created: str = ""
    docker_version: str = ""
    os: str = ""
    _json_names = {"os": "OS"}


@dataclass
class ArtifactDetail(JsonMixin):
    """Squashed view of all layers (reference pkg/fanal/types/artifact.go:341)."""
    os: OS = field(default_factory=OS)
    repository: Optional[Repository] = None
    packages: list = field(default_factory=list)      # [Package]
    applications: list = field(default_factory=list)  # [Application]
    misconfigurations: list = field(default_factory=list)  # [Misconfiguration]
    secrets: list = field(default_factory=list)       # [Secret]
    licenses: list = field(default_factory=list)
    custom_resources: list = field(default_factory=list)
    # fanald annotations squashed across layers (applier.py) — the
    # scanner surfaces them as one ResultClass.INGEST result
    ingest_errors: list = field(default_factory=list)
    _json_names = {"os": "OS", "ingest_errors": "IngestErrors"}
    _json_raw = ("ingest_errors",)


# --- db / vulnerability types (trivy-db pkg/types) ---

@dataclass
class DataSource(JsonMixin):
    id: str = ""
    name: str = ""
    url: str = ""
    _json_names = {"id": "ID", "name": "Name", "url": "URL"}
    _keep_zero = ("id", "name", "url")


@dataclass
class CVSS(JsonMixin):
    v2_vector: str = ""
    v3_vector: str = ""
    v40_vector: str = ""
    v2_score: float = 0.0
    v3_score: float = 0.0
    v40_score: float = 0.0
    _json_names = {"v2_vector": "V2Vector", "v3_vector": "V3Vector",
                   "v40_vector": "V40Vector", "v2_score": "V2Score",
                   "v3_score": "V3Score", "v40_score": "V40Score"}


@dataclass
class Vulnerability(JsonMixin):
    """Vulnerability details (trivy-db pkg/types/types.go Vulnerability)."""
    title: str = ""
    description: str = ""
    severity: str = ""
    cwe_ids: list = field(default_factory=list)
    vendor_severity: dict = field(default_factory=dict)
    cvss: dict = field(default_factory=dict)  # source -> CVSS
    references: list = field(default_factory=list)
    published_date: str = ""
    last_modified_date: str = ""
    _json_names = {"cwe_ids": "CweIDs", "vendor_severity": "VendorSeverity",
                   "cvss": "CVSS"}


@dataclass
class DetectedVulnerability(JsonMixin):
    vulnerability_id: str = ""
    vendor_ids: list = field(default_factory=list)
    pkg_id: str = ""
    pkg_name: str = ""
    pkg_path: str = ""
    pkg_identifier: PkgIdentifier = field(default_factory=PkgIdentifier)
    installed_version: str = ""
    fixed_version: str = ""
    status: str = ""
    layer: Layer = field(default_factory=Layer)
    severity_source: str = ""
    primary_url: str = ""
    data_source: Optional[DataSource] = None
    # embedded details (filled by FillInfo)
    vulnerability: Vulnerability = field(default_factory=Vulnerability)
    _json_names = {"vulnerability_id": "VulnerabilityID", "vendor_ids": "VendorIDs",
                   "pkg_id": "PkgID", "pkg_name": "PkgName", "pkg_path": "PkgPath",
                   "primary_url": "PrimaryURL", "severity_source": "SeveritySource"}
    # PkgIdentifier/Layer are non-pointer structs in the reference: Go
    # omitempty never elides them (npm.json.golden shows "Layer": {})
    _keep_zero = ("pkg_identifier", "layer")

    def to_json(self) -> dict:
        out = super().to_json()
        # Go embeds types.Vulnerability fields inline at the top level.
        emb = out.pop("Vulnerability", None) or {}
        out.update(emb)
        return out

    @property
    def severity(self) -> str:
        return self.vulnerability.severity or "UNKNOWN"


@dataclass
class DetectedLicense(JsonMixin):
    """Reference pkg/types/license.go."""
    severity: str = ""
    category: str = ""
    pkg_name: str = ""
    file_path: str = ""
    name: str = ""
    text: str = ""
    confidence: float = 1.0
    link: str = ""
    _json_names = {"pkg_name": "PkgName", "file_path": "FilePath"}
    _keep_zero = ("severity", "category", "pkg_name", "file_path", "name",
                  "confidence", "link")


@dataclass
class CauseMetadata(JsonMixin):
    provider: str = ""
    service: str = ""
    start_line: int = 0
    end_line: int = 0
    code: "Code" = field(default_factory=lambda: Code())


@dataclass
class DetectedMisconfiguration(JsonMixin):
    """Reference pkg/types/misconfiguration.go."""
    type: str = ""
    id: str = ""
    avd_id: str = ""
    title: str = ""
    description: str = ""
    message: str = ""
    namespace: str = ""
    query: str = ""
    resolution: str = ""
    severity: str = ""
    primary_url: str = ""
    references: list = field(default_factory=list)
    status: str = ""
    layer: Layer = field(default_factory=Layer)
    cause_metadata: CauseMetadata = field(default_factory=CauseMetadata)
    _json_names = {"id": "ID", "avd_id": "AVDID", "primary_url": "PrimaryURL"}
    _keep_zero = ("type", "id", "title", "description", "message",
                  "namespace", "query", "resolution", "severity", "status")


# --- result / report ---

@dataclass
class MisconfSummary(JsonMixin):
    successes: int = 0
    failures: int = 0
    exceptions: int = 0
    _keep_zero = ("successes", "failures", "exceptions")


@dataclass
class Result(JsonMixin):
    target: str = ""
    clazz: str = ""
    type: str = ""
    packages: list = field(default_factory=list)
    vulnerabilities: list = field(default_factory=list)
    misconf_summary: Optional[MisconfSummary] = None
    misconfigurations: list = field(default_factory=list)
    secrets: list = field(default_factory=list)
    licenses: list = field(default_factory=list)
    custom_resources: list = field(default_factory=list)
    # fanald degradation annotations (ResultClass.INGEST results)
    ingest_errors: list = field(default_factory=list)
    _json_names = {"clazz": "Class", "ingest_errors": "IngestErrors"}
    _json_raw = ("ingest_errors",)
    _keep_zero = ("target",)

    def is_empty(self) -> bool:
        # a config result whose checks were all excepted (or passed)
        # still carries its summary, like the reference's
        # misconfsToResults (local/scan.go:214-258)
        has_summary = self.misconf_summary is not None and (
            self.misconf_summary.successes
            or self.misconf_summary.exceptions)
        return not (self.packages or self.vulnerabilities
                    or self.misconfigurations or self.secrets
                    or self.licenses or self.custom_resources
                    or self.ingest_errors or has_summary)


@dataclass
class Metadata(JsonMixin):
    size: int = 0
    os: Optional[OS] = None
    image_id: str = ""
    diff_ids: list = field(default_factory=list)
    repo_tags: list = field(default_factory=list)
    repo_digests: list = field(default_factory=list)
    image_config: dict = field(default_factory=dict)
    _json_names = {"os": "OS", "image_id": "ImageID", "diff_ids": "DiffIDs"}
    # ImageConfig is a non-pointer struct in the reference
    # (types.Metadata → v1.ConfigFile): Go's omitempty never drops it,
    # so every report carries at least the zero config. Raw passthrough:
    # the stored dict is the image's own config JSON.
    _keep_zero = ("image_config",)
    _json_raw = ("image_config",)


# Marshal of the go-containerregistry v1.ConfigFile zero value — what
# the reference emits as Metadata.ImageConfig for non-image artifacts
# (fs/repo/sbom reports; see integration/testdata/npm.json.golden).
ZERO_IMAGE_CONFIG = {
    "architecture": "",
    "created": "0001-01-01T00:00:00Z",
    "os": "",
    "rootfs": {"type": "", "diff_ids": None},
    "config": {},
}


@dataclass
class Report(JsonMixin):
    schema_version: int = 2
    created_at: str = ""
    artifact_name: str = ""
    artifact_type: str = ""
    metadata: Metadata = field(default_factory=Metadata)
    results: list = field(default_factory=list)  # [Result]


# --- scan options / target ---

@dataclass
class ScanOptions:
    pkg_types: tuple = ("os", "library")
    scanners: tuple = (Scanner.VULN,)
    scan_removed_packages: bool = False
    list_all_packages: bool = False
    include_dev_deps: bool = False


@dataclass
class ScanTarget:
    name: str = ""
    os: OS = field(default_factory=OS)
    repository: Optional[Repository] = None
    packages: list = field(default_factory=list)
    applications: list = field(default_factory=list)
    secrets: list = field(default_factory=list)
    licenses: list = field(default_factory=list)
