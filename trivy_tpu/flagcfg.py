"""Flag binding: CLI > TRIVY_* env > trivy.yaml > defaults.

Mirrors the reference's flag system (pkg/flag/flag.go Bind: every flag
binds a viper key fed by the command line, a TRIVY_-prefixed env var,
and the config file, in that precedence). argparse has no layered
sources, so this module post-processes a parsed namespace: any flag
NOT explicitly present on the command line is re-resolved from the
environment, then from the config file, before the argparse default
stands.

Config keys follow the reference's trivy.yaml layout (nested viper
paths like `vulnerability.ignore-unfixed`, `db.repository`,
`scan.scanners` — pkg/flag/*_flags.go ConfigName fields); a flat
top-level key equal to the flag name is accepted too.
"""

from __future__ import annotations

import argparse
import os
from typing import Any, Optional

# flag dest → reference trivy.yaml config path (flat names always work)
# values may be a list: the first present path wins (viper aliases
# bind several config keys to one flag)
CONFIG_PATHS = {
    "scanners": ["scan.scanners", "scan.security-checks"],
    "skip_files": "scan.skip-files",
    "skip_dirs": "scan.skip-dirs",
    "parallel": "scan.parallel",
    "ignore_unfixed": "vulnerability.ignore-unfixed",
    "ignore_status": "vulnerability.ignore-status",
    "ignorefile": "ignorefile",
    "cache_dir": "cache.dir",
    "db": "db.path",
    "db_repository": "db.repository",
    "skip_db_update": "db.skip-update",
    "java_db": "javadb.path",
    "secret_config": "secret.config",
    "platform": "image.platform",
    "image_src": "image.source",
    "pkg_types": "pkg-types",
    "config_check": "misconfiguration.check-paths",
    "check_namespaces": "misconfiguration.namespaces",
    "detect_coalesce_wait_ms": "detect.coalesce-wait-ms",
    "detect_max_inflight_pairs": "detect.max-inflight-pairs",
    "detect_warmup": "detect.warmup",
    # graftfeed (input path): cross-request dedup + slice prefetch
    "detect_dedup": "detect.dedup",
    "stream_prefetch": "mesh.stream-prefetch",
    # graftguard (resilience.*): watchdog, breaker, admission,
    # failpoints
    "detect_dispatch_timeout_ms": "resilience.dispatch-timeout-ms",
    "breaker_fail_threshold": "resilience.breaker-fail-threshold",
    "breaker_reset_ms": "resilience.breaker-reset-ms",
    "admit_max_active": "resilience.admit-max-active",
    "admit_max_queue": "resilience.admit-max-queue",
    "admit_queue_ms": "resilience.admit-queue-ms",
    "failpoint": "resilience.failpoints",
    # meshguard (mesh.*): device mesh + per-device fault domains
    "mesh_devices": "mesh.devices",
    "mesh_db_shards": "mesh.db-shards",
    "mesh_min_devices": "mesh.min-devices",
    "mesh_rebuild_cooldown_ms": "mesh.rebuild-cooldown-ms",
    "mesh_probe_timeout_ms": "mesh.probe-timeout-ms",
    "mesh_hosts": "mesh.hosts",
    "mesh_host_loss_window_ms": "mesh.host-loss-window-ms",
    # graftstream (larger-than-device advisory tables) rides the
    # mesh.* config section — it is the mesh data dimension made real
    "table_device_budget_mb": "mesh.table-device-budget-mb",
    "table_stream_slices": "mesh.table-stream-slices",
    # graftfleet (fleet.* / cache.*): scan router + shared backends
    "cache_backend": "cache.backend",
    "replicas": "fleet.replicas",
    "ring_vnodes": "fleet.ring-vnodes",
    "replica_timeout_ms": "fleet.replica-timeout-ms",
    "replica_fail_threshold": "fleet.replica-fail-threshold",
    "replica_reset_ms": "fleet.replica-reset-ms",
    "replica_probe_interval_ms": "fleet.replica-probe-interval-ms",
    "replica_probe_timeout_ms": "fleet.replica-probe-timeout-ms",
    "route_retries": "fleet.route-retries",
    # fanald (ingest.*): supervised streaming ingest budgets
    "ingest_serial": "ingest.serial",
    "ingest_walkers": "ingest.walkers",
    "ingest_analyzers": "ingest.analyzers",
    "ingest_max_file_bytes": "ingest.max-file-bytes",
    "ingest_max_layer_bytes": "ingest.max-layer-bytes",
    "ingest_max_members": "ingest.max-members",
    "ingest_layer_deadline_ms": "ingest.layer-deadline-ms",
    "ingest_max_inflight_bytes": "ingest.max-inflight-bytes",
    # graftmemo (memo.*): detection-result memoization + redetectd
    "memo_backend": "memo.backend",
    "redetect_concurrency": "memo.redetect-concurrency",
}

_TRUE = {"1", "t", "true", "yes", "on"}
_FALSE = {"0", "f", "false", "no", "off"}


def split_commas(raw: str) -> list[str]:
    """Split a comma-joined value, ignoring commas inside parentheses
    — `--failpoint rpc.scan=flaky(0.05,7)` is ONE value (the failpoint
    grammar's paren form), not two. The single splitter shared by the
    append-flag coercion here and resilience.failpoints.parse_spec, so
    env-sourced flags and direct specs can never parse differently."""
    out, cur, depth = [], [], 0
    for ch in raw:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(depth - 1, 0)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


class ConfigError(SystemExit):
    pass


def _flag_name(action: argparse.Action) -> str:
    longs = [o for o in action.option_strings if o.startswith("--")]
    return (longs[0] if longs else action.option_strings[0]).lstrip("-")


def _env_keys(action: argparse.Action) -> list[str]:
    """One env var per long option — alias flags (--security-checks)
    bind their own TRIVY_* names like the reference's viper aliases."""
    return ["TRIVY_" + o.lstrip("-").upper().replace("-", "_")
            for o in action.option_strings if o.startswith("--")]


def _explicit(action: argparse.Action, argv: list[str]) -> bool:
    """Was the flag given on the command line? Handles --opt, --opt=v,
    and joined short options (-ftable). Long-option prefix
    abbreviations are disabled at the parser (build_parser sets
    allow_abbrev=False) so exact matching is sound."""
    for opt in action.option_strings:
        short = len(opt) == 2 and not opt.startswith("--")
        for a in argv:
            if a == opt or a.startswith(opt + "=") or \
                    (short and a.startswith(opt)):
                return True
    return False


def _coerce(action: argparse.Action, raw: Any, origin: str) -> Any:
    """Convert an env string / YAML value to the action's value type."""
    if isinstance(action, (argparse._StoreTrueAction,
                           argparse._StoreFalseAction,
                           argparse.BooleanOptionalAction)):
        if isinstance(raw, bool):
            return raw
        s = str(raw).strip().lower()
        if s in _TRUE:
            return True
        if s in _FALSE:
            return False
        raise ConfigError(
            f"{origin}: invalid boolean {raw!r} for --{_flag_name(action)}")
    if isinstance(action, argparse._AppendAction):
        if isinstance(raw, list):
            return [str(v) for v in raw]
        return [s.strip() for s in split_commas(str(raw)) if s.strip()]
    if isinstance(raw, list):  # YAML list for a comma-joined flag
        raw = ",".join(str(v) for v in raw)
    if action.type is int or isinstance(action.default, int) and \
            not isinstance(action.default, bool):
        try:
            return int(raw)
        except (TypeError, ValueError):
            raise ConfigError(
                f"{origin}: invalid integer {raw!r} for "
                f"--{_flag_name(action)}")
    if action.type is float or isinstance(action.default, float):
        # float-typed flags (--detect-coalesce-wait-ms) resolved from
        # env/config used to fall through to str() and blow up argless
        # downstream — coerce like int flags do
        try:
            return float(raw)
        except (TypeError, ValueError):
            raise ConfigError(
                f"{origin}: invalid number {raw!r} for "
                f"--{_flag_name(action)}")
    return str(raw)


def _config_lookup(doc: dict, action: argparse.Action):
    """→ (found, value): dotted reference paths first, then flat
    keys (one per long option, covering alias flags)."""
    paths = CONFIG_PATHS.get(action.dest) or []
    if isinstance(paths, str):
        paths = [paths]
    for path in paths:
        node: Any = doc
        for part in path.split("."):
            if not isinstance(node, dict) or part not in node:
                node = None
                break
            node = node[part]
        if node is not None:
            return True, node
    for opt in action.option_strings:
        if not opt.startswith("--"):
            continue
        flat = opt.lstrip("-")
        # a mapping here is a config SECTION that happens to share the
        # flag's name (e.g. `db:` vs --db), never a flag value
        if flat in doc and not isinstance(doc[flat], dict):
            return True, doc[flat]
    return False, None


def load_config_file(path: str, explicit: bool) -> Optional[dict]:
    """trivy.yaml; a missing DEFAULT config is fine, a missing
    explicitly-requested one is an error (pkg/commands/app.go)."""
    if not os.path.exists(path):
        if explicit:
            raise ConfigError(f"config file {path!r} not found")
        return None
    import yaml
    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
    except (OSError, yaml.YAMLError) as e:
        raise ConfigError(f"config file {path}: {e}")
    if doc is None:
        return {}
    if not isinstance(doc, dict):
        raise ConfigError(f"config file {path}: not a mapping")
    return doc


def apply_flag_sources(args: argparse.Namespace,
                       parser: argparse.ArgumentParser,
                       argv: list[str], env=None) -> argparse.Namespace:
    """Re-resolve every non-explicit flag: env, then config file.
    Only the ACTIVE subcommand's actions are consulted — another
    subparser's same-dest action must not overrule a flag the user
    gave explicitly."""
    env = env if env is not None else os.environ
    cfg_path = getattr(args, "config", "") or "trivy.yaml"
    doc = load_config_file(cfg_path,
                           explicit=bool(getattr(args, "config", "")))
    seen_dests: set = set()
    for action in _leaf_actions(parser, getattr(args, "command", None)):
        if action.dest in seen_dests:
            continue
        seen_dests.add(action.dest)
        if action.dest in ("help", "command", "config") or \
                not action.option_strings:
            continue
        if not hasattr(args, action.dest) or _explicit(action, argv):
            continue
        ek = next((k for k in _env_keys(action) if k in env), None)
        if ek is not None:
            setattr(args, action.dest,
                    _coerce(action, env[ek], f"${ek}"))
            continue
        if doc is not None:
            found, raw = _config_lookup(doc, action)
            if found:
                setattr(args, action.dest,
                        _coerce(action, raw, cfg_path))
    return args


def _leaf_actions(parser: argparse.ArgumentParser,
                  command: str | None = None):
    """Top-level actions plus subcommand actions; when ``command`` is
    given, only that subcommand's."""
    for action in parser._actions:
        if isinstance(action, argparse._SubParsersAction):
            for name, sub in action.choices.items():
                if command is None or name == command:
                    yield from sub._actions
        else:
            yield action


def generate_default_config(parser: argparse.ArgumentParser,
                            out_path: str = "trivy.yaml") -> str:
    """--generate-default-config: write every scan flag's default in
    the reference's nested layout (flag.go writeConfig analog)."""
    doc: dict = {}
    seen = set()
    for action in _leaf_actions(parser):
        if action.dest in ("help", "command", "config") or \
                not action.option_strings or action.dest in seen or \
                action.default in (None, argparse.SUPPRESS):
            continue
        seen.add(action.dest)
        path = CONFIG_PATHS.get(action.dest, _flag_name(action))
        if isinstance(path, list):
            path = path[0]  # canonical key only in generated config
        node = doc
        parts = path.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
            if not isinstance(node, dict):  # flat/nested name clash
                node = None
                break
        if node is not None:
            node[parts[-1]] = action.default
    import yaml
    with open(out_path, "w") as f:
        yaml.safe_dump(doc, f, sort_keys=True)
    return out_path
