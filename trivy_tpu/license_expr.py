"""SPDX license expression parser (reference pkg/licensing/expression).

Parses compound expressions — `A AND (B OR C) WITH exception`, trailing
`+` — into the same tree the reference's goyacc grammar
(expression/parser.go.y) builds, with matching precedence (OR < AND <
WITH < '+'), matching lexing (words split on whitespace; '(', ')', '+'
are terminals; an interior '+' stays inside the word), matching
stringification (versioned GNU ids render -only/-or-later; children are
parenthesized when the parent conjunction binds tighter), and the same
two normalization hooks (licensing.Normalize applied per simple
expression, NormalizeForSPDX character cleanup)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Union

# conjunction binding powers double as the yacc token ordering used by
# CompoundExpr.String() for parenthesization (types.go:60-80)
_OR, _AND, _WITH = 1, 2, 3

# GNU ids whose plus/bare forms render -or-later/-only (types.go:11-29)
VERSIONED = {
    "AGPL-1.0", "AGPL-3.0",
    "GFDL-1.1-invariants", "GFDL-1.1-no-invariants", "GFDL-1.1",
    "GFDL-1.2-invariants", "GFDL-1.2-no-invariants", "GFDL-1.2",
    "GFDL-1.3-invariants", "GFDL-1.3-no-invariants", "GFDL-1.3",
    "GPL-1.0", "GPL-2.0", "GPL-3.0",
    "LGPL-2.0", "LGPL-2.1", "LGPL-3.0",
}


class ParseError(ValueError):
    pass


@dataclass
class SimpleExpr:
    license: str
    has_plus: bool = False

    def render(self) -> str:
        if self.license in VERSIONED:
            return self.license + (
                "-or-later" if self.has_plus else "-only")
        return self.license + ("+" if self.has_plus else "")


@dataclass
class CompoundExpr:
    left: "Expr"
    conj: int           # _OR | _AND | _WITH
    conj_lit: str       # as written ("or", "AND", "WITH", ...)
    right: "Expr"

    def render(self) -> str:
        left = self.left.render()
        if isinstance(self.left, CompoundExpr) and \
                self.conj > self.left.conj:
            left = f"({left})"
        right = self.right.render()
        if isinstance(self.right, CompoundExpr) and \
                self.conj > self.right.conj:
            right = f"({right})"
        return f"{left} {self.conj_lit} {right}"


Expr = Union[SimpleExpr, CompoundExpr]

_CONJ = {"OR": _OR, "AND": _AND, "WITH": _WITH}


def _lex(s: str) -> list[str]:
    """Reference Lexer split (lexer.go:22-70): whitespace-separated
    words; '(', ')' always terminals; a leading '+' is a terminal; an
    interior '+' stays in the word unless followed by space/paren/end
    (so 'GPLv2+' lexes as 'GPLv2', '+')."""
    out: list[str] = []
    i, n = 0, len(s)
    while i < n:
        while i < n and s[i].isspace():
            i += 1
        if i >= n:
            break
        if s[i] in "()+":
            out.append(s[i])
            i += 1
            continue
        start = i
        while i < n:
            c = s[i]
            if c in "()" or c.isspace():
                break
            if c == "+":
                nxt = s[i + 1] if i + 1 < n else ""
                if nxt == "" or nxt.isspace() or nxt in "()":
                    break       # trailing plus → its own token
            i += 1
        out.append(s[start:i])
    return out


class _Parser:
    def __init__(self, tokens: list[str]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> str | None:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self) -> str | None:
        t = self.peek()
        if t is not None:
            self.i += 1
        return t

    def parse(self) -> Expr:
        e = self.expr(0)
        if self.peek() is not None:
            raise ParseError(f"unexpected token {self.peek()!r}")
        return e

    def expr(self, min_bp: int) -> Expr:
        left = self.primary()
        while True:
            t = self.peek()
            if t is None or t == ")":
                return left
            conj = _CONJ.get(t.upper())
            if conj is None:
                return left
            # left-assoc for OR/AND, right-assoc for WITH (%right)
            if conj < min_bp or (conj == min_bp and conj != _WITH):
                return left
            self.next()
            # the callee returns on an equal binding power unless the
            # operator is WITH, which gives left-assoc OR/AND and
            # right-assoc WITH with the same min_bp
            right = self.expr(conj)
            left = CompoundExpr(left, conj, t, right)

    def primary(self) -> Expr:
        t = self.peek()
        if t is None:
            raise ParseError("unexpected end of expression")
        if t == "(":
            self.next()
            e = self.expr(0)
            if self.next() != ")":
                raise ParseError("missing ')'")
            return e
        if t in (")", "+") or _CONJ.get(t.upper()) is not None:
            raise ParseError(f"unexpected token {t!r}")
        # one or more adjacent words form one simple expression
        # ("Public Domain"); a '+' terminal attaches to it
        words = [self.next()]
        while True:
            nxt = self.peek()
            if nxt is None or nxt in ("(", ")", "+") or \
                    _CONJ.get(nxt.upper()) is not None:
                break
            words.append(self.next())
        lic = " ".join(words)
        if self.peek() == "+":
            self.next()
            return SimpleExpr(lic, has_plus=True)
        return SimpleExpr(lic)


def parse(expr: str) -> Expr:
    toks = _lex(expr)
    if not toks:
        raise ParseError("empty expression")
    return _Parser(toks).parse()


def normalize_for_spdx(s: str) -> str:
    """Replace characters outside the SPDX idstring grammar with '-'
    (expression.go NormalizeForSPDX; ':' kept for DocumentRef). ASCII
    only — idstring = 1*(ALPHA / DIGIT / '-' / '.'), so non-ASCII
    letters are invalid too."""
    out = []
    for c in s:
        if ("a" <= c <= "z" or "A" <= c <= "Z" or "0" <= c <= "9"
                or c in "-.:"):
            out.append(c)
        else:
            out.append("-")
    return "".join(out)


def normalize(expr: str, *fns: Callable[[str], str],
              plus_fn: Callable[[str], str] | None = None) -> str:
    """Parse, apply the per-license normalizers to every simple
    expression, uppercase conjunctions, and render (expression.go
    Normalize). Raises ParseError on invalid input.

    plus_fn, when given, is consulted with the '+'-suffixed form of a
    plus expression first — the normalize table carries entries like
    'lgplv2+' that are more specific than bare-license-plus-suffix
    (the reference loses these: its lexer strips the '+' before
    licensing.Normalize ever sees it)."""
    tree = parse(expr)

    def walk(e: Expr) -> Expr:
        if isinstance(e, SimpleExpr):
            lic = e.license
            has_plus = e.has_plus
            if has_plus and plus_fn is not None:
                mapped = plus_fn(lic + "+")
                if mapped != lic + "+":
                    lic = mapped
                    has_plus = False
            for f in fns:
                lic = f(lic)
            return SimpleExpr(lic, has_plus)
        return CompoundExpr(walk(e.left), e.conj,
                            e.conj_lit.upper(), walk(e.right))

    return walk(tree).render()


def normalize_pkg_licenses(licenses: list[str]) -> str:
    """SPDX marshal entry point (spdx/marshal.go NormalizeLicense):
    '-with-' becomes a WITH conjunction, each license is parenthesized,
    the conjunction of all is AND, normalized through
    licensing.Normalize + NormalizeForSPDX. Returns '' when the joined
    expression does not parse (the reference logs and soldiers on)."""
    from .licensing import normalize as licensing_normalize
    parts = []
    for lic in licenses:
        lic = lic.replace("-with-", " WITH ").replace("-WITH-",
                                                      " WITH ")
        parts.append(f"({lic})")
    joined = " AND ".join(parts)
    if not joined:
        return ""
    try:
        return normalize(joined, licensing_normalize,
                         normalize_for_spdx,
                         plus_fn=licensing_normalize)
    except ParseError:
        return ""
