"""graftfleet — a horizontal serving tier: scan router, shared cache
backends, and replica fault domains.

Everything below the fleet scales *up* (one process, one mesh); this
package scales *out*. Three parts, layered on the serving spine (see
ARCHITECTURE.md "Serving tier (graftfleet)"):

  ring        consistent-hash ring with virtual nodes: artifacts map
              to replicas by key hash, and losing a replica remaps
              ONLY that replica's keys (its arc spreads over the
              survivors) instead of reshuffling the world;
  supervisor  per-replica fault domains — one CircuitBreaker per
              replica (resilience.BreakerRegistry, meshguard's
              pattern one level up), /healthz probe readmission once
              a lost replica's breaker admits the half-open probe;
  router      the Twirp front end clients point at unchanged: routes
              each RPC to the owning replica, fails over along the
              ring on replica faults, honors 429/503 + Retry-After
              admission sheds via the shared RetryPolicy, and
              propagates X-Trivy-Deadline-Ms so no retry ever
              outlives the client's budget.

The router is stateless by design: replicas share per-layer analysis
through a common cache backend (fanal redis/s3 behind the FSCache
interface), so a layer analyzed by one replica is a cache hit on all
of them and a failover Scan finds its blobs wherever it lands.

graftmemo (memo.py) extends the same sharing to detection RESULTS:
a content-addressed memo keyed by (blob digest, db_version) means a
layer detected by any replica is detected once per DB version
fleet-wide — the first subsystem that makes the fleet cheaper as it
scales, not merely faster. Its re-detect daemon lives in
detect/redetect.py (it is a detect-path consumer, not a fleet one).
"""

from .memo import FSMemo, MemoryMemo, MemoStore, open_memo
from .ring import HashRing
from .router import (RouterOptions, RouterState, serve_router,
                     serve_router_background)
from .supervisor import ReplicaOptions, ReplicaSet

__all__ = [
    "FSMemo", "HashRing", "MemoStore", "MemoryMemo", "open_memo",
    "ReplicaOptions", "ReplicaSet", "RouterOptions", "RouterState",
    "serve_router", "serve_router_background",
]
