"""Consistent-hash ring with virtual nodes.

The router keys every RPC by its artifact (artifact_id for Scan /
MissingBlobs / PutArtifact, diff_id for PutBlob) and asks the ring who
owns it. Virtual nodes (`vnodes` points per replica, sha256-placed on
a 64-bit circle) keep the shares balanced; consistency means a replica
leaving remaps only the keys on its own arcs — every other key keeps
its owner, so the fleet's per-replica caches and in-flight work stay
warm through membership churn.

The ring itself is immutable after construction on the routing path:
a LOST replica is not removed — the supervisor marks its fault domain
open and the router walks `successors(key)` past it, so the key's
ownership (and with it cache locality) snaps back the moment the
replica is readmitted. `add`/`remove` exist for real membership
changes (scale-out/scale-in) and for the remap property tests.
"""

from __future__ import annotations

import bisect
import hashlib
import threading


def _point(label: str) -> int:
    """64-bit ring position for one vnode label."""
    return int.from_bytes(
        hashlib.sha256(label.encode()).digest()[:8], "big")


class HashRing:
    """Thread-safe consistent-hash ring over opaque node names."""

    def __init__(self, nodes=(), vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = vnodes
        self._lock = threading.Lock()
        self._points: list[int] = []       # sorted vnode positions
        self._owners: list[str] = []       # _owners[i] owns _points[i]
        self._nodes: set[str] = set()
        for n in nodes:
            self.add(n)

    def add(self, node: str) -> None:
        with self._lock:
            if node in self._nodes:
                return
            self._nodes.add(node)
            for i in range(self.vnodes):
                p = _point(f"{node}#{i}")
                at = bisect.bisect_left(self._points, p)
                self._points.insert(at, p)
                self._owners.insert(at, node)

    def remove(self, node: str) -> None:
        with self._lock:
            if node not in self._nodes:
                return
            self._nodes.discard(node)
            keep = [(p, o) for p, o in zip(self._points, self._owners)
                    if o != node]
            self._points = [p for p, _ in keep]
            self._owners = [o for _, o in keep]

    def nodes(self) -> list[str]:
        with self._lock:
            return sorted(self._nodes)

    def __len__(self) -> int:
        with self._lock:
            return len(self._nodes)

    def node_for(self, key: str) -> str:
        """The replica owning `key` (first vnode clockwise)."""
        with self._lock:
            if not self._points:
                raise LookupError("empty ring")
            at = bisect.bisect_right(self._points, _point(key))
            return self._owners[at % len(self._owners)]

    def successors(self, key: str) -> list[str]:
        """Every replica in failover order for `key`: the owner first,
        then each DISTINCT replica as its first vnode appears walking
        clockwise. The full membership is always returned — the router
        walks it skipping open fault domains."""
        with self._lock:
            n = len(self._owners)
            if not n:
                return []
            start = bisect.bisect_right(self._points, _point(key)) % n
            out: list[str] = []
            seen: set[str] = set()
            for i in range(n):
                owner = self._owners[(start + i) % n]
                if owner not in seen:
                    seen.add(owner)
                    out.append(owner)
                    if len(seen) == len(self._nodes):
                        break
            return out
