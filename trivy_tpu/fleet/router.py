"""Scan router: the Twirp front end of a replica fleet.

Clients point at the router UNCHANGED — it speaks the same
`/twirp/trivy.{scanner,cache}.v1.*` routes (both JSON and binary
encodings), plus `/healthz`, `/version`, and `/metrics`. Each RPC is
keyed by its artifact (artifact_id, or diff_id for PutBlob) and
forwarded to the consistent-hash ring's owner; on a replica fault the
request fails over along `ring.successors(key)` while the replica's
own fault domain (supervisor.ReplicaSet) opens and background
`/healthz` probes readmit it.

Policy, per request:

  * 2xx              relay; the replica's breaker records a success.
  * 429/503          an admission shed from PR 4's queue — the replica
                     is healthy but busy, so its breaker is NOT
                     charged; the router tries the ring's next
                     replica, and when every replica sheds it sleeps
                     a RetryPolicy delay floored at the smallest
                     Retry-After before re-walking, up to the
                     retry budget.
  * other 4xx        the client's error, relayed terminally (the
                     replica answered; its breaker records a success).
  * 5xx / conn error charge the replica's fault domain, fail over.
  * deadline         X-Trivy-Deadline-Ms is re-stamped with the
                     REMAINING budget on every forward, each forward's
                     socket timeout is bounded by it, and no failover
                     or backoff sleep ever starts past it — an
                     exhausted budget returns 504 immediately.

The router holds no scan state: replicas share layer analysis through
a common cache backend (fanal redis/s3), so a failover Scan finds its
blobs wherever it lands.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import __version__
from ..log import get as _get_logger
from ..metrics import METRICS
from ..obs import (RECORDER, current_span_id, current_trace_id,
                   new_trace, span)
from ..obs import cost as _cost
from ..obs.recorder import (debug_incidents_payload,
                            debug_traces_payload)
from ..resilience import Deadline, FailpointError, RetryPolicy, failpoint
from ..server import (COST_HEADER, DB_VERSION_HEADER, DEADLINE_HEADER,
                      PARENT_SPAN_HEADER, REPLICA_HEADER,
                      ROUTE_DESCRIPTORS, TENANT_HEADER, TOKEN_HEADER,
                      TRACE_HEADER)
from .ring import HashRing
from .supervisor import ReplicaOptions, ReplicaSet

_log = _get_logger("fleet.router")


class _RouterServer(ThreadingHTTPServer):
    # graftfair: same accept-backlog rationale as listen.ScanServer
    # (defined locally — the router never imports the server stack):
    # a tenant burst must reach the admission/quota layer and earn a
    # well-formed 429, not die as a kernel RST in the default-5 backlog
    request_queue_size = 128

# request headers forwarded verbatim to the replica (the deadline
# header is re-stamped with the remaining budget, and the trace /
# parent-span headers are stamped per forward from the active span);
# tenant identity rides every hop so each replica's cost ledger and
# tenant series attribute to the ORIGINAL caller, not to the router
_FORWARD_HEADERS = ("Content-Type", TOKEN_HEADER, TENANT_HEADER)
# replica response headers relayed back to the client (db version
# included: the client sees WHICH advisory DB answered, and the router
# reads the same header to count mid-rollout version skew). The
# replica's X-Trivy-Cost is deliberately NOT here: the router collects
# every hop's cost doc — failed and shed hops included — and stamps
# ONE merged header, so a failover's client still sees the whole bill
# exactly once
_RELAY_HEADERS = ("Content-Type", "Retry-After", TRACE_HEADER,
                  DB_VERSION_HEADER)

# bounded cardinality for the db-version-skew counter's `versions`
# label (the PR 13 profile-reason clamp): the first K distinct
# version pairs get their own series, later pairs fold into "other" —
# the full pair still lands in the warn log and the incident recorder
_SKEW_LABEL_BUDGET = 8


@dataclass
class RouterOptions:
    """Router knobs (CLI `router` flags)."""
    vnodes: int = 64                  # ring points per replica
    replica_timeout_s: float = 60.0   # per-forward socket bound
    # gates the /debug surface (trace buffers carry scan detail); POST
    # bodies are relayed with the client's Trivy-Token for the
    # REPLICAS to enforce — the router itself only guards its buffers
    token: str = ""
    retry: RetryPolicy = field(default_factory=lambda: RetryPolicy(
        attempts=3, base_delay_s=0.05, max_delay_s=1.0, budget_s=10.0))
    replica: ReplicaOptions = field(default_factory=ReplicaOptions)


class _Unrouted(RuntimeError):
    """One full ring walk produced no relayable response. `shed` holds
    the best 429/503 to relay if the retry budget runs out; `floor` is
    the smallest Retry-After seen (0.0 when no replica shed)."""

    def __init__(self, floor: float, shed=None):
        super().__init__(f"no replica answered (floor={floor:g}s)")
        self.floor = floor
        self.shed = shed


class RouterState:
    def __init__(self, replicas, opts: RouterOptions | None = None,
                 probe=None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        self.opts = opts or RouterOptions()
        self.replicas = [r.rstrip("/") for r in replicas]
        self.ring = HashRing(self.replicas, vnodes=self.opts.vnodes)
        self._lock = threading.Lock()
        # last advertised advisory-DB digest per replica (forward
        # relays + readmission probes feed this; disagreement = a
        # mid-rollout fleet whose failovers are not bit-identical)
        self._db_versions: dict[str, str] = {}
        # graftcost: the router's OWN tenant aggregator, fed from the
        # cost headers the replicas relay — a separate instance from
        # the process-global TENANTS so an in-process fleet (tests,
        # bench) never double-counts a scan the replica already settled
        self.costs = _cost.TenantAggregator()
        # skew-label clamp state (see _SKEW_LABEL_BUDGET)
        self._skew_labels: set[str] = set()
        self._draining = False
        self._inflight = 0
        self.supervisor = ReplicaSet(
            self.replicas, self.opts.replica, probe=probe,
            db_version_cb=self.note_db_version)

    # ---- advisory-DB identity -----------------------------------------

    def note_db_version(self, replica: str, version: str) -> None:
        """Record one replica's advertised db_version (from a relayed
        Scan response or a readmission probe); warn + count when the
        fleet now disagrees. Counted per observed CHANGE, not per
        request, so a sustained skew is one increment per flip."""
        if not version:
            return
        with self._lock:
            if self._db_versions.get(replica) == version:
                return
            self._db_versions[replica] = version
            skewed = len(set(self._db_versions.values())) > 1
            snap = dict(self._db_versions)
        if skewed:
            # label with WHICH versions disagree (sorted short
            # digests): a rolling upgrade reads as one transient pair,
            # a split brain as the same pair climbing forever — the
            # unlabeled rate alone cannot tell them apart. The label
            # set is CLAMPED: a fleet churning through N rolling swaps
            # must not mint N scrape series (unbounded cardinality),
            # so pairs past the budget fold into "other" while the
            # full pair always reaches the log + incident recorder
            pair = "|".join(sorted(
                v[:19] for v in set(snap.values())))
            with self._lock:
                if pair in self._skew_labels or \
                        len(self._skew_labels) < _SKEW_LABEL_BUDGET:
                    self._skew_labels.add(pair)
                    label = pair
                else:
                    label = "other"
            METRICS.inc("trivy_tpu_fleet_db_version_skew_total",
                        versions=label)
            RECORDER.note_event("fleet_db_version_skew",
                                replica=replica, versions=pair)
            _log.warning(
                "fleet: advisory-DB version skew — replicas disagree "
                "(%s); failovers are NOT bit-identical until the "
                "rollout converges",
                ", ".join(f"{r}={v[:19]}" for r, v in sorted(
                    snap.items())))

    def db_versions(self) -> dict:
        with self._lock:
            return dict(self._db_versions)

    # ---- graceful drain ------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True

    def request_started(self) -> None:
        with self._lock:
            self._inflight += 1

    def request_finished(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def drain(self, timeout_s: float) -> bool:
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while True:
            with self._lock:
                if self._inflight == 0:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def status(self) -> dict:
        """→ /healthz payload."""
        return {
            "status": "draining" if self._draining else "ok",
            "fleet": {
                "ring": {"replicas": self.ring.nodes(),
                         "vnodes": self.ring.vnodes},
                **self.supervisor.status(),
                "db_versions": self.db_versions(),
                "failovers_total": int(
                    METRICS.get("trivy_tpu_fleet_failovers_total")),
                # graftcost fleet view: per-tenant scan counts and
                # cost split summed from relayed X-Trivy-Cost headers
                "tenants": self.costs.healthz_block(
                    include_system_live=False),
            },
        }

    def close(self) -> None:
        self.supervisor.close()


def route_key(path: str, req: dict) -> str:
    """The ring key for one decoded request: the artifact when the
    RPC names one, the blob otherwise — so an artifact's MissingBlobs,
    PutArtifact, and Scan all land on the same replica (its per-layer
    work stays local even without a shared backend), and PutBlob
    spreads by layer digest."""
    return req.get("artifact_id") or req.get("diff_id") \
        or req.get("target") or path


class RouterHandler(BaseHTTPRequestHandler):
    state: RouterState = None  # set by serve_router()
    protocol_version = "HTTP/1.1"
    _trace_id = ""  # per-request; set by do_POST before dispatch

    def log_message(self, *args):
        pass

    # ---- plumbing ------------------------------------------------------

    def _send(self, code: int, body: bytes, headers: dict) -> None:
        self.send_response(code)
        for k, v in headers.items():
            self.send_header(k, v)
        if self._trace_id and TRACE_HEADER not in headers:
            # the id is echoed END TO END: router-generated responses
            # (shed relays, 504s, errors) carry it just like relays,
            # so a client can always hand support one id to chase
            self.send_header(TRACE_HEADER, self._trace_id)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload).encode(),
                   {"Content-Type": "application/json"})

    def _relay(self, resp, cost_doc: dict | None = None) -> None:
        code, headers, body, replica = resp
        out = {k: headers[k] for k in _RELAY_HEADERS if headers.get(k)}
        if replica:
            # which replica actually answered — failovers make the
            # ring owner a guess; debugging needs the fact
            out[REPLICA_HEADER] = replica
        if cost_doc is not None:
            # ONE merged cost header per request: every hop's doc
            # (failed and shed forwards included) summed, never the
            # final replica's alone and never the same hop twice
            out[COST_HEADER] = json.dumps(cost_doc,
                                          separators=(",", ":"))
        self._send(code, body, out)

    # ---- GET surface ---------------------------------------------------

    def do_GET(self):
        self._trace_id = ""  # never echo a previous POST's id
        if self.path.startswith(("/debug/traces", "/debug/incidents",
                                 "/debug/perf", "/debug/profile",
                                 "/debug/costs")):
            token = self.state.opts.token
            if token and self.headers.get(TOKEN_HEADER) != token:
                return self._json(401, {"code": "unauthenticated",
                                        "msg": "invalid token"})
            if self.path.startswith("/debug/costs"):
                # fleet-wide tenant attribution, built purely from the
                # cost headers the replicas relayed — no conservation
                # block (the router dispatches nothing; reconciliation
                # lives on each replica's own /debug/costs)
                return self._json(200, {
                    "schema": _cost.COSTS_SCHEMA,
                    "scope": "fleet",
                    "tenants": self.state.costs.table(
                        include_system_live=False),
                })
            if self.path.startswith("/debug/traces"):
                self._json(200, debug_traces_payload(self.path))
            elif self.path.startswith("/debug/perf"):
                # the router dispatches nothing itself; its ledger is
                # usually empty but the surface is uniform — tooling
                # asks every process the same question
                from ..obs.perf import debug_perf_payload
                self._json(200, debug_perf_payload())
            elif self.path.startswith("/debug/profile"):
                from ..obs.perf import debug_profile_payload
                code, payload = debug_profile_payload(self.path)
                self._json(code, payload)
            else:
                self._json(200, debug_incidents_payload())
        elif self.path == "/healthz":
            if "text/plain" in (self.headers.get("Accept") or ""):
                self._send(200, b"ok", {"Content-Type": "text/plain"})
            else:
                self._json(200, self.state.status())
        elif self.path == "/version":
            self._json(200, {"Version": __version__})
        elif self.path == "/metrics":
            self._send(200, METRICS.render().encode(),
                       {"Content-Type": "text/plain; version=0.0.4"})
        else:
            self._json(404, {"code": "not_found", "msg": self.path})

    # ---- POST surface --------------------------------------------------

    def do_POST(self):
        t0 = time.perf_counter()
        st = self.state
        # count in-flight BEFORE the draining check: a request that
        # slipped past the check as the signal landed must still hold
        # the drain open until its forward completes — check-then-count
        # would let shutdown proceed under it
        st.request_started()
        try:
            if st.draining:
                # graceful drain: stop admitting; in-flight forwards
                # keep running to completion below. Drain the unread
                # request body first — replying with it still in the
                # socket buffer would corrupt this keep-alive
                # connection's next request.
                length = int(self.headers.get("Content-Length",
                                              "0") or 0)
                if length:
                    self.rfile.read(length)
                reset_s = st.opts.replica.reset_timeout_ms / 1e3
                return self._send(
                    503, json.dumps({"code": "unavailable",
                                     "msg": "router draining"}
                                    ).encode(),
                    {"Content-Type": "application/json",
                     "Retry-After": str(max(1, int(reset_s + 0.999)))})
            # the router MINTS the trace id when the client sent none,
            # so a routed scan is traceable even from untraced clients;
            # every forward re-stamps it (plus the per-hop parent span
            # id)
            tid = self.headers.get(TRACE_HEADER) or ""
            parent = self.headers.get(PARENT_SPAN_HEADER) or ""
            with new_trace(tid or None, parent_id=parent or None) as tid:
                self._trace_id = tid
                with span("router.rpc", route=self.path):
                    self._do_post()
        finally:
            st.request_finished()
            METRICS.observe("trivy_tpu_fleet_router_latency_seconds",
                            time.perf_counter() - t0)

    def _do_post(self):
        desc = ROUTE_DESCRIPTORS.get(self.path)
        if desc is None:
            return self._json(404, {"code": "bad_route",
                                    "msg": self.path})
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            ctype = (self.headers.get("Content-Type") or "") \
                .split(";")[0]
            if ctype in ("application/protobuf",
                         "application/x-protobuf"):
                from ..server.protowire import decode_msg
                req = decode_msg(body, desc)
            else:
                req = json.loads(body or b"{}")
        except (ValueError, json.JSONDecodeError):
            return self._json(400, {"code": "malformed",
                                    "msg": "bad body"})

        hdr = self.headers.get(DEADLINE_HEADER)
        deadline = Deadline(None)
        if hdr:
            try:
                deadline = Deadline(max(float(hdr), 0.0) / 1e3)
            except ValueError:
                pass   # unparseable header: no deadline
        fwd = {k: self.headers[k] for k in _FORWARD_HEADERS
               if self.headers.get(k)}
        # per-hop cost docs accumulate across failover hops AND retry
        # rounds — each forward that did work (served, shed, errored
        # with a ledger) appends exactly one doc
        hop_costs: list[dict] = []
        resp = self._route(route_key(self.path, req), body, fwd,
                           deadline, hop_costs)
        doc = None
        if hop_costs:
            doc = _cost.merge_cost_docs(hop_costs)
            st = self.state
            code = resp[0]
            outcome = ("ok" if code < 400
                       else "shed" if code in (429, 503) else "error")
            # fleet-wide attribution from relayed headers only (no
            # re-export of the tenant series — the replicas already
            # settled these scans into their own metrics)
            st.costs.fold_doc(doc, outcome=outcome)
        self._relay(resp, cost_doc=doc)

    def _route(self, key: str, body: bytes, fwd_headers: dict,
               deadline: Deadline, hop_costs: list | None = None):
        """→ (status, headers, body, replica) to relay. Walks the
        ring's failover order under the RetryPolicy; every decision is
        bounded by the client's deadline."""
        st = self.state
        # forwards beyond a request's first are failovers, counted
        # across retry rounds (the counter the bench scenario reads)
        forwards = [0]
        if hop_costs is None:
            hop_costs = []

        def attempt():
            return self._walk_ring(key, body, fwd_headers, deadline,
                                   forwards, hop_costs)

        def should_retry(e):
            if isinstance(e, _Unrouted) \
                    and deadline.remaining() > e.floor:
                return e.floor
            return None

        try:
            return st.opts.retry.call(attempt,
                                      should_retry=should_retry)
        except _Unrouted as e:
            if deadline.expired():
                return self._deadline_response()
            if e.shed is not None:
                # every replica shed: relay the least-loaded shed
                # (smallest Retry-After) so the client backs off the
                # way single-server admission control taught it to
                return e.shed
            reset_s = st.opts.replica.reset_timeout_ms / 1e3
            return (503, {"Content-Type": "application/json",
                          "Retry-After": str(max(1, int(reset_s + 0.999)))},
                    json.dumps({"code": "unavailable",
                                "msg": "no replica available"}).encode(),
                    None)

    def _deadline_response(self):
        return (504, {"Content-Type": "application/json"},
                json.dumps({"code": "deadline_exceeded",
                            "msg": "client deadline exhausted before "
                                   "a replica answered"}).encode(),
                None)

    def _walk_ring(self, key, body, fwd_headers, deadline, forwards,
                   hop_costs):
        """One pass over the failover order. Returns a relayable
        response or raises _Unrouted."""

        def _note_cost(sp, raw) -> None:
            doc = _cost.parse_cost_header(raw or "")
            if doc is not None:
                hop_costs.append(doc)
                # cost attrs on the hop span: the assembled routed
                # trace (the golden-fixture drill) shows what each
                # hop billed, failed and shed hops included
                sp.attrs["cost_tenant"] = doc.get("tenant", "default")
                sp.attrs["cost_device_ms"] = doc.get("device_ms", 0)
                sp.attrs["cost_queue_ms"] = doc.get("queue_ms", 0)

        st = self.state
        shed = None
        shed_floor = float("inf")
        successors = st.ring.successors(key)
        owner = successors[0] if successors else None
        for replica in successors:
            if not st.supervisor.available(replica):
                continue
            remaining = deadline.remaining()
            if remaining <= 0:
                return self._deadline_response()
            forwards[0] += 1
            # a failover = any forward past the ring owner — an
            # earlier replica faulted/shed this request, OR the owner
            # itself is a lost domain being walked past
            failover = forwards[0] > 1 or replica != owner
            if failover:
                METRICS.inc("trivy_tpu_fleet_failovers_total")
                # tail-based retention: a trace that failed over is a
                # trace worth keeping past ring churn
                RECORDER.note_event("fleet_failover",
                                    trace_id=current_trace_id(),
                                    replica=replica, hop=forwards[0])
            # one span per HOP (not per request): each forward's span
            # id rides X-Trivy-Parent-Span, so the replica fragment
            # that answered hangs under the hop that reached it and a
            # failover reads as sibling forward spans in the assembly
            with span("router.forward", replica=replica,
                      hop=forwards[0], failover=failover) as sp:
                try:
                    failpoint("rpc.route")
                    resp = self._forward(
                        replica, body, fwd_headers,
                        timeout=min(st.opts.replica_timeout_s,
                                    remaining), deadline=deadline)
                except urllib.error.HTTPError as e:
                    resp_body = e.read()
                    headers = {k: e.headers[k] for k in _RELAY_HEADERS
                               if e.headers.get(k)}
                    # a shed or failed hop still billed its tenant
                    # (queue ms, partial work) — its cost doc joins
                    # the merged header like any serving hop's
                    _note_cost(sp, e.headers.get(COST_HEADER))
                    sp.attrs["status"] = e.code
                    if e.code in (429, 503):
                        # admission shed: healthy-but-busy, not a
                        # fault — remember the least-loaded shed and
                        # keep walking
                        try:
                            ra = float(e.headers.get("Retry-After")
                                       or 1.0)
                        except ValueError:
                            ra = 1.0
                        if ra < shed_floor:
                            shed_floor = ra
                            shed = (e.code, headers, resp_body,
                                    replica)
                        continue
                    if 400 <= e.code < 500:
                        # the replica answered; the CLIENT is wrong —
                        # terminal relay, no failover, domain healthy
                        st.supervisor.record_success(replica)
                        st.note_db_version(
                            replica,
                            e.headers.get(DB_VERSION_HEADER) or "")
                        return (e.code, headers, resp_body, replica)
                    sp.attrs["error"] = f"http {e.code}"
                    st.supervisor.record_failure(replica)
                    _log.warning("fleet: replica %s returned %d; "
                                 "failing over", replica, e.code)
                    continue
                except (urllib.error.URLError, OSError,
                        FailpointError) as e:
                    sp.attrs["error"] = str(e)
                    st.supervisor.record_failure(replica)
                    _log.warning("fleet: replica %s unreachable (%s); "
                                 "failing over", replica, e)
                    continue
                sp.attrs["status"] = resp[0]
                _note_cost(sp, resp[1].get(COST_HEADER))
                st.supervisor.record_success(replica)
                # skew watch: which advisory DB answered this forward
                # (failover hops included — a failover onto a replica
                # running a different DB is exactly the hazard)
                st.note_db_version(
                    replica, resp[1].get(DB_VERSION_HEADER) or "")
                return resp + (replica,)
        raise _Unrouted(0.0 if shed is None else shed_floor, shed)

    def _forward(self, replica: str, body: bytes, fwd_headers: dict,
                 timeout: float, deadline: Deadline):
        headers = dict(fwd_headers)
        # trace propagation per hop: the router's (possibly minted)
        # trace id plus THIS hop's forward-span id as the remote
        # parent — replica spans were orphaned fragments before this
        headers[TRACE_HEADER] = current_trace_id()
        psid = current_span_id()
        if psid:
            headers[PARENT_SPAN_HEADER] = psid
        if deadline.at is not None:
            # re-stamp the REMAINING budget: the replica's admission
            # queue must never park this request past what the client
            # has left, not what it originally had
            headers[DEADLINE_HEADER] = str(
                max(int(deadline.remaining() * 1e3), 1))
        req = urllib.request.Request(replica + self.path, data=body,
                                     headers=headers, method="POST")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.headers, r.read()


def dump_fleet_trace(state: RouterState, path: str) -> None:
    """`router --trace FILE`: pull every replica's /debug/traces
    fragment, add the router's own recorder buffer, and write ONE
    assembled Chrome/Perfetto document — the whole fleet's recent
    span history, cross-process edges stitched."""
    from ..obs import collect as obs_collect
    fragments = [{"url": "router",
                  "spans": RECORDER.spans()}]
    fragments += obs_collect.fetch_fragments(state.replicas)
    obs_collect.write_trace(path, obs_collect.assemble(fragments))
    _log.warning("graftwatch fleet trace written to %s", path)


def drain_router_then_shutdown(httpd, state: RouterState,
                               grace_s: float = 10.0) -> None:
    """Graceful router shutdown: stop admitting (503 + Retry-After),
    let in-flight forwards finish (bounded), then stop the accept
    loop. serve_router wires SIGTERM/SIGINT here."""
    _log.warning("router drain: admission stopped; waiting up to "
                 "%.1fs for %d in-flight request(s)", grace_s,
                 state.inflight)
    state.begin_drain()
    if not state.drain(grace_s):
        _log.warning("router drain: grace period expired with %d "
                     "request(s) still in flight; shutting down "
                     "anyway", state.inflight)
    httpd.shutdown()


def serve_router(host: str, port: int, replicas,
                 opts: RouterOptions | None = None,
                 ready_event: threading.Event | None = None,
                 trace_path: str = "", drain_grace_s: float = 10.0):
    """Run the router in the foreground (CLI `router` command).
    `trace_path` dumps the assembled fleet trace on shutdown;
    `drain_grace_s` bounds the SIGTERM/SIGINT graceful drain."""
    state = RouterState(replicas, opts)
    # per-server subclass (the listen.py pattern): a router and its
    # replicas coexist in one process in tests/bench
    handler = type("RouterHandler", (RouterHandler,), {"state": state})
    httpd = _RouterServer((host, port), handler)
    import signal

    def _on_signal(signum, frame):
        # lint: allow(TPU112) reason=signal-time drain thread; the process is exiting and the drain ends by stopping the accept loop the main thread sits in
        threading.Thread(target=drain_router_then_shutdown,
                         args=(httpd, state, drain_grace_s),
                         name="router-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
    except ValueError:
        pass   # not the main thread
    if ready_event is not None:
        ready_event.set()
    try:
        httpd.serve_forever()
    finally:
        if trace_path:
            # pull fragments BEFORE closing: shutdown must not race
            # the replicas' own teardown out of the trace
            try:
                dump_fleet_trace(state, trace_path)
            except Exception:
                _log.exception("fleet trace dump failed")
        httpd.server_close()
        state.close()
    return httpd


def serve_router_background(host: str, port: int, replicas,
                            opts: RouterOptions | None = None,
                            probe=None):
    """Start in a daemon thread; returns (httpd, state) once
    listening. Callers own shutdown: `httpd.shutdown()` then
    `state.close()`."""
    state = RouterState(replicas, opts, probe=probe)
    handler = type("RouterHandler", (RouterHandler,), {"state": state})
    httpd = _RouterServer((host, port), handler)
    # lint: allow(TPU112) reason=serve loop exits when the caller runs httpd.shutdown() (documented caller-owned shutdown contract)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, state
