"""Replica fault domains — meshguard's pattern one level up.

Each server replica gets its own CircuitBreaker (BreakerRegistry keyed
by replica URL, exported as the labelled
`trivy_tpu_fleet_replica_state{replica="<url>"}` gauge). A routed RPC
that fails charges THAT replica's breaker; once the breaker leaves
closed the replica is LOST and the router walks the ring past it. A
maintenance thread runs readmission: once a lost replica's breaker
admits its half-open probe, a successful `/healthz` round-trip closes
the breaker and the replica rejoins the ring's ownership — its keys
snap back (the ring never forgot them), caches still warm.

Unlike meshguard there is no rebuild to coordinate: the ring is
immutable and replicas are stateless against the shared cache backend,
so losing one is pure routing. That keeps this supervisor a strict
subset of the mesh one — breakers, a lost set, and a probe loop.
"""

from __future__ import annotations

import threading
import urllib.request
from dataclasses import dataclass

from ..log import get as _get_logger
from ..resilience.breaker import CLOSED
from ..resilience.meshguard import BreakerRegistry

_log = _get_logger("fleet")


def replica_site(replica: str) -> str:
    """Breaker/log name for one replica's fault domain."""
    return f"fleet.replica:{replica}"


def healthz_probe(replica: str, timeout_s: float) -> str:
    """Default readmission probe: one `/healthz` round-trip. Any
    non-2xx or connection error raises. → the replica's advertised
    advisory-DB version ('' when absent) so the router's skew watch
    sees a readmitted replica's DB BEFORE traffic lands on it — a
    replica restarted mid-rollout may come back serving a different
    database than the fleet."""
    req = urllib.request.Request(replica.rstrip("/") + "/healthz")
    with urllib.request.urlopen(req, timeout=timeout_s) as r:
        if r.status != 200:
            raise RuntimeError(f"healthz returned {r.status}")
        body = r.read()
    try:
        import json
        return str(json.loads(body).get("db_version") or "")
    except (ValueError, AttributeError):
        return ""   # plain `ok` or a foreign payload: no version


@dataclass
class ReplicaOptions:
    """Replica fault-domain knobs (router flags --replica-fail-threshold,
    --replica-reset-ms, --replica-probe-interval-ms,
    --replica-probe-timeout-ms)."""
    fail_threshold: int = 3           # errors that open a replica domain
    reset_timeout_ms: float = 2000.0  # open → half-open probe window
    probe_interval_ms: float = 200.0  # readmission loop cadence
    probe_timeout_ms: float = 2000.0  # /healthz probe bound


class ReplicaSet:
    """Breaker registry + readmission loop over a set of replicas.

    `probe(replica)` (injectable for tests) defaults to the /healthz
    round-trip; it runs only on the maintenance thread, never on the
    request path."""

    def __init__(self, replicas, opts: ReplicaOptions | None = None,
                 probe=None, db_version_cb=None):
        self.replicas = list(replicas)
        self.opts = opts or ReplicaOptions()
        # router hook: a successful readmission probe reports the
        # replica's advertised db_version here (skew accounting)
        self._db_version_cb = db_version_cb
        self.registry = BreakerRegistry(
            fail_threshold=self.opts.fail_threshold,
            reset_timeout_s=self.opts.reset_timeout_ms / 1e3,
            gauge="trivy_tpu_fleet_replica_state",
            label="replica", name_fn=replica_site)
        self._lock = threading.Lock()
        self._lost: set[str] = set()
        self._readmissions = 0
        self._probe = probe
        self._stop = threading.Event()
        # eager breaker creation: every replica's state series exists
        # from boot, so a scrape sees the full fleet, not just the
        # replicas that have already faulted
        for r in self.replicas:
            self.registry.get(r)
        self._thread = threading.Thread(
            target=self._run, name="fleet-readmit", daemon=True)
        self._thread.start()

    # ---- hot-path surface ---------------------------------------------

    def available(self, replica: str) -> bool:
        """May the router forward to this replica? Lost domains wait
        for the probe loop — live traffic is never the half-open
        probe (a request-sized probe against a sick replica would
        burn a client's deadline on supervision)."""
        with self._lock:
            return replica not in self._lost

    def record_failure(self, replica: str) -> None:
        """Charge one routed-RPC failure to the replica's domain; once
        its breaker leaves closed the replica is lost."""
        br = self.registry.get(replica)
        br.record_failure()
        if br.state != CLOSED:
            with self._lock:
                if replica in self._lost or replica not in self.replicas:
                    return
                self._lost.add(replica)
            _log.warning("fleet: replica %s lost; routing past it "
                         "until a probe readmits", replica)
            try:
                from ..obs.recorder import RECORDER
                # pins the routed request's trace (record_failure runs
                # on the router handler thread, context intact)
                RECORDER.note_event("fleet_replica_lost",
                                    replica=replica)
            except Exception:
                _log.exception("fleet event note failed")

    def record_success(self, replica: str) -> None:
        self.registry.get(replica).record_success()

    def lost(self) -> list[str]:
        with self._lock:
            return sorted(self._lost)

    # ---- readmission loop ---------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(self.opts.probe_interval_ms / 1e3):
            try:
                self._probe_lost()
            except Exception:   # the supervisor must never die
                _log.exception("fleet readmission tick failed")

    def _probe_lost(self) -> None:
        with self._lock:
            lost = sorted(self._lost)
        for replica in lost:
            br = self.registry.get(replica)
            if not br.allow():
                continue   # still inside the open window
            try:
                if self._probe is not None:
                    version = self._probe(replica)
                else:
                    version = healthz_probe(
                        replica, self.opts.probe_timeout_ms / 1e3)
            except Exception:
                _log.warning("fleet: replica %s probe failed; domain "
                             "stays open", replica, exc_info=True)
                br.record_failure()
                continue
            br.record_success()
            with self._lock:
                self._lost.discard(replica)
                self._readmissions += 1
            _log.warning("fleet: replica %s readmitted", replica)
            if version and self._db_version_cb is not None:
                try:
                    self._db_version_cb(replica, str(version))
                except Exception:
                    _log.exception("fleet: db-version note failed")

    # ---- introspection / lifecycle ------------------------------------

    def status(self) -> dict:
        """→ router /healthz `fleet.replicas` payload."""
        with self._lock:
            lost = set(self._lost)
            readmissions = self._readmissions
        return {
            "replicas": {
                r: {**self.registry.get(r).status(),
                    "lost": r in lost}
                for r in self.replicas
            },
            "lost": sorted(lost),
            "readmissions": readmissions,
        }

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)
