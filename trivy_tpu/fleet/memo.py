"""graftmemo — content-addressed detection-result memoization.

At fleet scale most scan traffic is duplicate work: images share base
layers, and a trivy-db pull only changes the answer for (blob, db)
pairs whose inputs actually changed. The fanal cache (PR 6) already
dedupes layer *analysis* fleet-wide; this tier dedupes the *detect*
step — the device join — the same way:

  key      (blob cache id, advisory-table content digest). The blob id
           is already content+analyzer-version addressed
           (fanal.cache.cache_key), and the db_version is
           AdvisoryTable.content_digest() (PR 8), so an entry can
           never be served across a DB hot swap: old-version entries
           simply stop being addressed.
  value    per scan UNIT (the OS query batch, or one application's
           query batch) the list of detected hits, each serialized as
           (query index, advisory-group report fields). Hits are
           stored pre-`finish`: replay rebuilds engine Hit tuples
           against the CURRENT scan's fresh PkgQuery objects, so layer
           attribution, FillInfo, sorting — everything downstream —
           runs exactly as it would after a live device join. Bit
           identity holds by construction, not by hope.
  guard    every unit entry carries a digest of its canonical query
           batch (source, ecosystem, name, version, arch, cpe scope,
           in order). Replay requires an exact digest match, so unit
           attribution (below) only has to be SAFE, never clever — a
           wrong attribution can only cause a miss, never a wrong
           result.

Attribution: a unit is memoizable under blob B iff everything that
feeds its queries traces to B alone — for an application unit, every
package's origin layer is B; for the OS unit, every merged package,
the OS detection, and the repository hint all come from B. Partial
(fanald-annotated) blobs are never memoized: their salted cache ids
churn by design and their content is a degradation, not the layer.

Backends mirror fanal.cache.open_cache — fs (default), memory,
redis://, s3:// — with the same crash-safe atomic writes and
corrupt-entry quarantine semantics (PR 5/6), and the same
already-open-object passthrough so an in-process fleet shares one
MemoryMemo across N replicas. A memo backend fault (the `memo.get` /
`memo.put` failpoints, a dead redis, a full disk) degrades to a plain
re-detect — never a 5xx, never a stale-version result.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Optional

from ..log import get as _get_logger
from ..metrics import METRICS

_log = _get_logger("fleet.memo")

MEMO_SCHEMA = 1


def known_backend(backend: str) -> bool:
    """Is `backend` a spelling open_memo accepts? ("" / "off" =
    disabled; the rest mirrors fanal.cache.known_backend.)"""
    return backend in ("", "off", "fs", "memory") \
        or backend.startswith(("redis://", "s3://"))


def open_memo(backend, cache_dir: str = ""):
    """Backend selection for the result memo, mirroring
    fanal.cache.open_cache. "" or "off" → None (memoization
    disabled); an already-open memo OBJECT passes through unchanged
    (in-process fleets share one MemoryMemo across replicas)."""
    if not isinstance(backend, str):
        return backend
    if backend in ("", "off"):
        return None
    if backend.startswith("redis://"):
        return RedisMemo(backend)
    if backend.startswith("s3://"):
        return S3Memo(backend)
    if backend == "memory":
        return MemoryMemo()
    if backend == "fs":
        return FSMemo(cache_dir)
    raise ValueError(f"unknown memo backend {backend!r} "
                     "(off | fs | memory | redis://... | s3://...)")


def entry_key(blob_id: str, db_version: str) -> str:
    """One flat key per (blob, db_version) — filesystem/redis/s3 safe."""
    h = hashlib.sha256(f"{blob_id}|{db_version}".encode()).hexdigest()
    return f"memo-{h}"


def query_digest(queries) -> str:
    """Canonical digest of one unit's query batch. Covers everything
    the join + assembly read from a query (source bucket, version
    scheme, join name, version string, arch scope, CPE scope) in
    batch order — so replay is valid iff the stored hits answer
    EXACTLY this batch."""
    doc = [[q.source, q.ecosystem, q.name, q.version, q.arch,
            sorted(q.cpe_indices)] for q in queries]
    return hashlib.sha256(json.dumps(
        doc, separators=(",", ":")).encode()).hexdigest()


def encode_hits(queries, hits) -> Optional[list]:
    """Serialize engine Hits for one unit: (query index, group report
    fields). → None when a hit's query is not in the batch (defensive;
    the engine only ever reports input queries)."""
    index = {id(q): i for i, q in enumerate(queries)}
    out = []
    for h in hits:
        qi = index.get(id(h.query))
        if qi is None:
            return None
        out.append([qi, h.vuln_id, h.fixed_version, h.status,
                    h.severity, h.data_source, list(h.vendor_ids)])
    return out


def decode_hits(queries, doc: list):
    """Rebuild Hit tuples against THIS scan's fresh query objects.
    → None when the stored document doesn't line up (treated as a
    miss by the caller)."""
    from ..detect.engine import Hit
    hits = []
    try:
        for qi, vuln_id, fixed, status, severity, ds, vids in doc:
            if not isinstance(qi, int) or qi < 0:
                # a negative index would silently wrap to the END of
                # the batch (valid Python!) and attribute the hit to
                # the wrong package — corrupt-but-parseable entries
                # must be a MISS, never a wrong result
                return None
            hits.append(Hit(
                query=queries[qi], vuln_id=vuln_id,
                fixed_version=fixed, status=status, severity=severity,
                data_source=ds, vendor_ids=tuple(vids)))
    except (IndexError, TypeError, ValueError):
        return None
    return hits


# ---------------------------------------------------------------------------
# unit attribution


def unit_key(unit) -> str:
    """Stable name for one scan unit inside a blob's entry."""
    if unit == "os":
        return "os"
    return f"app:{unit.type}:{unit.file_path}"


def blob_index(blobs, blob_ids) -> dict:
    """diff_id → blob cache id, for blobs eligible for memoization
    (complete, diff-identified, unambiguous). Partial blobs (fanald
    annotations) are excluded here, which excludes every unit that
    touches them."""
    out: dict = {}
    for blob, bid in zip(blobs, blob_ids):
        if not blob.diff_id or blob.ingest_errors:
            continue
        if blob.diff_id in out:
            out[blob.diff_id] = None   # ambiguous: two blobs, one diff
        else:
            out[blob.diff_id] = bid
    return {k: v for k, v in out.items() if v is not None}


def unit_blob(unit, detail, blobs, index: dict) -> Optional[str]:
    """→ the blob cache id this unit is fully attributable to, or
    None (run the plain detect path). Conservative by design: the
    query-digest guard makes a missed attribution cost a memo miss,
    never a wrong result."""
    if unit == "os":
        pkg_diffs = {p.layer.diff_id for p in detail.packages}
        os_diffs = {b.diff_id for b in blobs if b.os.detected}
        repo_diffs = {b.diff_id for b in blobs
                      if b.repository is not None}
        cands = pkg_diffs or os_diffs
        if len(cands) != 1:
            return None
        (diff,) = cands
        if os_diffs != {diff} or not repo_diffs <= {diff}:
            return None
        return index.get(diff)
    diffs = {p.layer.diff_id for p in unit.packages}
    if len(diffs) != 1:
        return None
    (diff,) = diffs
    return index.get(diff)


# ---------------------------------------------------------------------------
# the store


class MemoStore:
    """Shared surface over one KV backend: entry read/merge-write with
    failpoint-gated degradation, per-key stats, and the known-blob
    registry redetectd sweeps. Subclasses implement `_read`/`_write`
    (and may override `_known_seed` to recover ids from a persistent
    backend). Thread-safe: one store is shared across server handler
    threads and the redetectd sweep."""

    backend = "memory"

    def __init__(self):
        self._lock = threading.Lock()
        # blob ids this process has stored or served — the redetectd
        # sweep's working set (a restarted replica re-learns it from
        # traffic; fs backends also re-seed from the entry dir)
        self._known: dict[str, None] = {}
        # per-(blob, db_version) hit/store counts: the acceptance
        # drill's probe ("the base layer's detect ran once fleet-wide")
        self._key_stats: dict[tuple, dict] = {}

    # -- backend contract ------------------------------------------------

    def _read(self, key: str):
        raise NotImplementedError

    def _write(self, key: str, doc: dict) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass

    # -- failpoint-gated, degrading IO ----------------------------------

    @staticmethod
    def _failpoint(site: str) -> None:
        from ..resilience import failpoint
        failpoint(site)

    def get_entry(self, blob_id: str, db_version: str
                  ) -> Optional[dict]:
        """→ the (blob, db_version) entry document, or None. A backend
        fault is a miss — the scan re-detects; it must never 5xx or
        serve another version's entry."""
        try:
            self._failpoint("memo.get")
            doc = self._read(entry_key(blob_id, db_version))
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            _log.warning("memo get degraded to a miss (%s: %s)",
                         type(e).__name__, e)
            return None
        if doc is None:
            return None
        if doc.get("schema") != MEMO_SCHEMA \
                or doc.get("db_version") != db_version:
            return None   # foreign schema / hash collision paranoia
        with self._lock:
            self._known.setdefault(blob_id, None)
        return doc

    def put_units(self, blob_id: str, db_version: str,
                  units: dict[str, dict]) -> int:
        """Merge `units` into the (blob, db_version) entry
        (read-modify-write; concurrent writers last-win per entry,
        which is safe because unit values are deterministic functions
        of the key). → units actually written (0 on a degraded
        backend)."""
        if not units:
            return 0
        try:
            self._failpoint("memo.put")
            key = entry_key(blob_id, db_version)
            doc = self._read(key)
            if not isinstance(doc, dict) \
                    or doc.get("schema") != MEMO_SCHEMA \
                    or doc.get("db_version") != db_version:
                doc = {"schema": MEMO_SCHEMA, "blob_id": blob_id,
                       "db_version": db_version, "units": {}}
            fresh = {k: v for k, v in units.items()
                     if doc["units"].get(k) != v}
            if not fresh:
                return 0
            doc["units"].update(fresh)
            self._write(key, doc)
        except Exception as e:  # noqa: BLE001 — degrade, never fail
            _log.warning("memo put dropped (%s: %s)",
                         type(e).__name__, e)
            return 0
        n = len(fresh)
        with self._lock:
            self._known.setdefault(blob_id, None)
            st = self._key_stats.setdefault(
                (blob_id, db_version), {"hits": 0, "stores": 0})
            st["stores"] += n
        METRICS.inc("trivy_tpu_memo_stores_total", n,
                    backend=self.backend)
        return n

    # -- accounting (MemoSession calls these per unit) -------------------

    def note_hit(self, blob_id: str, db_version: str) -> None:
        with self._lock:
            st = self._key_stats.setdefault(
                (blob_id, db_version), {"hits": 0, "stores": 0})
            st["hits"] += 1
        METRICS.inc("trivy_tpu_memo_hits_total", backend=self.backend)

    def note_miss(self) -> None:
        METRICS.inc("trivy_tpu_memo_misses_total",
                    backend=self.backend)

    def key_stats(self, blob_id: str, db_version: str) -> dict:
        with self._lock:
            return dict(self._key_stats.get(
                (blob_id, db_version)) or {"hits": 0, "stores": 0})

    # -- redetectd surface ----------------------------------------------

    def known_blobs(self) -> list[str]:
        with self._lock:
            return list(self._known)

    def status(self) -> dict:
        with self._lock:
            return {"backend": self.backend,
                    "known_blobs": len(self._known)}


class MemoryMemo(MemoStore):
    """In-process backend: tests, ephemeral scans, and the in-process
    fleet topologies (one object shared across N replicas)."""

    backend = "memory"

    def __init__(self):
        super().__init__()
        self._docs: dict[str, str] = {}

    def _read(self, key: str):
        with self._lock:
            raw = self._docs.get(key)
        return None if raw is None else json.loads(raw)

    def _write(self, key: str, doc: dict) -> None:
        raw = json.dumps(doc)
        with self._lock:
            self._docs[key] = raw


class FSMemo(MemoStore):
    """JSON-file-per-entry store under <root>/memo/ with the FSCache
    crash-safety contract — literally: reads and writes go through
    FSCache's `_read_json` (corrupt-entry quarantine to *.corrupt,
    miss on any fault) and `_write_atomic` (unique-temp-name atomic
    writes; a kill mid-put leaves a stray .tmp, never a truncated
    entry)."""

    backend = "fs"

    def __init__(self, root: str):
        super().__init__()
        self.root = os.path.join(root or ".", "memo")
        os.makedirs(self.root, exist_ok=True)
        # the known-blob registry re-seeds LAZILY from surviving
        # entries (first known_blobs() call — i.e. the first sweep),
        # so a restarted replica's sweep still covers yesterday's
        # working set WITHOUT serve() paying an O(total memo bytes)
        # startup scan just to recover blob ids
        self._seeded = False

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ".json")

    def _read(self, key: str):
        from ..fanal.cache import FSCache
        return FSCache._read_json(self._path(key))

    def _write(self, key: str, doc: dict) -> None:
        from ..fanal.cache import FSCache
        FSCache._write_atomic(self._path(key), doc)

    def known_blobs(self) -> list[str]:
        with self._lock:
            seeded, self._seeded = self._seeded, True
        if not seeded:
            from ..fanal.cache import FSCache
            for name in sorted(os.listdir(self.root)):
                if not name.endswith(".json"):
                    continue
                doc = FSCache._read_json(
                    os.path.join(self.root, name))
                if isinstance(doc, dict) and doc.get("blob_id"):
                    with self._lock:
                        self._known.setdefault(doc["blob_id"], None)
        return super().known_blobs()


class RedisMemo(MemoStore):
    """Shared fleet backend over the fanal RespClient. Entries live
    under their own `memo::` prefix so fanal's Clear/scan never
    touches them; corrupt entries quarantine with the PR 8
    read-compare-rename so a racing re-put keeps its fresh value."""

    backend = "redis"

    def __init__(self, url: str):
        super().__init__()
        from urllib.parse import urlparse

        from ..fanal.redis_cache import RespClient
        u = urlparse(url)
        db = 0
        if u.path and u.path.strip("/").isdigit():
            db = int(u.path.strip("/"))
        self.client = RespClient(u.hostname or "localhost",
                                 u.port or 6379,
                                 password=u.password or "", db=db)

    def close(self) -> None:
        self.client.close()

    @staticmethod
    def _rkey(key: str) -> str:
        return f"memo::{key}"

    def _read(self, key: str):
        raw = self.client.command("GET", self._rkey(key))
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError, ValueError):
            from ..fanal.redis_cache import RedisError
            quarantine = f"memo::corrupt::{key}"
            try:
                self.client.rename_if_value(self._rkey(key), raw,
                                            quarantine)
            except RedisError:
                pass
            _log.warning("quarantined corrupt memo entry %s "
                         "(serving a miss)", key)
            return None

    def _write(self, key: str, doc: dict) -> None:
        self.client.command("SET", self._rkey(key), json.dumps(doc))


class S3Memo(MemoStore):
    """Shared fleet backend over the fanal S3 client; entries live
    under a `memo/` key prefix next to fanal's."""

    backend = "s3"

    def __init__(self, url: str):
        super().__init__()
        from ..fanal.s3_cache import S3Cache
        self._s3 = S3Cache(url)

    def _read(self, key: str):
        return self._s3._get("memo", key)

    def _write(self, key: str, doc: dict) -> None:
        self._s3._put("memo", key, doc)


# ---------------------------------------------------------------------------
# per-scan session (the scanner drives this)


class MemoSession:
    """One scan_many call's memo view: entry reads are cached per
    blob, replays are resolved per unit, and stores are batched into
    one merge-write per blob at flush()."""

    def __init__(self, memo: MemoStore, db_version: str):
        self.memo = memo
        self.db_version = db_version
        self._entries: dict[str, Optional[dict]] = {}
        self._stores: dict[str, dict[str, dict]] = {}
        self.replays = 0

    def _entry(self, blob_id: str) -> Optional[dict]:
        if blob_id not in self._entries:
            self._entries[blob_id] = self.memo.get_entry(
                blob_id, self.db_version)
        return self._entries[blob_id]

    def consult(self, unit, queries, detail, blobs, blob_ids):
        """→ (hits | None, store_token | None). hits non-None means
        the unit replays from the memo (skip its dispatch);
        store_token non-None means the unit is attributable and its
        live result should be recorded via record()."""
        if not queries:
            return None, None
        bid = unit_blob(unit, detail, blobs,
                        blob_index(blobs, blob_ids))
        if bid is None:
            return None, None
        ukey = unit_key(unit)
        qd = query_digest(queries)
        entry = self._entry(bid)
        stored = (entry or {}).get("units", {}).get(ukey)
        if stored is not None and stored.get("q") == qd:
            hits = decode_hits(queries, stored.get("hits") or [])
            if hits is not None:
                self.memo.note_hit(bid, self.db_version)
                self.replays += 1
                return hits, None
        self.memo.note_miss()
        return None, (bid, ukey, qd, queries)

    def record(self, token, hits) -> None:
        """Queue one live unit result for the flush merge-write."""
        bid, ukey, qd, queries = token
        doc = encode_hits(queries, hits)
        if doc is None:
            return
        self._stores.setdefault(bid, {})[ukey] = {"q": qd,
                                                  "hits": doc}

    def flush(self) -> int:
        n = 0
        for bid, units in self._stores.items():
            n += self.memo.put_units(bid, self.db_version, units)
        self._stores.clear()
        return n
