"""Stdlib compatibility shims.

tomllib landed in the stdlib in Python 3.11; on 3.10 the identical
library is available as `tomli` (tomllib IS tomli, vendored). Import
it from here so every TOML call site works on both.
"""

from __future__ import annotations

try:
    import tomllib  # noqa: F401  (re-export)
except ModuleNotFoundError:  # Python < 3.11
    import tomli as tomllib  # noqa: F401
