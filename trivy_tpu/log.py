"""Logging (reference pkg/log/logger.go — zap SugaredLogger to stderr).

One process-wide logger writing WARN+ to stderr by default; --debug
drops the threshold. Import `logger` or call `get(name)` for a child.
"""

from __future__ import annotations

import logging
import sys

_root = logging.getLogger("trivy_tpu")
if not _root.handlers:
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s\t%(levelname)s\t%(message)s", "%Y-%m-%dT%H:%M:%S"))
    _root.addHandler(h)
    _root.setLevel(logging.WARNING)
    _root.propagate = False

logger = _root


def get(name: str) -> logging.Logger:
    return _root.getChild(name)


def set_debug(on: bool = True) -> None:
    _root.setLevel(logging.DEBUG if on else logging.WARNING)
