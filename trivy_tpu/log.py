"""Logging (reference pkg/log/logger.go — zap SugaredLogger to stderr).

One process-wide logger writing WARN+ to stderr by default; --debug
drops the threshold. Import `logger` or call `get(name)` for a child.

graftscope additions: the formatter includes the logger NAME (child
loggers from get() used to be indistinguishable from the root), every
line carries the active trace id (graftscope contextvar — the same id
the spans and the X-Trivy-Trace-Id header carry), and
TRIVY_TPU_LOG_FORMAT=json opts into a JSON-lines formatter for log
shippers.
"""

from __future__ import annotations

import json
import logging
import os
import sys

TEXT_FORMAT = ("%(asctime)s\t%(levelname)s\t%(name)s\t"
               "trace=%(trace_id)s\t%(message)s")
TIME_FORMAT = "%Y-%m-%dT%H:%M:%S"


class TraceContextFilter(logging.Filter):
    """Stamp the active graftscope trace id on every record ("-" when
    no trace is active). Attached to the HANDLER: records logged via
    child loggers skip ancestor-logger filters, but never handler
    filters."""

    def filter(self, record: logging.LogRecord) -> bool:
        try:
            from .obs.trace import current_trace_id
            record.trace_id = current_trace_id() or "-"
        except Exception:
            record.trace_id = "-"
        return True


class RecorderHandler(logging.Handler):
    """Feed every record into the graftwatch flight recorder's log
    ring (bounded, always-on) so an incident snapshot carries the
    recent log tail next to the recent spans. The import is lazy and
    guarded: log.py is imported everywhere, including processes that
    never touch obs, and a recorder failure must never sink a log
    call."""

    def emit(self, record: logging.LogRecord) -> None:
        try:
            from .obs.recorder import RECORDER
            RECORDER.record_log({
                "ts_unix": round(record.created, 6),
                "level": record.levelname,
                "logger": record.name,
                "msg": record.getMessage(),
                "trace_id": getattr(record, "trace_id", "-"),
            })
        except Exception:  # noqa: BLE001 — never raise out of logging
            pass


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts, level, logger, msg, trace_id."""

    def format(self, record: logging.LogRecord) -> str:
        doc = {
            "ts": self.formatTime(record, TIME_FORMAT),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
            "trace_id": getattr(record, "trace_id", "-"),
        }
        if record.exc_info:
            doc["exc"] = self.formatException(record.exc_info)
        return json.dumps(doc)


_root = logging.getLogger("trivy_tpu")
logger = _root


def configure(stream=None, fmt: str | None = None) -> logging.Handler:
    """(Re)install the process log handler. fmt: "json" | "text";
    None reads TRIVY_TPU_LOG_FORMAT (default text). Tests redirect
    output by passing their own stream."""
    if fmt is None:
        fmt = os.environ.get("TRIVY_TPU_LOG_FORMAT", "text")
    h = logging.StreamHandler(stream if stream is not None
                              else sys.stderr)
    h.addFilter(TraceContextFilter())
    h.setFormatter(JsonFormatter() if fmt == "json"
                   else logging.Formatter(TEXT_FORMAT, TIME_FORMAT))
    for old in list(_root.handlers):
        _root.removeHandler(old)
    _root.addHandler(h)
    # the flight-recorder tap rides alongside whatever stream handler
    # is installed: reconfiguring output must not silence the ring
    rh = RecorderHandler()
    rh.addFilter(TraceContextFilter())
    _root.addHandler(rh)
    return h


if not _root.handlers:
    configure()
    _root.setLevel(logging.WARNING)
    _root.propagate = False


def get(name: str) -> logging.Logger:
    return _root.getChild(name)


def set_debug(on: bool = True) -> None:
    _root.setLevel(logging.DEBUG if on else logging.WARNING)
