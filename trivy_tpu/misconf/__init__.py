"""Misconfiguration scanning (reference pkg/misconf + pkg/iac).

Bridges fanal config analyzers to the IaC engine: file-type detection
(pkg/iac/detection), per-type scanners (dockerfile native checks here;
kubernetes/cloudformation/terraform in trivy_tpu.iac), and
DetectedMisconfiguration results with cause locations."""

from .dockerfile import scan_dockerfile as _scan_dockerfile


def scan_dockerfile(path, content, lines=None, docs=None):
    return _scan_dockerfile(path, content, lines)


def _scan_kubernetes(path, content, lines=None, docs=None):
    from ..iac.kubernetes import scan_kubernetes
    return scan_kubernetes(path, content, lines, docs=docs)


def _scan_cloudformation(path, content, lines=None, docs=None):
    from ..iac.cloudformation import scan_cloudformation
    return scan_cloudformation(path, content, lines, docs=docs)


def _scan_tfplan(path, content, lines=None, docs=None):
    from ..iac.tfplan import scan_plan_file
    records = scan_plan_file(path, content)
    failures = [f for r in records for f in r.failures]
    successes = sum(r.successes for r in records)
    return failures, successes


def _scan_arm(path, content, lines=None, docs=None):
    from ..iac.azure import scan_arm
    return scan_arm(path, content, lines, docs)


FILE_TYPES = {
    "dockerfile": scan_dockerfile,
    "kubernetes": _scan_kubernetes,
    "cloudformation": _scan_cloudformation,
    "terraformplan": _scan_tfplan,
    "azure-arm": _scan_arm,
}

# ---- custom rego checks (reference pkg/misconf ScannerOption
# PolicyPaths/DataPaths/Namespaces → pkg/iac/rego) -------------------

_custom_scanner = None


def set_custom_checks(check_paths, data_paths=None, namespaces=None):
    """Configure user .rego checks for all subsequent misconf scans.
    Pass empty/None paths to clear."""
    global _custom_scanner
    if not check_paths:
        _custom_scanner = None
        return None
    from ..iac.rego import RegoChecksScanner
    _custom_scanner = RegoChecksScanner.from_paths(
        check_paths, data_paths=data_paths, namespaces=namespaces)
    _custom_scanner.fingerprint = _fingerprint_paths(
        check_paths, data_paths, namespaces)
    return _custom_scanner


def _fingerprint_paths(check_paths, data_paths, namespaces) -> str:
    """Stable hash of check/data file contents + namespaces, mixed into
    the layer cache key so cached blobs are invalidated when the policy
    set changes (reference pkg/fanal/cache/key.go hashes policy
    contents the same way)."""
    import hashlib
    import os
    h = hashlib.sha256()
    for group in (check_paths or []), (data_paths or []):
        for p in group:
            files = []
            if os.path.isdir(p):
                for root, _, names in os.walk(p):
                    files.extend(os.path.join(root, n)
                                 for n in sorted(names))
            elif os.path.exists(p):
                files = [p]
            for fp in files:
                h.update(fp.encode())
                try:
                    with open(fp, "rb") as f:
                        h.update(f.read())
                except OSError:
                    pass
        h.update(b"|")
    h.update(",".join(sorted(namespaces or [])).encode())
    return h.hexdigest()


def custom_checks_fingerprint() -> str:
    """'' when no custom checks are configured."""
    if _custom_scanner is None:
        return ""
    return getattr(_custom_scanner, "fingerprint", "")


def custom_checks_scanner():
    return _custom_scanner


def dockerfile_rego_input(content: bytes) -> dict:
    """Build the mixed-case rego input document for dockerfiles
    (reference pkg/iac/providers/dockerfile/dockerfile.go ToRego)."""
    from .dockerfile import parse_dockerfile
    text = content.decode(errors="replace")
    stages = []
    cur = {"Name": "", "Commands": []}
    stage_idx = -1
    for inst in parse_dockerfile(text):
        if inst.cmd == "FROM":
            if cur["Commands"]:
                stages.append(cur)
            stage_idx += 1
            cur = {"Name": inst.args, "Commands": []}
        value = inst.args
        cur["Commands"].append({
            "Cmd": inst.cmd.lower(),
            "SubCmd": "",
            "Flags": [],
            "Value": [value],
            "Original": f"{inst.cmd} {inst.args}",
            "JSON": False,
            "Stage": max(stage_idx, 0),
            "StartLine": inst.start_line,
            "EndLine": inst.end_line,
        })
    stages.append(cur)
    return {"Stages": [s for s in stages if s["Commands"]]}


def run_custom_checks(ftype: str, path: str, content: bytes, docs):
    """→ (failures, successes, exceptions) from user rego checks."""
    if _custom_scanner is None:
        return [], 0, 0
    text = content.decode(errors="replace")
    if ftype == "dockerfile":
        inputs = [dockerfile_rego_input(content)]
    elif docs is not None:
        inputs = [d for d in docs if d is not None]
    else:
        inputs = _parse_plain_docs(path, text)
    if not inputs:
        return [], 0, 0
    builtin = _builtin_namespaces(ftype) or []
    custom = sorted(".".join(m.package)
                    for m in _custom_scanner.check_modules())
    return _custom_scanner.scan_docs(
        ftype, path, inputs, text,
        extra_namespaces=sorted(set(builtin) | set(custom)))


def _parse_plain_docs(path: str, text: str):
    base = path.lower()
    try:
        if base.endswith((".yaml", ".yml")):
            import yaml
            return [d for d in yaml.safe_load_all(text) if d is not None]
        if base.endswith(".json"):
            import json
            data = json.loads(text)
            return data if isinstance(data, list) else [data]
        if base.endswith(".toml"):
            from ..compat import tomllib
            return [tomllib.loads(text)]
    except Exception:
        return []
    return []


def detect_file_type(path: str) -> str:
    """Path-only pre-gate; content sniffing happens in the analyzer
    (detection.sniff)."""
    base = path.rsplit("/", 1)[-1].lower()
    if base == "dockerfile" or base.startswith("dockerfile.") or \
            base.endswith(".dockerfile"):
        return "dockerfile"
    if base.endswith((".yaml", ".yml", ".json", ".tf", ".tf.json")):
        return "candidate"
    if base.endswith(".toml") and _custom_scanner is not None:
        return "candidate"
    return ""


def _builtin_namespaces(ftype: str):
    """Every check namespace a file type's builtin scanner evaluates,
    or None when the scanner doesn't have per-check accounting."""
    if ftype == "dockerfile":
        from .dockerfile import CHECKS
        return [f"builtin.dockerfile.{c.id}" for c in CHECKS]
    if ftype == "kubernetes":
        from ..iac.kubernetes import CHECKS
        return [c.namespace for c in CHECKS]
    return None


def apply_exceptions(ftype: str, path: str, content: bytes, docs,
                     failures, successes):
    """Rego exceptions over BUILTIN results (reference
    pkg/iac/rego/exceptions.go: `namespace.exceptions.exception[_] ==
    ns` and `endswith(rule, data.<ns>.exception[_][_])`, both
    input-aware). Native checks correspond to the reference's `deny`
    rules, so the rule-name tested is "deny". → (failures, successes,
    exceptions)."""
    scanner = custom_checks_scanner()
    if scanner is None or not scanner.has_exceptions():
        return failures, successes, 0
    if not failures and not successes:
        # the builtin scanner evaluated nothing for this file (e.g. a
        # kubernetes file with no workload/RBAC documents): there is
        # nothing to except
        return failures, successes, 0
    if ftype == "dockerfile":
        input_docs = [dockerfile_rego_input(content)]
    else:
        input_docs = [d for d in (docs or []) if d is not None]
    names = _builtin_namespaces(ftype)
    custom_ns = sorted(".".join(m.package)
                       for m in scanner.check_modules())
    if names is None:
        # no per-check registry: except whole failing checks only
        universe = sorted({f.namespace for f in failures}
                          | set(custom_ns))
        excepted = {
            ns for ns in {f.namespace for f in failures}
            if any(scanner.is_ignored(ns, "deny", doc, universe)
                   for doc in input_docs)}
        kept = [f for f in failures if f.namespace not in excepted]
        return kept, successes, len(excepted)
    # one namespace universe for builtin AND custom passes, like the
    # reference's single data.namespaces document
    universe = sorted(set(names) | set(custom_ns))
    excepted = set()
    for ns in names:
        if any(scanner.is_ignored(ns, "deny", doc, universe)
               for doc in input_docs):
            excepted.add(ns)
    if not excepted:
        return failures, successes, 0
    kept = [f for f in failures if f.namespace not in excepted]
    kept_failed_ns = {f.namespace for f in kept}
    exceptions = len(excepted)
    successes = max(len(names) - exceptions - len(kept_failed_ns), 0)
    return kept, successes, exceptions
