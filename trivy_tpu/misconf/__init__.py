"""Misconfiguration scanning (reference pkg/misconf + pkg/iac).

The reference's IaC stack is a 47k-LoC OPA/rego engine (SURVEY.md §2.4)
scheduled last in the build plan; this package establishes the pipeline —
file-type detection, per-type scanners, DetectedMisconfiguration results
with cause locations — with native Python checks for Dockerfiles first.
Terraform/CloudFormation/K8s scanners slot in behind the same interface.
"""

from .dockerfile import scan_dockerfile  # noqa: F401

FILE_TYPES = {
    "dockerfile": scan_dockerfile,
}


def detect_file_type(path: str) -> str:
    base = path.rsplit("/", 1)[-1].lower()
    if base == "dockerfile" or base.startswith("dockerfile.") or \
            base.endswith(".dockerfile"):
        return "dockerfile"
    return ""
