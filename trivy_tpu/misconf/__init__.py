"""Misconfiguration scanning (reference pkg/misconf + pkg/iac).

Bridges fanal config analyzers to the IaC engine: file-type detection
(pkg/iac/detection), per-type scanners (dockerfile native checks here;
kubernetes/cloudformation/terraform in trivy_tpu.iac), and
DetectedMisconfiguration results with cause locations."""

from .dockerfile import scan_dockerfile as _scan_dockerfile


def scan_dockerfile(path, content, lines=None, docs=None):
    return _scan_dockerfile(path, content, lines)


def _scan_kubernetes(path, content, lines=None, docs=None):
    from ..iac.kubernetes import scan_kubernetes
    return scan_kubernetes(path, content, lines, docs=docs)


def _scan_cloudformation(path, content, lines=None, docs=None):
    from ..iac.cloudformation import scan_cloudformation
    return scan_cloudformation(path, content, lines, docs=docs)


FILE_TYPES = {
    "dockerfile": scan_dockerfile,
    "kubernetes": _scan_kubernetes,
    "cloudformation": _scan_cloudformation,
}


def detect_file_type(path: str) -> str:
    """Path-only pre-gate; content sniffing happens in the analyzer
    (detection.sniff)."""
    base = path.rsplit("/", 1)[-1].lower()
    if base == "dockerfile" or base.startswith("dockerfile.") or \
            base.endswith(".dockerfile"):
        return "dockerfile"
    if base.endswith((".yaml", ".yml", ".json", ".tf", ".tf.json")):
        return "candidate"
    return ""
