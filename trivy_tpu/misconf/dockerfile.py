"""Dockerfile misconfiguration checks.

Native reimplementation of the trivy-checks dockerfile policies the
reference evaluates through rego (pkg/iac/scanners/dockerfile); check IDs
and severities follow the published AVD DS-series so findings line up."""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

from .. import types as T


@dataclass
class Instruction:
    cmd: str
    args: str
    start_line: int
    end_line: int


def parse_dockerfile(content: str) -> list[Instruction]:
    out = []
    cont = None
    for i, raw in enumerate(content.splitlines(), 1):
        line = raw.strip()
        if cont is not None:
            cont.args += " " + line.rstrip("\\").strip()
            cont.end_line = i
            if not line.endswith("\\"):
                out.append(cont)
                cont = None
            continue
        if not line or line.startswith("#"):
            continue
        parts = line.split(None, 1)
        cmd = parts[0].upper()
        args = parts[1] if len(parts) > 1 else ""
        inst = Instruction(cmd=cmd, args=args.rstrip("\\").strip(),
                           start_line=i, end_line=i)
        if args.endswith("\\"):
            cont = inst
        else:
            out.append(inst)
    if cont is not None:
        out.append(cont)
    return out


@dataclass
class Check:
    id: str
    avd_id: str
    title: str
    severity: str
    description: str
    resolution: str
    fn: object = None


def _mk(id_, title, severity, description, resolution):
    def deco(fn):
        CHECKS.append(Check(id=id_,
                            avd_id=f"AVD-DS-{int(id_[2:]):04d}",
                            title=title,
                            severity=severity, description=description,
                            resolution=resolution, fn=fn))
        return fn
    return deco


CHECKS: list[Check] = []


@_mk("DS001", "':latest' tag used", "MEDIUM",
     "When using a 'FROM' statement you should use a specific tag.",
     "Add a tag to the image in the 'FROM' statement")
def _latest_tag(insts):
    for inst in insts:
        if inst.cmd != "FROM":
            continue
        image = inst.args.split()[0]
        if image.lower() == "scratch" or "$" in image:
            continue
        if "@" in image:
            continue  # digest-pinned
        tag = image.rsplit(":", 1)[1] if ":" in image.split("/")[-1] else ""
        if tag == "latest" or (not tag and ":" not in image.split("/")[-1]):
            if not tag:
                continue  # bare name without tag → DS001 flags only :latest
            yield inst, f"Specify a tag in the 'FROM' statement for image " \
                        f"'{image.rsplit(':', 1)[0]}'"


@_mk("DS002", "Image user should not be 'root'", "HIGH",
     "Running containers with 'root' user can lead to a container escape "
     "situation.",
     "Add 'USER <non root user name>' line to the Dockerfile")
def _root_user(insts):
    users = [i for i in insts if i.cmd == "USER"]
    if not users:
        last_from = next((i for i in reversed(insts) if i.cmd == "FROM"),
                         None)
        if last_from is not None:
            yield last_from, "Specify at least 1 USER command in " \
                             "Dockerfile with non-root user as argument"
        return
    last = users[-1]
    if last.args.strip().split(":")[0] in ("root", "0"):
        yield last, "Last USER command in Dockerfile should not be 'root'"


@_mk("DS004", "Port 22 exposed", "MEDIUM",
     "Exposing port 22 might allow users to SSH into the container.",
     "Remove 'EXPOSE 22' statement from the Dockerfile")
def _ssh_port(insts):
    for inst in insts:
        if inst.cmd == "EXPOSE":
            for port in inst.args.split():
                if port.split("/")[0] == "22":
                    yield inst, "Port 22 should not be exposed in Dockerfile"


@_mk("DS005", "ADD instead of COPY", "LOW",
     "You should use COPY instead of ADD unless you want to extract a "
     "tar file.",
     "Use COPY instead of ADD")
def _add_instead_of_copy(insts):
    for inst in insts:
        if inst.cmd != "ADD":
            continue
        src = inst.args.split()[0] if inst.args.split() else ""
        if re.search(r"\.(tar|tar\.gz|tgz|tar\.bz2|tar\.xz)$", src) or \
                src.startswith(("http://", "https://")):
            continue
        yield inst, f"Consider using 'COPY {inst.args}' command instead"


@_mk("DS013", "'RUN cd ...' to change directory", "MEDIUM",
     "Use WORKDIR instead of proliferating instructions like "
     "'RUN cd … && do-something'.",
     "Use WORKDIR to change directory")
def _run_cd(insts):
    for inst in insts:
        if inst.cmd == "RUN" and re.match(r"^cd\s+\S+\s*$", inst.args):
            yield inst, f"RUN should not be used to change directory: " \
                        f"'{inst.args}'. Use 'WORKDIR' statement instead."


@_mk("DS017", "'RUN <package-manager> update' instruction alone", "HIGH",
     "The instruction 'RUN <package-manager> update' should always be "
     "followed by '<package-manager> install' in the same RUN statement.",
     "Combine '<package-manager> update' and '<package-manager> install' "
     "instructions")
def _update_alone(insts):
    for inst in insts:
        if inst.cmd != "RUN":
            continue
        args = inst.args
        if re.search(r"\b(apt-get|apt|yum|apk)\s+update\b", args) and \
                not re.search(r"\b(install|add|upgrade)\b", args):
            yield inst, "The instruction 'RUN <package-manager> update' " \
                        "should always be followed by " \
                        "'<package-manager> install' in the same RUN " \
                        "statement."


@_mk("DS025", "'apk add' without '--no-cache'", "HIGH",
     "You should use 'apk add' with '--no-cache' to clean package cached "
     "data and reduce image size.",
     "Add '--no-cache' to 'apk add' in Dockerfile")
def _apk_cache(insts):
    for inst in insts:
        if inst.cmd == "RUN" and re.search(r"\bapk\s+(\S+\s+)*add\b",
                                           inst.args) and \
                "--no-cache" not in inst.args:
            yield inst, f"'--no-cache' is missed: 'apk add' in " \
                        f"'{inst.args}'"


@_mk("DS026", "No HEALTHCHECK defined", "LOW",
     "You should add HEALTHCHECK instruction in your docker container "
     "images to perform the health check on running containers.",
     "Add HEALTHCHECK instruction in Dockerfile")
def _healthcheck(insts):
    if not any(i.cmd == "HEALTHCHECK" for i in insts):
        first = insts[0] if insts else None
        if first is not None:
            yield None, "Add HEALTHCHECK instruction in your Dockerfile"


def _stages(insts):
    """Split instructions into build stages at each FROM."""
    stages, cur = [], []
    for inst in insts:
        if inst.cmd == "FROM" and cur:
            stages.append(cur)
            cur = []
        cur.append(inst)
    if cur:
        stages.append(cur)
    return stages


def _from_alias(inst) -> str:
    # skip flag tokens: FROM --platform=linux/amd64 img AS name
    parts = [p for p in inst.args.split() if not p.startswith("--")]
    if len(parts) >= 3 and parts[1].upper() == "AS":
        return parts[2].lower()
    return ""


@_mk("DS006", "COPY '--from' references current FROM alias", "CRITICAL",
     "COPY '--from' should point to a previous build stage, not the "
     "stage it is defined in.",
     "Point the COPY '--from' to a previous stage or external image")
def _copy_from_self(insts):
    for stage in _stages(insts):
        alias = (_from_alias(stage[0])
                 if stage and stage[0].cmd == "FROM" else "")
        if not alias:
            continue
        for inst in stage:
            if inst.cmd != "COPY":
                continue
            m = re.search(r"--from=(\S+)", inst.args)
            if m and m.group(1).lower() == alias:
                yield inst, (f"'COPY --from' should not mention its "
                             f"own FROM alias '{alias}'")


@_mk("DS007", "Multiple ENTRYPOINT instructions listed", "CRITICAL",
     "There can only be one ENTRYPOINT instruction in a Dockerfile; "
     "only the last one takes effect.",
     "Remove the extra ENTRYPOINT instructions")
def _multi_entrypoint(insts):
    for stage in _stages(insts):
        eps = [i for i in stage if i.cmd == "ENTRYPOINT"]
        for inst in eps[1:]:
            yield inst, ("There are 2 or more ENTRYPOINT instructions "
                         "in this stage; only the last one takes "
                         "effect")


@_mk("DS008", "Exposed port out of range", "CRITICAL",
     "Exposed ports must be in the 0-65535 range.",
     "Use a port number inside 0-65535")
def _port_range(insts):
    for inst in insts:
        if inst.cmd != "EXPOSE":
            continue
        for port in inst.args.split():
            num = port.split("/")[0]
            if num.isdigit() and not 0 <= int(num) <= 65535:
                yield inst, (f"'EXPOSE' instruction should use port "
                             f"numbers in 0-65535 range ({num})")


@_mk("DS009", "WORKDIR path not absolute", "HIGH",
     "For clarity and reliability, always use absolute paths in "
     "WORKDIR.",
     "Use an absolute path in the WORKDIR instruction")
def _workdir_relative(insts):
    for inst in insts:
        if inst.cmd != "WORKDIR":
            continue
        p = inst.args.strip().strip("'\"")
        if p and not p.startswith(("/", "$", "C:", "c:")):
            yield inst, (f"WORKDIR path '{p}' should be absolute")


@_mk("DS010", "RUN using 'sudo'", "CRITICAL",
     "Avoid using 'sudo' in RUN instructions: it has unpredictable "
     "TTY and signal-forwarding behavior.",
     "Do not use 'sudo' in RUN instructions")
def _run_sudo(insts):
    for inst in insts:
        if inst.cmd == "RUN" and re.search(r"\bsudo\b", inst.args):
            yield inst, "Using 'sudo' in Dockerfile should be avoided"


@_mk("DS011", "COPY with multiple sources needs a directory "
     "destination", "CRITICAL",
     "When copying multiple sources, the destination must be a "
     "directory (end with '/').",
     "End the COPY destination with '/'")
def _copy_dest_dir(insts):
    for inst in insts:
        if inst.cmd != "COPY":
            continue
        raw = inst.args.strip()
        if raw.startswith("["):
            # JSON (exec) form: parse the array for the real tokens
            try:
                parsed = json.loads(raw)
            except ValueError:
                continue
            args = [str(a) for a in parsed] \
                if isinstance(parsed, list) else []
        else:
            args = [a for a in raw.split() if not a.startswith("--")]
        if len(args) > 2 and not args[-1].endswith(("/", "\\")):
            yield inst, (f"COPY with more than two arguments requires "
                         f"the last argument to end with '/'")


@_mk("DS012", "Duplicate FROM alias", "CRITICAL",
     "Build-stage aliases must be unique.",
     "Rename the duplicated stage alias")
def _dup_alias(insts):
    seen = {}
    for inst in insts:
        if inst.cmd != "FROM":
            continue
        alias = _from_alias(inst)
        if not alias:
            continue
        if alias in seen:
            yield inst, (f"Duplicate aliases '{alias}' are defined in "
                         f"multiple FROM instructions")
        seen[alias] = inst


@_mk("DS014", "RUN using 'wget' and 'curl' together", "LOW",
     "Using both tools doubles the image dependencies; pick one.",
     "Use either 'wget' or 'curl', not both")
def _wget_and_curl(insts):
    # stages build independent images: only flag a stage using both
    for stage in _stages(insts):
        has = {"wget": False, "curl": False}
        for inst in stage:
            if inst.cmd != "RUN":
                continue
            for tool in has:
                if re.search(rf"(^|[\s;&|]){tool}\b", inst.args):
                    has[tool] = True
        if has["wget"] and has["curl"]:
            for inst in stage:
                if inst.cmd == "RUN" and \
                        re.search(r"(^|[\s;&|])curl\b", inst.args):
                    yield inst, ("Shouldn't use both curl and wget")
                    break


def _clean_missing_check(id_, install_re, clean_phrase):
    """yum/dnf/zypper share one body: install without a cache clean in
    the same RUN statement."""
    @_mk(id_, f"'{clean_phrase}' missing", "HIGH",
         "Cached package data should be cleaned after installation to "
         "reduce image size.",
         f"Add '{clean_phrase}' to the same RUN statement")
    def check(insts):
        for inst in insts:
            if inst.cmd == "RUN" and \
                    re.search(install_re, inst.args) and \
                    clean_phrase not in inst.args:
                yield inst, (f"'{clean_phrase}' is missed: "
                             f"'{inst.args}'")
    return check


_clean_missing_check("DS015", r"\byum\s+(-\S+\s+)*install\b",
                     "yum clean all")


@_mk("DS016", "Multiple CMD instructions listed", "HIGH",
     "There can only be one CMD instruction in a Dockerfile; only the "
     "last one takes effect.",
     "Remove the extra CMD instructions")
def _multi_cmd(insts):
    for stage in _stages(insts):
        cmds = [i for i in stage if i.cmd == "CMD"]
        for inst in cmds[1:]:
            yield inst, ("There are 2 or more CMD instructions in this "
                         "stage; only the last one takes effect")


_clean_missing_check("DS019", r"\bdnf\s+(-\S+\s+)*install\b",
                     "dnf clean all")
_clean_missing_check("DS020", r"\bzypper\s+(-\S+\s+)*(install|in)\b",
                     "zypper clean")


@_mk("DS021", "'apt-get install' without '-y'", "HIGH",
     "Without '-y' the build may hang on a confirmation prompt.",
     "Add '-y' (or '--yes') to 'apt-get install'")
def _apt_yes(insts):
    for inst in insts:
        if inst.cmd != "RUN":
            continue
        for m in re.finditer(r"apt-get\s+(?:-\S+\s+)*install\b[^&|;]*",
                             inst.args):
            seg = m.group(0)
            if not re.search(r"(^|\s)(-y|--yes|--assume-yes|-qq)\b",
                             seg):
                yield inst, (f"'-y' flag is missed: '{seg.strip()}'")


@_mk("DS022", "MAINTAINER is deprecated", "LOW",
     "MAINTAINER has been deprecated since Docker 1.13.0.",
     "Use LABEL maintainer=... instead")
def _maintainer(insts):
    for inst in insts:
        if inst.cmd == "MAINTAINER":
            yield inst, (f"MAINTAINER should not be used: 'MAINTAINER "
                         f"{inst.args}'")


@_mk("DS023", "Multiple HEALTHCHECK instructions listed", "CRITICAL",
     "Only one HEALTHCHECK instruction may be present; only the last "
     "one takes effect.",
     "Remove the extra HEALTHCHECK instructions")
def _multi_healthcheck(insts):
    for stage in _stages(insts):
        hcs = [i for i in stage if i.cmd == "HEALTHCHECK"]
        for inst in hcs[1:]:
            yield inst, ("There are 2 or more HEALTHCHECK "
                         "instructions in this stage; only the last "
                         "one takes effect")


@_mk("DS024", "'apt-get dist-upgrade' used", "HIGH",
     "Full distribution upgrades inside a container defeat image "
     "reproducibility.",
     "Remove 'apt-get dist-upgrade'")
def _dist_upgrade(insts):
    for inst in insts:
        if inst.cmd == "RUN" and \
                re.search(r"\bapt-get\s+(-\S+\s+)*dist-upgrade\b",
                          inst.args):
            yield inst, ("'apt-get dist-upgrade' should not be used in "
                         "Dockerfile")


@_mk("DS029", "'apt-get install' without '--no-install-recommends'",
     "HIGH",
     "Skipping recommended packages keeps images small.",
     "Add '--no-install-recommends' to 'apt-get install'")
def _apt_no_recommends(insts):
    for inst in insts:
        if inst.cmd != "RUN":
            continue
        for m in re.finditer(r"apt-get\s+(?:-\S+\s+)*install\b[^&|;]*",
                             inst.args):
            seg = m.group(0)
            if "--no-install-recommends" not in seg:
                yield inst, (f"'--no-install-recommends' is missed: "
                             f"'{seg.strip()}'")


def scan_dockerfile(path: str, content: bytes,
                    lines: list[str] | None = None
                    ) -> tuple[list[T.DetectedMisconfiguration], int]:
    """→ (failures, successes_count)."""
    text = content.decode(errors="replace")
    insts = parse_dockerfile(text)
    if not insts:
        return [], 0
    src_lines = text.splitlines()
    failures = []
    successes = 0
    for check in CHECKS:
        found = list(check.fn(insts))
        if not found:
            successes += 1
            continue
        for inst, message in found:
            m = T.DetectedMisconfiguration(
                type="dockerfile",
                id=check.id,
                avd_id=check.avd_id,
                title=check.title,
                description=check.description,
                message=message,
                namespace=f"builtin.dockerfile.{check.id}",
                resolution=check.resolution,
                severity=check.severity,
                primary_url=f"https://avd.aquasec.com/misconfig/"
                            f"{check.id.lower()}",
                status="FAIL",
            )
            if inst is not None:
                m.cause_metadata = T.CauseMetadata(
                    provider="Dockerfile", service="general",
                    start_line=inst.start_line, end_line=inst.end_line,
                    code=T.Code(lines=[
                        T.CodeLine(number=n + 1, content=src_lines[n],
                                   is_cause=True, first_cause=(
                                       n + 1 == inst.start_line),
                                   last_cause=(n + 1 == inst.end_line),
                                   highlighted=src_lines[n])
                        for n in range(inst.start_line - 1,
                                       min(inst.end_line, len(src_lines)))
                    ]))
            failures.append(m)
    return failures, successes
