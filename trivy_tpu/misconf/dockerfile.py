"""Dockerfile misconfiguration checks.

Native reimplementation of the trivy-checks dockerfile policies the
reference evaluates through rego (pkg/iac/scanners/dockerfile); check IDs
and severities follow the published AVD DS-series so findings line up."""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from .. import types as T


@dataclass
class Instruction:
    cmd: str
    args: str
    start_line: int
    end_line: int


def parse_dockerfile(content: str) -> list[Instruction]:
    out = []
    cont = None
    for i, raw in enumerate(content.splitlines(), 1):
        line = raw.strip()
        if cont is not None:
            cont.args += " " + line.rstrip("\\").strip()
            cont.end_line = i
            if not line.endswith("\\"):
                out.append(cont)
                cont = None
            continue
        if not line or line.startswith("#"):
            continue
        parts = line.split(None, 1)
        cmd = parts[0].upper()
        args = parts[1] if len(parts) > 1 else ""
        inst = Instruction(cmd=cmd, args=args.rstrip("\\").strip(),
                           start_line=i, end_line=i)
        if args.endswith("\\"):
            cont = inst
        else:
            out.append(inst)
    if cont is not None:
        out.append(cont)
    return out


@dataclass
class Check:
    id: str
    avd_id: str
    title: str
    severity: str
    description: str
    resolution: str
    fn: object = None


def _mk(id_, title, severity, description, resolution):
    def deco(fn):
        CHECKS.append(Check(id=id_,
                            avd_id=f"AVD-DS-{int(id_[2:]):04d}",
                            title=title,
                            severity=severity, description=description,
                            resolution=resolution, fn=fn))
        return fn
    return deco


CHECKS: list[Check] = []


@_mk("DS001", "':latest' tag used", "MEDIUM",
     "When using a 'FROM' statement you should use a specific tag.",
     "Add a tag to the image in the 'FROM' statement")
def _latest_tag(insts):
    for inst in insts:
        if inst.cmd != "FROM":
            continue
        image = inst.args.split()[0]
        if image.lower() == "scratch" or "$" in image:
            continue
        if "@" in image:
            continue  # digest-pinned
        tag = image.rsplit(":", 1)[1] if ":" in image.split("/")[-1] else ""
        if tag == "latest" or (not tag and ":" not in image.split("/")[-1]):
            if not tag:
                continue  # bare name without tag → DS001 flags only :latest
            yield inst, f"Specify a tag in the 'FROM' statement for image " \
                        f"'{image.rsplit(':', 1)[0]}'"


@_mk("DS002", "Image user should not be 'root'", "HIGH",
     "Running containers with 'root' user can lead to a container escape "
     "situation.",
     "Add 'USER <non root user name>' line to the Dockerfile")
def _root_user(insts):
    users = [i for i in insts if i.cmd == "USER"]
    if not users:
        last_from = next((i for i in reversed(insts) if i.cmd == "FROM"),
                         None)
        if last_from is not None:
            yield last_from, "Specify at least 1 USER command in " \
                             "Dockerfile with non-root user as argument"
        return
    last = users[-1]
    if last.args.strip().split(":")[0] in ("root", "0"):
        yield last, "Last USER command in Dockerfile should not be 'root'"


@_mk("DS004", "Port 22 exposed", "MEDIUM",
     "Exposing port 22 might allow users to SSH into the container.",
     "Remove 'EXPOSE 22' statement from the Dockerfile")
def _ssh_port(insts):
    for inst in insts:
        if inst.cmd == "EXPOSE":
            for port in inst.args.split():
                if port.split("/")[0] == "22":
                    yield inst, "Port 22 should not be exposed in Dockerfile"


@_mk("DS005", "ADD instead of COPY", "LOW",
     "You should use COPY instead of ADD unless you want to extract a "
     "tar file.",
     "Use COPY instead of ADD")
def _add_instead_of_copy(insts):
    for inst in insts:
        if inst.cmd != "ADD":
            continue
        src = inst.args.split()[0] if inst.args.split() else ""
        if re.search(r"\.(tar|tar\.gz|tgz|tar\.bz2|tar\.xz)$", src) or \
                src.startswith(("http://", "https://")):
            continue
        yield inst, f"Consider using 'COPY {inst.args}' command instead"


@_mk("DS013", "'RUN cd ...' to change directory", "MEDIUM",
     "Use WORKDIR instead of proliferating instructions like "
     "'RUN cd … && do-something'.",
     "Use WORKDIR to change directory")
def _run_cd(insts):
    for inst in insts:
        if inst.cmd == "RUN" and re.match(r"^cd\s+\S+\s*$", inst.args):
            yield inst, f"RUN should not be used to change directory: " \
                        f"'{inst.args}'. Use 'WORKDIR' statement instead."


@_mk("DS017", "'RUN <package-manager> update' instruction alone", "HIGH",
     "The instruction 'RUN <package-manager> update' should always be "
     "followed by '<package-manager> install' in the same RUN statement.",
     "Combine '<package-manager> update' and '<package-manager> install' "
     "instructions")
def _update_alone(insts):
    for inst in insts:
        if inst.cmd != "RUN":
            continue
        args = inst.args
        if re.search(r"\b(apt-get|apt|yum|apk)\s+update\b", args) and \
                not re.search(r"\b(install|add|upgrade)\b", args):
            yield inst, "The instruction 'RUN <package-manager> update' " \
                        "should always be followed by " \
                        "'<package-manager> install' in the same RUN " \
                        "statement."


@_mk("DS025", "'apk add' without '--no-cache'", "HIGH",
     "You should use 'apk add' with '--no-cache' to clean package cached "
     "data and reduce image size.",
     "Add '--no-cache' to 'apk add' in Dockerfile")
def _apk_cache(insts):
    for inst in insts:
        if inst.cmd == "RUN" and re.search(r"\bapk\s+(\S+\s+)*add\b",
                                           inst.args) and \
                "--no-cache" not in inst.args:
            yield inst, f"'--no-cache' is missed: 'apk add' in " \
                        f"'{inst.args}'"


@_mk("DS026", "No HEALTHCHECK defined", "LOW",
     "You should add HEALTHCHECK instruction in your docker container "
     "images to perform the health check on running containers.",
     "Add HEALTHCHECK instruction in Dockerfile")
def _healthcheck(insts):
    if not any(i.cmd == "HEALTHCHECK" for i in insts):
        first = insts[0] if insts else None
        if first is not None:
            yield None, "Add HEALTHCHECK instruction in your Dockerfile"


def scan_dockerfile(path: str, content: bytes,
                    lines: list[str] | None = None
                    ) -> tuple[list[T.DetectedMisconfiguration], int]:
    """→ (failures, successes_count)."""
    text = content.decode(errors="replace")
    insts = parse_dockerfile(text)
    if not insts:
        return [], 0
    src_lines = text.splitlines()
    failures = []
    successes = 0
    for check in CHECKS:
        found = list(check.fn(insts))
        if not found:
            successes += 1
            continue
        for inst, message in found:
            m = T.DetectedMisconfiguration(
                type="dockerfile",
                id=check.id,
                avd_id=check.avd_id,
                title=check.title,
                description=check.description,
                message=message,
                namespace=f"builtin.dockerfile.{check.id}",
                resolution=check.resolution,
                severity=check.severity,
                primary_url=f"https://avd.aquasec.com/misconfig/"
                            f"{check.id.lower()}",
                status="FAIL",
            )
            if inst is not None:
                m.cause_metadata = T.CauseMetadata(
                    provider="Dockerfile", service="general",
                    start_line=inst.start_line, end_line=inst.end_line,
                    code=T.Code(lines=[
                        T.CodeLine(number=n + 1, content=src_lines[n],
                                   is_cause=True, first_cause=(
                                       n + 1 == inst.start_line),
                                   last_cause=(n + 1 == inst.end_line),
                                   highlighted=src_lines[n])
                        for n in range(inst.start_line - 1,
                                       min(inst.end_line, len(src_lines)))
                    ]))
            failures.append(m)
    return failures, successes
