"""``python -m trivy_tpu.obs.perfcheck OLD.json NEW.json`` — the
perf-regression gate.

ROADMAP's standing caveat is that perf PRs ship with no way to tell a
real regression from bench noise: the bench trajectory holds one JSON
tail per round and comparing them is eyeball work. This gate makes the
comparison mechanical and noise-aware, so a recorded device round
becomes a baseline the fleet can actually hold:

  * both inputs are bench tails — the single JSON object bench.py
    prints (a BENCH_rXX.json wrapper with a ``parsed`` object is
    unwrapped automatically). Schema problems (not an object, no
    numeric metrics, NaN/Inf values) exit 2 — a malformed baseline
    must fail loudly, not silently compare nothing;
  * metrics are the numeric leaves, addressed by dotted path
    (``secrets.secret_mbps_device``). Direction is inferred from the
    name: throughput-shaped metrics (``*_per_sec``, ``*mbps``,
    ``*throughput*``, ``*speedup*``, ``*ips*``, ``*hit_rate*``) must
    not drop, latency/cost-shaped ones (``*_ms``, ``*_s``, ``*p99*``,
    ``*bytes*``, ``*waste*``, ``*compile*``, ``*shed*``, ``*failed*``)
    must not rise; unclassifiable names are reported but never gate.
    A few leaves carry a HARD cap gated on the new value alone
    (``cost_overhead_pct`` < 2 — graftcost attribution must stay
    nearly free), because relative compare against a near-zero
    healthy baseline pages on jitter;
  * noise awareness: a leaf that is a LIST of numbers is a repeat
    spread — the comparison uses medians and widens the bound by
    k·MAD/|median| (median absolute deviation, robust to one bad
    repeat), so a delta inside the scenario's own observed spread
    never pages. Scalars use the flat relative threshold
    (``--threshold``, default 10%);
  * allow-list: ``--allow metric=reason`` (repeatable) or
    ``--allow-file FILE`` (``{"allow": [{"metric":..., "reason":...}]}``)
    waives a KNOWN regression — every entry must carry a reason, like
    graftlint's ``--baseline``; a reason-less waiver exits 2.

Exit codes: 0 clean (or all regressions allow-listed), 1 unwaived
regression, 2 malformed input / bad allow-list.
"""

from __future__ import annotations

import json
import math

# name fragments that classify a metric's good direction; HIGHER is
# checked first so "mb_s" / "per_sec" never fall through to the
# lower-better "_s" suffix rule
_HIGHER = ("per_sec", "mbps", "mb_s", "throughput", "speedup",
           "hit_rate", "ips", "occupancy")
_LOWER_FRAGMENTS = ("p99", "p50", "latency", "waste", "shed", "lost",
                    "failed", "compile", "overflow", "stall",
                    "overhead")
_LOWER_SUFFIXES = ("_ms", "_s", "_seconds", "_bytes")

# hard ceilings, gated on the NEW value alone: a percentage that must
# simply stay small (graftcost's attribution overhead) has a near-zero
# healthy baseline, and relative compare against near-zero turns every
# jitter into a page — these leaves skip the relative gate and fail
# only when the fresh round exceeds the cap
_ABS_CAPS = {"cost_overhead_pct": 2.0}


class SchemaError(ValueError):
    """The input is not a valid bench tail."""


def _classify(name: str) -> str | None:
    for frag in _HIGHER:
        if frag in name:
            return "higher"
    for frag in _LOWER_FRAGMENTS:
        if frag in name:
            return "lower"
    if name.endswith(_LOWER_SUFFIXES) or "bytes" in name:
        return "lower"
    return None


def direction(path: str) -> str | None:
    """→ "higher" | "lower" | None for one dotted metric path. The
    leaf name decides first; an unclassifiable leaf inherits from the
    full path (so `graftprof.transfer_bytes.dense` reads as byte-
    shaped even though its leaf is just the path label)."""
    leaf = _classify(path.rsplit(".", 1)[-1].lower())
    if leaf is not None:
        return leaf
    return _classify(path.lower())


def _is_num(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def flatten(doc: dict, prefix: str = "") -> dict:
    """→ {dotted_path: float | [float, ...]} over the tail's numeric
    leaves; a list kept whole is a repeat spread. Non-finite values
    raise SchemaError — a NaN baseline gates nothing."""
    out: dict = {}
    for key, v in doc.items():
        path = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(v, dict):
            out.update(flatten(v, path))
        elif _is_num(v):
            if not math.isfinite(v):
                raise SchemaError(f"{path}: non-finite value {v!r}")
            out[path] = float(v)
        elif isinstance(v, list) and v and all(_is_num(x) for x in v):
            vals = [float(x) for x in v]
            if any(not math.isfinite(x) for x in vals):
                raise SchemaError(f"{path}: non-finite repeat value")
            out[path] = vals
    return out


def load_tail(path: str) -> dict:
    """Read one bench tail → its flat metric map. Raises SchemaError
    on anything that is not a usable tail."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise SchemaError(f"{path}: unreadable: {e}") from None
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]   # BENCH_rXX.json driver wrapper
    if not isinstance(doc, dict):
        raise SchemaError(f"{path}: top level is not an object")
    flat = flatten(doc)
    if not flat:
        raise SchemaError(f"{path}: no numeric metrics in tail")
    return flat


def _median(xs: list[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _mad(xs: list[float]) -> float:
    """Median absolute deviation — robust spread of a repeat list."""
    m = _median(xs)
    return _median([abs(x - m) for x in xs])


def _value_and_noise(v) -> tuple[float, float]:
    """→ (comparison value, absolute noise scale): scalars carry no
    self-described noise; repeat lists compare by median with their
    MAD as the noise scale."""
    if isinstance(v, list):
        return _median(v), _mad(v)
    return v, 0.0


def compare(old: dict, new: dict, threshold: float = 0.10,
            mad_k: float = 3.0) -> dict:
    """Diff two flat metric maps. → {"regressions": [...],
    "improvements": [...], "unclassified": [...], "missing": [...],
    "checked": n}. A metric regresses when it moved in its bad
    direction by more than max(threshold, mad_k·MAD/|median|) —
    the per-scenario repeat spread widens the bound, never narrows
    it."""
    regressions, improvements, unclassified, missing = [], [], [], []
    capped = []
    checked = 0
    # cap pass over NEW: gate capped leaves on their absolute ceiling,
    # even when the metric has no baseline yet (a fresh scenario's
    # first round must still respect the cap)
    for path in sorted(new):
        cap = _ABS_CAPS.get(path.rsplit(".", 1)[-1].lower())
        if cap is None:
            continue
        nv, _ = _value_and_noise(new[path])
        checked += 1
        if nv > cap:
            capped.append({"metric": path, "value": nv, "cap": cap})
    for path in sorted(old):
        if path not in new:
            missing.append(path)
            continue
        if path.rsplit(".", 1)[-1].lower() in _ABS_CAPS:
            continue   # gated by the cap pass, not relative drift
        d = direction(path)
        ov, onoise = _value_and_noise(old[path])
        nv, nnoise = _value_and_noise(new[path])
        if d is None:
            if ov != nv:
                unclassified.append({"metric": path, "old": ov,
                                     "new": nv})
            continue
        checked += 1
        scale = max(abs(ov), 1e-12)
        delta = (ov - nv) if d == "higher" else (nv - ov)
        rel = delta / scale
        noise_rel = mad_k * max(onoise, nnoise) / scale
        bound = max(threshold, noise_rel)
        entry = {"metric": path, "old": ov, "new": nv,
                 "direction": d, "change": round(-rel, 4)
                 if d == "higher" else round(rel, 4),
                 "bound": round(bound, 4)}
        if rel > bound:
            regressions.append(entry)
        elif rel < -bound:
            improvements.append(entry)
    return {"regressions": regressions, "improvements": improvements,
            "capped": capped, "unclassified": unclassified,
            "missing": missing, "checked": checked}


def load_allowlist(allow_args: list[str],
                   allow_file: str | None) -> dict[str, str]:
    """→ {metric: reason}. Every waiver MUST carry a non-empty reason
    (the graftlint --baseline contract) — raises SchemaError
    otherwise."""
    allow: dict[str, str] = {}
    if allow_file:
        try:
            with open(allow_file) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SchemaError(
                f"--allow-file {allow_file}: unreadable: {e}") from None
        entries = doc.get("allow") if isinstance(doc, dict) else None
        if not isinstance(entries, list):
            raise SchemaError(f"--allow-file {allow_file}: expected "
                              f'{{"allow": [...]}}')
        for i, e in enumerate(entries):
            if not isinstance(e, dict) or not e.get("metric"):
                raise SchemaError(
                    f"--allow-file entry {i}: missing metric")
            if not str(e.get("reason") or "").strip():
                raise SchemaError(
                    f"--allow-file entry {e['metric']!r}: every "
                    f"waiver must carry a reason")
            allow[str(e["metric"])] = str(e["reason"])
    for spec in allow_args:
        metric, sep, reason = spec.partition("=")
        if not sep or not metric or not reason.strip():
            raise SchemaError(
                f"--allow {spec!r}: expected metric=reason (the "
                f"reason is required)")
        allow[metric] = reason
    return allow


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m trivy_tpu.obs.perfcheck",
        description="diff two bench-tail JSON documents with "
                    "noise-aware bounds; exit 1 on an unwaived "
                    "regression, 2 on malformed input")
    ap.add_argument("old", metavar="OLD.json")
    ap.add_argument("new", metavar="NEW.json")
    ap.add_argument("--threshold", type=float, default=0.10,
                    help="flat relative regression bound for metrics "
                         "without a repeat spread (default 0.10)")
    ap.add_argument("--mad-k", type=float, default=3.0,
                    help="repeat-spread widening: bound = max("
                         "threshold, K*MAD/|median|) (default 3.0)")
    ap.add_argument("--allow", action="append", default=[],
                    metavar="METRIC=REASON",
                    help="waive one known regression (reason "
                         "required; repeatable)")
    ap.add_argument("--allow-file", default="",
                    help='JSON allow-list: {"allow": [{"metric": ..., '
                         '"reason": ...}]}')
    ap.add_argument("--quiet", action="store_true",
                    help="suppress the improvement/summary lines")
    args = ap.parse_args(argv)
    try:
        allow = load_allowlist(args.allow, args.allow_file or None)
        old = load_tail(args.old)
        new = load_tail(args.new)
    except SchemaError as e:
        print(f"perfcheck: {e}")
        return 2
    report = compare(old, new, threshold=args.threshold,
                     mad_k=args.mad_k)
    failed = []
    for r in report["regressions"]:
        reason = allow.get(r["metric"])
        if reason is not None:
            print(f"ALLOWED  {r['metric']}: {r['old']} -> {r['new']} "
                  f"({r['change']:+.1%} vs bound {r['bound']:.1%}) — "
                  f"{reason}")
        else:
            failed.append(r)
            print(f"REGRESS  {r['metric']}: {r['old']} -> {r['new']} "
                  f"({r['change']:+.1%}, bound {r['bound']:.1%})")
    for r in report["capped"]:
        reason = allow.get(r["metric"])
        if reason is not None:
            print(f"ALLOWED  {r['metric']}: {r['value']} over cap "
                  f"{r['cap']} — {reason}")
        else:
            failed.append(r)
            print(f"REGRESS  {r['metric']}: {r['value']} exceeds "
                  f"hard cap {r['cap']}")
    if not args.quiet:
        for r in report["improvements"]:
            print(f"improve  {r['metric']}: {r['old']} -> {r['new']} "
                  f"({r['change']:+.1%})")
        for path in report["missing"]:
            print(f"missing  {path}: present in OLD, absent in NEW "
                  f"(scenario skipped?)")
        flagged = len(report["regressions"]) + len(report["capped"])
        print(f"perfcheck: {report['checked']} metrics checked, "
              f"{len(failed)} regression(s), "
              f"{flagged - len(failed)} allowed, "
              f"{len(report['improvements'])} improvement(s)")
    return 1 if failed else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
