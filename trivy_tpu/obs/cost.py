"""graftcost — per-request cost attribution and tenant usage telemetry.

graftscope answers "what happened to THIS request"; graftprof answers
"what did the DEVICE do all day". Neither can say *whose scans* burned
the device: a detectd merged dispatch serves eight requests with one
launch, a memo hit serves a request with no launch at all, and a
streamed slice upload serves whoever walks the table next. graftcost
closes that gap with a request-scoped CostLedger carried on the same
contextvars graftscope rides, charged at every shared-resource seam:

  apportionment   merged dispatches (detectd, mesh cells, streamed
                  walks) split the launch's device ms and result
                  transfer bytes pro-rata by each coalesced request's
                  real (nonzero) pair share — a request that
                  contributed 0 of 1024 pairs pays 0, one that
                  contributed 512 pays half. The split happens in ONE
                  place (`_apportion`, fed by `charge_device_ms` /
                  `ledgered_transfer`), so the contract lives once,
                  like stream.ledgered_sync_join does for the shape
                  ledger.
  conservation    every charge writes the graftprof LEDGER and the
                  cost side from the SAME measurement, so summed
                  per-tenant device ms / conserved transfer bytes
                  reconcile with the ledger totals by construction.
                  Work nobody requested — warmup compiles, blameless
                  redetect sweeps, probes — runs with no request
                  ledger installed and lands in the SYSTEM tenant, so
                  nothing leaks and nothing double-counts.
                  `conservation_report()` is the reconciliation read;
                  graftstorm enforces it on every topology as the
                  `cost_conservation` invariant.
  queue vs service  admission-queue waits and detectd coalesce-window
                  waits are queue ms, kept distinct from service ms
                  (wall since ledger install minus queue): a tenant
                  whose requests are *slow* looks different from one
                  whose requests are *queued*.
  tenancy         identity arrives as the X-Trivy-Tenant header
                  (the RPC client stamps it from RemoteScanner's
                  tenant=, the router relays it; default "default"). Label cardinality is bounded by a
                  top-K-plus-"other" clamp (the PR 13 profile-reason
                  pattern): the first K distinct tenants get their own
                  series, the long tail folds into "other", and the
                  full tenant id still rides the per-request
                  X-Trivy-Cost header and trace attrs.

Surfaces: the compact X-Trivy-Cost response header (summed across
router failover hops), trivy_tpu_tenant_* series under the TPU109
catalog + strict exposition gate, the token-gated /debug/costs table
(server-local; the router aggregates a fleet-wide one from relayed
headers), the /healthz `tenants` block, and per-tenant scan-latency
burn rates in the SLO engine.

Lock discipline (graftlint TPU106 covers obs/): every mutation of
shared ledger/aggregator state happens under the owning instance
lock; charges never go inside device code (TPU107/TPU108). This
module must stay importable without the resilience/server stacks —
the client imports obs.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time

from ..metrics import METRICS

# request-scoped ledger: installed by the server handler (or a test)
# around one Scan RPC; copied onto detectd/fetch threads by the same
# contextvars.copy_context() plumbing that carries graftscope spans
_COST: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_cost", default=None)
# merged-dispatch share vector: ((ledger-or-None, weight), ...) —
# installed into the dispatch/fetch Contexts by detectd's flush so
# every charge inside the merged launch apportions instead of
# charging one victim
_SHARES: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_cost_shares", default=None)

# transfer paths that participate in the conservation contract:
# device→host result bytes. shard_upload (host→device streaming) is
# excluded — it is charged per-walk by the streaming layer and the
# ledger already reports it separately under shard_uploads.
CONSERVED_TRANSFER_PATHS = ("compact", "dense", "overflow")

# numeric ledger fields a request accumulates; secret bytes use a
# "secret_bytes.<path>" key per serving path (device / host)
_CORE_FIELDS = ("queue_ms", "device_ms", "transfer_bytes", "host_ms",
                "ingest_bytes", "ingest_ms", "sbom_parse_ms",
                "avoided_ms")


class CostLedger:
    """One request's accumulated cost. Thread-safe: detectd dispatch
    and fetch threads charge the same ledger a handler thread settles.
    `live` ledgers (the SYSTEM tenant) export device/transfer charges
    to METRICS immediately — they never settle through a request."""

    def __init__(self, tenant: str = "default", live: bool = False):
        self.tenant = tenant or "default"
        self._lock = threading.Lock()
        self._v: dict[str, float] = {}
        self._t0 = time.perf_counter()
        self._live = live
        self.outcome: str | None = None

    # ---- charging ------------------------------------------------------

    def charge(self, field: str, amount: float) -> None:
        if amount <= 0:
            return
        with self._lock:
            self._v[field] = self._v.get(field, 0.0) + float(amount)
        if self._live and field in ("device_ms", "transfer_bytes"):
            series = ("trivy_tpu_tenant_device_ms_total"
                      if field == "device_ms"
                      else "trivy_tpu_tenant_transfer_bytes_total")
            METRICS.inc(series, float(amount), tenant=self.tenant)

    # ---- reads ---------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._v)

    def value(self, field: str) -> float:
        with self._lock:
            return self._v.get(field, 0.0)

    def secret_bytes(self) -> float:
        with self._lock:
            return sum(v for k, v in self._v.items()
                       if k.startswith("secret_bytes."))

    def wall_ms(self) -> float:
        return (time.perf_counter() - self._t0) * 1e3

    def header_doc(self) -> dict:
        """→ the X-Trivy-Cost JSON document: the per-request cost split
        a client (or the router's fleet aggregator) consumes.
        service_ms is wall-since-install minus queue ms — time the
        request was being WORKED, not parked."""
        v = self.snapshot()
        queue = v.get("queue_ms", 0.0)
        doc = {
            "tenant": self.tenant,
            "queue_ms": round(queue, 3),
            "service_ms": round(max(self.wall_ms() - queue, 0.0), 3),
            "device_ms": round(v.get("device_ms", 0.0), 3),
            "transfer_bytes": int(v.get("transfer_bytes", 0.0)),
            "host_ms": round(v.get("host_ms", 0.0), 3),
            "avoided_ms": round(v.get("avoided_ms", 0.0), 3),
            "hops": 1,
        }
        for opt in ("ingest_bytes", "ingest_ms", "sbom_parse_ms"):
            if v.get(opt, 0.0) > 0:
                doc[opt] = round(v[opt], 3)
        sb = sum(val for k, val in v.items()
                 if k.startswith("secret_bytes."))
        if sb > 0:
            doc["secret_bytes"] = int(sb)
        return doc

    def header_json(self) -> str:
        return json.dumps(self.header_doc(), separators=(",", ":"))


# work nobody requested: warmup compiles, blameless redetect sweeps,
# liveness probes. They run with no request ledger installed, so every
# unattributed charge lands here instead of leaking or double-counting
# into a tenant — the other half of the conservation contract.
SYSTEM = CostLedger("system", live=True)


# ---------------------------------------------------------------------------
# charge entry points (the ONE shared helper set every seam calls)

# bench baseline switch: bench.py measures what graftcost itself
# costs by re-running a point with attribution OFF. Disabled mode
# keeps every graftprof LEDGER write (perf telemetry must not change
# under the A/B) but skips ledger install, apportionment, and settle
# exports. Conservation is meaningless while off — only the bench
# A/B uses this, always restoring True in a finally.
_ENABLED = True


def set_attribution_enabled(flag: bool) -> None:
    global _ENABLED
    _ENABLED = bool(flag)


def attribution_enabled() -> bool:
    return _ENABLED


def active() -> CostLedger | None:
    """→ the current request's ledger, or None outside a request."""
    return _COST.get()


def install_shares(shares) -> None:
    """Install the merged-dispatch share vector into the CURRENT
    context. detectd's flush calls this via Context.run on the
    dispatch and fetch Contexts it builds per round — Context.run
    mutations persist in the Context object, so every subsequent
    charge inside that round apportions."""
    _SHARES.set(tuple(shares))


def _apportion(field: str, amount: float) -> None:
    """Charge `amount` of `field` to whoever owns the current context:
    pro-rata across an installed share vector (merged dispatch), else
    the request ledger, else SYSTEM. The single place the
    apportionment contract lives."""
    if amount <= 0 or not _ENABLED:
        return
    shares = _SHARES.get()
    if shares:
        total = sum(w for _led, w in shares)
        if total > 0:
            for led, w in shares:
                if w <= 0:
                    continue
                (led or SYSTEM).charge(field, amount * (w / total))
            return
    led = _COST.get()
    (led or SYSTEM).charge(field, amount)


class _Ewma:
    """Device ms per real row, exponentially smoothed — the exchange
    rate `note_work_avoided` uses to price memo hits in ms. An
    ESTIMATE by construction (the avoided dispatch never ran); kept
    out of the conservation sums for exactly that reason."""

    def __init__(self, alpha: float = 0.2):
        self._lock = threading.Lock()
        self._alpha = alpha
        self._ms_per_row = 0.0

    def update(self, ms: float, rows: int) -> None:
        if rows <= 0 or ms < 0:
            return
        rate = ms / rows
        with self._lock:
            if self._ms_per_row == 0.0:
                self._ms_per_row = rate
            else:
                self._ms_per_row += \
                    self._alpha * (rate - self._ms_per_row)

    def rate(self) -> float:
        with self._lock:
            return self._ms_per_row


_EWMA = _Ewma()


def charge_device_ms(site: str, ms: float, real_rows: int = 0) -> None:
    """One device-side launch+sync measurement: writes the graftprof
    LEDGER and the cost side from the SAME number (the conservation
    contract), then apportions across the current context. real_rows
    (when the caller knows it) feeds the work-avoided exchange
    rate."""
    if ms <= 0:
        return
    from .perf import LEDGER
    LEDGER.note_device_ms(site, ms)
    if not _ENABLED:
        return
    _EWMA.update(ms, real_rows)
    _apportion("device_ms", ms)


def ledgered_transfer(path: str, nbytes: float) -> None:
    """Device→host result bytes: one call feeds the graftprof transfer
    ledger AND the cost apportionment, replacing the bare
    LEDGER.note_transfer at every result-fetch seam so the two sides
    cannot drift."""
    if nbytes <= 0:
        return
    from .perf import LEDGER
    LEDGER.note_transfer(path, nbytes)
    if path in CONSERVED_TRANSFER_PATHS:
        _apportion("transfer_bytes", float(nbytes))


def charge_queue_ms(ms: float, ledger: CostLedger | None = None) -> None:
    """Admission-queue or coalesce-window wait. Queue time outside any
    request context is nobody's cost — dropped, not SYSTEM's."""
    led = ledger if ledger is not None else _COST.get()
    if led is not None and ms > 0:
        led.charge("queue_ms", ms)


def charge_host_ms(ms: float) -> None:
    """Host CPU ms for a fallback join (breaker-open / device-error
    paths). Apportioned like device ms — a merged round that fell back
    still served every coalesced request."""
    _apportion("host_ms", ms)


def charge_ingest(nbytes: float, ms: float) -> None:
    """fanald layer work: decompressed bytes plus walker/analyzer
    wall ms, charged per layer on the request's own thread."""
    _apportion("ingest_bytes", nbytes)
    _apportion("ingest_ms", ms)


def charge_sbom_parse(ms: float) -> None:
    """graftbom document decode wall ms. SBOM scans never bill fanal
    bytes — the document IS the inventory — so parse time is its own
    field rather than riding ingest_ms, keeping the archive-vs-SBOM
    cost split legible in /debug/costs."""
    _apportion("sbom_parse_ms", ms)


def charge_secret_bytes(path: str, nbytes: float) -> None:
    """Secrets-engine scanned bytes by serving path ("device" /
    "host")."""
    _apportion(f"secret_bytes.{path}", nbytes)


def note_work_avoided(units: int,
                      ledger: CostLedger | None = None) -> None:
    """Memo/cache replay — and graftfeed's merged-dispatch dedup:
    `units` detect units (pairs) served without dispatching. Priced in
    ms via the EWMA exchange rate — an estimate, surfaced as
    avoided_ms and excluded from conservation. Pass `ledger` to bill a
    specific request directly (detectd credits each coalesced
    request's collapsed duplicates from the dispatcher thread, where
    no request context is installed — the charge_queue_ms idiom);
    without one the current context's shares/ledger/SYSTEM chain
    applies."""
    if units <= 0 or not _ENABLED:
        return
    ms = units * _EWMA.rate()
    if ms <= 0:
        return
    if ledger is not None:
        ledger.charge("avoided_ms", ms)
        return
    _apportion("avoided_ms", ms)


@contextlib.contextmanager
def request_ledger(tenant: str):
    """Install a fresh CostLedger for one request on the current
    context (the server handler wraps _do_post in this); yields the
    ledger so the caller can stamp the outcome and settle it."""
    led = CostLedger(tenant)
    if not _ENABLED:
        # bench A/B baseline: the handler still gets a ledger object
        # to stamp outcomes on, but nothing installs, charges, or
        # exports — active() stays None so no header is stamped
        yield led
        return
    token = _COST.set(led)
    try:
        yield led
    finally:
        _COST.reset(token)


# ---------------------------------------------------------------------------
# tenant aggregation (top-K + "other" cardinality clamp)

_TENANT_MAX_LEN = 64


def normalize_tenant(raw: str | None) -> str:
    """Syntactic clamp for hostile tenant ids. X-Trivy-Tenant is
    attacker-controlled: an oversized value is truncated to
    _TENANT_MAX_LEN chars, control / non-printable characters are
    squashed to "_", and an empty or all-junk value falls back to
    "default". This runs at the server door BEFORE the id can mint
    quota state, a ledger, or a metric label. Cardinality bombs (10k
    *distinct* well-formed names) are the next layer's job: quota
    buckets and metric labels key on TENANTS.resolve(), whose top-K
    clamp folds the long tail into "other"."""
    if not raw:
        return "default"
    cleaned = "".join(
        ch if ch.isprintable() else "_"
        for ch in raw[:_TENANT_MAX_LEN])
    cleaned = cleaned.strip()
    return cleaned or "default"


def _new_tenant_row() -> dict:
    return {"scans": {}, "queue_ms": 0.0, "service_ms": 0.0,
            "device_ms": 0.0, "transfer_bytes": 0.0, "host_ms": 0.0,
            "ingest_bytes": 0.0, "ingest_ms": 0.0,
            "sbom_parse_ms": 0.0,
            "secret_bytes": 0.0, "avoided_ms": 0.0}


class TenantAggregator:
    """Per-tenant running totals behind /debug/costs, /healthz, and
    the trivy_tpu_tenant_* series. Cardinality is bounded: the first
    `top_k` distinct tenant ids get their own label, everything after
    folds into "other" (the PR 13 profile-reason clamp) — the full id
    still rides the X-Trivy-Cost header and span attrs. "default" and
    "system" are reserved rows outside the K budget."""

    RESERVED = ("default", "system", "other")

    def __init__(self, top_k: int = 8):
        self._lock = threading.Lock()
        self.top_k = int(top_k)
        self._rows: dict[str, dict] = {
            "default": _new_tenant_row(),
            "system": _new_tenant_row(),
        }

    def configure(self, top_k: int | None = None) -> None:
        with self._lock:
            if top_k is not None:
                self.top_k = int(top_k)

    def resolve(self, tenant: str) -> str:
        """→ the bounded label for `tenant`, minting its row if the K
        budget allows."""
        t = tenant or "default"
        with self._lock:
            if t in self._rows:
                return t
            named = sum(1 for k in self._rows
                        if k not in self.RESERVED)
            if named >= self.top_k:
                self._rows.setdefault("other", _new_tenant_row())
                return "other"
            self._rows[t] = _new_tenant_row()
            return t

    def _fold_numbers(self, label: str, doc: dict,
                      outcome: str | None) -> None:
        with self._lock:
            row = self._rows.setdefault(label, _new_tenant_row())
            for field in ("queue_ms", "service_ms", "device_ms",
                          "transfer_bytes", "host_ms", "ingest_bytes",
                          "ingest_ms", "sbom_parse_ms",
                          "secret_bytes", "avoided_ms"):
                row[field] += float(doc.get(field, 0.0))
            if outcome:
                row["scans"][outcome] = \
                    row["scans"].get(outcome, 0) + 1

    def _export(self, label: str, doc: dict,
                outcome: str | None) -> None:
        METRICS.inc("trivy_tpu_tenant_device_ms_total",
                    float(doc.get("device_ms", 0.0)), tenant=label)
        METRICS.inc("trivy_tpu_tenant_transfer_bytes_total",
                    float(doc.get("transfer_bytes", 0.0)),
                    tenant=label)
        avoided = float(doc.get("avoided_ms", 0.0))
        if avoided > 0:
            METRICS.inc("trivy_tpu_tenant_work_avoided_ms_total",
                        avoided, tenant=label)
        METRICS.observe("trivy_tpu_tenant_queue_ms",
                        float(doc.get("queue_ms", 0.0)), tenant=label)
        if outcome:
            METRICS.inc("trivy_tpu_tenant_scans_total", tenant=label,
                        outcome=outcome)

    def settle(self, ledger: CostLedger,
               outcome: str | None = None) -> str:
        """Fold one finished request's ledger into its (clamped)
        tenant row and export the tenant series. → the bounded
        label."""
        if not _ENABLED:
            return "default"
        label = self.resolve(ledger.tenant)
        doc = ledger.header_doc()
        self._fold_numbers(label, doc, outcome)
        self._export(label, doc, outcome)
        return label

    def fold_doc(self, doc: dict, outcome: str | None = None,
                 export: bool = False) -> str:
        """Fold one X-Trivy-Cost document (already merged across hops
        by the router) into the aggregate — the fleet-wide view the
        router's /debug/costs serves."""
        label = self.resolve(str(doc.get("tenant", "") or "default"))
        self._fold_numbers(label, doc, outcome)
        if export:
            self._export(label, doc, outcome)
        return label

    def labels(self) -> list[str]:
        with self._lock:
            return list(self._rows)

    def table(self, include_system_live: bool = True) -> dict:
        """→ {tenant: totals row} — the /debug/costs body. The SYSTEM
        ledger never settles, so its live totals merge into the
        "system" row here."""
        with self._lock:
            out = {k: {**{f: (round(v, 3)
                             if isinstance(v, float) else v)
                          for f, v in row.items() if f != "scans"},
                       "scans": dict(row["scans"])}
                   for k, row in self._rows.items()}
        if include_system_live:
            live = SYSTEM.snapshot()
            row = out.setdefault("system",
                                 {**_new_tenant_row(), "scans": {}})
            for field, val in live.items():
                key = ("secret_bytes"
                       if field.startswith("secret_bytes.") else field)
                if key in row:
                    row[key] = round(row[key] + val, 3)
        return out

    def totals(self) -> dict:
        """→ summed device_ms / transfer_bytes across every row plus
        the live SYSTEM ledger — the attributed side of the
        conservation equation."""
        dev = xfer = 0.0
        with self._lock:
            for row in self._rows.values():
                dev += row["device_ms"]
                xfer += row["transfer_bytes"]
        live = SYSTEM.snapshot()
        dev += live.get("device_ms", 0.0)
        xfer += live.get("transfer_bytes", 0.0)
        return {"device_ms": dev, "transfer_bytes": xfer}

    def healthz_block(self, include_system_live: bool = True) -> dict:
        """→ the /healthz `tenants` block: per-tenant scan counts and
        the headline cost split, small enough to read at 3am. The
        router's fleet aggregator passes include_system_live=False —
        the live SYSTEM ledger is the REPLICA process's background
        work, not something relayed headers attributed."""
        table = self.table(include_system_live)
        return {
            t: {"scans": sum(row["scans"].values()),
                "device_ms": round(row["device_ms"], 3),
                "transfer_bytes": int(row["transfer_bytes"]),
                "queue_ms": round(row["queue_ms"], 3),
                "avoided_ms": round(row["avoided_ms"], 3)}
            for t, row in table.items()
        }

    def reset_for_tests(self) -> None:
        with self._lock:
            self._rows = {"default": _new_tenant_row(),
                          "system": _new_tenant_row()}


TENANTS = TenantAggregator()


# ---------------------------------------------------------------------------
# conservation + debug surfaces

def conservation_report(rel_tol: float = 0.01,
                        abs_tol_ms: float = 0.5,
                        abs_tol_bytes: float = 4096.0) -> dict:
    """Reconcile the attributed cost totals (tenant rows + live
    SYSTEM) against the graftprof dispatch LEDGER. Both sides are
    written from the same measurements by charge_device_ms /
    ledgered_transfer, so they agree by construction once traffic
    quiesces; the tolerances absorb float pro-rata splits and
    charges racing the two reads."""
    from .perf import LEDGER
    agg = LEDGER.aggregate()
    ledger_ms = float(agg.get("device_ms_total", 0.0))
    ledger_bytes = float(sum(
        int(agg.get("transfer_bytes", {}).get(p, 0))
        for p in CONSERVED_TRANSFER_PATHS))
    att = TENANTS.totals()

    def _ok(a: float, b: float, abs_tol: float) -> bool:
        return abs(a - b) <= max(abs_tol, rel_tol * max(a, b))

    return {
        "device_ms": {
            "ledger": round(ledger_ms, 3),
            "attributed": round(att["device_ms"], 3),
            "ok": _ok(ledger_ms, att["device_ms"], abs_tol_ms),
        },
        "transfer_bytes": {
            "ledger": int(ledger_bytes),
            "attributed": int(att["transfer_bytes"]),
            "ok": _ok(ledger_bytes, att["transfer_bytes"],
                      abs_tol_bytes),
        },
    }


COSTS_SCHEMA = "trivy-tpu-costs/1"


def debug_costs_payload() -> dict:
    """→ the token-gated /debug/costs body (server-local; the router
    builds its fleet-wide variant from relayed headers)."""
    return {
        "schema": COSTS_SCHEMA,
        "pid": os.getpid(),
        "tenants": TENANTS.table(),
        "conservation": conservation_report(),
        "avoided_ms_per_row_ewma": round(_EWMA.rate(), 6),
    }


def merge_cost_docs(docs: list[dict]) -> dict:
    """Sum X-Trivy-Cost documents across router failover hops into the
    ONE header the client sees: numeric fields add (each hop's queue
    and service time was really spent), hops accumulate, tenant comes
    from the last hop that stated one."""
    out: dict = {"tenant": "default", "hops": 0}
    for doc in docs:
        if not isinstance(doc, dict):
            continue
        for k, v in doc.items():
            if k == "tenant":
                if v:
                    out["tenant"] = v
            elif k == "hops":
                out["hops"] += int(v) if isinstance(v, (int, float)) \
                    else 1
            elif isinstance(v, (int, float)):
                out[k] = round(out.get(k, 0) + v, 3)
    for field in ("queue_ms", "service_ms", "device_ms",
                  "transfer_bytes", "host_ms", "avoided_ms"):
        out.setdefault(field, 0)
    out["transfer_bytes"] = int(out["transfer_bytes"])
    return out


def parse_cost_header(raw: str) -> dict | None:
    """Parse one X-Trivy-Cost header value; None on junk (a cost
    header must never sink the response that carries it)."""
    if not raw:
        return None
    try:
        doc = json.loads(raw)
    except (ValueError, TypeError):
        return None
    return doc if isinstance(doc, dict) else None


def reset_for_tests() -> None:
    """Reset every module singleton (the SYSTEM ledger keeps its
    identity — context snapshots hold references to it)."""
    TENANTS.reset_for_tests()
    with SYSTEM._lock:
        SYSTEM._v = {}
    with _EWMA._lock:
        _EWMA._ms_per_row = 0.0
