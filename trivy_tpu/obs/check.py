"""``python -m trivy_tpu.obs.check`` — offline graftwatch validator.

Incident files and assembled trace dumps are the artifacts an operator
ships around (bug reports, postmortems) and the artifacts tooling
ingests — a malformed one discovered at read time is evidence lost.
This validator checks them offline, with no server running:

  * schema: incident files must carry the trivy-tpu-incident/1 shape
    (reason, captured_unix, spans/logs/pinned); trace dumps must be
    Chrome trace-event documents whose "X" events carry the graftscope
    span args (span_id/trace_id/parent_id) with numeric ts/dur;
  * span-edge acyclicity: parent pointers must form a forest — a
    cycle (possible only through id collision or a corrupted merge)
    would hang any consumer that walks parents;
  * id discipline: duplicate span ids inside one document are flagged
    (the collect assembler dedupes; a file that still has duplicates
    was built wrong);
  * graftstorm replay artifacts (trivy-tpu-storm-replay/1): the
    schedule grammar and load parameters `--replay` needs, plus the
    embedded incident document when one was captured;
  * graftprof live-capture manifests (trivy-tpu-profile/1): the
    reason/timing fields and a non-empty artifact file list — an
    empty capture is a profile that profiled nothing;
  * graftcost documents (trivy-tpu-costs/1): /debug/costs bodies and
    the merged fleet doc `obs.collect --costs` assembles — the tenant
    table's numeric totals, the scans outcome map, and the
    conservation block's ledger/attributed/ok triples.

Wired into tier-1 alongside graftlint (tests/test_graftwatch.py runs
it over freshly produced incidents and trace dumps, plus corrupted
variants). Exit 0 clean, 1 findings, 2 unreadable input.
"""

from __future__ import annotations

import json


def _walk_parents(span_id: str, parents: dict[str, str]) -> str | None:
    """Follow parent pointers from span_id; → an error string on a
    cycle, None when the chain terminates."""
    seen = {span_id}
    cur = parents.get(span_id, "")
    steps = 0
    while cur:
        if cur in seen:
            return (f"span {span_id}: parent chain cycles back "
                    f"through {cur}")
        seen.add(cur)
        cur = parents.get(cur, "")
        steps += 1
        if steps > len(parents) + 1:
            return f"span {span_id}: parent chain does not terminate"
    return None


def _check_span_set(spans: list[dict], where: str) -> list[str]:
    """Shared span-list validation: required fields, types, duplicate
    ids, parent acyclicity."""
    problems: list[str] = []
    parents: dict[str, str] = {}
    for i, s in enumerate(spans):
        if not isinstance(s, dict):
            problems.append(f"{where}[{i}]: not an object")
            continue
        sid = s.get("span_id")
        if not sid or not isinstance(sid, str):
            problems.append(f"{where}[{i}]: missing span_id")
            continue
        if not isinstance(s.get("name"), str) or not s.get("name"):
            problems.append(f"{where}[{i}] ({sid}): missing name")
        for field in ("ts_unix", "dur_ms"):
            v = s.get(field)
            if not isinstance(v, (int, float)) or v < 0:
                problems.append(
                    f"{where}[{i}] ({sid}): bad {field} {v!r}")
        if sid in parents:
            problems.append(f"{where}: duplicate span id {sid}")
            continue
        parents[sid] = s.get("parent_id") or ""
    for sid in parents:
        err = _walk_parents(sid, parents)
        if err:
            problems.append(f"{where}: {err}")
    return problems


def check_incident(doc: dict) -> list[str]:
    """Validate one incident document (recorder.FlightRecorder.SCHEMA)."""
    problems: list[str] = []
    if doc.get("schema") != "trivy-tpu-incident/1":
        problems.append(f"unknown incident schema {doc.get('schema')!r}")
    if not isinstance(doc.get("reason"), str) or not doc.get("reason"):
        problems.append("missing reason")
    if not isinstance(doc.get("captured_unix"), (int, float)):
        problems.append("missing captured_unix")
    for field in ("spans", "logs", "events"):
        if not isinstance(doc.get(field), list):
            problems.append(f"missing {field} list")
    if not isinstance(doc.get("pinned"), dict):
        problems.append("missing pinned map")
    if isinstance(doc.get("spans"), list):
        problems += _check_span_set(doc["spans"], "spans")
    if isinstance(doc.get("pinned"), dict):
        for tid, entry in doc["pinned"].items():
            if not isinstance(entry, dict) \
                    or not isinstance(entry.get("spans"), list):
                problems.append(f"pinned[{tid}]: malformed entry")
                continue
            problems += _check_span_set(entry["spans"],
                                        f"pinned[{tid}]")
            for s in entry["spans"]:
                if isinstance(s, dict) and \
                        s.get("trace_id") not in ("", tid):
                    problems.append(
                        f"pinned[{tid}]: span {s.get('span_id')} "
                        f"belongs to trace {s.get('trace_id')}")
    if isinstance(doc.get("logs"), list):
        for i, rec in enumerate(doc["logs"]):
            if not isinstance(rec, dict) or "msg" not in rec:
                problems.append(f"logs[{i}]: malformed record")
    return problems


def check_trace(doc: dict) -> list[str]:
    """Validate one Chrome trace-event document (graftscope export or
    collect.assemble output)."""
    problems: list[str] = []
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return ["missing traceEvents list"]
    parents: dict[str, str] = {}
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"traceEvents[{i}]: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            problems.append(f"traceEvents[{i}]: unknown phase {ph!r}")
            continue
        if ph != "X":
            continue   # instants/metadata carry no span identity
        missing = [k for k in ("name", "ts", "dur", "pid", "tid",
                               "args") if k not in ev]
        if missing:
            problems.append(
                f"traceEvents[{i}]: missing {', '.join(missing)}")
            continue
        for field in ("ts", "dur"):
            if not isinstance(ev[field], (int, float)) \
                    or ev[field] < 0:
                problems.append(
                    f"traceEvents[{i}]: bad {field} {ev[field]!r}")
        args = ev["args"]
        sid = args.get("span_id") if isinstance(args, dict) else None
        if not sid:
            problems.append(f"traceEvents[{i}]: args.span_id missing")
            continue
        if sid in parents:
            problems.append(f"duplicate span id {sid}")
            continue
        parents[sid] = args.get("parent_id") or ""
    for sid in parents:
        err = _walk_parents(sid, parents)
        if err:
            problems.append(err)
    return problems


def check_storm_replay(doc: dict) -> list[str]:
    """Validate one graftstorm failing-schedule replay artifact
    (resilience.storm.REPLAY_SCHEMA): the schedule grammar, the load
    parameters `--replay` needs to reproduce the run, and — when an
    incident was captured with it — the embedded incident document."""
    problems: list[str] = []
    sched = doc.get("schedule")
    if not isinstance(sched, dict):
        problems.append("missing schedule object")
    else:
        for field in ("seed", "topology", "horizon_ms"):
            if field not in sched:
                problems.append(f"schedule: missing {field}")
        events = sched.get("events")
        if not isinstance(events, list):
            problems.append("schedule: missing events list")
        else:
            for i, ev in enumerate(events):
                if not isinstance(ev, dict):
                    problems.append(f"events[{i}]: not an object")
                    continue
                kind = ev.get("kind", "failpoint")
                if kind not in ("failpoint", "kill_replica",
                                "swap_table", "db_swap",
                                "hostile_layer", "host_loss",
                                "adversarial_tenant"):
                    problems.append(
                        f"events[{i}]: unknown kind {kind!r}")
                if kind == "adversarial_tenant" and (
                        not isinstance(ev.get("arg"), (int, float))
                        or ev["arg"] < 1):
                    # arg is the flood's burst size; a replay with a
                    # zero-request flood reproduces nothing
                    problems.append(
                        f"events[{i}]: adversarial_tenant with bad "
                        f"flood size {ev.get('arg')!r}")
                if kind == "hostile_layer" and \
                        ev.get("variant") not in ("truncated",
                                                  "bomb"):
                    problems.append(
                        f"events[{i}]: hostile_layer with unknown "
                        f"variant {ev.get('variant')!r}")
                if not isinstance(ev.get("at_ms"), (int, float)) \
                        or ev["at_ms"] < 0:
                    problems.append(
                        f"events[{i}]: bad at_ms {ev.get('at_ms')!r}")
                if kind == "failpoint" and not ev.get("site"):
                    problems.append(f"events[{i}]: failpoint without "
                                    f"a site")
    load = doc.get("load")
    if not isinstance(load, dict):
        problems.append("missing load object")
    else:
        for field in ("requests", "concurrency", "load_seed"):
            if not isinstance(load.get(field), int):
                problems.append(f"load: missing {field}")
        # tenants is optional (older replays predate the graftcost
        # tenant-mix knob); when present it must be a positive int or
        # --replay cannot reproduce the recorded tenant round-robin
        if "tenants" in load and (
                not isinstance(load["tenants"], int)
                or load["tenants"] < 1):
            problems.append(
                f"load: bad tenants {load['tenants']!r}")
        # graftfair tenant-quota knobs: optional (older replays
        # predate them); when present they must be non-negative
        # numbers or --replay arms different quotas than the run
        for field in ("admit_tenant_max_active",
                      "admit_tenant_max_queue", "admit_tenant_rate"):
            if field in load and (
                    not isinstance(load[field], (int, float))
                    or load[field] < 0):
                problems.append(
                    f"load: bad {field} {load[field]!r}")
    if not isinstance(doc.get("violations"), dict):
        problems.append("missing violations map")
    incident = doc.get("incident")
    if incident is not None:
        if not isinstance(incident, dict):
            problems.append("incident is not an object")
        else:
            problems += [f"incident: {p}"
                         for p in check_incident(incident)]
    return problems


def check_profile(doc: dict) -> list[str]:
    """Validate one graftprof live-capture manifest
    (trivy-tpu-profile/1, written next to the jax.profiler artifact
    dir by obs.perf.Profiler.capture)."""
    problems: list[str] = []
    if doc.get("schema") != "trivy-tpu-profile/1":
        problems.append(f"unknown profile schema {doc.get('schema')!r}")
    if not isinstance(doc.get("reason"), str) or not doc.get("reason"):
        problems.append("missing reason")
    for field in ("requested_ms", "duration_ms", "started_unix"):
        v = doc.get(field)
        if not isinstance(v, (int, float)) or v < 0:
            problems.append(f"bad {field} {v!r}")
    if not isinstance(doc.get("artifact_dir"), str) \
            or not doc.get("artifact_dir"):
        problems.append("missing artifact_dir")
    files = doc.get("files")
    if not isinstance(files, list) \
            or not all(isinstance(f, str) for f in files):
        problems.append("missing files list")
    elif not files:
        # a capture that produced no artifact files profiled nothing —
        # the operator shipped an empty directory
        problems.append("capture produced no profile artifacts")
    return problems


def check_costs(doc: dict) -> list[str]:
    """Validate one graftcost document (trivy-tpu-costs/1): a server's
    /debug/costs body, the router's fleet-scope table, or the merged
    fleet doc `obs.collect --costs` assembles. The tenant table is the
    contract: every row carries the numeric totals fields plus a scans
    outcome map; the optional conservation block carries the
    ledger/attributed/ok triple per axis."""
    problems: list[str] = []
    if doc.get("schema") != "trivy-tpu-costs/1":
        problems.append(f"unknown costs schema {doc.get('schema')!r}")
    tenants = doc.get("tenants")
    if not isinstance(tenants, dict):
        problems.append("missing tenants table")
    else:
        for tenant, row in tenants.items():
            if not isinstance(row, dict):
                problems.append(f"tenants[{tenant}]: not an object")
                continue
            for field in ("queue_ms", "service_ms", "device_ms",
                          "transfer_bytes", "host_ms", "ingest_bytes",
                          "ingest_ms", "secret_bytes", "avoided_ms"):
                v = row.get(field)
                if not isinstance(v, (int, float)) or v < 0:
                    problems.append(
                        f"tenants[{tenant}]: bad {field} {v!r}")
            scans = row.get("scans")
            if not isinstance(scans, dict) or not all(
                    isinstance(n, int) for n in scans.values()):
                problems.append(
                    f"tenants[{tenant}]: malformed scans map")
    conservation = doc.get("conservation")
    if conservation is not None:
        if not isinstance(conservation, dict):
            problems.append("conservation is not an object")
        else:
            for axis in ("device_ms", "transfer_bytes"):
                rec = conservation.get(axis)
                if not isinstance(rec, dict):
                    problems.append(f"conservation: missing {axis}")
                    continue
                for field in ("ledger", "attributed"):
                    if not isinstance(rec.get(field), (int, float)):
                        problems.append(
                            f"conservation[{axis}]: bad {field} "
                            f"{rec.get(field)!r}")
                if not isinstance(rec.get("ok"), bool):
                    problems.append(
                        f"conservation[{axis}]: missing ok verdict")
    # fleet-merged docs carry per-source fragments; each must itself
    # be a costs doc (or an unreachable-process error stub)
    sources = doc.get("sources")
    if sources is not None:
        if not isinstance(sources, list):
            problems.append("sources is not a list")
        else:
            for i, frag in enumerate(sources):
                if not isinstance(frag, dict):
                    problems.append(f"sources[{i}]: not an object")
                    continue
                if frag.get("error"):
                    continue   # unreachable process, recorded as such
                problems += [f"sources[{i}]: {p}"
                             for p in check_costs(frag)]
    return problems


def check_file(path: str) -> list[str]:
    """Validate one file, auto-detecting its kind by content."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f"unreadable: {e}"]
    if not isinstance(doc, dict):
        return ["top level is not an object"]
    if "traceEvents" in doc:
        return check_trace(doc)
    if doc.get("schema", "").startswith("trivy-tpu-storm-replay"):
        return check_storm_replay(doc)
    if doc.get("schema", "").startswith("trivy-tpu-profile"):
        return check_profile(doc)
    if doc.get("schema", "").startswith("trivy-tpu-costs"):
        return check_costs(doc)
    if "schema" in doc or "reason" in doc:
        return check_incident(doc)
    return ["neither a trace dump (traceEvents), an incident file "
            "(schema/reason), a profile manifest, nor a storm replay "
            "artifact"]


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m trivy_tpu.obs.check",
        description="validate graftwatch incident files and trace "
                    "dumps offline (schema + span-edge acyclicity)")
    ap.add_argument("paths", nargs="+", metavar="FILE")
    ap.add_argument("--quiet", action="store_true",
                    help="suppress per-file OK lines")
    args = ap.parse_args(argv)
    bad = unreadable = 0
    for path in args.paths:
        problems = check_file(path)
        if not problems:
            if not args.quiet:
                print(f"{path}: OK")
            continue
        if problems[0].startswith("unreadable:"):
            unreadable += 1
        bad += 1
        for p in problems:
            print(f"{path}: {p}")
    if unreadable:
        return 2
    return 1 if bad else 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
