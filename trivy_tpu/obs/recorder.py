"""graftwatch flight recorder: the always-on span/log ring.

graftscope's COLLECTOR (trace.py) is opt-in — it exists to dump a
complete Chrome trace of a run the operator asked to record. In
production nobody asked, and the trace you need is the one of the scan
that just misbehaved. The flight recorder closes that gap: every
finished span and every log record lands in a bounded ring buffer,
always, so the last few seconds of pipeline history are available the
moment something trips.

  ring        fixed-size slot arrays for spans and log records. The
              hot-path append is LOCK-FREE: an itertools counter
              (atomic in CPython — its __next__ is one C call) hands
              each writer a distinct slot, so concurrent handler
              threads never contend on a lock per span. Memory is
              bounded by construction — the ring never grows.
  pinning     tail-based retention. Most traces age out of the ring
              within seconds under load; traces worth keeping are
              PINNED into a side store that churn cannot evict:
              slow root spans (over `slow_trace_ms`), spans that
              recorded an error attribute, and every trace touching a
              watchdog trip, breaker transition, mesh rebuild, or
              fleet failover (the resilience stack calls note_event).
  incidents   auto-capture. A breaker opening or a failpoint-injected
              fault snapshots the ring + pins to a timestamped JSON
              file under `incident_dir` (cooldown-limited so a fault
              storm writes one file, not thousands). /debug/incidents
              lists them; `python -m trivy_tpu.obs.check` validates
              them offline.

The recorder exposes the per-process bounded buffer that
`/debug/traces?trace_id=` serves (server/listen.py, fleet/router.py)
and `trivy_tpu.obs.collect` assembles across processes.
"""

from __future__ import annotations

import itertools
import json
import os
import re
import tempfile
import threading
import time

# span names that root a request/scan: only these pin a trace for
# being slow — a slow inner span is attributed through its root
_ROOT_SPANS = ("scan", "server.rpc", "router.rpc", "client.scan")

_SLUG_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


def span_to_json(s) -> dict:
    """Serialize one trace.Span (duck-typed: recorder must not import
    trace — trace imports the recorder)."""
    attrs = {}
    for k, v in s.attrs.items():
        if isinstance(v, (str, int, float, bool)) or v is None:
            attrs[k] = v
        else:
            attrs[k] = str(v)
    return {
        "name": s.name,
        "trace_id": s.trace_id,
        "span_id": s.span_id,
        "parent_id": s.parent_id,
        "ts_unix": round(s.wall_start, 6),
        "dur_ms": round(s.dur * 1e3, 3),
        "cpu_ms": round(s.cpu * 1e3, 3),
        "thread_id": s.thread_id,
        "attrs": attrs,
    }


class FlightRecorder:
    """Process-wide always-on recorder (RECORDER, shared like METRICS).

    Lock discipline (graftlint TPU106 covers this module): the ring
    slot stores are lock-free by design — each writer owns a distinct
    slot index from the atomic counter, so they are intentionally NOT
    under the lock and the slot arrays are never mutated under it.
    The pin store, incident clock, and event list are ordinary shared
    containers and every mutation of those happens under `_lock`."""

    SCHEMA = "trivy-tpu-incident/1"

    def __init__(self, span_slots: int = 4096, log_slots: int = 1024):
        self._lock = threading.Lock()
        # rings: rebound wholesale on configure(), slot-stored lock-free
        # on the hot path; readers take a local ref so a concurrent
        # resize can never index past the array they snapshotted
        self._span_ring = self._new_ring(span_slots)
        self._span_ctr = itertools.count()
        self._log_ring = self._new_ring(log_slots)
        self._log_ctr = itertools.count()
        # pinned traces: trace_id → {"reason", "pinned_unix", spans: []}
        self._pins: dict = {}
        self._pin_tids: frozenset = frozenset()
        self._events: list = []   # recent notable events (bounded)
        self.max_pinned = 32
        self.max_spans_per_pin = 512
        self.max_events = 256
        self.slow_trace_s = 1.0
        self.incident_cooldown_s = 30.0
        self.incident_dir = os.environ.get(
            "TRIVY_TPU_INCIDENT_DIR",
            os.path.join(tempfile.gettempdir(), "trivy-tpu-incidents"))
        self._last_incident = 0.0
        self._incident_seq = itertools.count()

    @staticmethod
    def _new_ring(n: int) -> list:
        # NOT a container literal: the ring is the one structure whose
        # writes stay outside the lock (see class docstring)
        return list(itertools.repeat(None, max(int(n), 16)))

    def configure(self, incident_dir: str | None = None,
                  slow_trace_ms: float | None = None,
                  incident_cooldown_s: float | None = None,
                  span_slots: int | None = None,
                  log_slots: int | None = None) -> None:
        if incident_dir is not None:
            self.incident_dir = incident_dir
        if slow_trace_ms is not None:
            self.slow_trace_s = slow_trace_ms / 1e3
        if incident_cooldown_s is not None:
            self.incident_cooldown_s = incident_cooldown_s
        if span_slots is not None:
            self._span_ring = self._new_ring(span_slots)
        if log_slots is not None:
            self._log_ring = self._new_ring(log_slots)

    # ---- hot path ------------------------------------------------------

    def record_span(self, s) -> None:
        """Called by trace.span() on every finished span. Ring append
        is one counter bump + one slot store; the pin checks are plain
        reads unless the trace is actually pinned/pin-worthy."""
        ring = self._span_ring
        ring[next(self._span_ctr) % len(ring)] = s
        tids = self._pin_tids
        if s.trace_id and s.trace_id in tids:
            self._append_pinned(s)
            return
        if s.dur >= self.slow_trace_s and s.name in _ROOT_SPANS:
            self.pin(s.trace_id, "slow_trace")
        elif "error" in s.attrs:
            self.pin(s.trace_id, "error")

    def record_log(self, rec: dict) -> None:
        """Called by the log handler (log.RecorderHandler) per record."""
        ring = self._log_ring
        ring[next(self._log_ctr) % len(ring)] = rec

    # ---- pinning -------------------------------------------------------

    def _append_pinned(self, s) -> None:
        with self._lock:
            entry = self._pins.get(s.trace_id)
            if entry is not None \
                    and len(entry["spans"]) < self.max_spans_per_pin:
                entry["spans"].append(s)

    def pin(self, trace_id: str, reason: str) -> None:
        """Pin one trace: its spans already in the ring are copied to
        the pin store and future spans append there too, so churn can
        never age an incident trace out."""
        if not trace_id:
            return
        existing = [s for s in self._span_ring
                    if s is not None and s.trace_id == trace_id]
        with self._lock:
            if trace_id in self._pins:
                return
            if len(self._pins) >= self.max_pinned:
                # evict the oldest pin — tail-based retention bounds
                # the pin store the same way the ring bounds itself
                oldest = min(self._pins,
                             key=lambda t: self._pins[t]["pinned_unix"])
                del self._pins[oldest]
            self._pins[trace_id] = {
                "reason": reason,
                "pinned_unix": time.time(),
                "spans": existing[:self.max_spans_per_pin],
            }
            self._pin_tids = frozenset(self._pins)

    def pinned(self) -> dict:
        """→ {trace_id: {reason, pinned_unix, spans: [json]}}."""
        with self._lock:
            snap = {t: dict(e) for t, e in self._pins.items()}
        return {t: {"reason": e["reason"],
                    "pinned_unix": round(e["pinned_unix"], 3),
                    "spans": [span_to_json(s) for s in e["spans"]]}
                for t, e in snap.items()}

    # ---- events --------------------------------------------------------

    def note_event(self, kind: str, incident: bool = False,
                   trace_id: str | None = None, **attrs) -> None:
        """Record one notable event (watchdog trip, breaker
        transition, mesh rebuild, fleet failover). Pins the active (or
        given) trace; `incident=True` additionally snapshots the ring
        to an incident file (cooldown-limited)."""
        if trace_id is None:
            from .trace import current_trace_id
            trace_id = current_trace_id()
        ev = {"kind": kind, "ts_unix": round(time.time(), 6),
              "trace_id": trace_id or "", **attrs}
        with self._lock:
            self._events.append(ev)
            del self._events[:-self.max_events]
        if trace_id:
            self.pin(trace_id, kind)
        if incident:
            self.incident(kind, detail=attrs)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    # ---- reads ---------------------------------------------------------

    def spans(self, trace_id: str | None = None) -> list[dict]:
        """Ring + pin snapshot as JSON dicts, deduped by span id and
        sorted by wall time; `trace_id` filters."""
        ring = [s for s in self._span_ring if s is not None]
        with self._lock:
            for entry in self._pins.values():
                ring.extend(entry["spans"])
        if trace_id:
            ring = [s for s in ring if s.trace_id == trace_id]
        seen: set = set()
        out = []
        for s in ring:
            if s.span_id in seen:
                continue
            seen.add(s.span_id)
            out.append(span_to_json(s))
        out.sort(key=lambda d: d["ts_unix"])
        return out

    def trace_ids(self) -> dict[str, int]:
        """→ {trace_id: span count} over the ring + pins (the
        /debug/traces listing when no trace_id is asked for)."""
        counts: dict[str, int] = {}
        for d in self.spans():
            if d["trace_id"]:
                counts[d["trace_id"]] = counts.get(d["trace_id"], 0) + 1
        return counts

    def logs(self) -> list[dict]:
        ring = [r for r in self._log_ring if r is not None]
        ring.sort(key=lambda d: d.get("ts_unix", 0.0))
        return ring

    # ---- incidents -----------------------------------------------------

    def incident(self, reason: str, detail: dict | None = None,
                 force: bool = False) -> str | None:
        """Snapshot the ring (spans, logs, pins, events) to a
        timestamped JSON file under `incident_dir`. Returns the path,
        or None when inside the cooldown window (`force` bypasses it —
        operator-requested captures are never rate-limited)."""
        now = time.time()
        with self._lock:
            if not force and \
                    now - self._last_incident < self.incident_cooldown_s:
                return None
            self._last_incident = now
        doc = {
            "schema": self.SCHEMA,
            "reason": reason,
            "detail": {k: str(v) for k, v in (detail or {}).items()},
            "captured_unix": round(now, 6),
            "pid": os.getpid(),
            "spans": self.spans(),
            "logs": self.logs(),
            "events": self.events(),
            "pinned": self.pinned(),
        }
        slug = _SLUG_RE.sub("-", reason)[:48] or "incident"
        ts = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        name = f"incident-{ts}-{slug}-{next(self._incident_seq)}.json"
        path = os.path.join(self.incident_dir, name)
        try:
            os.makedirs(self.incident_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, path)
        except OSError:
            return None   # a full disk must never sink the caller
        from ..metrics import METRICS
        METRICS.inc("trivy_tpu_incidents_total",
                    reason=reason.split(":", 1)[0])
        return path

    def incidents(self, limit: int = 50) -> list[dict]:
        """List incident files, newest first (the /debug/incidents
        payload)."""
        try:
            names = [n for n in os.listdir(self.incident_dir)
                     if n.startswith("incident-") and n.endswith(".json")]
        except OSError:
            return []
        out = []
        for name in names:
            path = os.path.join(self.incident_dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append({"file": name, "path": path,
                        "size": st.st_size,
                        "mtime_unix": round(st.st_mtime, 3)})
        out.sort(key=lambda d: d["mtime_unix"], reverse=True)
        return out[:limit]

    # ---- tests ---------------------------------------------------------

    def reset_for_tests(self) -> None:
        self._span_ring = self._new_ring(len(self._span_ring))
        self._log_ring = self._new_ring(len(self._log_ring))
        with self._lock:
            self._pins = {}
            self._pin_tids = frozenset()
            self._events = []
            self._last_incident = 0.0


RECORDER = FlightRecorder()


# ---------------------------------------------------------------------------
# /debug HTTP payloads — shared by the scan server (server/listen.py)
# and the fleet router (fleet/router.py), so every process answers the
# same debug surface from its own recorder

def debug_traces_payload(path: str) -> dict:
    """Payload for GET /debug/traces[?trace_id=...]: the named trace's
    spans, or (without a trace_id) the buffer's trace listing."""
    import urllib.parse
    q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
    trace_id = (q.get("trace_id") or [""])[0]
    if trace_id:
        return {
            "trace_id": trace_id,
            "pid": os.getpid(),
            "spans": RECORDER.spans(trace_id),
        }
    return {
        "pid": os.getpid(),
        "traces": RECORDER.trace_ids(),
        "pinned": {t: e["reason"]
                   for t, e in RECORDER.pinned().items()},
        "spans": RECORDER.spans(),
    }


def debug_incidents_payload() -> dict:
    """Payload for GET /debug/incidents: the incident-file listing."""
    return {
        "pid": os.getpid(),
        "dir": RECORDER.incident_dir,
        "incidents": RECORDER.incidents(),
    }
