"""Strict Prometheus text-exposition parser (format 0.0.4).

Born as the tier-1 test gate (tests/helpers.py, PR 2) that keeps the
live /metrics payload scrapeable; promoted to a production module in
PR 8 so graftstorm's `metrics_wellformed` invariant and the test suite
enforce ONE definition of "strict" — two hand-rolled copies would
drift until a payload tier-1 rejects passed a chaos run, or vice
versa. tests/helpers.py re-exports `parse_exposition` from here.

The scraper is forgiving; this parser is not. A malformed family,
label escape, or histogram inconsistency raises ValueError so a bad
series fails the caller instead of the production scraper:

  * samples must follow their family's `# TYPE` line (no duplicate
    TYPE, no TYPE after samples);
  * label blocks parse with full exposition escaping (\\\\, \\", \\n),
    no duplicate labels, no junk;
  * histogram families emit only `_bucket`/`_sum`/`_count` children,
    with per-label-set bucket ordering + cumulativity, a `+Inf`
    bucket, and `_count` equal to it.
"""

from __future__ import annotations

import math
import re

_NAME_RE = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*\Z")
_SAMPLE_RE = re.compile(
    r"(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>\S+)(?: (?P<ts>-?\d+))?\Z")
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _parse_label_block(block: str) -> dict:
    """Parse `a="x",b="y"` with exposition escaping (\\\\, \\", \\n)."""
    labels = {}
    i, n = 0, len(block)
    while i < n:
        eq = block.index("=", i)
        name = block[i:eq]
        if not _NAME_RE.match(name):
            raise ValueError(f"bad label name {name!r}")
        if eq + 1 >= n or block[eq + 1] != '"':
            raise ValueError(f"label {name}: value not quoted")
        j = eq + 2
        out = []
        while True:
            if j >= n:
                raise ValueError(f"label {name}: unterminated value")
            c = block[j]
            if c == "\\":
                if j + 1 >= n:
                    raise ValueError(f"label {name}: dangling escape")
                nxt = block[j + 1]
                if nxt == "n":
                    out.append("\n")
                elif nxt in ('"', "\\"):
                    out.append(nxt)
                else:
                    raise ValueError(
                        f"label {name}: bad escape \\{nxt}")
                j += 2
            elif c == '"':
                j += 1
                break
            else:
                out.append(c)
                j += 1
        if name in labels:
            raise ValueError(f"duplicate label {name}")
        labels[name] = "".join(out)
        if j < n:
            if block[j] != ",":
                raise ValueError(f"junk after label {name}")
            j += 1
        i = j
    return labels


def _parse_value(raw: str) -> float:
    if raw in ("+Inf", "Inf"):
        return math.inf
    if raw == "-Inf":
        return -math.inf
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"bad sample value {raw!r}") from None


def parse_exposition(text: str) -> dict:
    """Strictly parse Prometheus text exposition format 0.0.4.

    → {family: {"type": str, "help": str | None,
                "samples": [(sample_name, {labels}, value)]}}

    Raises ValueError on malformed lines, samples without a preceding
    # TYPE, sample names that don't belong to their family (histogram
    children must be _bucket/_sum/_count), duplicate TYPE lines, and
    histogram inconsistencies: unordered or non-cumulative buckets,
    missing le="+Inf", or +Inf bucket ≠ _count.
    """
    families: dict = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            rest = line[len("# HELP "):]
            name, _, help_text = rest.partition(" ")
            if not _NAME_RE.match(name):
                raise ValueError(f"line {lineno}: bad HELP name")
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": []})
            fam["help"] = re.sub(
                r"\\(n|\\)",
                lambda m: "\n" if m.group(1) == "n" else "\\",
                help_text)
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE "):].split(" ")
            if len(parts) != 2 or not _NAME_RE.match(parts[0]) \
                    or parts[1] not in _TYPES:
                raise ValueError(f"line {lineno}: bad TYPE line")
            name, kind = parts
            fam = families.setdefault(
                name, {"type": None, "help": None, "samples": []})
            if fam["type"] is not None:
                raise ValueError(f"line {lineno}: duplicate TYPE {name}")
            if fam["samples"]:
                raise ValueError(
                    f"line {lineno}: TYPE {name} after its samples")
            fam["type"] = kind
            continue
        if line.startswith("#"):
            continue  # comment
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample "
                             f"{line!r}")
        sname = m.group("name")
        labels = _parse_label_block(m.group("labels") or "")
        value = _parse_value(m.group("value"))
        base = sname
        for suffix in ("_bucket", "_sum", "_count"):
            if sname.endswith(suffix) \
                    and sname[:-len(suffix)] in families \
                    and families[sname[:-len(suffix)]]["type"] \
                    in ("histogram", "summary"):
                base = sname[:-len(suffix)]
                break
        fam = families.get(base)
        if fam is None or fam["type"] is None:
            raise ValueError(
                f"line {lineno}: sample {sname} without # TYPE")
        if fam["type"] == "histogram" and base == sname:
            # (summary families legally emit bare-name quantile
            # samples; only histograms are restricted to children)
            raise ValueError(
                f"line {lineno}: bare sample {sname} for "
                f"histogram family")
        fam["samples"].append((sname, labels, value))

    for name, fam in families.items():
        if fam["type"] == "histogram":
            _check_histogram(name, fam["samples"])
    return families


def _check_histogram(name: str, samples: list) -> None:
    """Bucket cumulativity, +Inf presence, _sum/_count consistency —
    per label set (ignoring le)."""
    series: dict = {}
    for sname, labels, value in samples:
        rest = tuple(sorted((k, v) for k, v in labels.items()
                            if k != "le"))
        slot = series.setdefault(
            rest, {"buckets": [], "sum": None, "count": None})
        if sname == f"{name}_bucket":
            if "le" not in labels:
                raise ValueError(f"{name}: bucket without le label")
            slot["buckets"].append((_parse_value(labels["le"]), value))
        elif sname == f"{name}_sum":
            slot["sum"] = value
        elif sname == f"{name}_count":
            slot["count"] = value
    for rest, slot in series.items():
        buckets = slot["buckets"]
        if not buckets:
            raise ValueError(f"{name}{dict(rest)}: no buckets")
        edges = [e for e, _ in buckets]
        if edges != sorted(edges):
            raise ValueError(f"{name}{dict(rest)}: le out of order")
        counts = [c for _, c in buckets]
        if any(b > a for a, b in zip(counts[1:], counts)):
            raise ValueError(
                f"{name}{dict(rest)}: buckets not cumulative")
        if not math.isinf(edges[-1]):
            raise ValueError(f"{name}{dict(rest)}: missing le=\"+Inf\"")
        if slot["count"] is None or slot["sum"] is None:
            raise ValueError(f"{name}{dict(rest)}: missing _sum/_count")
        if slot["count"] != counts[-1]:
            raise ValueError(
                f"{name}{dict(rest)}: _count {slot['count']} != +Inf "
                f"bucket {counts[-1]}")
