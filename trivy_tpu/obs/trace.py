"""graftscope: context-local pipeline tracing.

The reference's observability is logs, because its unit of work is one
CLI run; a batched RPC service needs to answer "where did this 400ms
scan go — walker, host prep, XLA compile, device execute, or hit
assembly?" per request. This module provides the span primitive the
whole pipeline is instrumented with:

    with span("detect.prepare", queries=len(qs)) as sp:
        ...
        sp.attrs["n_pairs"] = prep.n_pairs

Spans carry a trace id (stamped per scan / per RPC, propagated from
client to server via the X-Trivy-Trace-Id header), a span id, their
parent span id (contextvar nesting — correct across server handler
threads; a remote parent forwarded via the X-Trivy-Parent-Span header
links fragments across processes), wall + process time, and free-form
attributes.

Two sinks receive finished spans (graftwatch):

  * the always-on flight recorder (obs/recorder.py) — a bounded
    lock-free ring every span lands in, serving /debug/traces and
    incident capture; its per-span cost is one counter bump and one
    slot store;
  * the COLLECTOR, only while recording is enabled (`--trace FILE` on
    the CLI, the server's --trace flag, or bench.py's phase
    breakdown) — the opt-in complete-trace dump.

Export is Chrome trace-event JSON ("X" complete events, microsecond
timestamps), loadable in Perfetto / chrome://tracing.

Instrumentation never goes INSIDE device code — under jit tracing a
span would time the trace, not the device, and a clock read lowers to
nothing. graftlint rule TPU107 enforces this.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import threading
import time
import uuid

from .recorder import RECORDER

# active span (for parent linkage) and active trace id; contextvars so
# each server handler thread / asyncio task nests independently
_SPAN: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_span", default=None)
_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_trace", default="")
# remote parent span id (X-Trivy-Parent-Span): adopted by the first
# span opened under it with no LOCAL parent, so a server fragment's
# root span links to the router/client span that forwarded the RPC
_REMOTE_PARENT: contextvars.ContextVar = contextvars.ContextVar(
    "trivy_tpu_remote_parent", default="")


def _new_id(nbytes: int = 8) -> str:
    return uuid.uuid4().hex[:2 * nbytes]


class Span:
    """One finished (or in-flight) timed region."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "attrs",
                 "wall_start", "start", "dur", "cpu", "thread_id")

    def __init__(self, name: str, trace_id: str, parent_id: str,
                 attrs: dict):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.attrs = attrs
        self.wall_start = 0.0   # time.time() at enter
        self.start = 0.0        # perf_counter at enter
        self.dur = 0.0          # perf_counter seconds
        self.cpu = 0.0          # process_time seconds
        self.thread_id = 0


class Collector:
    """Process-wide sink for finished spans (bounded, thread-safe).

    Shared across server handler threads — every mutation of the span
    buffer happens under the lock (graftlint TPU106 covers this
    module)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._enabled = False
        self._limit = 200_000
        self._dropped = 0

    @property
    def enabled(self) -> bool:
        return self._enabled

    def enable(self, limit: int | None = None) -> None:
        with self._lock:
            self._spans = []
            self._dropped = 0
            if limit is not None:
                self._limit = limit
            self._enabled = True

    def disable(self) -> None:
        with self._lock:
            self._enabled = False

    def record(self, s: Span) -> None:
        if not self._enabled:
            return
        with self._lock:
            if len(self._spans) >= self._limit:
                self._dropped += 1
                return
            self._spans.append(s)

    @property
    def dropped(self) -> int:
        return self._dropped

    def snapshot(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def drain(self) -> list[Span]:
        with self._lock:
            out = self._spans
            self._spans = []
            return out

    def phase_totals(self) -> dict[str, dict]:
        """Aggregate recorded spans by name → {count, total_ms,
        cpu_ms} (bench.py's per-phase breakdown)."""
        out: dict[str, dict] = {}
        for s in self.snapshot():
            agg = out.setdefault(s.name,
                                 {"count": 0, "total_ms": 0.0,
                                  "cpu_ms": 0.0})
            agg["count"] += 1
            agg["total_ms"] += s.dur * 1e3
            agg["cpu_ms"] += s.cpu * 1e3
        for agg in out.values():
            agg["total_ms"] = round(agg["total_ms"], 3)
            agg["cpu_ms"] = round(agg["cpu_ms"], 3)
        return out


COLLECTOR = Collector()


def recording() -> bool:
    return COLLECTOR.enabled


def current_trace_id() -> str:
    return _TRACE.get()


def current_span_id() -> str:
    """Span id of the innermost active span ('' outside any span) —
    what the client/router forward as X-Trivy-Parent-Span."""
    s = _SPAN.get()
    return s.span_id if s is not None else ""


@contextlib.contextmanager
def new_trace(trace_id: str | None = None,
              parent_id: str | None = None):
    """Set a fresh trace id for the enclosed work (per-RPC stamp).
    `parent_id` installs a REMOTE parent span id: the first span
    opened inside (with no local parent) adopts it, stitching this
    process's fragment under the caller's forwarding span."""
    tid = trace_id or _new_id(16)
    tok = _TRACE.set(tid)
    ptok = _REMOTE_PARENT.set(parent_id) if parent_id else None
    try:
        yield tid
    finally:
        if ptok is not None:
            _REMOTE_PARENT.reset(ptok)
        _TRACE.reset(tok)


@contextlib.contextmanager
def ensure_trace(trace_id: str | None = None):
    """Reuse the active trace id, or start one if none is active —
    the per-scan stamp (scanner.scan_many) that must not clobber a
    server-stamped per-RPC id."""
    cur = _TRACE.get()
    if cur and trace_id is None:
        yield cur
        return
    with new_trace(trace_id) as tid:
        yield tid


@contextlib.contextmanager
def span(name: str, **attrs):
    """Time a region; nests via contextvars. Yields the Span so callers
    can attach attributes discovered mid-flight (`sp.attrs[...] = x`).
    Every finished span lands in the always-on flight recorder's ring
    (graftwatch); the COLLECTOR additionally keeps it only while
    recording is enabled. A span with no local parent adopts the
    remote parent id installed by new_trace(parent_id=...)."""
    parent = _SPAN.get()
    s = Span(name, _TRACE.get(),
             parent.span_id if parent is not None
             else _REMOTE_PARENT.get(), dict(attrs))
    s.thread_id = threading.get_ident()
    s.wall_start = time.time()
    s.cpu = time.process_time()
    s.start = time.perf_counter()
    tok = _SPAN.set(s)
    try:
        yield s
    finally:
        s.dur = time.perf_counter() - s.start
        s.cpu = time.process_time() - s.cpu
        _SPAN.reset(tok)
        RECORDER.record_span(s)
        COLLECTOR.record(s)


def add_attr(**attrs) -> None:
    """Attach attributes to the innermost active span (no-op outside
    any span)."""
    s = _SPAN.get()
    if s is not None:
        s.attrs.update(attrs)


# ---------------------------------------------------------------------------
# Chrome trace-event export (Perfetto / chrome://tracing)

def chrome_trace(spans: list[Span] | None = None,
                 dropped: int | None = None) -> dict:
    """→ the Chrome trace-event JSON document for `spans` (default: the
    COLLECTOR's current buffer). "X" complete events; ts/dur in
    microseconds relative to the earliest span; span/trace/parent ids
    and attributes ride in `args`. Truncation is never silent: spans
    dropped at the collector's limit surface as a trailing instant
    event ("graftscope.dropped_spans")."""
    if spans is None:
        spans = COLLECTOR.snapshot()
    if dropped is None:
        dropped = COLLECTOR.dropped
    base = min((s.start for s in spans), default=0.0)
    pid = os.getpid()
    events = []
    for s in spans:
        events.append({
            "name": s.name,
            "cat": "graftscope",
            "ph": "X",
            "ts": round((s.start - base) * 1e6, 3),
            "dur": round(s.dur * 1e6, 3),
            "pid": pid,
            "tid": s.thread_id,
            "args": {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "cpu_ms": round(s.cpu * 1e3, 3),
                **s.attrs,
            },
        })
    if dropped:
        end = max((e["ts"] + e["dur"] for e in events), default=0.0)
        events.append({
            "name": "graftscope.dropped_spans", "cat": "graftscope",
            "ph": "i", "s": "g", "ts": end, "pid": pid, "tid": 0,
            "args": {"dropped": dropped},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str,
                       spans: list[Span] | None = None) -> None:
    doc = chrome_trace(spans)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
