"""graftwatch trace assembly: one trace across router + replicas.

A scan routed through the graftfleet router produces span fragments in
three places — the router process, the replica that served it, and
(on failover) the replicas that refused it. Each process exposes its
flight-recorder buffer at `/debug/traces?trace_id=`; this module pulls
those fragments and assembles ONE Chrome/Perfetto trace-event document
spanning router → replica → detect → device, failover hops included.

Cross-process rules:

  * fragments are deduped by span id (in-process test fleets share one
    recorder, and a retry may surface the same span twice);
  * parent edges stitch via the X-Trivy-Parent-Span header: a
    fragment's root span carries the forwarding span's id as its
    parent, so the assembled tree is connected without any clock
    agreement between processes;
  * timestamps use each span's WALL clock (ts_unix) — perf_counter
    bases are process-local and meaningless across machines — offset
    to the earliest span in the document;
  * every source process gets its own Chrome pid plus a
    process_name metadata event naming its URL.

`discover(router_url)` reads the router's /healthz to find the
replica set, so `python -m trivy_tpu.obs.collect --router URL
--trace-id ID -o FILE` (and `router --trace FILE` on shutdown) need
only the router address.

`--costs` switches the sweep to graftcost: it pulls every process's
token-gated /debug/costs, sums the REPLICA tenant tables into one
fleet-wide trivy-tpu-costs/1 document (the router's own fleet-scope
table is kept as a source fragment but excluded from the merge — it
aggregates the same relayed headers the replicas attributed locally,
and summing both would double-count), and folds the replicas'
conservation blocks into one fleet verdict. `--perf` additionally
embeds each process's /debug/perf dispatch-ledger fragment (implies
`--costs`). `obs.check` validates the result offline.
"""

from __future__ import annotations

import json
import urllib.parse
import urllib.request


def fetch_fragment(base_url: str, trace_id: str | None = None,
                   timeout: float = 5.0) -> dict:
    """GET one process's /debug/traces buffer. Raises on transport
    errors — callers decide whether a missing fragment is fatal (a
    replica that died mid-incident is exactly when you want the other
    fragments anyway)."""
    url = base_url.rstrip("/") + "/debug/traces"
    if trace_id:
        url += "?" + urllib.parse.urlencode({"trace_id": trace_id})
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def fetch_fragments(base_urls, trace_id: str | None = None,
                    timeout: float = 5.0) -> list[dict]:
    """Fetch from every URL, skipping unreachable processes (their
    absence is recorded as an empty fragment with an `error`)."""
    out = []
    for url in base_urls:
        try:
            frag = fetch_fragment(url, trace_id, timeout)
        except Exception as e:  # noqa: BLE001 — best-effort sweep
            out.append({"url": url, "spans": [], "error": str(e)})
            continue
        frag["url"] = url
        out.append(frag)
    return out


def discover(router_url: str, timeout: float = 5.0) -> list[str]:
    """→ [router_url, replica...] from the router's /healthz fleet
    block."""
    with urllib.request.urlopen(
            router_url.rstrip("/") + "/healthz", timeout=timeout) as r:
        doc = json.loads(r.read())
    replicas = ((doc.get("fleet") or {}).get("ring") or {}) \
        .get("replicas") or []
    return [router_url.rstrip("/")] + list(replicas)


def assemble(fragments: list[dict]) -> dict:
    """→ one Chrome trace-event document over every fragment's spans,
    deduped by span id; each source gets its own pid + process_name
    metadata row."""
    events = []
    seen: set = set()
    base = None
    for frag in fragments:
        for s in frag.get("spans") or ():
            if s["span_id"] in seen:
                continue
            ts = float(s.get("ts_unix") or 0.0)
            if base is None or ts < base:
                base = ts
    base = base or 0.0
    for pid, frag in enumerate(fragments, start=1):
        url = frag.get("url") or f"process-{pid}"
        added = False
        for s in frag.get("spans") or ():
            if s["span_id"] in seen:
                continue
            seen.add(s["span_id"])
            added = True
            events.append({
                "name": s["name"],
                "cat": "graftwatch",
                "ph": "X",
                "ts": round((float(s.get("ts_unix") or 0.0) - base)
                            * 1e6, 3),
                "dur": round(float(s.get("dur_ms") or 0.0) * 1e3, 3),
                "pid": pid,
                "tid": s.get("thread_id", 0),
                "args": {
                    "trace_id": s.get("trace_id", ""),
                    "span_id": s["span_id"],
                    "parent_id": s.get("parent_id", ""),
                    "cpu_ms": s.get("cpu_ms", 0.0),
                    **(s.get("attrs") or {}),
                },
            })
        if added:
            events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "tid": 0, "args": {"name": url},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def collect_trace(router_url: str, trace_id: str | None = None,
                  timeout: float = 5.0, urls=None) -> dict:
    """Discover the fleet behind `router_url` (or use explicit
    `urls`), fetch every fragment, and assemble one document."""
    if urls is None:
        urls = discover(router_url, timeout)
    return assemble(fetch_fragments(urls, trace_id, timeout))


def fetch_debug(base_url: str, endpoint: str, token: str = "",
                timeout: float = 5.0) -> dict:
    """GET one process's token-gated /debug/<endpoint> payload."""
    req = urllib.request.Request(
        base_url.rstrip("/") + "/debug/" + endpoint,
        headers={"Trivy-Token": token} if token else {})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _merge_tenant_tables(tables: list[dict]) -> dict:
    """Sum per-tenant totals rows across replica tables: numeric
    fields add, scans outcome maps add per outcome."""
    out: dict = {}
    for table in tables:
        for tenant, row in table.items():
            if not isinstance(row, dict):
                continue
            dst = out.setdefault(tenant, {"scans": {}})
            for field, v in row.items():
                if field == "scans":
                    for outcome, n in (v or {}).items():
                        dst["scans"][outcome] = \
                            dst["scans"].get(outcome, 0) + int(n)
                elif isinstance(v, (int, float)):
                    dst[field] = round(dst.get(field, 0) + v, 3)
    return out


def collect_costs(router_url: str, token: str = "",
                  timeout: float = 5.0, urls=None,
                  with_perf: bool = False) -> dict:
    """Discover the fleet behind `router_url` (or use explicit
    `urls`), pull every /debug/costs, and assemble one fleet-wide
    trivy-tpu-costs/1 document. Replica tenant tables merge; the
    router's fleet-scope table stays a source fragment only (it
    re-aggregates the replicas' relayed headers). Conservation folds
    across replica fragments: sums per axis, verdict ANDed — one
    leaking replica fails the fleet."""
    if urls is None:
        urls = discover(router_url, timeout)
    sources: list[dict] = []
    replica_tables: list[dict] = []
    cons_sum: dict = {}
    cons_seen = False
    for url in urls:
        try:
            frag = fetch_debug(url, "costs", token, timeout)
        except Exception as e:  # noqa: BLE001 — best-effort sweep
            sources.append({"url": url, "error": str(e)})
            continue
        frag["url"] = url
        sources.append(frag)
        if frag.get("scope") == "fleet":
            continue   # the router re-aggregates replica headers
        if isinstance(frag.get("tenants"), dict):
            replica_tables.append(frag["tenants"])
        cons = frag.get("conservation")
        if isinstance(cons, dict):
            cons_seen = True
            for axis in ("device_ms", "transfer_bytes"):
                rec = cons.get(axis) or {}
                dst = cons_sum.setdefault(
                    axis, {"ledger": 0, "attributed": 0, "ok": True})
                dst["ledger"] = round(
                    dst["ledger"] + rec.get("ledger", 0), 3)
                dst["attributed"] = round(
                    dst["attributed"] + rec.get("attributed", 0), 3)
                dst["ok"] = bool(dst["ok"] and rec.get("ok", False))
    doc = {
        "schema": "trivy-tpu-costs/1",
        "scope": "fleet-merged",
        "tenants": _merge_tenant_tables(replica_tables),
        "sources": sources,
    }
    if cons_seen:
        doc["conservation"] = cons_sum
    if with_perf:
        perf = []
        for url in urls:
            try:
                frag = fetch_debug(url, "perf", token, timeout)
            except Exception as e:  # noqa: BLE001
                perf.append({"url": url, "error": str(e)})
                continue
            frag["url"] = url
            perf.append(frag)
        doc["perf"] = perf
    return doc


def write_trace(path: str, doc: dict) -> None:
    import os
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def main(argv=None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m trivy_tpu.obs.collect",
        description="assemble one Chrome/Perfetto trace across a "
                    "graftfleet router and its replicas")
    ap.add_argument("--router", required=True,
                    help="router base URL (replicas discovered via "
                         "its /healthz)")
    ap.add_argument("--trace-id", default="",
                    help="assemble one trace (default: every span "
                         "still in the fleet's flight recorders)")
    ap.add_argument("--url", action="append", default=[],
                    help="extra process URL to pull a fragment from "
                         "(repeatable)")
    ap.add_argument("-o", "--output", required=True,
                    help="output trace file (Perfetto / "
                         "chrome://tracing)")
    ap.add_argument("--timeout", type=float, default=5.0)
    ap.add_argument("--costs", action="store_true",
                    help="assemble one fleet-wide trivy-tpu-costs/1 "
                         "document from every process's /debug/costs "
                         "instead of a trace")
    ap.add_argument("--perf", action="store_true",
                    help="embed each process's /debug/perf fragment "
                         "in the costs document (implies --costs)")
    ap.add_argument("--token", default="",
                    help="Trivy-Token for the token-gated /debug "
                         "endpoints (--costs/--perf)")
    args = ap.parse_args(argv)
    urls = discover(args.router, args.timeout) + list(args.url)
    if args.costs or args.perf:
        doc = collect_costs(args.router, args.token, args.timeout,
                            urls=urls, with_perf=args.perf)
        write_trace(args.output, doc)
        print(f"{len(doc['tenants'])} tenants from "
              f"{len(doc['sources'])} processes → {args.output}")
        return 0
    doc = collect_trace(args.router, args.trace_id or None,
                        args.timeout, urls=urls)
    write_trace(args.output, doc)
    print(f"{len(doc['traceEvents'])} events from {len(urls)} "
          f"processes → {args.output}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
