"""Device backend status for /healthz.

The health endpoint must never block behind a dead backend init, so it
never touches jax itself: the detect engine calls note_dispatch() on
its (already-jax-initialized) dispatch path, which caches the backend
identity once and stamps the last-successful-dispatch time; healthz
reads the cached view. Before the first dispatch the platform reports
"uninitialized" — an honest answer for a server that has not yet run
device work.
"""

from __future__ import annotations

import threading
import time

_lock = threading.Lock()
_state = {
    "platform": "",
    "device_count": 0,
    "last_dispatch_unix": 0.0,
}


def note_dispatch() -> None:
    """Record a successful device dispatch (called from the detect
    engine's dispatch path, where jax is already live)."""
    if not _state["platform"]:
        try:
            import jax
            devs = jax.devices()
            platform = getattr(devs[0], "platform", "") or "unknown"
            count = len(devs)
        except Exception:  # backend probe must never sink a dispatch
            platform, count = "unknown", 0
        with _lock:
            if not _state["platform"]:
                _state["platform"] = platform
                _state["device_count"] = count
    with _lock:
        _state["last_dispatch_unix"] = time.time()
    # graftprof memory telemetry rides the same contract: jax is live
    # HERE (we just dispatched), so the throttled backend memory-stats
    # sample happens now and /healthz only ever reads the cached view
    from .perf import LEDGER
    LEDGER.sample_memory()


def device_status() -> dict:
    """→ {platform, device_count, last_dispatch_age_s, memory} for
    /healthz. The memory block is graftprof's cached view (HBM
    watermarks sampled on the dispatch path + host-resident component
    bytes) — like everything here, it never touches jax."""
    from .perf import LEDGER
    with _lock:
        snap = dict(_state)
    last = snap.pop("last_dispatch_unix")
    snap["platform"] = snap["platform"] or "uninitialized"
    snap["last_dispatch_age_s"] = (
        round(time.time() - last, 3) if last else None)
    snap["memory"] = LEDGER.memory_status()
    return snap


def _reset_for_tests() -> None:
    with _lock:
        _state["platform"] = ""
        _state["device_count"] = 0
        _state["last_dispatch_unix"] = 0.0
