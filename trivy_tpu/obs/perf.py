"""graftprof — device-side performance telemetry.

graftwatch (PR 7) made the *request* path observable; the *device*
path stayed a black box: nothing attributed XLA compile time, padding
waste, hit-buffer occupancy drift, or HBM residency, and a bench
regression was indistinguishable from bench noise. graftprof closes
that gap with three pieces that every device entry point feeds:

  ledger    the dispatch ledger (LEDGER): every launch site — the
            single-chip engine, detectd merged dispatches, mesh
            cells, the shift-or secrets engine, redetectd sweeps —
            records per-dispatch padded-vs-real rows, device→host
            bytes by result path (compact / dense / the overflow
            re-fetch), hit-buffer fill and budget adaptations, and
            first-dispatch-of-shape compile wall time. Exported as
            trivy_tpu_device_* series under the strict exposition
            parser and summarized per shape at the token-gated
            /debug/perf.
  memory    HBM/host watermark gauges sampled (throttled) from the
            backend's memory stats on the dispatch path — never from
            /healthz, which must not block behind a dead backend —
            plus resident-bytes accounting for the big host-side
            structures (advisory table, secret rule bank, version
            pool, memo store), so table growth toward the HBM cliff
            is visible before it kills a swap.
  profiler  on-demand live capture (PROF): /debug/profile?ms=N runs
            a jax.profiler trace against live traffic (token-gated,
            one-at-a-time, cooldown-limited) and writes the artifact
            plus a trivy-tpu-profile/1 manifest into the incident
            dir; an SLO burn-rate threshold can auto-trigger one
            capture, tying graftwatch paging to an actionable
            profile. The CLI's --profile-dir rides the same
            exclusivity (capture_dir).

The perf-regression gate lives next door in obs/perfcheck.py.
Lock discipline (graftlint TPU106 covers obs/): every mutation of
shared ledger/profiler state happens under the instance lock; ledger
notes never go inside device code (TPU107/TPU108 — clocks and METRICS
under jit trace once and lie).
"""

from __future__ import annotations

import contextlib
import itertools
import json
import os
import re
import threading
import time

from ..metrics import METRICS

_SLUG_RE = re.compile(r"[^a-zA-Z0-9_.-]+")


def _new_row() -> dict:
    return {
        "dispatches": 0, "warm_dispatches": 0,
        "real_rows": 0, "padded_rows": 0, "waste_bytes": 0,
        "compiles": 0, "compile_ms": 0.0,
        "hit_fill_sum": 0.0, "hit_fill_n": 0, "overflows": 0,
    }


def _new_upload_row() -> dict:
    return {
        "uploads": 0, "prefetched": 0, "bytes": 0,
        "waits": 0, "cold_waits": 0,
        "stall_ms": 0.0, "cold_stall_ms": 0.0,
    }


class DispatchLedger:
    """Process-wide per-shape dispatch accounting (LEDGER, shared like
    METRICS). Shape key = (site, padded rows, hit capacity): each key
    is one compiled XLA program family, so the /debug/perf table reads
    as "what programs does this process run, how often, how wasteful".

    row_bytes scales the waste accounting to the site's row size: a
    detect pair costs one dense-bit byte, a secrets chunk row costs
    its full chunk length — so waste_bytes is comparable across
    sites."""

    def __init__(self):
        self._lock = threading.Lock()
        self._shapes: dict[tuple, dict] = {}
        self._transfers: dict[str, int] = {}
        self._device_ms: dict[str, float] = {}
        self._adapt = {"up": 0, "down": 0}
        self._resident: dict[str, int] = {}
        self._uploads: dict[str, dict] = {}
        self._mem: dict[str, dict] = {}
        self._mem_last = 0.0
        self._mem_peak = 0
        self.mem_sample_interval_s = 5.0

    # ---- dispatch accounting ------------------------------------------

    def note_dispatch(self, site: str, real: int, padded: int,
                      h_cap: int = 0, row_bytes: int = 1,
                      warm: bool = False) -> None:
        """One accepted device launch: `real` real rows inside a
        `padded`-row dispatch. Warmup dispatches are compiles, not
        traffic — counted separately so occupancy means what it
        says."""
        waste = max(padded - real, 0) * row_bytes
        with self._lock:
            row = self._shapes.setdefault((site, padded, h_cap),
                                          _new_row())
            if warm:
                row["warm_dispatches"] += 1
            else:
                row["dispatches"] += 1
                row["real_rows"] += real
                row["padded_rows"] += padded
                row["waste_bytes"] += waste
        if not warm:
            METRICS.inc("trivy_tpu_device_dispatches_total", site=site)
            if padded:
                METRICS.observe("trivy_tpu_device_padding_waste_ratio",
                                (padded - real) / padded, site=site)

    def note_compile(self, site: str, padded: int, h_cap: int,
                     ms: float, warm: bool = False) -> None:
        """First-dispatch-of-shape compile wall time (the launch call
        that traced + lowered + compiled the new shape). The phase
        label keeps warmup compiles distinguishable from the
        mid-traffic ones a latency page cares about."""
        with self._lock:
            row = self._shapes.setdefault((site, padded, h_cap),
                                          _new_row())
            row["compiles"] += 1
            row["compile_ms"] += ms
        METRICS.observe("trivy_tpu_device_compile_ms", ms,
                        phase="warmup" if warm else "traffic")

    def note_device_ms(self, site: str, ms: float) -> None:
        """Wall ms one launch+sync spent on the device path, by site.
        Written by obs.cost.charge_device_ms from the SAME measurement
        it apportions to tenants — the two sides of the graftcost
        conservation contract come from one clock read."""
        with self._lock:
            self._device_ms[site] = \
                self._device_ms.get(site, 0.0) + float(ms)

    def note_transfer(self, path: str, nbytes: float) -> None:
        """Device→host result bytes by path: "compact" (O(hits) hit
        buffers), "dense" (full padded vectors), "overflow" (the dense
        re-fetch a hit-buffer overflow pays on top of its wasted
        compact fetch)."""
        with self._lock:
            self._transfers[path] = \
                self._transfers.get(path, 0) + int(nbytes)
        METRICS.inc("trivy_tpu_device_transfer_bytes_total",
                    float(nbytes), path=path)

    def note_hits(self, site: str, padded: int, h_cap: int,
                  n_hits: int) -> None:
        """Hit-buffer fill fraction for one compacted dispatch (>1.0
        = overflow: that dispatch fell back to the dense fetch)."""
        if h_cap <= 0:
            return
        with self._lock:
            row = self._shapes.setdefault((site, padded, h_cap),
                                          _new_row())
            row["hit_fill_sum"] += n_hits / h_cap
            row["hit_fill_n"] += 1
            if n_hits > h_cap:
                row["overflows"] += 1

    def note_shard_upload(self, site: str, nbytes: int,
                          prefetched: bool,
                          path: str = "shard_upload") -> None:
        """One host→device upload (graftstream advisory slices;
        graftfeed query columns with site/path "query_upload").
        `prefetched` means the double buffer shipped it AHEAD of need,
        overlapped with the previous slice's (or dispatch's) compute;
        a non-prefetched upload ran inside a dispatch's wait (the cold
        path). Counts in the transfer ledger under `path` so streaming
        and input-feed overhead show at /debug/perf next to the result
        fetches."""
        with self._lock:
            row = self._uploads.setdefault(site, _new_upload_row())
            row["uploads"] += 1
            row["bytes"] += int(nbytes)
            if prefetched:
                row["prefetched"] += 1
        self.note_transfer(path, float(nbytes))

    def note_shard_wait(self, site: str, stall_ms: float,
                        cold: bool) -> None:
        """Time one dispatch spent blocked making a slice resident.
        Steady-state double buffering means stalls ≈ 0 after the first
        slice of a walk — the overlap property the streaming tests
        assert from these rows. `cold` = the upload itself ran inside
        this wait (nothing had prefetched the slice)."""
        with self._lock:
            row = self._uploads.setdefault(site, _new_upload_row())
            row["waits"] += 1
            row["stall_ms"] += stall_ms
            if cold:
                row["cold_waits"] += 1
                row["cold_stall_ms"] += stall_ms
        METRICS.observe("trivy_tpu_device_upload_stall_ms", stall_ms)

    def shard_upload_stats(self) -> dict:
        """→ {site: upload/stall aggregates} — the graftstream
        overlap view (/debug/perf `shard_uploads`, bench table_sweep,
        and the tier-1 double-buffer assertion)."""
        with self._lock:
            return {site: dict(row)
                    for site, row in self._uploads.items()}

    def note_budget_adapt(self, direction: str) -> None:
        """One hit-budget adaptation ("up" on overflow, "down" on a
        sustained sparse streak)."""
        with self._lock:
            self._adapt[direction] = self._adapt.get(direction, 0) + 1
        METRICS.inc("trivy_tpu_device_hit_budget_adaptations_total",
                    direction=direction)

    # ---- memory telemetry ---------------------------------------------

    def note_resident(self, component: str, nbytes: int) -> None:
        """Host-resident bytes of one big structure (advisory_table,
        secret_bank, version_pool, memo). Idempotent per component —
        callers re-stamp on growth/swap."""
        with self._lock:
            self._resident[component] = int(nbytes)
        METRICS.set_gauge("trivy_tpu_device_resident_bytes",
                          float(nbytes), component=component)

    def sample_memory(self, force: bool = False) -> None:
        """Throttled backend memory-stats sample. Called from the
        dispatch path (obs.device.note_dispatch) where jax is already
        live — /healthz only ever reads the cached view, so a dead
        backend can never block a probe. Backends without memory_stats
        (CPU) simply leave the view empty."""
        now = time.monotonic()
        with self._lock:
            if not force and \
                    now - self._mem_last < self.mem_sample_interval_s:
                return
            self._mem_last = now
        stats: dict[str, dict] = {}
        try:
            import jax
            for d in jax.local_devices():
                fn = getattr(d, "memory_stats", None)
                ms = fn() if callable(fn) else None
                if not ms:
                    continue
                in_use = int(ms.get("bytes_in_use", 0))
                limit = int(ms.get("bytes_limit", 0)
                            or ms.get("bytes_reservable_limit", 0))
                peak = int(ms.get("peak_bytes_in_use", 0))
                stats[str(d.id)] = {
                    "platform": getattr(d, "platform", "") or "unknown",
                    "bytes_in_use": in_use,
                    "bytes_limit": limit,
                    "peak_bytes_in_use": peak,
                }
                METRICS.set_gauge("trivy_tpu_device_hbm_bytes",
                                  float(in_use), device=str(d.id),
                                  kind="in_use")
                if limit:
                    METRICS.set_gauge("trivy_tpu_device_hbm_bytes",
                                      float(limit), device=str(d.id),
                                      kind="limit")
                if peak:
                    METRICS.set_gauge("trivy_tpu_device_hbm_bytes",
                                      float(peak), device=str(d.id),
                                      kind="peak")
        except Exception:
            return  # a memory probe must never sink a dispatch
        if stats:
            peak_total = sum(s["peak_bytes_in_use"] or s["bytes_in_use"]
                             for s in stats.values())
            with self._lock:
                self._mem = stats
                self._mem_peak = max(self._mem_peak, peak_total)

    def memory_status(self) -> dict:
        """→ the /healthz `device.memory` block: the cached backend
        view plus host-resident components. Pure cache reads — never
        touches jax."""
        with self._lock:
            return {
                "backends": {k: dict(v) for k, v in self._mem.items()},
                "watermark_bytes": self._mem_peak,
                "resident_bytes": dict(self._resident),
            }

    # ---- reads ---------------------------------------------------------

    def shape_table(self) -> list[dict]:
        """→ the /debug/perf per-shape rows, sorted by site then
        size."""
        with self._lock:
            snap = {k: dict(v) for k, v in self._shapes.items()}
        rows = []
        for (site, padded, h_cap), r in sorted(snap.items()):
            rows.append({
                "site": site, "t_pad": padded, "h_cap": h_cap,
                "dispatches": r["dispatches"],
                "warm_dispatches": r["warm_dispatches"],
                "compiles": r["compiles"],
                "compile_ms": round(r["compile_ms"], 3),
                "mean_occupancy": round(
                    r["real_rows"] / r["padded_rows"], 4)
                if r["padded_rows"] else None,
                "waste_bytes": r["waste_bytes"],
                "mean_hit_fill": round(
                    r["hit_fill_sum"] / r["hit_fill_n"], 4)
                if r["hit_fill_n"] else None,
                "overflows": r["overflows"],
            })
        return rows

    def aggregate(self) -> dict:
        """→ the ledger's process totals — the bench-tail /
        device-child `graftprof` block perfcheck consumes."""
        with self._lock:
            shapes = [dict(v) for v in self._shapes.values()]
            transfers = dict(self._transfers)
            device_ms = dict(self._device_ms)
            adapt = dict(self._adapt)
            uploads = {site: dict(row)
                       for site, row in self._uploads.items()}
        real = sum(r["real_rows"] for r in shapes)
        padded = sum(r["padded_rows"] for r in shapes)
        return {
            "dispatches": sum(r["dispatches"] for r in shapes),
            "warm_dispatches": sum(r["warm_dispatches"]
                                   for r in shapes),
            "distinct_shapes": len(shapes),
            # raw row sums ride along so a scenario DELTA can
            # recompute the ratio over just its own dispatches
            "real_rows": real,
            "padded_rows": padded,
            "padding_waste_ratio": round(1.0 - real / padded, 4)
            if padded else None,
            "waste_bytes": sum(r["waste_bytes"] for r in shapes),
            "compiles": sum(r["compiles"] for r in shapes),
            "compile_ms": round(sum(r["compile_ms"] for r in shapes),
                                3),
            "overflows": sum(r["overflows"] for r in shapes),
            "transfer_bytes": transfers,
            # graftcost: per-site device wall ms (launch+sync), the
            # ledger side of the cost-conservation reconciliation
            "device_ms": {k: round(v, 3) for k, v in device_ms.items()},
            "device_ms_total": round(sum(device_ms.values()), 3),
            "budget_adaptations": adapt,
            # graftstream: host→device slice-upload overlap aggregates
            # (uploads/prefetched/stall_ms per site)
            "shard_uploads": uploads,
        }

    def site_dispatches(self) -> dict[str, int]:
        """→ {site: non-warm dispatch count} — the reconciliation read
        the acceptance drill sums against trivy_tpu_detect_* counts."""
        out: dict[str, int] = {}
        with self._lock:
            for (site, _padded, _h), r in self._shapes.items():
                out[site] = out.get(site, 0) + r["dispatches"]
        return out

    def reset_for_tests(self) -> None:
        with self._lock:
            self._shapes = {}
            self._transfers = {}
            self._device_ms = {}
            self._adapt = {"up": 0, "down": 0}
            self._resident = {}
            self._uploads = {}
            self._mem = {}
            self._mem_last = 0.0
            self._mem_peak = 0


LEDGER = DispatchLedger()


# ---------------------------------------------------------------------------
# resident-bytes helpers (called once per structure build, not hot)

def ndarray_bytes(*arrays) -> int:
    """Sum .nbytes over whatever numpy/jax arrays the caller has; non-
    arrays are skipped (duck-typed so callers never import numpy just
    to account)."""
    total = 0
    for a in arrays:
        nb = getattr(a, "nbytes", None)
        if isinstance(nb, (int, float)):
            total += int(nb)
    return total


def table_resident_bytes(table) -> int:
    """Columnar footprint of one AdvisoryTable (the device-shippable
    arrays; the Python group objects are the GC-frozen long tail and
    not what the HBM cliff cares about)."""
    return ndarray_bytes(*(getattr(table, name, None)
                           for name in ("lo_tok", "hi_tok", "flags",
                                        "hash_u64", "group")))


def stamp_table_resident(table) -> int:
    """Stamp one AdvisoryTable's footprint into the resident-bytes
    view: the whole-table figure PLUS the per-column breakdown
    (`advisory_table.lo_tok`, …) the graftstream slice planner budgets
    from — the build sites used to stamp only the total, so /healthz
    could not say WHICH column was marching toward the HBM cliff."""
    cols = getattr(table, "nbytes_by_column", None)
    if not callable(cols):
        total = table_resident_bytes(table)
        LEDGER.note_resident("advisory_table", total)
        return total
    breakdown = cols()
    total = sum(breakdown.values())
    LEDGER.note_resident("advisory_table", total)
    for name, nb in breakdown.items():
        LEDGER.note_resident(f"advisory_table.{name}", nb)
    return total


# ---------------------------------------------------------------------------
# live profiler capture

class ProfilerBusy(RuntimeError):
    """A capture is already running (one-at-a-time by design: two
    concurrent jax.profiler traces corrupt each other)."""


class ProfilerCooldown(RuntimeError):
    """Inside the cooldown window after the previous capture."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"profiler cooling down; retry in "
                         f"{retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


class Profiler:
    """On-demand jax.profiler capture against live traffic (PROF,
    process singleton). One capture at a time; operator captures are
    cooldown-limited so a curl loop cannot turn the serving process
    into a profiling appliance; artifacts (the TensorBoard trace dir
    plus a trivy-tpu-profile/1 manifest obs.check validates) land in
    the flight recorder's incident dir, where incident tooling already
    looks."""

    SCHEMA = "trivy-tpu-profile/1"
    MAX_MS = 60_000.0

    def __init__(self):
        self._lock = threading.Lock()
        self._active = False
        self._last_end = 0.0          # monotonic; 0 = never captured
        self._seq = itertools.count()
        self.cooldown_s = 30.0
        # SLO auto-trigger: short-window burn rate at/above this
        # starts one capture (0 = off); auto captures share the
        # cooldown so a sustained burn yields one profile per window
        self.auto_burn_threshold = 0.0
        self.auto_capture_ms = 2000.0

    def configure(self, cooldown_s: float | None = None,
                  auto_burn_threshold: float | None = None,
                  auto_capture_ms: float | None = None) -> None:
        with self._lock:
            if cooldown_s is not None:
                self.cooldown_s = float(cooldown_s)
            if auto_burn_threshold is not None:
                self.auto_burn_threshold = float(auto_burn_threshold)
            if auto_capture_ms is not None:
                self.auto_capture_ms = float(auto_capture_ms)

    def _admit(self, force: bool) -> None:
        with self._lock:
            if self._active:
                raise ProfilerBusy("a profile capture is already "
                                   "running")
            now = time.monotonic()
            if not force and self._last_end and \
                    now - self._last_end < self.cooldown_s:
                raise ProfilerCooldown(
                    self.cooldown_s - (now - self._last_end))
            self._active = True

    def _release(self) -> None:
        with self._lock:
            self._active = False
            self._last_end = time.monotonic()

    def capture(self, ms: float, reason: str = "manual",
                force: bool = False) -> dict:
        """Blocking capture of `ms` milliseconds of live device
        traffic. → the manifest document (schema trivy-tpu-profile/1,
        manifest path under `manifest`). Raises ProfilerBusy /
        ProfilerCooldown when not admitted."""
        ms = min(max(float(ms), 1.0), self.MAX_MS)
        self._admit(force)
        try:
            from .recorder import RECORDER
            started_unix = time.time()
            slug = _SLUG_RE.sub("-", reason)[:48] or "manual"
            name = "profile-{}-{}-{}".format(
                time.strftime("%Y%m%dT%H%M%S",
                              time.gmtime(started_unix)),
                slug, next(self._seq))
            out_dir = os.path.join(RECORDER.incident_dir, name)
            os.makedirs(out_dir, exist_ok=True)
            import jax
            t0 = time.perf_counter()
            jax.profiler.start_trace(out_dir)
            try:
                time.sleep(ms / 1e3)
            finally:
                jax.profiler.stop_trace()
            duration_ms = (time.perf_counter() - t0) * 1e3
            files = []
            for root, _dirs, names in os.walk(out_dir):
                for n in names:
                    files.append(os.path.relpath(
                        os.path.join(root, n), out_dir))
            doc = {
                "schema": self.SCHEMA,
                "reason": reason,
                "requested_ms": ms,
                "duration_ms": round(duration_ms, 1),
                "started_unix": round(started_unix, 3),
                "artifact_dir": out_dir,
                "files": sorted(files),
                "pid": os.getpid(),
            }
            manifest = out_dir + ".json"
            tmp = manifest + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f)
            os.replace(tmp, manifest)
            doc["manifest"] = manifest
            # metric label clamped to the documented closed set: the
            # free-form reason (operator-supplied via ?reason=) lives
            # in the manifest only — unbounded label values would mint
            # permanent series in the registry
            label = reason.split(":", 1)[0]
            if label not in ("manual", "slo_burn", "cli"):
                label = "other"
            METRICS.inc("trivy_tpu_profile_captures_total",
                        reason=label)
            return doc
        finally:
            self._release()

    @contextlib.contextmanager
    def capture_dir(self, out_dir: str):
        """The CLI --profile-dir path: profile the enclosed work into
        a caller-chosen directory under the same one-at-a-time
        exclusivity (no cooldown — an operator-requested CLI run is
        never rate-limited)."""
        self._admit(force=True)
        try:
            import jax
            jax.profiler.start_trace(out_dir)
            try:
                yield out_dir
            finally:
                jax.profiler.stop_trace()
                METRICS.inc("trivy_tpu_profile_captures_total",
                            reason="cli")
        finally:
            # a failed start_trace must release the one-at-a-time
            # slot, or the profiler is wedged busy for the process
            self._release()

    # ---- SLO auto-trigger ---------------------------------------------

    def observe_burn(self, rates: dict) -> None:
        """Called by SLO.export() with the burn_rates() document: when
        any objective's SHORT-window burn rate is at/above the
        configured threshold, start one background capture (cooldown-
        limited) so the page that burn rate fires comes with an
        actionable profile attached."""
        thr = self.auto_burn_threshold
        if not thr:
            return
        worst = None
        for name, doc in rates.items():
            windows = doc.get("windows") or {}
            if not windows:
                continue
            short = min(windows, key=lambda w: int(w.rstrip("s")))
            burn = windows[short].get("burn_rate", 0.0)
            if burn >= thr and (worst is None or burn > worst[1]):
                worst = (name, burn)
        if worst is None:
            return
        with self._lock:
            if self._active:
                return
            now = time.monotonic()
            if self._last_end and \
                    now - self._last_end < self.cooldown_s:
                return
        # lint: allow(TPU112) reason=one-shot capture bounded by auto_capture_ms; the busy/cooldown gates in capture() serialize overlapping fires
        threading.Thread(target=self._auto_capture, args=worst,
                         name="graftprof-auto", daemon=True).start()

    def _auto_capture(self, objective: str, burn: float) -> None:
        from ..log import get as _get_logger
        log = _get_logger("perf")
        try:
            doc = self.capture(self.auto_capture_ms,
                               reason=f"slo_burn:{objective}")
        except (ProfilerBusy, ProfilerCooldown):
            return  # lost the admit race — one capture is plenty
        except Exception:
            log.exception("auto profile capture failed")
            return
        log.warning("SLO burn %.2f on %s auto-captured a device "
                    "profile: %s", burn, objective, doc["manifest"])
        from .recorder import RECORDER
        RECORDER.note_event("profile.auto", objective=objective,
                            burn=round(burn, 3),
                            artifact=doc["manifest"])

    def reset_for_tests(self) -> None:
        with self._lock:
            self._active = False
            self._last_end = 0.0
            self.cooldown_s = 30.0
            self.auto_burn_threshold = 0.0
            self.auto_capture_ms = 2000.0


PROF = Profiler()


# ---------------------------------------------------------------------------
# /debug HTTP payloads — shared by the scan server and the fleet
# router, like recorder.debug_traces_payload

def debug_perf_payload() -> dict:
    """Payload for GET /debug/perf: the per-shape dispatch-ledger
    table, process totals, and the memory view."""
    return {
        "pid": os.getpid(),
        "shapes": LEDGER.shape_table(),
        "totals": LEDGER.aggregate(),
        "memory": LEDGER.memory_status(),
    }


def debug_profile_payload(path: str) -> tuple[int, dict]:
    """Handle GET /debug/profile?ms=N[&reason=...]: run one blocking
    capture against live traffic. → (http_code, json_payload); 409
    while another capture runs, 429 + retry_after_s inside the
    cooldown (the endpoint is already token-gated by the caller)."""
    import math
    import urllib.parse
    q = urllib.parse.parse_qs(urllib.parse.urlparse(path).query)
    try:
        ms = float((q.get("ms") or ["500"])[0])
    except ValueError:
        return 400, {"code": "invalid_argument",
                     "msg": "ms must be a number"}
    # NaN fails BOTH range comparisons — without the isfinite check it
    # would slip through, start a capture, blow up in time.sleep, and
    # burn the cooldown window on a 500
    if not math.isfinite(ms) or ms <= 0 or ms > Profiler.MAX_MS:
        return 400, {"code": "invalid_argument",
                     "msg": f"ms must be in (0, {int(Profiler.MAX_MS)}]"}
    reason = (q.get("reason") or ["manual"])[0]
    try:
        doc = PROF.capture(ms, reason=reason)
    except ProfilerBusy as e:
        return 409, {"code": "already_exists", "msg": str(e)}
    except ProfilerCooldown as e:
        return 429, {"code": "resource_exhausted", "msg": str(e),
                     "retry_after_s": round(e.retry_after_s, 1)}
    except Exception as e:  # noqa: BLE001 — a broken profiler must
        # surface as a clean 500, never kill the handler thread
        return 500, {"code": "internal",
                     "msg": f"{type(e).__name__}: {e}"}
    return 200, doc
