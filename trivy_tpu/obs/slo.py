"""graftwatch SLO engine: declared objectives over sliding windows.

/metrics says what the process did since boot; an operator paging at
3am needs "are we inside our objectives RIGHT NOW, and how fast are we
burning error budget". This module tracks three declared objectives
over short+long sliding windows and exports multi-window burn-rate
gauges (the standard multi-window multi-burn-rate alerting shape —
page on the short window, ticket on the long one):

  scan_latency_p99   fraction of completed scans under the latency
                     threshold must stay ≥ target (default: 99% under
                     2s). Only completed scans count — a shed request
                     has no latency.
  scan_errors        fraction of Scan RPCs that did not fail must stay
                     ≥ target (default 99.9%). SHED-AWARE: admission
                     429/503s are LOAD the deployment chose to refuse,
                     not errors — they count in the denominator as
                     good (refusing work under pressure is the SLO
                     behaving, not breaking).
  device_serving     fraction of joins served by the device path (vs
                     the NumPy host fallback) must stay ≥ target
                     (default 95%) — the "is the TPU actually carrying
                     the fleet" objective.

burn rate = bad_fraction / (1 - target): 1.0 means burning budget
exactly at the rate that exhausts it over the window's SLO period,
>1 means faster. Windows with no events burn 0 (no traffic, no burn).

The engine is a process singleton (SLO) like METRICS/GUARD; gauges
are (re)computed on export() — the /metrics and /healthz handlers
call it — so scrapes always see current-window values under the
strict exposition parser.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from ..metrics import METRICS


@dataclass(frozen=True)
class Objective:
    name: str
    target: float       # good-event ratio the objective promises
    help: str


DEFAULT_OBJECTIVES = (
    Objective("scan_latency_p99", 0.99,
              "completed scans under the latency threshold"),
    Objective("scan_errors", 0.999,
              "Scan RPCs that did not fail (sheds count as good)"),
    Objective("device_serving", 0.95,
              "joins served by the device path, not the host fallback"),
)


class SLOEngine:
    """Sliding-window good/bad event tracker per objective.

    Thread-safe: scan handler threads, the detect engine, and the
    detectd dispatcher all observe concurrently — every event-store
    mutation happens under the lock (graftlint TPU106 covers obs/).
    The clock is injectable so burn-rate math is testable on
    synthetic traffic without real sleeps."""

    def __init__(self, objectives=DEFAULT_OBJECTIVES,
                 windows=(300.0, 3600.0),
                 latency_threshold_s: float = 2.0,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self.windows = tuple(float(w) for w in windows)
        self.latency_threshold_s = latency_threshold_s
        self.objectives = {o.name: o for o in objectives}
        # per-objective deque of (ts, good: bool); pruned past the
        # longest window on every observe
        self._events = {name: deque() for name in self.objectives}
        # graftcost: per-tenant scan_latency_p99 event deques, keyed
        # by the TenantAggregator's CLAMPED label (top-K + "other"),
        # so the tenant-labeled burn gauges share the cardinality
        # bound of every other tenant series
        self._tenant_events: dict[str, deque] = {}

    def configure(self, latency_threshold_ms: float | None = None,
                  windows=None, targets: dict | None = None,
                  clock=None) -> None:
        with self._lock:
            if latency_threshold_ms is not None:
                self.latency_threshold_s = latency_threshold_ms / 1e3
            if windows is not None:
                self.windows = tuple(float(w) for w in windows)
            if targets:
                for name, target in targets.items():
                    obj = self.objectives.get(name)
                    if obj is None:
                        raise ValueError(f"unknown SLO objective "
                                         f"{name!r}")
                    self.objectives[name] = Objective(
                        obj.name, float(target), obj.help)
            if clock is not None:
                self._clock = clock

    # ---- observation ---------------------------------------------------

    def _observe(self, name: str, good: bool) -> None:
        horizon = max(self.windows)
        with self._lock:
            now = self._clock()
            ev = self._events[name]
            ev.append((now, good))
            while ev and ev[0][0] < now - horizon:
                ev.popleft()

    def observe_scan(self, latency_s: float, outcome: str,
                     tenant: str | None = None) -> None:
        """One Scan RPC: outcome 'ok' | 'error' | 'shed'. Sheds are
        load, not errors — they count toward availability's
        denominator as good and are excluded from the latency
        objective entirely (a refused scan has no latency). `tenant`
        (already clamped by the caller) additionally lands the
        latency event in that tenant's burn-rate window."""
        if outcome != "shed":
            good = (outcome == "ok"
                    and latency_s <= self.latency_threshold_s)
            self._observe("scan_latency_p99", good)
            if tenant:
                horizon = max(self.windows)
                with self._lock:
                    now = self._clock()
                    ev = self._tenant_events.setdefault(tenant,
                                                        deque())
                    ev.append((now, good))
                    while ev and ev[0][0] < now - horizon:
                        ev.popleft()
        self._observe("scan_errors", outcome != "error")

    def observe_join(self, device: bool) -> None:
        """One join dispatch: device path (good) or host fallback."""
        self._observe("device_serving", bool(device))

    # ---- math ----------------------------------------------------------

    def _window_stats(self, name: str, window: float,
                      now: float) -> tuple[int, int]:
        """→ (total, bad) inside `window` seconds. Caller holds the
        lock."""
        total = bad = 0
        for ts, good in self._events[name]:
            if ts >= now - window:
                total += 1
                if not good:
                    bad += 1
        return total, bad

    def burn_rates(self) -> dict:
        """→ {objective: {target, windows: {"<w>s": {total, bad,
        bad_ratio, burn_rate}}}} — the /healthz `slo` block."""
        with self._lock:
            now = self._clock()
            out = {}
            for name, obj in self.objectives.items():
                windows = {}
                for w in self.windows:
                    total, bad = self._window_stats(name, w, now)
                    ratio = bad / total if total else 0.0
                    budget = 1.0 - obj.target
                    burn = ratio / budget if budget > 0 else 0.0
                    windows[f"{int(w)}s"] = {
                        "total": total, "bad": bad,
                        "bad_ratio": round(ratio, 6),
                        "burn_rate": round(burn, 4),
                    }
                out[name] = {"target": obj.target,
                             "windows": windows}
            return out

    def tenant_burn_rates(self) -> dict:
        """→ {tenant: {window: burn_rate}} for the scan_latency_p99
        objective — per-tenant error-budget burn over the same
        windows, keyed by clamped tenant label."""
        obj = self.objectives["scan_latency_p99"]
        budget = 1.0 - obj.target
        with self._lock:
            now = self._clock()
            out: dict = {}
            for tenant, ev in self._tenant_events.items():
                windows = {}
                for w in self.windows:
                    total = bad = 0
                    for ts, good in ev:
                        if ts >= now - w:
                            total += 1
                            if not good:
                                bad += 1
                    ratio = bad / total if total else 0.0
                    burn = ratio / budget if budget > 0 else 0.0
                    windows[f"{int(w)}s"] = round(burn, 4)
                out[tenant] = windows
            return out

    def export(self) -> dict:
        """Recompute and publish the burn-rate gauges (and the
        device-serving ratio over the short window); returns the
        burn_rates() document so /healthz shares one computation."""
        rates = self.burn_rates()
        for name, doc in rates.items():
            for wname, w in doc["windows"].items():
                METRICS.set_gauge("trivy_tpu_slo_burn_rate",
                                  w["burn_rate"], objective=name,
                                  window=wname)
        # graftcost: tenant-labeled latency burn (cardinality already
        # clamped at observe time — labels are TenantAggregator
        # output, never raw header values)
        for tenant, windows in self.tenant_burn_rates().items():
            for wname, burn in windows.items():
                METRICS.set_gauge("trivy_tpu_slo_burn_rate", burn,
                                  objective="scan_latency_p99",
                                  window=wname, tenant=tenant)
        short = f"{int(min(self.windows))}s"
        dev = rates["device_serving"]["windows"][short]
        ratio = 1.0 - dev["bad_ratio"] if dev["total"] else 1.0
        METRICS.set_gauge("trivy_tpu_device_serving_ratio", ratio)
        # graftprof auto-trigger: a short-window burn past the
        # configured threshold starts one background profile capture
        # (cooldown-limited), so the page this export feeds arrives
        # with an actionable device trace attached
        from .perf import PROF
        PROF.observe_burn(rates)
        return rates

    def reset_for_tests(self) -> None:
        with self._lock:
            for ev in self._events.values():
                ev.clear()
            self._tenant_events = {}


SLO = SLOEngine()
