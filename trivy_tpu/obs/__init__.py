"""graftwatch — tracing, flight recorder, SLOs, and backend status.

v1 ("graftscope") was single-process: `trace` holds the span/tracer
core (context-local spans, trace-id propagation, Chrome trace-event
export) and `device` the cached backend view /healthz serves. v2
("graftwatch") makes it fleet-wide:

  recorder  the always-on flight recorder: every finished span and
            log record lands in a bounded lock-free ring; slow/error/
            incident traces are pinned past churn; breaker openings
            and failpoint-injected faults auto-capture timestamped
            incident files (/debug/incidents).
  slo       declared objectives (scan latency p99, error rate,
            device-serving ratio) over sliding windows with
            multi-window burn-rate gauges; shed-aware (admission
            429s are load, not errors).
  collect   cross-process trace assembly: pulls /debug/traces
            fragments from the router + every replica and stitches
            one Chrome/Perfetto document via forwarded parent-span
            ids (X-Trivy-Parent-Span).
  check     offline validator for incident files, trace dumps, and
            profile manifests (`python -m trivy_tpu.obs.check`),
            wired into tier-1.

v3 ("graftprof") adds the device-performance layer:

  perf      the dispatch ledger (per-shape padded-vs-real rows,
            compile wall time, device→host bytes, hit-buffer fill),
            HBM/resident-memory telemetry, and the live jax.profiler
            capture behind /debug/profile (operator-requested or SLO
            burn-triggered).
  perfcheck the noise-aware bench-tail regression gate
            (`python -m trivy_tpu.obs.perfcheck OLD.json NEW.json`).

Metrics live in `trivy_tpu.metrics` (the registry predates this
package and is imported everywhere). See ARCHITECTURE.md "Fleet
observability (graftwatch)" for the span taxonomy, retention policy,
and SLO definitions.
"""

from .device import device_status, note_dispatch
from .perf import LEDGER, PROF
from .recorder import RECORDER
from .slo import SLO
from .trace import (COLLECTOR, add_attr, chrome_trace, current_span_id,
                    current_trace_id, ensure_trace, new_trace,
                    recording, span, write_chrome_trace)

__all__ = [
    "COLLECTOR", "LEDGER", "PROF", "RECORDER", "SLO", "add_attr",
    "chrome_trace", "current_span_id", "current_trace_id",
    "device_status", "ensure_trace", "new_trace", "note_dispatch",
    "recording", "span", "write_chrome_trace",
]
