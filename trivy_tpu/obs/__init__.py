"""graftscope — tracing, device-phase timing, and backend status.

`trace` is the span/tracer core (context-local spans, trace-id
propagation, Chrome trace-event export); `device` is the cached
backend view /healthz serves. Metrics live in `trivy_tpu.metrics`
(the registry predates this package and is imported everywhere).

See ARCHITECTURE.md "Observability (graftscope)" for the span
taxonomy and how to add a span.
"""

from .device import device_status, note_dispatch
from .trace import (COLLECTOR, add_attr, chrome_trace, current_trace_id,
                    ensure_trace, new_trace, recording, span,
                    write_chrome_trace)

__all__ = [
    "COLLECTOR", "add_attr", "chrome_trace", "current_trace_id",
    "device_status", "ensure_trace", "new_trace", "note_dispatch",
    "recording", "span", "write_chrome_trace",
]
