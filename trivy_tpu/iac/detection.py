"""Config file-type detection (reference pkg/iac/detection/detect.go).

Terraform by extension; YAML/JSON sniffed by content for CloudFormation
(Resources with AWS:: types / AWSTemplateFormatVersion) and Kubernetes
(apiVersion+kind), matching the reference's sniffers."""

from __future__ import annotations

import json


def _is_cfn(data) -> bool:
    if not isinstance(data, dict):
        return False
    if "AWSTemplateFormatVersion" in data:
        return True
    res = data.get("Resources")
    if isinstance(res, dict):
        for v in res.values():
            if isinstance(v, dict) and \
                    str(v.get("Type", "")).startswith("AWS::"):
                return True
    return False


def _is_k8s(data) -> bool:
    return isinstance(data, dict) and "apiVersion" in data and \
        "kind" in data


# full YAML/JSON composition is paid only for files that could be IaC:
# bounded size and containing a dialect marker somewhere in the bytes
# (a cheap substring scan, vs. the full position-aware parse)
MAX_SNIFF_SIZE = 3 * 1024 * 1024
_MARKERS = (b"apiVersion", b"AWSTemplateFormatVersion", b"Resources",
            b"planned_values", b"deploymentTemplate.json")


def sniff(path: str, content: bytes):
    """→ (file_type, parsed_docs | None).  The parsed documents are
    forwarded to the scanner so YAML/JSON is composed only once per file
    (the per-file analyzer otherwise pays two full parse passes)."""
    base = path.rsplit("/", 1)[-1].lower()
    if base == "dockerfile" or base.startswith("dockerfile.") or \
            base.endswith(".dockerfile"):
        return "dockerfile", None
    if base.endswith((".tf", ".tf.json")) or \
            base.endswith("terraform.tfvars"):
        return "terraform", None
    if len(content) > MAX_SNIFF_SIZE or \
            not any(m in content for m in _MARKERS):
        return "", None
    if base.endswith((".yaml", ".yml")):
        text = content.decode("utf-8", errors="replace")
        from .yamlpos import load_documents
        docs = load_documents(text)
        for doc in docs:
            if _is_cfn(doc):
                return "cloudformation", docs
            if _is_k8s(doc):
                return "kubernetes", docs
        return "", None
    if base.endswith(".json"):
        try:
            data = json.loads(content.decode("utf-8", errors="replace"))
        except Exception:
            return "", None
        docs = data if isinstance(data, list) else [data]
        for doc in docs:
            if _is_cfn(doc):
                return "cloudformation", docs
            if _is_k8s(doc):
                return "kubernetes", docs
            if _is_tfplan(doc):
                return "terraformplan", docs
            if _is_arm(doc):
                return "azure-arm", docs
        return "", None
    return "", None


def _is_arm(doc) -> bool:
    """ARM deployment template (reference pkg/iac/detection
    FileTypeAzureARM: $schema …/deploymentTemplate.json)."""
    return isinstance(doc, dict) and \
        "deploymentTemplate.json" in str(doc.get("$schema", ""))


def _is_tfplan(doc) -> bool:
    """terraform show -json output (reference pkg/iac/detection
    FileTypeTerraformPlanJSON: format_version + planned values)."""
    return isinstance(doc, dict) and "format_version" in doc and \
        ("planned_values" in doc or "resource_changes" in doc) and \
        "terraform_version" in doc


def detect_config_type(path: str, content: bytes) -> str:
    """→ one of terraform/cloudformation/kubernetes/dockerfile/'' ."""
    return sniff(path, content)[0]
