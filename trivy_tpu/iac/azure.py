"""Azure ARM template scanner (reference pkg/iac/scanners/azure/arm).

Parses ARM deployment templates (JSON with positions via the YAML
loader, like the cloudformation scanner), evaluates the ARM expression
language (`[concat(parameters('x'), '-suffix')]` — reference
pkg/iac/scanners/azure/{expressions,functions,resolver}), walks the
resource tree including nested child resources
(deployment.go GetResourcesByType), adapts resources into the shared
cloud-state model (pkg/iac/adapters/arm/*), and evaluates an AVD-AZU
check set over it.

Unresolvable expressions (reference(), runtime params) become UNKNOWN
and pass checks, matching the tri-state semantics used by the terraform
and cloudformation scanners.
"""

from __future__ import annotations

import hashlib
import json
import re

from .. import types as T
from .cloud import Attr, CloudResource, UNKNOWN, Unknown
from .core import Check, build_misconf, ignored_ids_by_line, is_ignored
from .yamlpos import load_documents, value_range


# ---- ARM expression language -----------------------------------------

class _ExprError(Exception):
    pass


_EXPR_TOKEN = re.compile(r"""
    (?P<ws>\s+)
  | (?P<str>'(?:''|[^'])*')
  | (?P<num>-?\d+(?:\.\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<punct>[().,\[\]])
""", re.VERBOSE)


def _lex_expr(src: str):
    toks = []
    pos = 0
    while pos < len(src):
        m = _EXPR_TOKEN.match(src, pos)
        if not m:
            raise _ExprError(f"bad expression at {src[pos:]!r}")
        pos = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "str":
            toks.append(("str", text[1:-1].replace("''", "'")))
        elif kind == "num":
            toks.append(("num", float(text) if "." in text
                         else int(text)))
        elif kind == "ident":
            toks.append(("ident", text))
        else:
            toks.append(("punct", text))
    toks.append(("eof", None))
    return toks


class _ExprParser:
    """expr := call | literal; postfix: .prop | [index]"""

    def __init__(self, toks):
        self.toks = toks
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def parse(self):
        node = self.parse_expr()
        if self.peek()[0] != "eof":
            raise _ExprError("trailing tokens")
        return node

    def parse_expr(self):
        k, v = self.next()
        if k == "str" or k == "num":
            node = ("lit", v)
        elif k == "ident":
            if self.peek() == ("punct", "("):
                self.next()
                args = []
                if self.peek() != ("punct", ")"):
                    while True:
                        args.append(self.parse_expr())
                        if self.peek() == ("punct", ","):
                            self.next()
                            continue
                        break
                if self.next() != ("punct", ")"):
                    raise _ExprError("expected )")
                node = ("call", v.lower(), args)
            else:
                node = ("lit", v)   # bare identifiers: true/false/null
        else:
            raise _ExprError(f"unexpected {v!r}")
        while True:
            if self.peek() == ("punct", "."):
                self.next()
                k2, name = self.next()
                if k2 != "ident":
                    raise _ExprError("expected property name")
                node = ("prop", node, name)
            elif self.peek() == ("punct", "["):
                self.next()
                idx = self.parse_expr()
                if self.next() != ("punct", "]"):
                    raise _ExprError("expected ]")
                node = ("index", node, idx)
            else:
                return node


class ArmEvaluator:
    """Evaluates ARM template expressions against a deployment's
    parameters/variables (reference resolver.go + functions/*.go)."""

    def __init__(self, parameters: dict, variables: dict):
        self.parameters = parameters or {}
        self.variables = variables or {}
        self._var_cache: dict = {}
        self._var_stack: set = set()

    # entry: resolve any JSON value recursively
    def resolve(self, value):
        if isinstance(value, str):
            return self.resolve_string(value)
        if isinstance(value, dict):
            return {k: self.resolve(v) for k, v in value.items()}
        if isinstance(value, list):
            return [self.resolve(v) for v in value]
        return value

    def resolve_string(self, s: str):
        if len(s) >= 2 and s.startswith("[") and s.endswith("]") and \
                not s.startswith("[["):
            try:
                node = _ExprParser(_lex_expr(s[1:-1])).parse()
                return self.eval(node)
            except _ExprError:
                return UNKNOWN
        if s.startswith("[["):
            return s[1:]
        return s

    def eval(self, node):
        kind = node[0]
        if kind == "lit":
            v = node[1]
            if v == "true":
                return True
            if v == "false":
                return False
            if v == "null":
                return None
            return v
        if kind == "prop":
            base = self.eval(node[1])
            if isinstance(base, Unknown):
                return UNKNOWN
            if isinstance(base, dict):
                # case-insensitive property lookup (ARM is)
                for k, v in base.items():
                    if k.lower() == node[2].lower():
                        return self.resolve(v)
            return UNKNOWN
        if kind == "index":
            base = self.eval(node[1])
            idx = self.eval(node[2])
            if isinstance(base, Unknown) or isinstance(idx, Unknown):
                return UNKNOWN
            try:
                return self.resolve(base[idx])
            except (KeyError, IndexError, TypeError):
                return UNKNOWN
        if kind == "call":
            return self.call(node[1], [self.eval(a) for a in node[2]])
        return UNKNOWN

    def call(self, name, args):
        if any(isinstance(a, Unknown) for a in args) and name not in (
                "coalesce", "if"):
            return UNKNOWN
        fn = getattr(self, f"_fn_{name}", None)
        if fn is None:
            return UNKNOWN
        try:
            return fn(*args)
        except Exception:
            return UNKNOWN

    # -- function library (subset of pkg/iac/scanners/azure/functions)
    def _fn_parameters(self, name):
        p = self.parameters.get(name)
        if isinstance(p, dict) and "defaultValue" in p:
            return self.resolve(p["defaultValue"])
        return UNKNOWN

    def _fn_variables(self, name):
        if name in self._var_cache:
            return self._var_cache[name]
        if name in self._var_stack or name not in self.variables:
            return UNKNOWN
        self._var_stack.add(name)
        try:
            v = self.resolve(self.variables[name])
        finally:
            self._var_stack.discard(name)
        self._var_cache[name] = v
        return v

    def _fn_concat(self, *args):
        if all(isinstance(a, list) for a in args):
            return [x for a in args for x in a]
        return "".join(_arm_str(a) for a in args)

    def _fn_format(self, fmt, *args):
        def sub(m):
            return _arm_str(args[int(m.group(1))])
        return re.sub(r"\{(\d+)\}", sub, fmt)

    def _fn_tolower(self, s):
        return _arm_str(s).lower()

    def _fn_toupper(self, s):
        return _arm_str(s).upper()

    def _fn_trim(self, s):
        return _arm_str(s).strip()

    def _fn_substring(self, s, start, length=None):
        s = _arm_str(s)
        start = int(start)
        return s[start:] if length is None else s[start:start + int(length)]

    def _fn_replace(self, s, old, new):
        return _arm_str(s).replace(old, new)

    def _fn_split(self, s, delim):
        if isinstance(delim, list):
            pat = "|".join(re.escape(d) for d in delim)
            return re.split(pat, _arm_str(s))
        return _arm_str(s).split(delim)

    def _fn_string(self, v):
        return _arm_str(v)

    def _fn_int(self, v):
        return int(float(v))

    def _fn_bool(self, v):
        if isinstance(v, str):
            return v.lower() == "true"
        return bool(v)

    def _fn_length(self, v):
        return len(v)

    def _fn_empty(self, v):
        return not v

    def _fn_contains(self, coll, item):
        if isinstance(coll, str):
            return _arm_str(item).lower() in coll.lower()
        if isinstance(coll, dict):
            return any(k.lower() == _arm_str(item).lower()
                       for k in coll)
        return item in coll

    def _fn_startswith(self, s, pre):
        return _arm_str(s).lower().startswith(_arm_str(pre).lower())

    def _fn_endswith(self, s, suf):
        return _arm_str(s).lower().endswith(_arm_str(suf).lower())

    def _fn_indexof(self, s, sub):
        return _arm_str(s).lower().find(_arm_str(sub).lower())

    def _fn_equals(self, a, b):
        if isinstance(a, str) and isinstance(b, str):
            return a.lower() == b.lower()
        return a == b

    def _fn_not(self, v):
        return not v

    def _fn_and(self, *args):
        return all(args)

    def _fn_or(self, *args):
        return any(args)

    def _fn_if(self, cond, then, els):
        if isinstance(cond, Unknown):
            return UNKNOWN
        return then if cond else els

    def _fn_coalesce(self, *args):
        for a in args:
            if a is not None and not isinstance(a, Unknown):
                return a
        return None

    def _fn_union(self, *args):
        if all(isinstance(a, dict) for a in args):
            out = {}
            for a in args:
                out.update(a)
            return out
        out = []
        for a in args:
            for x in a:
                if x not in out:
                    out.append(x)
        return out

    def _fn_intersection(self, *args):
        first = args[0]
        if all(isinstance(a, dict) for a in args):
            return {k: v for k, v in first.items()
                    if all(k in a for a in args[1:])}
        return [x for x in first if all(x in a for a in args[1:])]

    def _fn_first(self, v):
        return v[0] if v else ""

    def _fn_last(self, v):
        return v[-1] if v else ""

    def _fn_min(self, *a):
        vals = a[0] if len(a) == 1 and isinstance(a[0], list) else a
        return min(vals)

    def _fn_max(self, *a):
        vals = a[0] if len(a) == 1 and isinstance(a[0], list) else a
        return max(vals)

    def _fn_add(self, a, b):
        return a + b

    def _fn_sub(self, a, b):
        return a - b

    def _fn_mul(self, a, b):
        return a * b

    def _fn_div(self, a, b):
        return a // b

    def _fn_mod(self, a, b):
        return a % b

    def _fn_createarray(self, *args):
        return list(args)

    def _fn_createobject(self, *args):
        return {args[i]: args[i + 1] for i in range(0, len(args), 2)}

    def _fn_json(self, s):
        return json.loads(s)

    def _fn_range(self, start, count):
        return list(range(int(start), int(start) + int(count)))

    def _fn_uniquestring(self, *args):
        h = hashlib.sha256("|".join(_arm_str(a)
                                    for a in args).encode())
        return h.hexdigest()[:13]

    def _fn_guid(self, *args):
        h = hashlib.sha256("|".join(_arm_str(a)
                                    for a in args).encode()).hexdigest()
        return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:32]}"

    def _fn_resourceid(self, *args):
        return "/".join(_arm_str(a) for a in args)

    def _fn_resourcegroup(self):
        return {"id": "resourcegroup-id", "name": "resource-group",
                "location": "eastus"}

    def _fn_subscription(self):
        return {"id": "subscription-id",
                "subscriptionId": "subscription-id",
                "tenantId": "tenant-id"}

    def _fn_deployment(self):
        return {"name": "deployment"}

    # runtime-only: unknown
    def _fn_reference(self, *a):
        return UNKNOWN

    def _fn_list(self, *a):
        return UNKNOWN

    def _fn_listkeys(self, *a):
        return UNKNOWN

    def _fn_utcnow(self, *a):
        return UNKNOWN

    def _fn_newguid(self, *a):
        return UNKNOWN

    def _fn_copyindex(self, *a):
        return UNKNOWN


def _arm_str(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, (dict, list)):
        return json.dumps(v)
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


# ---- deployment model -------------------------------------------------

class ArmResource:
    def __init__(self, rtype: str, name, properties: dict, raw: dict,
                 rng: tuple, prop_ranges):
        self.type = rtype
        self.name = name
        self.properties = properties or {}
        self.raw = raw
        self.rng = rng
        self._prop_ranges = prop_ranges  # callable(key) -> rng

    def prop(self, *path, default=None):
        cur = self.properties
        for p in path:
            if isinstance(cur, Unknown):
                return UNKNOWN
            if not isinstance(cur, dict):
                return default
            hit = None
            for k, v in cur.items():
                if k.lower() == p.lower():
                    hit = v
                    break
            if hit is None:
                return default
            cur = hit
        return cur

    def prop_rng(self, key):
        return self._prop_ranges(key)


def parse_deployment(content: bytes):
    """→ (resources: [ArmResource], src_text) or (None, "") when the
    document is not an ARM template."""
    text = content.decode("utf-8", errors="replace")
    try:
        docs = load_documents(text)
    except Exception:
        return None, ""
    if not docs:
        return None, ""
    doc = docs[0]
    if not isinstance(doc, dict) or "$schema" not in doc or \
            "deploymentTemplate.json" not in str(doc.get("$schema", "")):
        if not (isinstance(doc, dict) and "resources" in doc and
                "contentVersion" in doc):
            return None, ""
    ev = ArmEvaluator(doc.get("parameters") or {},
                      doc.get("variables") or {})
    resources: list[ArmResource] = []

    def add(node, parent_type=""):
        if not isinstance(node, dict):
            return
        rtype = node.get("type", "")
        if parent_type and "/" not in rtype.split("/", 1)[-1] and \
                not rtype.startswith("Microsoft."):
            rtype = parent_type + "/" + rtype
        rng = _node_rng(node)
        props_raw = node.get("properties") or {}

        def prop_ranges(key):
            r = value_range(props_raw, key, (0, 0))
            if r == (0, 0) and hasattr(props_raw, "key_lines"):
                for k, kr in props_raw.key_lines.items():
                    if k.lower() == key.lower():
                        return kr
            return r if r != (0, 0) else rng

        resources.append(ArmResource(
            rtype=rtype,
            name=ev.resolve(node.get("name", "")),
            properties=ev.resolve(props_raw),
            raw=node, rng=rng, prop_ranges=prop_ranges))
        for child in node.get("resources") or []:
            add(child, parent_type=rtype)

    for rnode in doc.get("resources") or []:
        add(rnode)
    return resources, text


def _node_rng(node):
    start = getattr(node, "start", 0)
    end = getattr(node, "end", 0)
    return (start, end) if start else (0, 0)


def resources_by_type(resources, rtype: str):
    rl = rtype.lower()
    for r in resources:
        t = r.type.lower()
        if t == rl or t.endswith("/" + rl):
            yield r


# ---- adapter: ARM resources → shared cloud state ---------------------

def adapt_arm(resources: list[ArmResource]) -> list[CloudResource]:
    out: list[CloudResource] = []
    for res in resources_by_type(resources,
                                 "Microsoft.Storage/storageAccounts"):
        r = CloudResource("azurerm_storage_account",
                          _arm_str(res.name), rng=res.rng)
        r.attrs["enable_https_traffic_only"] = Attr(
            res.prop("supportsHttpsTrafficOnly", default=False),
            res.prop_rng("supportsHttpsTrafficOnly"))
        r.attrs["min_tls_version"] = Attr(
            res.prop("minimumTlsVersion", default="TLS1_0"),
            res.prop_rng("minimumTlsVersion"))
        r.attrs["allow_blob_public_access"] = Attr(
            res.prop("allowBlobPublicAccess", default=True),
            res.prop_rng("allowBlobPublicAccess"))
        out.append(r)
    for res in resources_by_type(resources,
                                 "blobServices/containers"):
        r = CloudResource("azurerm_storage_container",
                          _arm_str(res.name), rng=res.rng)
        r.attrs["container_access_type"] = Attr(
            res.prop("publicAccess", default="None"),
            res.prop_rng("publicAccess"))
        out.append(r)

    # NSG rules: inline securityRules and child resources
    for res in resources_by_type(
            resources, "Microsoft.Network/networkSecurityGroups"):
        for rule in (res.prop("securityRules", default=[]) or []):
            if isinstance(rule, Unknown):
                continue
            props = rule.get("properties", rule) if \
                isinstance(rule, dict) else {}
            out.append(_nsg_rule(props, res.rng))
    for res in resources_by_type(
            resources,
            "Microsoft.Network/networkSecurityGroups/securityRules"):
        out.append(_nsg_rule(res.properties, res.rng))

    for res in resources_by_type(resources,
                                 "Microsoft.KeyVault/vaults"):
        r = CloudResource("azurerm_key_vault", _arm_str(res.name),
                          rng=res.rng)
        r.attrs["purge_protection_enabled"] = Attr(
            res.prop("enablePurgeProtection", default=False),
            res.prop_rng("enablePurgeProtection"))
        r.attrs["soft_delete_retention_days"] = Attr(
            res.prop("softDeleteRetentionInDays", default=0),
            res.prop_rng("softDeleteRetentionInDays"))
        acls = res.prop("networkAcls")
        r.attrs["network_acls_default_action"] = Attr(
            (acls or {}).get("defaultAction") if isinstance(acls, dict)
            else (UNKNOWN if isinstance(acls, Unknown) else None),
            res.prop_rng("networkAcls"))
        out.append(r)
    for res in resources_by_type(resources, "vaults/secrets"):
        r = CloudResource("azurerm_key_vault_secret",
                          _arm_str(res.name), rng=res.rng)
        attrs = res.prop("attributes", default={})
        r.attrs["expiration_date"] = Attr(
            attrs.get("exp") if isinstance(attrs, dict) else UNKNOWN,
            res.prop_rng("attributes"))
        r.attrs["content_type"] = Attr(
            res.prop("contentType", default=""),
            res.prop_rng("contentType"))
        out.append(r)

    for res in resources_by_type(resources, "Microsoft.Sql/servers"):
        r = CloudResource("azurerm_mssql_server", _arm_str(res.name),
                          rng=res.rng)
        r.attrs["minimum_tls_version"] = Attr(
            res.prop("minimalTlsVersion", default=""),
            res.prop_rng("minimalTlsVersion"))
        r.attrs["public_network_access_enabled"] = Attr(
            _arm_str(res.prop("publicNetworkAccess",
                              default="Enabled")).lower() == "enabled",
            res.prop_rng("publicNetworkAccess"))
        out.append(r)
    for res in resources_by_type(resources, "servers/firewallRules"):
        r = CloudResource("azurerm_sql_firewall_rule",
                          _arm_str(res.name), rng=res.rng)
        r.attrs["start_ip_address"] = Attr(
            res.prop("startIpAddress"), res.prop_rng("startIpAddress"))
        r.attrs["end_ip_address"] = Attr(
            res.prop("endIpAddress"), res.prop_rng("endIpAddress"))
        out.append(r)

    for res in resources_by_type(
            resources, "Microsoft.DBforPostgreSQL/servers"):
        r = CloudResource("azurerm_postgresql_server",
                          _arm_str(res.name), rng=res.rng)
        r.attrs["ssl_enforcement_enabled"] = Attr(
            _arm_str(res.prop("sslEnforcement",
                              default="Disabled")).lower() == "enabled",
            res.prop_rng("sslEnforcement"))
        out.append(r)

    for res in resources_by_type(resources, "Microsoft.Web/sites"):
        r = CloudResource("azurerm_app_service", _arm_str(res.name),
                          rng=res.rng)
        r.attrs["https_only"] = Attr(
            res.prop("httpsOnly", default=False),
            res.prop_rng("httpsOnly"))
        site_cfg = res.prop("siteConfig", default={})
        r.attrs["min_tls_version"] = Attr(
            site_cfg.get("minTlsVersion", "1.2")
            if isinstance(site_cfg, dict) else UNKNOWN,
            res.prop_rng("siteConfig"))
        out.append(r)

    for res in resources_by_type(
            resources, "Microsoft.Compute/virtualMachines"):
        r = CloudResource("azurerm_linux_virtual_machine",
                          _arm_str(res.name), rng=res.rng)
        lincfg = res.prop("osProfile", "linuxConfiguration")
        if isinstance(lincfg, dict):
            r.attrs["disable_password_authentication"] = Attr(
                lincfg.get("disablePasswordAuthentication", False),
                res.prop_rng("osProfile"))
            out.append(r)
        elif isinstance(lincfg, Unknown):
            r.attrs["disable_password_authentication"] = Attr(
                UNKNOWN, res.prop_rng("osProfile"))
            out.append(r)

    for res in resources_by_type(
            resources, "Microsoft.ContainerService/managedClusters"):
        r = CloudResource("azurerm_kubernetes_cluster",
                          _arm_str(res.name), rng=res.rng)
        r.attrs["role_based_access_control_enabled"] = Attr(
            res.prop("enableRBAC", default=False),
            res.prop_rng("enableRBAC"))
        r.attrs["private_cluster_enabled"] = Attr(
            res.prop("apiServerAccessProfile", "enablePrivateCluster",
                     default=False),
            res.prop_rng("apiServerAccessProfile"))
        out.append(r)
    return out


def _nsg_rule(props: dict, rng: tuple) -> CloudResource:
    if not isinstance(props, dict):
        props = {}

    def get(key, default=None):
        for k, v in props.items():
            if k.lower() == key.lower():
                return v
        return default

    r = CloudResource("azurerm_network_security_rule", "", rng=rng)
    srcs = list(get("sourceAddressPrefixes") or [])
    one = get("sourceAddressPrefix")
    if one:
        srcs.append(one)
    dsts = list(get("destinationAddressPrefixes") or [])
    one = get("destinationAddressPrefix")
    if one:
        dsts.append(one)
    ports = list(get("destinationPortRanges") or [])
    one = get("destinationPortRange")
    if one is not None:
        ports.append(one)
    r.attrs["access"] = Attr(get("access", ""), rng)
    r.attrs["direction"] = Attr(get("direction", ""), rng)
    r.attrs["source_address_prefixes"] = Attr(srcs, rng)
    r.attrs["destination_address_prefixes"] = Attr(dsts, rng)
    r.attrs["destination_port_ranges"] = Attr(
        [_arm_str(p) for p in ports if not isinstance(p, Unknown)], rng)
    return r


# ---- AVD-AZU checks ---------------------------------------------------

AZURE_CHECKS: list[Check] = []


def _azu(id_, title, severity, service, description="", resolution=""):
    def deco(fn):
        AZURE_CHECKS.append(Check(
            id=id_, avd_id=id_, title=title, severity=severity,
            description=description, resolution=resolution,
            provider="Azure", service=service,
            namespace=f"builtin.azure.{service}.{id_}", fn=fn))
        return fn
    return deco


def _of(resources, kind):
    return [r for r in resources if r.kind == kind]


@_azu("AVD-AZU-0008", "Storage accounts should enforce HTTPS", "HIGH",
      "storage",
      description="Requiring secure transfer ensures data in transit "
                  "is encrypted.",
      resolution="Set supportsHttpsTrafficOnly to true.")
def _storage_https(resources):
    for r in _of(resources, "azurerm_storage_account"):
        v = r.val("enable_https_traffic_only")
        if v is False:
            yield ("Account does not enforce HTTPS.",
                   r.attr_rng("enable_https_traffic_only"))


@_azu("AVD-AZU-0011", "Storage accounts should use a secure TLS policy",
      "CRITICAL", "storage",
      description="TLS 1.0/1.1 are vulnerable; storage accounts should "
                  "require TLS1_2.",
      resolution="Set minimumTlsVersion to TLS1_2.")
def _storage_tls(resources):
    for r in _of(resources, "azurerm_storage_account"):
        v = r.val("min_tls_version")
        if isinstance(v, str) and v in ("TLS1_0", "TLS1_1"):
            yield (f"Account uses insecure TLS version ({v}).",
                   r.attr_rng("min_tls_version"))


@_azu("AVD-AZU-0007", "Storage containers should deny public access",
      "HIGH", "storage",
      description="Anonymous public read access to containers exposes "
                  "blob data.",
      resolution="Set publicAccess to None.")
def _container_public(resources):
    for r in _of(resources, "azurerm_storage_container"):
        v = r.val("container_access_type")
        if isinstance(v, str) and v.lower() in ("blob", "container"):
            yield ("Container allows public access.",
                   r.attr_rng("container_access_type"))


def _is_public_prefix(p) -> bool:
    if not isinstance(p, str):
        return False
    return p in ("*", "0.0.0.0", "0.0.0.0/0", "internet", "Internet",
                 "any", "Any") or p.endswith("/0")


@_azu("AVD-AZU-0047",
      "Security group rules should not allow ingress from any IP",
      "CRITICAL", "network",
      description="Opening inbound traffic to every address exposes "
                  "the resource to the internet.",
      resolution="Restrict sourceAddressPrefix.")
def _nsg_public_ingress(resources):
    for r in _of(resources, "azurerm_network_security_rule"):
        if _arm_str(r.val("access", "")).lower() != "allow":
            continue
        if _arm_str(r.val("direction", "")).lower() != "inbound":
            continue
        for p in r.val("source_address_prefixes", []) or []:
            if _is_public_prefix(p):
                yield ("Security group rule allows ingress from "
                       "public internet.", r.rng)
                break


@_azu("AVD-AZU-0051",
      "Security group rules should not allow egress to any IP",
      "CRITICAL", "network",
      description="Unrestricted egress eases data exfiltration.",
      resolution="Restrict destinationAddressPrefix.")
def _nsg_public_egress(resources):
    for r in _of(resources, "azurerm_network_security_rule"):
        if _arm_str(r.val("access", "")).lower() != "allow":
            continue
        if _arm_str(r.val("direction", "")).lower() != "outbound":
            continue
        for p in r.val("destination_address_prefixes", []) or []:
            if _is_public_prefix(p):
                yield ("Security group rule allows egress to public "
                       "internet.", r.rng)
                break


def _rule_covers_port(r, port: int) -> bool:
    for pr in r.val("destination_port_ranges", []) or []:
        pr = str(pr)
        if pr == "*":
            return True
        if "-" in pr:
            try:
                lo, hi = pr.split("-", 1)
                if int(lo) <= port <= int(hi):
                    return True
            except ValueError:
                continue
        else:
            try:
                if int(pr) == port:
                    return True
            except ValueError:
                continue
    return False


@_azu("AVD-AZU-0050", "SSH should be blocked from the internet",
      "CRITICAL", "network",
      description="SSH port 22 open to the internet invites "
                  "brute-force attacks.",
      resolution="Block port 22 from public sources.")
def _nsg_ssh(resources):
    for r in _of(resources, "azurerm_network_security_rule"):
        if _arm_str(r.val("access", "")).lower() != "allow" or \
                _arm_str(r.val("direction", "")).lower() != "inbound":
            continue
        if any(_is_public_prefix(p)
               for p in r.val("source_address_prefixes", []) or []) \
                and _rule_covers_port(r, 22):
            yield ("SSH port 22 is exposed to the internet.", r.rng)


@_azu("AVD-AZU-0048", "RDP should be blocked from the internet",
      "CRITICAL", "network",
      description="RDP port 3389 open to the internet invites "
                  "brute-force attacks.",
      resolution="Block port 3389 from public sources.")
def _nsg_rdp(resources):
    for r in _of(resources, "azurerm_network_security_rule"):
        if _arm_str(r.val("access", "")).lower() != "allow" or \
                _arm_str(r.val("direction", "")).lower() != "inbound":
            continue
        if any(_is_public_prefix(p)
               for p in r.val("source_address_prefixes", []) or []) \
                and _rule_covers_port(r, 3389):
            yield ("RDP port 3389 is exposed to the internet.", r.rng)


@_azu("AVD-AZU-0016", "Key vaults should have purge protection",
      "MEDIUM", "keyvault",
      description="Purge protection prevents immediate permanent "
                  "deletion of vault contents.",
      resolution="Set enablePurgeProtection to true.")
def _kv_purge(resources):
    for r in _of(resources, "azurerm_key_vault"):
        if r.val("purge_protection_enabled") is False:
            yield ("Vault does not enable purge protection.",
                   r.attr_rng("purge_protection_enabled"))


@_azu("AVD-AZU-0013", "Key vaults should restrict network access",
      "CRITICAL", "keyvault",
      description="Without a network ACL default-deny, the vault is "
                  "reachable from any network.",
      resolution="Set networkAcls.defaultAction to Deny.")
def _kv_acl(resources):
    for r in _of(resources, "azurerm_key_vault"):
        v = r.val("network_acls_default_action")
        if v is None or (isinstance(v, str) and v.lower() == "allow"):
            yield ("Vault network ACL does not default to Deny.",
                   r.attr_rng("network_acls_default_action"))


@_azu("AVD-AZU-0017", "Key vault secrets should have an expiry",
      "MEDIUM", "keyvault",
      description="Secrets without expiration dates linger forever if "
                  "leaked.",
      resolution="Set attributes.exp on the secret.")
def _kv_secret_exp(resources):
    for r in _of(resources, "azurerm_key_vault_secret"):
        if not r.unknown("expiration_date") and \
                not r.val("expiration_date"):
            yield ("Secret has no expiration date.", r.rng)


@_azu("AVD-AZU-0018",
      "PostgreSQL servers should enforce SSL connections", "HIGH",
      "database",
      description="Unencrypted database connections expose credentials "
                  "and data.",
      resolution="Set sslEnforcement to Enabled.")
def _pg_ssl(resources):
    for r in _of(resources, "azurerm_postgresql_server"):
        if r.val("ssl_enforcement_enabled") is False:
            yield ("SSL enforcement is disabled.",
                   r.attr_rng("ssl_enforcement_enabled"))


@_azu("AVD-AZU-0026",
      "SQL servers should use a secure TLS version", "MEDIUM",
      "database",
      description="Old TLS versions are vulnerable to downgrade "
                  "attacks.",
      resolution="Set minimalTlsVersion to 1.2.")
def _sql_tls(resources):
    for r in _of(resources, "azurerm_mssql_server"):
        v = r.val("minimum_tls_version")
        if isinstance(v, str) and v in ("1.0", "1.1"):
            yield (f"Server allows TLS {v}.",
                   r.attr_rng("minimum_tls_version"))


@_azu("AVD-AZU-0027",
      "SQL firewall rules should not allow public access", "HIGH",
      "database",
      description="A 0.0.0.0 firewall range opens the server to every "
                  "Azure/Internet address.",
      resolution="Restrict firewall start/end addresses.")
def _sql_fw(resources):
    for r in _of(resources, "azurerm_sql_firewall_rule"):
        start = _arm_str(r.val("start_ip_address", ""))
        end = _arm_str(r.val("end_ip_address", ""))
        if start == "0.0.0.0" and end in ("0.0.0.0", "255.255.255.255"):
            yield ("Firewall rule allows public access.", r.rng)


@_azu("AVD-AZU-0002", "App services should enforce HTTPS", "HIGH",
      "appservice",
      description="HTTP traffic to the app is unencrypted.",
      resolution="Set httpsOnly to true.")
def _app_https(resources):
    for r in _of(resources, "azurerm_app_service"):
        if r.val("https_only") is False:
            yield ("App service does not enforce HTTPS.",
                   r.attr_rng("https_only"))


@_azu("AVD-AZU-0039",
      "Linux VMs should disable password authentication", "HIGH",
      "compute",
      description="SSH keys resist brute-force attacks; passwords "
                  "do not.",
      resolution="Set disablePasswordAuthentication to true.")
def _vm_password(resources):
    for r in _of(resources, "azurerm_linux_virtual_machine"):
        if r.val("disable_password_authentication") is False:
            yield ("VM allows password authentication.",
                   r.attr_rng("disable_password_authentication"))


@_azu("AVD-AZU-0042", "AKS clusters should enable RBAC", "HIGH",
      "container",
      description="RBAC limits who can read/modify cluster state.",
      resolution="Set enableRBAC to true.")
def _aks_rbac(resources):
    for r in _of(resources, "azurerm_kubernetes_cluster"):
        if r.val("role_based_access_control_enabled") is False:
            yield ("Cluster does not enable RBAC.",
                   r.attr_rng("role_based_access_control_enabled"))


# ---- scanning entry ---------------------------------------------------

def scan_arm(path: str, content: bytes, lines=None, docs=None):
    """→ (failures, successes) in the shared misconf shape."""
    resources, text = parse_deployment(content)
    if resources is None:
        return [], 0
    adapted = adapt_arm(resources)
    src_lines = text.splitlines()
    ignores = ignored_ids_by_line(text)
    failures = []
    successes = 0
    for check in AZURE_CHECKS:
        found = [x for x in check.fn(adapted)
                 if not is_ignored(ignores, check, x[1][0])]
        if not found:
            successes += 1
            continue
        for msg, rng in found:
            failures.append(build_misconf(
                check, "azure-arm", msg, rng, src_lines))
    return failures, successes


def is_arm_template(doc) -> bool:
    return isinstance(doc, dict) and (
        "deploymentTemplate.json" in str(doc.get("$schema", "")) or
        ("resources" in doc and "contentVersion" in doc))


# ---- terraform azurerm adapter --------------------------------------

_TF_AZURE_KINDS = {
    "azurerm_storage_account", "azurerm_storage_container",
    "azurerm_network_security_rule", "azurerm_key_vault",
    "azurerm_key_vault_secret", "azurerm_postgresql_server",
    "azurerm_mssql_server", "azurerm_sql_firewall_rule",
    "azurerm_app_service", "azurerm_linux_virtual_machine",
    "azurerm_kubernetes_cluster",
}


def adapt_azurerm(module) -> list:
    """Terraform azurerm_* resources → the same CloudResource shapes
    the ARM-template adapter produces (the AZURE_CHECKS read terraform
    argument names — the ARM adapter normalizes TO them, reference
    pkg/iac/adapters/{arm,terraform}/azure share one provider
    model)."""
    from .cloud import Attr, CloudResource, block_attr

    out = []
    for res in module.resources:
        t = res.type
        if t not in _TF_AZURE_KINDS:
            continue
        cr = CloudResource(t, res.name, rng=res.rng(), path=res.path)
        for key, (value, rng) in res.attrs.items():
            cr.attrs[key] = Attr(value, rng)
        if t == "azurerm_network_security_rule":
            # singular argument variants normalize to the plural lists
            for single, plural in (
                    ("source_address_prefix",
                     "source_address_prefixes"),
                    ("destination_address_prefix",
                     "destination_address_prefixes"),
                    ("destination_port_range",
                     "destination_port_ranges")):
                if plural not in cr.attrs and single in cr.attrs:
                    a = cr.attrs[single]
                    cr.attrs[plural] = Attr([a.value], a.rng)
            # the NSG checks iterate these lists (the ARM adapter
            # pre-sanitizes); Unknown values/elements must neither
            # crash nor fire
            from .cloud import Unknown as _Unk
            for key in ("source_address_prefixes",
                        "destination_address_prefixes",
                        "destination_port_ranges"):
                a = cr.attrs.get(key)
                if a is None:
                    continue
                if isinstance(a.value, _Unk):
                    cr.attrs[key] = Attr([], a.rng)
                elif isinstance(a.value, list):
                    cr.attrs[key] = Attr(
                        [x for x in a.value
                         if isinstance(x, (str, int))], a.rng)
        elif t == "azurerm_key_vault":
            for b in res.blocks("network_acls"):
                v, rng = block_attr(module, b, "default_action", "")
                cr.attrs["network_acls_default_action"] = Attr(v, rng)
            # terraform default: purge protection off
            if "purge_protection_enabled" not in cr.attrs:
                cr.attrs["purge_protection_enabled"] = Attr(False)
        elif t == "azurerm_app_service":
            # terraform default: https_only off
            if "https_only" not in cr.attrs:
                cr.attrs["https_only"] = Attr(False)
        elif t == "azurerm_linux_virtual_machine":
            # terraform default: password auth DISABLED unless set
            if "disable_password_authentication" not in cr.attrs:
                cr.attrs["disable_password_authentication"] = \
                    Attr(True)
        elif t == "azurerm_kubernetes_cluster":
            # legacy nested block form: role_based_access_control {}
            for b in res.blocks("role_based_access_control"):
                v, rng = block_attr(module, b, "enabled", True)
                cr.attrs["role_based_access_control_enabled"] = \
                    Attr(v, rng)
        out.append(cr)
    return out
