"""Kubernetes manifest checks (reference pkg/iac/scanners/kubernetes +
trivy-checks kubernetes KSV-series policies).

Parses multi-document YAML/JSON manifests with source positions, walks
pod specs out of every workload kind, and evaluates native
reimplementations of the published KSV checks — IDs, severities, and
message shapes follow avd.aquasec.com so output lines up with the
reference's rego results."""

from __future__ import annotations

import json

from .. import types as T
from .core import Check, run_checks
from .yamlpos import PosDict, PosList, load_documents, value_range

_WORKLOAD_KINDS = {
    "Pod", "Deployment", "StatefulSet", "DaemonSet", "ReplicaSet",
    "ReplicationController", "Job", "CronJob",
}


def _dig(d, *keys):
    """Safe nested lookup: any non-dict along the path → None."""
    for k in keys:
        if not isinstance(d, dict):
            return None
        d = d.get(k)
    return d


def _pod_spec(doc):
    kind = doc.get("kind")
    if kind == "Pod":
        return doc.get("spec")
    if kind == "CronJob":
        return _dig(doc, "spec", "jobTemplate", "spec", "template",
                    "spec")
    if kind in _WORKLOAD_KINDS:
        return _dig(doc, "spec", "template", "spec")
    return None


def _containers(spec):
    """→ [(container_dict, range)] over containers + initContainers."""
    out = []
    for key in ("containers", "initContainers"):
        lst = spec.get(key)
        if isinstance(lst, PosList):
            for i, c in enumerate(lst):
                if isinstance(c, dict):
                    out.append((c, value_range(lst, i)))
        elif isinstance(lst, list):
            out.extend((c, (0, 0)) for c in lst if isinstance(c, dict))
    return out


def _rng(container, key, fallback):
    r = value_range(container, key)
    return r if r != (0, 0) else fallback


def _sec_ctx(c):
    sc = c.get("securityContext")
    return sc if isinstance(sc, dict) else {}


def _name(doc):
    md = doc.get("metadata")
    if isinstance(md, dict):
        return md.get("name", "")
    return ""


def _cname(c):
    return c.get("name", "")


class _Ctx:
    def __init__(self, doc):
        self.doc = doc
        self.kind = doc.get("kind", "")
        self.name = _name(doc)
        self.spec = _pod_spec(doc) if isinstance(doc, dict) else None
        self.containers = _containers(self.spec) \
            if isinstance(self.spec, dict) else []


CHECKS: list[Check] = []


def _k(id_, title, severity, description="", resolution=""):
    def deco(fn):
        CHECKS.append(Check(
            id=id_, avd_id=f"AVD-{id_[:3]}-{int(id_[3:]):04d}",
            title=title, severity=severity, description=description,
            resolution=resolution, provider="Kubernetes",
            service="general",
            namespace=f"builtin.kubernetes.{id_}", fn=fn))
        return fn
    return deco


@_k("KSV001", "Process can elevate its own privileges", "MEDIUM",
    "A program inside the container can elevate its own privileges and "
    "run as root.",
    "Set 'set containers[].securityContext.allowPrivilegeEscalation' "
    "to 'false'.")
def _priv_escalation(ctx):
    for c, crng in ctx.containers:
        sc = _sec_ctx(c)
        if sc.get("allowPrivilegeEscalation") is not False:
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.allowPrivilegeEscalation'"
                   f" to false", _rng(c, "securityContext", crng))


@_k("KSV003", "Default capabilities not dropped", "LOW",
    "The container should drop all default capabilities and add only "
    "those that are needed for its execution.",
    "Add 'ALL' to containers[].securityContext.capabilities.drop.")
def _drop_caps(ctx):
    for c, crng in ctx.containers:
        caps = _sec_ctx(c).get("capabilities")
        drop = caps.get("drop") if isinstance(caps, dict) else None
        names = {str(x).upper() for x in drop} if isinstance(drop, list) \
            else set()
        if not ({"ALL", "NET_RAW"} & names):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should add 'ALL' to 'securityContext.capabilities."
                   f"drop'", crng)


@_k("KSV008", "Access to host IPC namespace", "HIGH",
    "Sharing the host's IPC namespace allows container processes to "
    "communicate with processes on the host.",
    "Do not set 'spec.template.spec.hostIPC' to true.")
def _host_ipc(ctx):
    if ctx.spec.get("hostIPC") is True:
        yield (f"{ctx.kind} '{ctx.name}' should not set "
               f"'spec.template.spec.hostIPC' to true",
               value_range(ctx.spec, "hostIPC"))


@_k("KSV009", "Access to host network", "HIGH",
    "Sharing the host's network namespace permits processes in the pod "
    "to communicate with processes bound to the host's loopback adapter.",
    "Do not set 'spec.template.spec.hostNetwork' to true.")
def _host_network(ctx):
    if ctx.spec.get("hostNetwork") is True:
        yield (f"{ctx.kind} '{ctx.name}' should not set "
               f"'spec.template.spec.hostNetwork' to true",
               value_range(ctx.spec, "hostNetwork"))


@_k("KSV010", "Access to host PID", "HIGH",
    "Sharing the host's PID namespace allows visibility on host "
    "processes, potentially leaking information such as environment "
    "variables and configuration.",
    "Do not set 'spec.template.spec.hostPID' to true.")
def _host_pid(ctx):
    if ctx.spec.get("hostPID") is True:
        yield (f"{ctx.kind} '{ctx.name}' should not set "
               f"'spec.template.spec.hostPID' to true",
               value_range(ctx.spec, "hostPID"))


@_k("KSV011", "CPU not limited", "LOW",
    "Enforcing CPU limits prevents DoS via resource exhaustion.",
    "Add a cpu limitation to 'spec.resources.limits.cpu'.")
def _cpu_limit(ctx):
    for c, crng in ctx.containers:
        limits = (c.get("resources") or {}).get("limits") \
            if isinstance(c.get("resources"), dict) else None
        if not (isinstance(limits, dict) and limits.get("cpu")):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'resources.limits.cpu'",
                   _rng(c, "resources", crng))


@_k("KSV012", "Runs as root user", "MEDIUM",
    "Force the running image to run as a non-root user to ensure least "
    "privileges.",
    "Set 'containers[].securityContext.runAsNonRoot' to true.")
def _run_as_non_root(ctx):
    pod_sc = ctx.spec.get("securityContext")
    pod_val = pod_sc.get("runAsNonRoot") \
        if isinstance(pod_sc, dict) else None
    for c, crng in ctx.containers:
        c_val = _sec_ctx(c).get("runAsNonRoot")
        # container-level setting overrides the pod-level one
        effective = c_val if c_val is not None else pod_val
        if effective is not True:
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.runAsNonRoot' to true",
                   _rng(c, "securityContext", crng))


@_k("KSV013", "Image tag ':latest' used", "MEDIUM",
    "It is best to avoid using the ':latest' image tag when deploying "
    "containers in production, as it is harder to track which version "
    "of the image is running.",
    "Use a specific container image tag that is not 'latest'.")
def _latest_tag(ctx):
    for c, crng in ctx.containers:
        image = str(c.get("image", ""))
        if not image:
            continue
        last = image.split("/")[-1]
        if "@" in last:
            continue
        tag = last.rsplit(":", 1)[1] if ":" in last else ""
        if tag in ("", "latest"):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should specify an image tag", _rng(c, "image", crng))


@_k("KSV014", "Root file system is not read-only", "HIGH",
    "An immutable root file system prevents applications from writing "
    "to their local disk.",
    "Change 'containers[].securityContext.readOnlyRootFilesystem' to "
    "true.")
def _readonly_rootfs(ctx):
    for c, crng in ctx.containers:
        if _sec_ctx(c).get("readOnlyRootFilesystem") is not True:
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.readOnlyRootFilesystem'"
                   f" to true", _rng(c, "securityContext", crng))


@_k("KSV015", "CPU requests not specified", "LOW",
    "When containers have resource requests specified, the scheduler "
    "can make better decisions about which nodes to place pods on.",
    "Set 'containers[].resources.requests.cpu'.")
def _cpu_request(ctx):
    for c, crng in ctx.containers:
        req = (c.get("resources") or {}).get("requests") \
            if isinstance(c.get("resources"), dict) else None
        if not (isinstance(req, dict) and req.get("cpu")):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'resources.requests.cpu'",
                   _rng(c, "resources", crng))


@_k("KSV016", "Memory requests not specified", "LOW",
    "When containers have memory requests specified, the scheduler can "
    "make better decisions about which nodes to place pods on.",
    "Set 'containers[].resources.requests.memory'.")
def _mem_request(ctx):
    for c, crng in ctx.containers:
        req = (c.get("resources") or {}).get("requests") \
            if isinstance(c.get("resources"), dict) else None
        if not (isinstance(req, dict) and req.get("memory")):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'resources.requests.memory'",
                   _rng(c, "resources", crng))


@_k("KSV017", "Privileged container", "HIGH",
    "Privileged containers share namespaces with the host system and "
    "do not offer any security isolation.",
    "Change 'containers[].securityContext.privileged' to false.")
def _privileged(ctx):
    for c, crng in ctx.containers:
        if _sec_ctx(c).get("privileged") is True:
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.privileged' to false",
                   _rng(c, "securityContext", crng))


@_k("KSV018", "Memory not limited", "LOW",
    "Enforcing memory limits prevents DoS via resource exhaustion.",
    "Set a limit value under 'containers[].resources.limits.memory'.")
def _mem_limit(ctx):
    for c, crng in ctx.containers:
        limits = (c.get("resources") or {}).get("limits") \
            if isinstance(c.get("resources"), dict) else None
        if not (isinstance(limits, dict) and limits.get("memory")):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'resources.limits.memory'",
                   _rng(c, "resources", crng))


@_k("KSV020", "Runs with UID <= 10000", "LOW",
    "Force the container to run with user ID > 10000 to avoid "
    "conflicts with the host's user table.",
    "Set 'containers[].securityContext.runAsUser' to an integer > "
    "10000.")
def _low_uid(ctx):
    pod_sc = ctx.spec.get("securityContext")
    pod_uid = pod_sc.get("runAsUser") if isinstance(pod_sc, dict) else None
    for c, crng in ctx.containers:
        uid = _sec_ctx(c).get("runAsUser", pod_uid)
        if uid is None or (isinstance(uid, int) and uid <= 10000):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.runAsUser' > 10000",
                   _rng(c, "securityContext", crng))


@_k("KSV021", "Runs with GID <= 10000", "LOW",
    "Force the container to run with group ID > 10000 to avoid "
    "conflicts with the host's user table.",
    "Set 'containers[].securityContext.runAsGroup' to an integer > "
    "10000.")
def _low_gid(ctx):
    pod_sc = ctx.spec.get("securityContext")
    pod_gid = pod_sc.get("runAsGroup") if isinstance(pod_sc, dict) else None
    for c, crng in ctx.containers:
        gid = _sec_ctx(c).get("runAsGroup", pod_gid)
        if gid is None or (isinstance(gid, int) and gid <= 10000):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.runAsGroup' > 10000",
                   _rng(c, "securityContext", crng))


@_k("KSV022", "Non-default capabilities added", "MEDIUM",
    "Adding capabilities beyond the default set increases the risk of "
    "container breakout.",
    "Do not set 'spec.containers[].securityContext.capabilities.add'.")
def _added_caps(ctx):
    for c, crng in ctx.containers:
        caps = _sec_ctx(c).get("capabilities")
        add = caps.get("add") if isinstance(caps, dict) else None
        if isinstance(add, list) and add:
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should not set 'securityContext.capabilities.add'",
                   _rng(c, "securityContext", crng))


@_k("KSV023", "hostPath volumes mounted", "MEDIUM",
    "HostPath volumes must be forbidden.",
    "Do not set 'spec.volumes[*].hostPath'.")
def _hostpath(ctx):
    vols = ctx.spec.get("volumes")
    if not isinstance(vols, list):
        return
    for i, v in enumerate(vols):
        if isinstance(v, dict) and "hostPath" in v:
            yield (f"{ctx.kind} '{ctx.name}' should not set "
                   f"'spec.template.volumes.hostPath'",
                   value_range(vols, i) if isinstance(vols, PosList)
                   else (0, 0))


@_k("KSV025", "SELinux custom options set", "MEDIUM",
    "Setting a custom SELinux user or role option should be forbidden.",
    "Do not set 'spec.securityContext.seLinuxOptions', "
    "'spec.containers[*].securityContext.seLinuxOptions'.")
def _selinux(ctx):
    pod_sc = ctx.spec.get("securityContext")
    if isinstance(pod_sc, dict) and "seLinuxOptions" in pod_sc:
        opts = pod_sc["seLinuxOptions"]
        if isinstance(opts, dict) and (opts.get("user") or
                                       opts.get("role")):
            yield (f"{ctx.kind} '{ctx.name}' should not set a custom "
                   f"SELinux user or role",
                   value_range(pod_sc, "seLinuxOptions"))
    for c, crng in ctx.containers:
        opts = _sec_ctx(c).get("seLinuxOptions")
        if isinstance(opts, dict) and (opts.get("user") or
                                       opts.get("role")):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should not set a custom SELinux user or role",
                   _rng(c, "securityContext", crng))


@_k("KSV030", "Runtime/default seccomp profile not set", "LOW",
    "The runtime default seccomp profile must be required, or allow "
    "specific additional profiles.",
    "Set 'spec.securityContext.seccompProfile.type' to 'RuntimeDefault'"
    " or 'Localhost'.")
def _seccomp(ctx):
    pod_sc = ctx.spec.get("securityContext")
    pod_type = ""
    if isinstance(pod_sc, dict):
        prof = pod_sc.get("seccompProfile")
        if isinstance(prof, dict):
            pod_type = str(prof.get("type", ""))
    for c, crng in ctx.containers:
        prof = _sec_ctx(c).get("seccompProfile")
        ctype = str(prof.get("type", "")) if isinstance(prof, dict) else ""
        eff = ctype or pod_type
        if eff not in ("RuntimeDefault", "Localhost"):
            yield (f"Container '{_cname(c)}' of {ctx.kind} '{ctx.name}' "
                   f"should set 'securityContext.seccompProfile.type' to"
                   f" 'RuntimeDefault'", _rng(c, "securityContext", crng))


@_k("KSV104", "Seccomp policies disabled", "MEDIUM",
    "A program inside the container can bypass the Seccomp profile "
    "protection policies.",
    "Specify a seccomp profile (and never 'Unconfined') for every "
    "container.")
def _seccomp_disabled(ctx):
    """Fires when a container's EFFECTIVE seccomp profile (container
    securityContext, falling back to the pod's) is absent or
    Unconfined — the reference golden fires this on charts with no
    seccomp configuration at all (helm_testchart.json.golden)."""
    pod_sc = ctx.spec.get("securityContext")
    pod_type = ""
    if isinstance(pod_sc, dict):
        prof = pod_sc.get("seccompProfile")
        if isinstance(prof, dict):
            pod_type = str(prof.get("type", ""))
    for c, crng in ctx.containers:
        prof = _sec_ctx(c).get("seccompProfile")
        ctype = str(prof.get("type", "")) if isinstance(prof, dict) \
            else ""
        eff = ctype or pod_type
        if not eff or eff == "Unconfined":
            yield (f"Container '{_cname(c)}' of {ctx.kind} "
                   f"'{ctx.name}' should specify a seccomp profile",
                   _rng(c, "securityContext", crng))


@_k("KSV105", "Containers must not set runAsUser to 0", "LOW",
    "Containers should be forbidden from running with a root UID.",
    "Set 'securityContext.runAsUser' to a non-zero integer.")
def _run_as_root_uid(ctx):
    pod_sc = ctx.spec.get("securityContext")
    pod_uid = pod_sc.get("runAsUser") if isinstance(pod_sc, dict) \
        else None
    for c, crng in ctx.containers:
        uid = _sec_ctx(c).get("runAsUser", pod_uid)
        if uid == 0:
            yield ("securityContext.runAsUser should be set to a "
                   "value greater than 0",
                   _rng(c, "securityContext", crng))


@_k("KSV106", "Container capabilities must only include "
    "NET_BIND_SERVICE", "LOW",
    "Containers must drop ALL capabilities, and are only permitted to "
    "add back the NET_BIND_SERVICE capability.",
    "Set 'securityContext.capabilities.drop' to ['ALL'] and only add "
    "'NET_BIND_SERVICE' back if needed.")
def _caps_net_bind_only(ctx):
    for c, crng in ctx.containers:
        caps = _sec_ctx(c).get("capabilities")
        caps = caps if isinstance(caps, dict) else {}
        drop = caps.get("drop") or []
        add = caps.get("add") or []
        if not (isinstance(drop, list) and
                any(str(d).upper() == "ALL" for d in drop)):
            yield ("container should drop all",
                   _rng(c, "securityContext", crng))
        if isinstance(add, list) and any(
                str(a).upper() != "NET_BIND_SERVICE" for a in add):
            yield (f"Container '{_cname(c)}' of {ctx.kind} "
                   f"'{ctx.name}' should only add the "
                   f"'NET_BIND_SERVICE' capability",
                   _rng(c, "securityContext", crng))


@_k("KSV117", "Prevent binding to privileged ports", "HIGH",
    "Privileged ports (below 1024) require escalated privileges to "
    "bind, and binding them in containers suggests running with more "
    "privilege than needed.",
    "Use container ports of 1024 or above.")
def _privileged_ports(ctx):
    for c, crng in ctx.containers:
        ports = c.get("ports")
        if not isinstance(ports, list):
            continue
        for p in ports:
            port = p.get("containerPort") if isinstance(p, dict) \
                else None
            if isinstance(port, int) and 0 < port < 1024:
                yield (f"{ctx.kind.lower()} {ctx.name} should not set "
                       f"spec.template.spec.containers.ports."
                       f"containerPort to {port}",
                       _rng(c, "ports", crng))


@_k("KSV002", "Default AppArmor profile not set", "MEDIUM",
    "A program inside the container can bypass AppArmor protection "
    "policies.",
    "Remove 'container.apparmor.security.beta.kubernetes.io' "
    "annotation or set it to 'runtime/default'.")
def _apparmor(ctx):
    # AppArmor annotations live on the Pod metadata — for controllers
    # that is the pod-template metadata, not the workload's own
    sources = [ctx.doc.get("metadata")]
    if ctx.kind == "CronJob":
        sources.append(_dig(ctx.doc, "spec", "jobTemplate", "spec",
                            "template", "metadata"))
    elif ctx.kind != "Pod":
        sources.append(_dig(ctx.doc, "spec", "template", "metadata"))
    for md in sources:
        annotations = md.get("annotations") \
            if isinstance(md, dict) else None
        if not isinstance(annotations, dict):
            continue
        for key, val in annotations.items():
            if str(key).startswith(
                    "container.apparmor.security.beta.kubernetes.io/") \
                    and str(val) == "unconfined":
                yield (f"{ctx.kind} '{ctx.name}' should specify an "
                       f"AppArmor profile",
                       value_range(annotations, key))


@_k("KSV028", "Non-ephemeral volume types used", "LOW",
    "According to pod security standard 'Volume types', non-ephemeral "
    "volume types must not be used.",
    "Do not Set 'spec.volumes[*]' to any of the disallowed volume "
    "types.")
def _volume_types(ctx):
    allowed = {"configMap", "csi", "downwardAPI", "emptyDir",
               "ephemeral", "persistentVolumeClaim", "projected",
               "secret", "name"}
    vols = ctx.spec.get("volumes")
    if not isinstance(vols, list):
        return
    for i, v in enumerate(vols):
        if not isinstance(v, dict):
            continue
        bad = [k for k in v if k not in allowed]
        if bad:
            yield (f"{ctx.kind} '{ctx.name}' should not use volume type "
                   f"'{bad[0]}'",
                   value_range(vols, i) if isinstance(vols, PosList)
                   else (0, 0))


def _has_root_gid(sc) -> bool:
    """Shared by KSV029 and its PSS twin KSV116 (the upstream bundle
    carries both)."""
    return isinstance(sc, dict) and (
        sc.get("runAsGroup") == 0 or sc.get("fsGroup") == 0 or
        (isinstance(sc.get("supplementalGroups"), list) and
         0 in sc["supplementalGroups"]))


@_k("KSV029", "A root primary or supplementary GID set", "LOW",
    "Containers should be forbidden from running with a root primary "
    "or supplementary GID.",
    "Set 'containers[].securityContext.runAsGroup' to a non-zero "
    "integer or leave it unset.")
def _root_gid(ctx):
    scopes = [(ctx.spec.get("securityContext"), ctx.spec,
               "securityContext")]
    scopes += [(_sec_ctx(c), c, "securityContext")
               for c, _ in ctx.containers]
    for sc, holder, key in scopes:
        if _has_root_gid(sc):
            yield (f"{ctx.kind} '{ctx.name}' should not set a root "
                   f"group ID", value_range(holder, key))


@_k("KSV036", "Protecting Pod service account tokens", "MEDIUM",
    "Ensure that Pod specifications disable the secret token being "
    "mounted by setting automountServiceAccountToken: false.",
    "Set 'spec.automountServiceAccountToken' to false.")
def _sa_token(ctx):
    # fires only on an EXPLICIT true: the reference's rego leaves the
    # unset default alone (helm_testchart.json.golden evaluates this
    # check as a success on a chart that never sets it)
    if ctx.spec.get("automountServiceAccountToken") is True:
        yield (f"{ctx.kind} '{ctx.name}' should set "
               f"'spec.automountServiceAccountToken' to false",
               value_range(ctx.spec, "automountServiceAccountToken",
                           (ctx.spec.start, ctx.spec.start)
                           if isinstance(ctx.spec, PosDict) else (0, 0)))


@_k("KSV005", "SYS_ADMIN capability added", "HIGH",
    "SYS_ADMIN gives the container full administration operations on "
    "the host.",
    "Remove 'SYS_ADMIN' from 'securityContext.capabilities.add'.")
def _sys_admin(ctx):
    for c, crng in ctx.containers:
        caps = _sec_ctx(c).get("capabilities")
        add = caps.get("add") if isinstance(caps, dict) else None
        if isinstance(add, list) and any(
                str(a).upper() == "SYS_ADMIN" for a in add):
            yield (f"Container '{_cname(c)}' of {ctx.kind} "
                   f"'{ctx.name}' should not include 'SYS_ADMIN' in "
                   f"'securityContext.capabilities.add'",
                   _rng(c, "securityContext", crng))


@_k("KSV006", "hostPath volume mounted with docker.sock", "HIGH",
    "Mounting docker.sock gives the container full control of the "
    "host's container runtime.",
    "Do not mount '/var/run/docker.sock'.")
def _docker_sock(ctx):
    vols = ctx.spec.get("volumes")
    if not isinstance(vols, list):
        return
    for v in vols:
        hp = v.get("hostPath") if isinstance(v, dict) else None
        path = hp.get("path") if isinstance(hp, dict) else ""
        if path == "/var/run/docker.sock":
            yield (f"{ctx.kind} '{ctx.name}' should not mount "
                   f"'/var/run/docker.sock'",
                   value_range(ctx.spec, "volumes"))


@_k("KSV007", "hostAliases is set", "LOW",
    "Managing /etc/hosts via hostAliases can redirect traffic to "
    "malicious hosts.",
    "Do not set 'spec.hostAliases'.")
def _host_aliases(ctx):
    if ctx.spec.get("hostAliases") is not None:
        yield (f"{ctx.kind} '{ctx.name}' should not set "
               f"'spec.hostAliases'",
               value_range(ctx.spec, "hostAliases"))


@_k("KSV024", "Access to host ports", "HIGH",
    "hostPort binds the container to the node's network identity.",
    "Do not set 'containers[].ports[].hostPort'.")
def _host_ports(ctx):
    for c, crng in ctx.containers:
        ports = c.get("ports")
        if not isinstance(ports, list):
            continue
        for p in ports:
            if isinstance(p, dict) and p.get("hostPort") is not None:
                yield (f"Container '{_cname(c)}' of {ctx.kind} "
                       f"'{ctx.name}' should not set 'hostPort'",
                       _rng(c, "ports", crng))


_SAFE_SYSCTLS = {
    "kernel.shm_rmid_forced", "net.ipv4.ip_local_port_range",
    "net.ipv4.ip_unprivileged_port_start", "net.ipv4.tcp_syncookies",
    "net.ipv4.ping_group_range",
}


@_k("KSV026", "Unsafe sysctl options set", "MEDIUM",
    "Only a small allowlist of sysctls is considered safe to set from "
    "a pod.",
    "Remove unsafe entries from 'securityContext.sysctls'.")
def _unsafe_sysctls(ctx):
    sc = ctx.spec.get("securityContext")
    sysctls = sc.get("sysctls") if isinstance(sc, dict) else None
    if not isinstance(sysctls, list):
        return
    for s in sysctls:
        name = s.get("name") if isinstance(s, dict) else None
        if name and name not in _SAFE_SYSCTLS:
            yield (f"{ctx.kind} '{ctx.name}' should not set unsafe "
                   f"sysctl '{name}'",
                   value_range(ctx.spec, "securityContext"))


@_k("KSV027", "Non-default /proc mask set", "MEDIUM",
    "Changing procMount from the default exposes host information to "
    "the container.",
    "Do not set 'securityContext.procMount'.")
def _proc_mount(ctx):
    for c, crng in ctx.containers:
        pm = _sec_ctx(c).get("procMount")
        if pm is not None and str(pm) != "Default":
            yield (f"Container '{_cname(c)}' of {ctx.kind} "
                   f"'{ctx.name}' should not set "
                   f"'securityContext.procMount'",
                   _rng(c, "securityContext", crng))


@_k("KSV037", "Workload deployed in default or kube-system namespace",
    "MEDIUM",
    "Deploying user workloads into kube-system blurs the boundary "
    "with cluster-control components.",
    "Deploy workloads into a dedicated namespace.")
def _system_namespace(ctx):
    if ctx.kind not in _WORKLOAD_KINDS:
        return  # RBAC objects in kube-system are normal
    md = ctx.doc.get("metadata")
    ns = md.get("namespace") if isinstance(md, dict) else ""
    if ns == "kube-system":
        yield (f"{ctx.kind} '{ctx.name}' should not be deployed in "
               f"the 'kube-system' namespace",
               value_range(md, "namespace") if isinstance(md, PosDict)
               else (0, 0))


@_k("KSV110", "Workloads in the default namespace", "LOW",
    "Checks whether a workload runs in the default namespace, which "
    "offers no isolation boundary.",
    "Create and use a dedicated namespace.")
def _default_namespace(ctx):
    if ctx.kind not in _WORKLOAD_KINDS:
        return
    md = ctx.doc.get("metadata")
    ns = md.get("namespace") if isinstance(md, dict) else None
    # only an EXPLICIT default namespace fires — rendered manifests
    # with no namespace field pass (the helm goldens confirm the
    # reference bundle behaves this way)
    if ns == "default":
        yield (f"{ctx.kind} '{ctx.name}' should not be set with "
               f"'default' namespace",
               value_range(md, "namespace")
               if isinstance(md, PosDict) else (0, 0))


@_k("KSV116", "Runs with a root primary or supplementary GID", "LOW",
    "Containers should be forbidden from running with a root primary "
    "or supplementary GID.",
    "Set securityContext gid fields to non-zero values.")
def _root_gid_pss(ctx):
    if _has_root_gid(ctx.spec.get("securityContext")):
        yield (f"{ctx.kind} '{ctx.name}' should not run with a root "
               f"primary or supplementary GID",
               value_range(ctx.spec, "securityContext"))
    for c, crng in ctx.containers:
        if _sec_ctx(c).get("runAsGroup") == 0:
            yield (f"Container '{_cname(c)}' of {ctx.kind} "
                   f"'{ctx.name}' should not run with a root GID",
                   _rng(c, "securityContext", crng))


# --- RBAC checks (Role / ClusterRole documents) ----------------------

def _rbac_rules(ctx):
    if ctx.kind not in ("Role", "ClusterRole"):
        return []
    rules = ctx.doc.get("rules")
    return [r for r in rules if isinstance(r, dict)] \
        if isinstance(rules, list) else []


def _rule_rng(ctx):
    return value_range(ctx.doc, "rules") \
        if isinstance(ctx.doc, PosDict) else (0, 0)


@_k("KSV041", "Manage secrets", "CRITICAL",
    "Roles able to read secrets can exfiltrate every credential in "
    "their scope.",
    "Remove 'secrets' from the role's resources, or narrow the "
    "verbs.")
def _rbac_secrets(ctx):
    for rule in _rbac_rules(ctx):
        resources = rule.get("resources") or []
        verbs = rule.get("verbs") or []
        if "secrets" in resources and any(
                v in ("get", "list", "watch", "*") for v in verbs):
            yield (f"{ctx.kind} '{ctx.name}' should not have access "
                   f"to resource 'secrets'", _rule_rng(ctx))


@_k("KSV044", "No wildcard verb roles", "CRITICAL",
    "A '*' verb grants every action on the rule's resources.",
    "List the needed verbs explicitly.")
def _rbac_wildcard_verbs(ctx):
    for rule in _rbac_rules(ctx):
        if "*" in (rule.get("verbs") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not use wildcard "
                   f"verbs", _rule_rng(ctx))


@_k("KSV045", "No wildcard resource roles", "CRITICAL",
    "A '*' resource grants the rule's verbs on every resource kind.",
    "List the needed resources explicitly.")
def _rbac_wildcard_resources(ctx):
    for rule in _rbac_rules(ctx):
        if "*" in (rule.get("resources") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not use wildcard "
                   f"resources", _rule_rng(ctx))


_MODIFY_VERBS = {"create", "update", "patch", "delete",
                 "deletecollection", "*"}


@_k("KSV042", "Delete pod logs", "MEDIUM",
    "The ability to delete pod logs lets an attacker cover their "
    "tracks.",
    "Remove delete verbs on the pods/log resource.")
def _rbac_pod_logs(ctx):
    for rule in _rbac_rules(ctx):
        if "pods/log" in (rule.get("resources") or []) and \
                {"delete", "deletecollection", "*"} & \
                set(rule.get("verbs") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not allow deleting "
                   f"pod logs", _rule_rng(ctx))


@_k("KSV043", "Impersonate privileged groups", "CRITICAL",
    "Impersonating privileged groups grants their full privileges.",
    "Remove the impersonate verb on groups.")
def _rbac_impersonate_groups(ctx):
    for rule in _rbac_rules(ctx):
        if "groups" in (rule.get("resources") or []) and \
                "impersonate" in (rule.get("verbs") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not allow "
                   f"impersonating groups", _rule_rng(ctx))


@_k("KSV049", "Manage configmaps", "MEDIUM",
    "Some workloads store sensitive data in configmaps; write access "
    "allows tampering with application behavior.",
    "Narrow configmap verbs to read-only.")
def _rbac_configmaps(ctx):
    for rule in _rbac_rules(ctx):
        if "configmaps" in (rule.get("resources") or []) and \
                _MODIFY_VERBS & set(rule.get("verbs") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not allow managing "
                   f"configmaps", _rule_rng(ctx))


@_k("KSV053", "Getting shell on pods", "HIGH",
    "The pods/exec resource with create lets a role open a shell in "
    "any pod in scope.",
    "Remove create on pods/exec.")
def _rbac_pod_exec(ctx):
    for rule in _rbac_rules(ctx):
        if "pods/exec" in (rule.get("resources") or []) and \
                {"create", "*"} & set(rule.get("verbs") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not allow getting "
                   f"a shell on pods", _rule_rng(ctx))


@_k("KSV056", "Manage Kubernetes networking resources", "HIGH",
    "Write access to services/ingresses/network policies lets a role "
    "redirect cluster traffic.",
    "Narrow networking resource verbs to read-only.")
def _rbac_networking(ctx):
    netres = {"services", "endpoints", "endpointslices", "ingresses",
              "networkpolicies"}
    for rule in _rbac_rules(ctx):
        if netres & set(rule.get("resources") or []) and \
                _MODIFY_VERBS & set(rule.get("verbs") or []):
            yield (f"{ctx.kind} '{ctx.name}' should not allow managing "
                   f"networking resources", _rule_rng(ctx))


@_k("KSV047", "Privilege escalation verbs", "HIGH",
    "The escalate, bind and impersonate verbs allow privilege "
    "escalation through the RBAC system itself.",
    "Remove 'escalate', 'bind' and 'impersonate' verbs.")
def _rbac_escalation(ctx):
    for rule in _rbac_rules(ctx):
        bad = {"escalate", "bind", "impersonate"} & \
            set(rule.get("verbs") or [])
        if bad:
            yield (f"{ctx.kind} '{ctx.name}' should not grant "
                   f"privilege-escalation verbs "
                   f"({', '.join(sorted(bad))})", _rule_rng(ctx))


@_k("KSV103", "HostProcess container defined", "HIGH",
    "Windows pods offer the ability to run HostProcess containers "
    "which enables privileged access to the Windows node.",
    "Do not enable 'hostProcess' on any securityContext.")
def _host_process(ctx):
    scopes = [(ctx.spec.get("securityContext"), ctx.spec,
               "securityContext")]
    scopes += [(_sec_ctx(c), c, "securityContext")
               for c, _ in ctx.containers]
    for sc, holder, key in scopes:
        if not isinstance(sc, dict):
            continue
        wo = sc.get("windowsOptions")
        if isinstance(wo, dict) and wo.get("hostProcess") is True:
            yield (f"{ctx.kind} '{ctx.name}' should not set "
                   f"'windowsOptions.hostProcess' to true",
                   value_range(holder, key))


def scan_kubernetes(path: str, content: bytes, lines=None,
                    docs=None) -> tuple[list, int]:
    """→ (failures, successes) over all workload documents in the file.
    `docs` carries pre-parsed documents from detection.sniff."""
    text = content.decode("utf-8", errors="replace")
    if docs is None:
        if path.endswith(".json"):
            try:
                raw = json.loads(text)
            except Exception:
                return [], 0
            docs = raw if isinstance(raw, list) else [raw]
        else:
            docs = load_documents(text)
    contexts = []
    for doc in docs:
        if not isinstance(doc, dict) or doc.get("kind") is None:
            continue
        items = doc.get("items")
        subdocs = items if doc.get("kind") == "List" and \
            isinstance(items, list) else [doc]
        for d in subdocs:
            if not isinstance(d, dict):
                continue
            kind = d.get("kind")
            if kind in _WORKLOAD_KINDS:
                ctx = _Ctx(d)
                if isinstance(ctx.spec, dict):
                    contexts.append(ctx)
            elif kind in ("Role", "ClusterRole"):
                # RBAC documents: pod-spec checks no-op on the empty
                # spec; the KSV041/044/045/047 family gates on kind
                ctx = _Ctx(d)
                ctx.spec = {}
                contexts.append(ctx)
    if not contexts:
        return [], 0

    def call(check):
        for ctx in contexts:
            yield from check.fn(ctx)

    return run_checks(CHECKS, "kubernetes", text, call)
