"""HCL2 lexer, parser, and expression evaluator.

The reference embeds hashicorp/hcl and a 6.2k-LoC terraform scanner
(pkg/iac/scanners/terraform, pkg/iac/terraform value model); this is a
native subset sized for misconfiguration scanning: blocks, attributes,
the full operator grammar, string templates, heredocs, for-expressions,
splats, and the commonly used function library.  Anything outside the
subset (`...` grouping mode, template directives, unresolved
references) evaluates to Unknown, which checks treat as passing — the
same stance the reference takes for values it cannot know before
`terraform apply`.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

from .cloud import UNKNOWN, Unknown

# --- lexer ----------------------------------------------------------

_PUNCT2 = ("==", "!=", "<=", ">=", "&&", "||", "=>", "::")
_PUNCT1 = "{}[]()=,.?:<>!+-*/%"


@dataclass
class Tok:
    kind: str       # ident num str tmpl punct nl heredoc eof
    value: object
    line: int


class HclError(Exception):
    pass


def lex(text: str) -> list[Tok]:
    toks: list[Tok] = []
    i, n, line = 0, len(text), 1
    while i < n:
        c = text[i]
        if c == "\n":
            toks.append(Tok("nl", "\n", line))
            line += 1
            i += 1
            continue
        if c in " \t\r":
            i += 1
            continue
        if c == "#" or text.startswith("//", i):
            j = text.find("\n", i)
            i = n if j < 0 else j
            continue
        if text.startswith("/*", i):
            j = text.find("*/", i + 2)
            if j < 0:
                break
            line += text.count("\n", i, j)
            i = j + 2
            continue
        if text.startswith("<<", i):
            m = re.match(r"<<(-?)([A-Za-z_][A-Za-z0-9_-]*)\r?\n",
                         text[i:])
            if m:
                indent, tag = m.group(1), m.group(2)
                start = i + m.end()
                end_re = re.compile(
                    r"^[ \t]*" + re.escape(tag) + r"[ \t]*$",
                    re.MULTILINE)
                em = end_re.search(text, start)
                if em is None:
                    raise HclError(f"unterminated heredoc {tag}")
                body = text[start:em.start()]
                if indent == "-":
                    body = re.sub(r"^[ \t]+", "", body, flags=re.M)
                body = body.rstrip("\n")
                if re.search(r"(?<!\$)\$\{|(?<!%)%\{", body):
                    # interpolated heredoc — out of subset → unknown,
                    # never a concrete (and wrong) literal
                    toks.append(Tok("str", [("interp", None)], line))
                else:
                    toks.append(Tok(
                        "str",
                        [body.replace("$${", "${").replace("%%{", "%{")],
                        line))
                line += text.count("\n", i, em.end())
                i = em.end()
                continue
        if c == '"':
            parts, j, ln = _lex_template(text, i + 1, line)
            toks.append(Tok("str", parts, line))
            line = ln
            i = j
            continue
        if c.isdigit() or (c == "." and i + 1 < n and
                           text[i + 1].isdigit()):
            m = re.match(r"\d+(\.\d+)?([eE][+-]?\d+)?", text[i:])
            s = m.group(0)
            toks.append(Tok("num", float(s) if "." in s or "e" in s
                            or "E" in s else int(s), line))
            i += m.end()
            continue
        if c.isalpha() or c == "_":
            m = re.match(r"[A-Za-z_][A-Za-z0-9_-]*", text[i:])
            toks.append(Tok("ident", m.group(0), line))
            i += m.end()
            continue
        two = text[i:i + 2]
        if two in _PUNCT2:
            toks.append(Tok("punct", two, line))
            i += 2
            continue
        if c in _PUNCT1:
            toks.append(Tok("punct", c, line))
            i += 1
            continue
        raise HclError(f"unexpected character {c!r} at line {line}")
    toks.append(Tok("eof", None, line))
    return toks


def _lex_template(text: str, i: int, line: int):
    """Parse a quoted template starting after the opening quote.
    → (parts, next_index, line); parts are str literals and
    ('interp', token-list) tuples."""
    parts: list = []
    buf: list[str] = []
    n = len(text)
    while i < n:
        c = text[i]
        if c == '"':
            if buf:
                parts.append("".join(buf))
            return parts, i + 1, line
        if c == "\\" and i + 1 < n:
            esc = text[i + 1]
            buf.append({"n": "\n", "t": "\t", '"': '"', "\\": "\\",
                        "r": "\r"}.get(esc, "\\" + esc))
            i += 2
            continue
        if text.startswith("$${", i) or text.startswith("%%{", i):
            buf.append(text[i + 1:i + 3])
            i += 3
            continue
        if text.startswith("${", i):
            if buf:
                parts.append("".join(buf))
                buf = []
            depth, j = 1, i + 2
            while j < n and depth:
                if text[j] == "{":
                    depth += 1
                elif text[j] == "}":
                    depth -= 1
                elif text[j] == '"':  # nested string
                    _, j, line = _lex_template(text, j + 1, line)
                    continue
                j += 1
            inner = text[i + 2:j - 1]
            parts.append(("interp", lex(inner)))
            i = j
            continue
        if text.startswith("%{", i):
            # template directives (if/for) are out of subset → unknown
            parts.append(("interp", None))
            depth, j = 1, i + 2
            while j < n and depth:
                if text[j] == "{":
                    depth += 1
                elif text[j] == "}":
                    depth -= 1
                j += 1
            i = j
            continue
        if c == "\n":
            line += 1
        buf.append(c)
        i += 1
    raise HclError("unterminated string")


# --- AST ------------------------------------------------------------

@dataclass
class Attr:
    name: str
    expr: object
    start: int
    end: int


@dataclass
class Block:
    type: str
    labels: list
    body: "Body"
    start: int
    end: int


@dataclass
class Body:
    attrs: list = field(default_factory=list)    # [Attr]
    blocks: list = field(default_factory=list)   # [Block]


@dataclass
class Lit:
    value: object


@dataclass
class Tmpl:
    parts: list


@dataclass
class Ref:
    chain: list      # mix of str names and Index markers


@dataclass
class IndexOp:
    expr: object     # expression or SPLAT


SPLAT = object()


@dataclass
class Call:
    name: str
    args: list


@dataclass
class Un:
    op: str
    x: object


@dataclass
class Bin:
    op: str
    x: object
    y: object


@dataclass
class Cond:
    c: object
    t: object
    f: object


@dataclass
class ListE:
    items: list


@dataclass
class MapE:
    items: list      # [(key_expr_or_name, value_expr)]


class Unsupported:
    """out-of-subset constructs — evaluate to Unknown."""


@dataclass
class ForE:
    """[for v in coll : body if cond] / {for k, v in coll : key =>
    value if cond} (no `...` grouping — that parses to Unsupported)."""
    names: list      # [value_name] or [key_name, value_name]
    coll: object
    key: object      # None for list comprehension
    body: object
    cond: object     # optional filter


# --- parser ---------------------------------------------------------

class Parser:
    def __init__(self, toks: list[Tok]):
        self.toks = toks
        self.i = 0
        self._saw_ellipsis = False  # grouping-mode detection

    def peek(self, skip_nl=False) -> Tok:
        j = self.i
        if skip_nl:
            while self.toks[j].kind == "nl":
                j += 1
        return self.toks[j]

    def next(self, skip_nl=False) -> Tok:
        if skip_nl:
            while self.toks[self.i].kind == "nl":
                self.i += 1
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def expect(self, kind, value=None, skip_nl=False) -> Tok:
        t = self.next(skip_nl=skip_nl)
        if t.kind != kind or (value is not None and t.value != value):
            raise HclError(
                f"expected {value or kind}, got {t.value!r} "
                f"(line {t.line})")
        return t

    def parse_body(self, top=False) -> Body:
        body = Body()
        while True:
            t = self.peek(skip_nl=True)
            if t.kind == "eof":
                break
            if t.kind == "punct" and t.value == "}" and not top:
                break
            if t.kind not in ("ident", "str"):
                raise HclError(
                    f"unexpected {t.value!r} in body (line {t.line})")
            name_tok = self.next(skip_nl=True)
            name = name_tok.value if name_tok.kind == "ident" else \
                "".join(p for p in name_tok.value if isinstance(p, str))
            t = self.peek()
            if t.kind == "punct" and t.value == "=":
                self.next()
                expr = self.parse_expr()
                end_line = self.toks[self.i - 1].line
                body.attrs.append(Attr(name, expr, name_tok.line,
                                       end_line))
            else:
                labels = []
                while True:
                    t = self.peek()
                    if t.kind == "ident":
                        labels.append(self.next().value)
                    elif t.kind == "str":
                        parts = self.next().value
                        labels.append("".join(
                            p for p in parts if isinstance(p, str)))
                    else:
                        break
                self.expect("punct", "{")
                inner = self.parse_body()
                close = self.expect("punct", "}", skip_nl=True)
                body.blocks.append(Block(name, labels, inner,
                                         name_tok.line, close.line))
        return body

    # expression parsing — precedence climbing
    def parse_expr(self):
        return self.parse_cond()

    def parse_cond(self):
        c = self.parse_or()
        t = self.peek()
        if t.kind == "punct" and t.value == "?":
            self.next()
            a = self.parse_expr()
            self.expect("punct", ":", skip_nl=True)
            b = self.parse_expr()
            return Cond(c, a, b)
        return c

    def _bin(self, sub, ops):
        x = sub()
        while True:
            t = self.peek()
            if t.kind == "punct" and t.value in ops:
                self.next()
                x = Bin(t.value, x, sub())
            else:
                return x

    def parse_or(self):
        return self._bin(self.parse_and, ("||",))

    def parse_and(self):
        return self._bin(self.parse_eq, ("&&",))

    def parse_eq(self):
        return self._bin(self.parse_cmp, ("==", "!="))

    def parse_cmp(self):
        return self._bin(self.parse_add, ("<", ">", "<=", ">="))

    def parse_add(self):
        return self._bin(self.parse_mul, ("+", "-"))

    def parse_mul(self):
        return self._bin(self.parse_unary, ("*", "/", "%"))

    def parse_unary(self):
        t = self.peek()
        if t.kind == "punct" and t.value in ("!", "-"):
            self.next()
            return Un(t.value, self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self):
        x = self.parse_primary()
        while True:
            t = self.peek()
            if t.kind == "punct" and t.value == ".":
                self.next()
                if self.peek().kind == "punct" and \
                        self.peek().value == ".":
                    # "..." — call varargs expansion OR for-expression
                    # grouping mode; record it so _parse_for can fall
                    # back to Unsupported (the dots are consumed here)
                    while self.peek().kind == "punct" and \
                            self.peek().value == ".":
                        self.next()
                    self._saw_ellipsis = True
                    return x
                nt = self.next()
                if nt.kind == "ident":
                    x = self._extend(x, nt.value)
                elif nt.kind == "num":
                    x = self._extend(x, IndexOp(Lit(nt.value)))
                elif nt.kind == "punct" and nt.value == "*":
                    x = self._extend(x, IndexOp(SPLAT))
                else:
                    raise HclError(f"bad attribute access "
                                   f"(line {nt.line})")
            elif t.kind == "punct" and t.value == "[":
                self.next()
                it = self.peek(skip_nl=True)
                if it.kind == "punct" and it.value == "*":
                    self.next(skip_nl=True)
                    idx = IndexOp(SPLAT)
                else:
                    idx = IndexOp(self.parse_expr())
                self.expect("punct", "]", skip_nl=True)
                x = self._extend(x, idx)
            else:
                return x

    @staticmethod
    def _extend(x, part):
        if isinstance(x, Ref):
            return Ref(x.chain + [part])
        return Ref([x, part])     # indexing a non-ref expression

    def parse_primary(self):
        t = self.next(skip_nl=True)
        if t.kind == "num":
            return Lit(t.value)
        if t.kind == "str":
            if len(t.value) == 1 and isinstance(t.value[0], str):
                return Lit(t.value[0])
            if not t.value:
                return Lit("")
            return Tmpl(t.value)
        if t.kind == "ident":
            if t.value == "true":
                return Lit(True)
            if t.value == "false":
                return Lit(False)
            if t.value == "null":
                return Lit(None)
            # function call?
            name = t.value
            while self.peek().kind == "punct" and \
                    self.peek().value == "::":
                self.next()
                name += "::" + self.expect("ident").value
            if self.peek().kind == "punct" and self.peek().value == "(":
                self.next()
                args = []
                while True:
                    nt = self.peek(skip_nl=True)
                    if nt.kind == "punct" and nt.value == ")":
                        self.next(skip_nl=True)
                        break
                    args.append(self.parse_expr())
                    nt = self.peek(skip_nl=True)
                    if nt.kind == "punct" and nt.value == ",":
                        self.next(skip_nl=True)
                return Call(name, args)
            return Ref([name])
        if t.kind == "punct" and t.value == "(":
            e = self.parse_expr()
            self.expect("punct", ")", skip_nl=True)
            return e
        if t.kind == "punct" and t.value == "[":
            first = self.peek(skip_nl=True)
            if first.kind == "ident" and first.value == "for":
                self.next(skip_nl=True)
                return self._parse_for("]")
            items = []
            while True:
                nt = self.peek(skip_nl=True)
                if nt.kind == "punct" and nt.value == "]":
                    self.next(skip_nl=True)
                    break
                items.append(self.parse_expr())
                nt = self.peek(skip_nl=True)
                if nt.kind == "punct" and nt.value == ",":
                    self.next(skip_nl=True)
            return ListE(items)
        if t.kind == "punct" and t.value == "{":
            first = self.peek(skip_nl=True)
            if first.kind == "ident" and first.value == "for":
                self.next(skip_nl=True)
                return self._parse_for("}")
            items = []
            while True:
                nt = self.peek(skip_nl=True)
                if nt.kind == "punct" and nt.value == "}":
                    self.next(skip_nl=True)
                    break
                if nt.kind in ("ident", "str"):
                    kt = self.next(skip_nl=True)
                    key = kt.value if kt.kind == "ident" else "".join(
                        p for p in kt.value if isinstance(p, str))
                elif nt.kind == "punct" and nt.value == "(":
                    key_expr = self.parse_expr()
                    key = key_expr
                else:
                    key = self.parse_expr()
                sep = self.next(skip_nl=True)
                if not (sep.kind == "punct" and sep.value in
                        ("=", ":")):
                    raise HclError(f"expected = or : in object "
                                   f"(line {sep.line})")
                items.append((key, self.parse_expr()))
                nt = self.peek(skip_nl=True)
                if nt.kind == "punct" and nt.value == ",":
                    self.next(skip_nl=True)
            return MapE(items)
        raise HclError(f"unexpected token {t.value!r} (line {t.line})")

    def _parse_for(self, close_c):
        """After the consumed `for` keyword. `...` grouping mode falls
        back to Unsupported (skip to the closing bracket)."""
        open_c = "[" if close_c == "]" else "{"
        names = []
        t = self.next(skip_nl=True)
        if t.kind != "ident":
            raise HclError(f"bad for-expression (line {t.line})")
        names.append(t.value)
        if self.peek(skip_nl=True).value == ",":
            self.next(skip_nl=True)
            t = self.next(skip_nl=True)
            if t.kind != "ident":
                raise HclError(f"bad for-expression (line {t.line})")
            names.append(t.value)
        t = self.next(skip_nl=True)
        if not (t.kind == "ident" and t.value == "in"):
            raise HclError(f"expected 'in' (line {t.line})")
        coll = self.parse_expr()
        t = self.next(skip_nl=True)
        if not (t.kind == "punct" and t.value == ":"):
            raise HclError(f"expected ':' (line {t.line})")
        key = None
        saw = self._saw_ellipsis
        self._saw_ellipsis = False
        body = self.parse_expr()
        if close_c == "}":
            t = self.next(skip_nl=True)
            if not (t.kind == "punct" and t.value == "=>"):
                raise HclError(f"expected '=>' (line {t.line})")
            key = body
            self._saw_ellipsis = False
            body = self.parse_expr()
        # grouping mode only exists in map-fors; a list-for body may
        # legitimately contain a call-varargs `...` (f(xs...))
        grouping = self._saw_ellipsis and close_c == "}"
        self._saw_ellipsis = saw
        cond = None
        nt = self.peek(skip_nl=True)
        if grouping or (nt.kind == "punct" and nt.value == "."):
            # value grouping `...` — out of subset (parse_postfix
            # consumed the dots while parsing the value expression)
            if not (nt.kind == "punct" and nt.value == close_c):
                self._skip_until_close(open_c, close_c)
            else:
                self.next(skip_nl=True)
            return Unsupported()
        if nt.kind == "ident" and nt.value == "if":
            self.next(skip_nl=True)
            cond = self.parse_expr()
        self.expect("punct", close_c, skip_nl=True)
        return ForE(names, coll, key, body, cond)

    def _skip_until_close(self, open_c, close_c):
        depth = 1
        while depth:
            t = self.next(skip_nl=True)
            if t.kind == "eof":
                raise HclError("unterminated for-expression")
            if t.kind == "punct":
                if t.value == open_c:
                    depth += 1
                elif t.value == close_c:
                    depth -= 1


def parse(text: str) -> Body:
    return Parser(lex(text)).parse_body(top=True)


# --- evaluator ------------------------------------------------------

def _is_unknown(v) -> bool:
    return isinstance(v, Unknown)


def _contains_unknown(v) -> bool:
    if _is_unknown(v):
        return True
    if isinstance(v, list):
        return any(_contains_unknown(x) for x in v)
    if isinstance(v, dict):
        return any(_contains_unknown(x) for x in v.values())
    return False


class Scope:
    """Name resolution for expression evaluation."""

    def __init__(self, variables=None, locals_=None, resolver=None):
        self.variables = variables or {}
        self.locals = locals_ or {}
        self.resolver = resolver  # fn(chain) → value for resource refs
        self.bindings: dict = {}  # for-expression loop variables

    def child(self, bindings: dict) -> "Scope":
        s = Scope(self.variables, self.locals, self.resolver)
        s.bindings = {**self.bindings, **bindings}
        return s

    def resolve(self, chain):
        head = chain[0]
        if head in self.bindings:
            return _walk_chain(self.bindings[head], chain[1:], self)
        if head == "var":
            if len(chain) >= 2 and isinstance(chain[1], str):
                base = self.variables.get(chain[1], UNKNOWN)
                return _walk_chain(base, chain[2:], self)
            return UNKNOWN
        if head == "local":
            if len(chain) >= 2 and isinstance(chain[1], str):
                base = self.locals.get(chain[1], UNKNOWN)
                return _walk_chain(base, chain[2:], self)
            return UNKNOWN
        if self.resolver is not None:
            return self.resolver(chain)
        return UNKNOWN


def _walk_chain(value, rest, scope):
    for i, part in enumerate(rest):
        if _is_unknown(value):
            return UNKNOWN
        if isinstance(part, str):
            if isinstance(value, dict):
                value = value.get(part, UNKNOWN)
            else:
                return UNKNOWN
        elif isinstance(part, IndexOp):
            if part.expr is SPLAT:
                # full splat: map the REMAINING chain over each
                # element (hcl: null splats to an empty tuple, any
                # other non-list value wraps to [value])
                rest2 = rest[i + 1:]
                if value is None:
                    return []
                if not isinstance(value, (list, tuple)):
                    value = [value]
                return [_walk_chain(v, rest2, scope) for v in value]
            idx = evaluate(part.expr, scope)
            if _is_unknown(idx):
                return UNKNOWN
            try:
                value = value[idx if not isinstance(idx, float)
                              else int(idx)]
            except (TypeError, KeyError, IndexError):
                return UNKNOWN
        else:
            return UNKNOWN
    return value


def evaluate(node, scope: Scope):
    if isinstance(node, Lit):
        return node.value
    if isinstance(node, ForE):
        coll = evaluate(node.coll, scope)
        if _is_unknown(coll):
            return UNKNOWN
        if isinstance(coll, dict):
            pairs = list(coll.items())
        elif isinstance(coll, (list, tuple)):
            pairs = list(enumerate(coll))
        else:
            return UNKNOWN
        out_list: list = []
        out_map: dict = {}
        for k, v in pairs:
            if len(node.names) == 2:
                child = scope.child({node.names[0]: k,
                                     node.names[1]: v})
            else:
                child = scope.child({node.names[0]: v})
            if node.cond is not None:
                c = evaluate(node.cond, child)
                if _is_unknown(c):
                    return UNKNOWN  # filter unknowable → whole result
                if not c:
                    continue
            val = evaluate(node.body, child)
            if node.key is None:
                out_list.append(val)
            else:
                kk = evaluate(node.key, child)
                if _is_unknown(kk):
                    return UNKNOWN
                if isinstance(kk, (list, tuple, dict)):
                    return UNKNOWN  # HCL rejects non-scalar keys
                out_map[_to_str(kk)] = val  # HCL map keys: strings
        return out_map if node.key is not None else out_list
    if isinstance(node, Tmpl):
        out = []
        for p in node.parts:
            if isinstance(p, str):
                out.append(p)
            else:
                _, toks = p[0], p[1]
                if toks is None:
                    return UNKNOWN
                try:
                    expr = Parser(toks).parse_expr()
                except HclError:
                    return UNKNOWN
                v = evaluate(expr, scope)
                if _is_unknown(v):
                    return UNKNOWN
                out.append(_to_str(v))
        return "".join(out)
    if isinstance(node, Ref):
        head = node.chain[0]
        if not isinstance(head, str):
            base = evaluate(head, scope)
            return _walk_chain(base, node.chain[1:], scope)
        return scope.resolve(node.chain)
    if isinstance(node, Call):
        return _call(node.name, [evaluate(a, scope)
                                 for a in node.args], node, scope)
    if isinstance(node, Un):
        v = evaluate(node.x, scope)
        if _is_unknown(v):
            return UNKNOWN
        try:
            return (not v) if node.op == "!" else (-v)
        except TypeError:
            return UNKNOWN
    if isinstance(node, Bin):
        x = evaluate(node.x, scope)
        if node.op == "||":
            if x is True:
                return True
            y = evaluate(node.y, scope)
            if _is_unknown(x) or _is_unknown(y):
                return UNKNOWN
            return bool(x or y)
        if node.op == "&&":
            if x is False:
                return False
            y = evaluate(node.y, scope)
            if _is_unknown(x) or _is_unknown(y):
                return UNKNOWN
            return bool(x and y)
        y = evaluate(node.y, scope)
        if _is_unknown(x) or _is_unknown(y):
            return UNKNOWN
        try:
            if node.op == "==":
                return x == y
            if node.op == "!=":
                return x != y
            if node.op == "<":
                return x < y
            if node.op == ">":
                return x > y
            if node.op == "<=":
                return x <= y
            if node.op == ">=":
                return x >= y
            if node.op == "+":
                return x + y
            if node.op == "-":
                return x - y
            if node.op == "*":
                return x * y
            if node.op == "/":
                return x / y if y else UNKNOWN
            if node.op == "%":
                return x % y if y else UNKNOWN
        except (TypeError, ValueError):
            # e.g. string % formatting on arbitrary scanned input
            return UNKNOWN
    if isinstance(node, Cond):
        c = evaluate(node.c, scope)
        if _is_unknown(c):
            return UNKNOWN
        return evaluate(node.t if c else node.f, scope)
    if isinstance(node, ListE):
        return [evaluate(i, scope) for i in node.items]
    if isinstance(node, MapE):
        out = {}
        for k, v in node.items:
            key = k if isinstance(k, str) else evaluate(k, scope)
            if _is_unknown(key):
                continue
            out[_to_str(key)] = evaluate(v, scope)
        return out
    if isinstance(node, Unsupported):
        return UNKNOWN
    return UNKNOWN


def _to_str(v):
    if v is True:
        return "true"
    if v is False:
        return "false"
    if v is None:
        return ""
    if isinstance(v, float) and v.is_integer():
        return str(int(v))
    return str(v)


def _call(name, args, node, scope):
    name = name.split("::")[-1]     # provider::fn → fn
    if name == "try":
        for a in args:
            if not _is_unknown(a):
                return a
        return UNKNOWN
    if name == "can":
        return UNKNOWN if any(_is_unknown(a) for a in args) else True
    if name == "coalesce":
        for a in args:
            if _is_unknown(a):
                return UNKNOWN
            if a not in (None, ""):
                return a
        return None
    if any(_contains_unknown(a) for a in args):
        return UNKNOWN
    try:
        if name == "lower":
            return str(args[0]).lower()
        if name == "upper":
            return str(args[0]).upper()
        if name == "length":
            return len(args[0])
        if name == "concat":
            out = []
            for a in args:
                out.extend(a)
            return out
        if name == "join":
            return _to_str(args[0]).join(_to_str(x) for x in args[1])
        if name == "split":
            return str(args[1]).split(str(args[0]))
        if name == "replace":
            return str(args[0]).replace(str(args[1]), str(args[2]))
        if name == "trimspace":
            return str(args[0]).strip()
        if name == "format":
            fmt = re.sub(r"%([#vdsfq])",
                         lambda m: {"v": "%s", "d": "%d", "s": "%s",
                                    "f": "%f", "q": '"%s"',
                                    "#": "%"}[m.group(1)], args[0])
            return fmt % tuple(args[1:])
        if name == "tostring":
            return _to_str(args[0])
        if name == "tonumber":
            f = float(args[0])
            return int(f) if f.is_integer() else f
        if name == "tobool":
            return args[0] in (True, "true")
        if name in ("tolist", "toset"):
            return list(args[0])
        if name == "tomap":
            return dict(args[0])
        if name == "jsonencode":
            return json.dumps(args[0], separators=(",", ":"))
        if name == "jsondecode":
            return json.loads(args[0])
        if name == "merge":
            out = {}
            for a in args:
                if isinstance(a, dict):
                    out.update(a)
            return out
        if name == "lookup":
            d = args[0]
            if isinstance(d, dict) and args[1] in d:
                return d[args[1]]
            return args[2] if len(args) > 2 else UNKNOWN
        if name == "element":
            seq = args[0]
            return seq[int(args[1]) % len(seq)] if seq else UNKNOWN
        if name == "contains":
            return args[1] in args[0]
        if name == "keys":
            return sorted(args[0].keys())
        if name == "values":
            return [args[0][k] for k in sorted(args[0].keys())]
        if name == "min":
            return min(args[0] if len(args) == 1 and
                       isinstance(args[0], list) else args)
        if name == "max":
            return max(args[0] if len(args) == 1 and
                       isinstance(args[0], list) else args)
        if name == "compact":
            return [x for x in args[0] if x not in (None, "")]
        if name == "flatten":
            out = []

            def rec(xs):
                for x in xs:
                    if isinstance(x, list):
                        rec(x)
                    else:
                        out.append(x)
            rec(args[0])
            return out
        if name == "distinct":
            seen, out = set(), []
            for x in args[0]:
                k = json.dumps(x, sort_keys=True, default=str)
                if k not in seen:
                    seen.add(k)
                    out.append(x)
            return out
        if name == "startswith":
            return str(args[0]).startswith(str(args[1]))
        if name == "endswith":
            return str(args[0]).endswith(str(args[1]))
        if name == "substr":
            s, off, ln = str(args[0]), int(args[1]), int(args[2])
            return s[off:] if ln < 0 else s[off:off + ln]
    except (TypeError, ValueError, IndexError, KeyError,
            ZeroDivisionError, json.JSONDecodeError):
        return UNKNOWN
    # file/templatefile/cidr*/uuid/timestamp/... → not statically known
    return UNKNOWN
