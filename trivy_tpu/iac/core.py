"""Shared IaC check model and result assembly.

Reference counterparts: pkg/iac/scan (Result/Rule model),
pkg/iac/ignore (inline ignore comments), and the rego metadata blocks of
trivy-checks that carry id/avd_id/severity/resolution.
"""

from __future__ import annotations

import re
from dataclasses import dataclass

from .. import types as T


@dataclass
class Check:
    """One policy: metadata + a function evaluated against parsed input.

    The function signature is scanner-specific; it yields
    (message, (start_line, end_line)) per failure occurrence, or nothing
    when the check passes.
    """
    id: str
    avd_id: str
    title: str
    severity: str
    description: str = ""
    resolution: str = ""
    provider: str = ""
    service: str = "general"
    namespace: str = ""
    fn: object = None


def build_misconf(check: Check, file_type: str, message: str,
                  rng: tuple[int, int], src_lines: list[str],
                  status: str = "FAIL") -> T.DetectedMisconfiguration:
    """Assemble a DetectedMisconfiguration with cause-code lines the way
    the reference renders rego results (pkg/misconf/scanner.go
    ResultsToMisconf + pkg/iac/scan code extraction)."""
    start, end = rng
    m = T.DetectedMisconfiguration(
        type=file_type,
        id=check.id,
        avd_id=check.avd_id,
        title=check.title,
        description=check.description,
        message=message,
        namespace=check.namespace or f"builtin.{file_type}.{check.id}",
        resolution=check.resolution,
        severity=check.severity,
        primary_url=f"https://avd.aquasec.com/misconfig/{check.id.lower()}",
        status=status,
    )
    if start > 0:
        end = min(max(end, start), len(src_lines)) if src_lines else end
        code_lines = []
        for n in range(start, min(end, start + 10 - 1) + 1):
            content = src_lines[n - 1] if n - 1 < len(src_lines) else ""
            code_lines.append(T.CodeLine(
                number=n, content=content, is_cause=True,
                first_cause=(n == start), last_cause=(n == end),
                highlighted=content))
        m.cause_metadata = T.CauseMetadata(
            provider=check.provider, service=check.service,
            start_line=start, end_line=end,
            code=T.Code(lines=code_lines))
    else:
        m.cause_metadata = T.CauseMetadata(
            provider=check.provider, service=check.service)
    return m


_IGNORE_RE = re.compile(
    r"(?:#|//)\s*trivy:ignore:([A-Za-z0-9-]+)")


def ignored_ids_by_line(text: str) -> dict[int, set[str]]:
    """Inline ignore comments (reference pkg/iac/ignore/parse.go):
    `#trivy:ignore:AVD-XXX-0001` suppresses findings caused on the same
    line or the line immediately below the comment."""
    out: dict[int, set[str]] = {}
    for i, line in enumerate(text.splitlines(), 1):
        for m in _IGNORE_RE.finditer(line):
            ident = m.group(1).upper()
            stripped = line[:m.start()].strip()
            target = i if stripped else i + 1
            out.setdefault(target, set()).add(ident)
    return out


def is_ignored(ignores: dict[int, set[str]], check: Check,
               start_line: int) -> bool:
    ids = ignores.get(start_line)
    if not ids:
        return False
    wanted = {check.id.upper(), check.avd_id.upper(), "*"}
    return bool(ids & wanted)


def run_checks(checks: list[Check], file_type: str, text: str,
               call, src_lines=None):
    """Drive a check list: `call(check)` yields (message, range) failures.
    → (failures, successes) applying inline ignores."""
    if src_lines is None:
        src_lines = text.splitlines()
    ignores = ignored_ids_by_line(text)
    failures: list[T.DetectedMisconfiguration] = []
    successes = 0
    for check in checks:
        found = list(call(check))
        kept = [(msg, rng) for msg, rng in found
                if not is_ignored(ignores, check, rng[0])]
        if not kept:
            successes += 1
            continue
        for msg, rng in kept:
            failures.append(
                build_misconf(check, file_type, msg, rng, src_lines))
    return failures, successes
