"""YAML loading with source positions.

The reference's IaC parsers keep per-node line ranges so every finding
carries cause metadata (pkg/iac/scanners/kubernetes/parser,
pkg/iac/scanners/cloudformation/parser/property.go).  PyYAML's
compose tree carries marks; this module converts it to plain Python
values wrapped in position-aware dict/list subclasses.

Unknown tags (CloudFormation's !Ref/!GetAtt/!Sub short forms) are
converted to single-key mappings {"Fn::X"/"Ref": value} the same way
cfn's long form would parse, so the adapter handles one shape.
"""

from __future__ import annotations

import yaml


class PosDict(dict):
    """dict that knows its own line range and each key's value range."""

    def __init__(self):
        super().__init__()
        self.start = 0       # 1-based first line
        self.end = 0         # 1-based last line
        self.key_lines = {}  # key -> (start, end) of the value


class PosList(list):
    def __init__(self):
        super().__init__()
        self.start = 0
        self.end = 0
        self.item_lines = []  # per-item (start, end)


_CFN_SHORT = {
    "!Ref": "Ref", "!Condition": "Condition",
}


def _node_range(node) -> tuple[int, int]:
    start = node.start_mark.line + 1
    end = node.end_mark.line + 1
    # end_mark points just past the node; for block nodes that is usually
    # the first line of the next sibling.
    if node.end_mark.column == 0 and end > start:
        end -= 1
    return start, end


def _intrinsic_key(tag: str) -> str:
    name = tag.lstrip("!")
    return _CFN_SHORT.get(tag, "Ref" if name == "Ref" else f"Fn::{name}")


_MAX_DEPTH = 200


def _construct(node, depth=0):
    if depth > _MAX_DEPTH:
        # cyclic alias graph (a: &x [*x]) or absurd nesting — bail out
        raise yaml.YAMLError("document too deep or cyclic")
    tag = node.tag
    if isinstance(node, yaml.MappingNode):
        out = PosDict()
        out.start, out.end = _node_range(node)
        for knode, vnode in node.value:
            key = _construct(knode, depth + 1)
            if isinstance(key, (PosDict, PosList)):
                key = str(key)
            out[key] = _construct(vnode, depth + 1)
            out.key_lines[key] = _node_range(vnode)
        if tag.startswith("!"):
            # short-form intrinsic over a mapping body (e.g. !If {...})
            return {_intrinsic_key(tag): out}
        return out
    if isinstance(node, yaml.SequenceNode):
        out = PosList()
        out.start, out.end = _node_range(node)
        for item in node.value:
            out.append(_construct(item, depth + 1))
            out.item_lines.append(_node_range(item))
        if tag.startswith("!"):
            # short-form intrinsic over a sequence (e.g. !Join [..])
            return {_intrinsic_key(tag): list(out)}
        return out
    # scalar
    value = node.value
    if tag == "tag:yaml.org,2002:null":
        return None
    if tag == "tag:yaml.org,2002:bool":
        return value.lower() in ("true", "yes", "on")
    if tag == "tag:yaml.org,2002:int":
        try:
            return int(value, 0) if isinstance(value, str) else int(value)
        except ValueError:
            return value
    if tag == "tag:yaml.org,2002:float":
        try:
            return float(value)
        except ValueError:
            return value
    if tag.startswith("!"):
        # CloudFormation short-form intrinsic: !GetAtt a.b → Fn::GetAtt
        key = _intrinsic_key(tag)
        if key == "Fn::GetAtt" and isinstance(value, str):
            return {key: value.split(".")}
        return {key: value}
    return value


def load_documents(text: str):
    """→ list of position-aware documents (PosDict/PosList/scalars)."""
    docs = []
    try:
        for node in yaml.compose_all(text, Loader=yaml.SafeLoader):
            if node is None:
                continue
            docs.append(_construct(node))
    except (yaml.YAMLError, RecursionError):
        return []
    return docs


def value_range(container, key_or_index, default=(0, 0)):
    """Line range of container[key] / container[i], if tracked."""
    if isinstance(container, PosDict):
        return container.key_lines.get(key_or_index, default)
    if isinstance(container, PosList):
        try:
            return container.item_lines[key_or_index]
        except (IndexError, TypeError):
            return default
    return default
