"""Terraform module scanner (reference pkg/iac/scanners/terraform +
adapters/terraform, ~9k LoC of Go around hashicorp/hcl).

A module = all .tf files in one directory, evaluated together:
variable defaults (+terraform.tfvars overrides), locals to fixpoint,
then each resource body — with cross-resource references left Unknown —
adapted into the shared cloud-state model and run through the same
AVD-AWS checks as CloudFormation.  Split companion resources
(aws_s3_bucket_* / aws_security_group_rule) are joined to their parent
by the reference expression in their `bucket`/`security_group_id`
attribute, the way the reference's terraform adapter resolves block
references."""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import types as T
from .cloud import (AWS_CHECKS, UNKNOWN, Attr, CloudResource,
                    Unknown, block_attr)
from .core import build_misconf, ignored_ids_by_line, is_ignored
from .hcl import Block, HclError, Ref, Scope, evaluate, parse


@dataclass
class TfResource:
    type: str
    name: str
    block: Block
    path: str
    attrs: dict = field(default_factory=dict)    # name → (value, rng)
    raw: dict = field(default_factory=dict)      # name → AST expr

    def value(self, key, default=None):
        v = self.attrs.get(key)
        return default if v is None else v[0]

    def rng(self, key=None):
        if key is not None and key in self.attrs:
            return self.attrs[key][1]
        return (self.block.start, self.block.end)

    def blocks(self, btype):
        return [b for b in self.block.body.blocks if b.type == btype]


class TfModule:
    def __init__(self, files: dict[str, str]):
        """files: path → text for one directory's .tf/.tfvars files."""
        self.files = files
        self.bodies: dict[str, object] = {}
        self.variables: dict[str, object] = {}
        self.locals: dict[str, object] = {}
        self.resources: list[TfResource] = []
        self._load()

    def _load(self):
        tfvars = {}
        for path, text in sorted(self.files.items()):
            if path.endswith(".tfvars"):
                base = path.rsplit("/", 1)[-1]
                # terraform auto-loads only terraform.tfvars and
                # *.auto.tfvars; other var files need an explicit
                # -var-file and must not override defaults here
                if base != "terraform.tfvars" and \
                        not base.endswith(".auto.tfvars"):
                    continue
                try:
                    body = parse(text)
                except HclError:
                    continue
                scope = Scope()
                for a in body.attrs:
                    tfvars[a.name] = evaluate(a.expr, scope)
                continue
            try:
                self.bodies[path] = parse(text)
            except HclError:
                continue
        # variable defaults
        empty = Scope()
        for path, body in self.bodies.items():
            for b in body.blocks:
                if b.type == "variable" and b.labels:
                    default = UNKNOWN
                    for a in b.body.attrs:
                        if a.name == "default":
                            default = evaluate(a.expr, empty)
                    self.variables[b.labels[0]] = default
        self.variables.update(tfvars)
        # locals to fixpoint (handles local→local chains)
        local_exprs = {}
        for body in self.bodies.values():
            for b in body.blocks:
                if b.type == "locals":
                    for a in b.body.attrs:
                        local_exprs[a.name] = a.expr
        self.locals = {k: UNKNOWN for k in local_exprs}
        for _ in range(4):
            scope = self._scope()
            changed = False
            for k, expr in local_exprs.items():
                v = evaluate(expr, scope)
                if not _same(v, self.locals[k]):
                    self.locals[k] = v
                    changed = True
            if not changed:
                break
        # resources
        scope = self._scope()
        for path, body in self.bodies.items():
            for b in body.blocks:
                if b.type == "resource" and len(b.labels) >= 2:
                    res = TfResource(b.labels[0], b.labels[1], b, path)
                    for a in b.body.attrs:
                        res.attrs[a.name] = (
                            evaluate(a.expr, scope), (a.start, a.end))
                        res.raw[a.name] = a.expr
                    self.resources.append(res)

    def _scope(self):
        return Scope(variables=self.variables, locals_=self.locals)

    def eval_block_attrs(self, block: Block):
        """Evaluate a nested block's attributes, memoized per block:
        adapters fetch several keys from the same block and variables/
        locals are fixed after _load, so one evaluation suffices."""
        cache = getattr(self, "_block_attr_cache", None)
        if cache is None:
            cache = self._block_attr_cache = {}
        hit = cache.get(id(block))
        if hit is not None:
            return hit
        scope = self._scope()
        out = {a.name: (evaluate(a.expr, scope), (a.start, a.end))
               for a in block.body.attrs}
        cache[id(block)] = out
        return out


def _same(a, b):
    if isinstance(a, Unknown) and isinstance(b, Unknown):
        return True
    if isinstance(a, Unknown) or isinstance(b, Unknown):
        return False
    return a == b


def _ref_target(expr, rtype: str):
    """If expr references `<rtype>.<name>[...]`, return name."""
    if isinstance(expr, Ref) and len(expr.chain) >= 2 and \
            expr.chain[0] == rtype and isinstance(expr.chain[1], str):
        return expr.chain[1]
    return None


def _a(res: TfResource, key, out: CloudResource, name=None):
    if key in res.attrs:
        v, rng = res.attrs[key]
        out.attrs[name or key] = Attr(v, rng)


def _sse_kms_key(module, sse_block):
    """kms_master_key_id from an inline
    server_side_encryption_configuration → rule →
    apply_server_side_encryption_by_default chain."""
    return _sse_kms_key_from_rules(
        module, [b for b in sse_block.body.blocks
                 if b.type == "rule"])


def _sse_kms_key_from_rules(module, rule_blocks):
    for b in rule_blocks:
        for db in b.body.blocks:
            if db.type == "apply_server_side_encryption_by_default":
                attrs = module.eval_block_attrs(db)
                if "kms_master_key_id" in attrs:
                    return attrs["kms_master_key_id"][0]
                return ""  # explicit default-encryption, no CMK
    return None


def _block_val(module, res, btype, key):
    """First nested block's attr value, e.g. versioning.enabled."""
    for b in res.blocks(btype):
        attrs = module.eval_block_attrs(b)
        if key in attrs:
            return attrs[key][0], attrs[key][1]
        return None, (b.start, b.end)
    return None, None


def _sg_rules_from_blocks(module, res, btype):
    rules = []
    for b in res.blocks(btype):
        attrs = module.eval_block_attrs(b)
        cidrs = []
        for key in ("cidr_blocks", "ipv6_cidr_blocks"):
            v = attrs.get(key, (None, None))[0]
            if isinstance(v, list):
                cidrs.extend(x for x in v
                             if not isinstance(x, Unknown))
        desc = attrs.get("description", ("", None))[0]
        rules.append({"cidrs": cidrs,
                      "description": desc
                      if not isinstance(desc, Unknown) else "",
                      "rng": (b.start, b.end)})
    return rules


def adapt_terraform(module: TfModule) -> list[CloudResource]:
    out: list[CloudResource] = []
    buckets: dict[str, CloudResource] = {}
    groups: dict[str, CloudResource] = {}

    for res in module.resources:
        t = res.type
        cr = CloudResource(t, res.name, rng=res.rng(), path=res.path)

        if t == "aws_s3_bucket":
            _a(res, "acl", cr)
            v, rng = _block_val(module, res, "versioning", "enabled")
            if v is not None:
                cr.attrs["versioning_enabled"] = Attr(v, rng)
            if res.blocks("server_side_encryption_configuration"):
                b = res.blocks("server_side_encryption_configuration")[0]
                cr.attrs["encryption_enabled"] = Attr(
                    True, (b.start, b.end))
                kms = _sse_kms_key(module, b)
                if kms is not None:
                    cr.attrs["sse_kms_key_id"] = Attr(
                        kms, (b.start, b.end))
            if res.blocks("logging"):
                b = res.blocks("logging")[0]
                cr.attrs["logging_enabled"] = Attr(True,
                                                   (b.start, b.end))
            buckets[res.name] = cr
            out.append(cr)

        elif t == "aws_security_group":
            _a(res, "description", cr)
            cr.attrs["ingress"] = Attr(
                _sg_rules_from_blocks(module, res, "ingress"))
            cr.attrs["egress"] = Attr(
                _sg_rules_from_blocks(module, res, "egress"))
            groups[res.name] = cr
            out.append(cr)

        elif t == "aws_instance":
            mo, rng = {}, None
            for b in res.blocks("metadata_options"):
                attrs = module.eval_block_attrs(b)
                mo = {"http_tokens":
                      attrs.get("http_tokens", (None, None))[0],
                      "http_endpoint":
                      attrs.get("http_endpoint", (None, None))[0]}
                rng = (b.start, b.end)
            if rng is not None:
                cr.attrs["metadata_options"] = Attr(mo, rng)
            for b in res.blocks("root_block_device"):
                attrs = module.eval_block_attrs(b)
                cr.attrs["root_block_device"] = Attr(
                    {"encrypted":
                     attrs.get("encrypted", (None, None))[0]},
                    (b.start, b.end))
            ebds = []
            for b in res.blocks("ebs_block_device"):
                attrs = module.eval_block_attrs(b)
                ebds.append({"encrypted":
                             attrs.get("encrypted", (None, None))[0],
                             "rng": (b.start, b.end)})
            if ebds:
                cr.attrs["ebs_block_device"] = Attr(ebds)
            out.append(cr)

        elif t == "aws_ebs_volume":
            _a(res, "encrypted", cr)
            out.append(cr)

        elif t in ("aws_db_instance", "aws_rds_cluster"):
            _a(res, "storage_encrypted", cr)
            _a(res, "backup_retention_period", cr)
            _a(res, "publicly_accessible", cr)
            _a(res, "replicate_source_db", cr)
            out.append(cr)

        elif t == "aws_efs_file_system":
            _a(res, "encrypted", cr)
            out.append(cr)

        elif t == "aws_cloudtrail":
            _a(res, "is_multi_region_trail", cr)
            _a(res, "enable_log_file_validation", cr)
            _a(res, "kms_key_id", cr)
            _a(res, "cloud_watch_logs_group_arn", cr)
            out.append(cr)

        elif t in ("aws_lb", "aws_alb"):
            cr.kind = "aws_lb"
            _a(res, "internal", cr)
            _a(res, "load_balancer_type", cr)
            _a(res, "drop_invalid_header_fields", cr)
            out.append(cr)

        elif t in ("aws_iam_policy", "aws_iam_role_policy",
                   "aws_iam_user_policy", "aws_iam_group_policy"):
            _a(res, "policy", cr, "policy_document")
            out.append(cr)

        elif t == "aws_eks_cluster":
            logs = res.value("enabled_cluster_log_types")
            if not isinstance(logs, (list, Unknown)):
                logs = []
            cr.attrs["enabled_log_types"] = Attr(
                logs, res.rng("enabled_cluster_log_types"))
            encrypted = False
            for b in res.blocks("encryption_config"):
                enc_res, _ = block_attr(module, b, "resources", None)
                if isinstance(enc_res, Unknown):
                    encrypted = UNKNOWN
                elif isinstance(enc_res, list) and \
                        "secrets" in enc_res:
                    encrypted = True
            cr.attrs["secrets_encrypted"] = Attr(encrypted)
            pub, cidrs = True, None
            p_rng = cr.rng
            for b in res.blocks("vpc_config"):
                pub, p_rng = block_attr(module, b,
                                        "endpoint_public_access",
                                        True)
                c, _ = block_attr(module, b, "public_access_cidrs",
                                  None)
                if isinstance(c, Unknown) or (
                        isinstance(c, list) and
                        any(not isinstance(x, str) for x in c)):
                    cidrs = UNKNOWN   # unresolved: must not fire
                elif isinstance(c, list):
                    cidrs = [x for x in c if isinstance(x, str)]
            cr.attrs["endpoint_public_access"] = Attr(pub, p_rng)
            if cidrs is not None:
                cr.attrs["public_access_cidrs"] = Attr(cidrs)
            out.append(cr)

        elif t == "aws_ecr_repository":
            scan = False
            s_rng = cr.rng
            for b in res.blocks("image_scanning_configuration"):
                scan, s_rng = block_attr(module, b, "scan_on_push",
                                            False)
            cr.attrs["scan_on_push"] = Attr(scan, s_rng)
            _a(res, "image_tag_mutability", cr)
            enc, e_rng = _block_val(module, res,
                                    "encryption_configuration",
                                    "encryption_type")
            cr.attrs["encryption_type"] = Attr(
                enc if enc is not None else "AES256",
                e_rng or cr.rng)
            out.append(cr)

        elif t == "aws_cloudwatch_log_group":
            _a(res, "kms_key_id", cr)
            out.append(cr)

        elif t == "aws_ecs_task_definition":
            _a(res, "container_definitions", cr)
            out.append(cr)

        elif t == "aws_ecs_cluster":
            ci, c_rng = None, cr.rng
            for b in res.blocks("setting"):
                attrs = module.eval_block_attrs(b)
                if attrs.get("name", (None, None))[0] == \
                        "containerInsights":
                    v = attrs.get("value", (None, None))[0]
                    ci = v if isinstance(v, Unknown) else \
                        (v == "enabled")
                    c_rng = (b.start, b.end)
            if ci is not None:
                cr.attrs["container_insights"] = Attr(ci, c_rng)
            else:
                cr.attrs["container_insights"] = Attr(False, cr.rng)
            out.append(cr)

        elif t == "aws_lb_listener":
            _a(res, "protocol", cr)
            action = {}
            a_rng = cr.rng
            for b in res.blocks("default_action"):
                attrs = module.eval_block_attrs(b)
                action["type"] = attrs.get("type", (None, None))[0]
                a_rng = (b.start, b.end)
                for rb in b.body.blocks:
                    if rb.type == "redirect":
                        rattrs = module.eval_block_attrs(rb)
                        # keep Unknown as Unknown — the check must
                        # not fire on unresolvable values
                        action["redirect_protocol"] = rattrs.get(
                            "protocol", ("", None))[0]
            if action:
                cr.attrs["default_action"] = Attr(action, a_rng)
            out.append(cr)

        elif t == "aws_kms_key":
            _a(res, "enable_key_rotation", cr)
            _a(res, "key_usage", cr)
            out.append(cr)

        elif t == "aws_sqs_queue":
            _a(res, "kms_master_key_id", cr)
            _a(res, "sqs_managed_sse_enabled", cr)
            out.append(cr)

        elif t == "aws_sns_topic":
            _a(res, "kms_master_key_id", cr)
            out.append(cr)

        elif t == "aws_dynamodb_table":
            pitr = False
            for b in res.blocks("point_in_time_recovery"):
                pitr, _ = block_attr(module, b, "enabled", False)
            cr.attrs["pitr_enabled"] = Attr(pitr)
            kms = ""
            for b in res.blocks("server_side_encryption"):
                kms, _ = block_attr(module, b, "kms_key_arn", "")
            cr.attrs["sse_kms_key"] = Attr(kms)
            out.append(cr)

        elif t == "aws_cloudfront_distribution":
            cr.attrs["logging_enabled"] = Attr(
                bool(res.blocks("logging_config")))
            policies = []
            for btype in ("default_cache_behavior",
                          "ordered_cache_behavior"):
                for b in res.blocks(btype):
                    vp, rng = block_attr(module, b,
                                            "viewer_protocol_policy",
                                            "")
                    if isinstance(vp, str) and vp:
                        policies.append({"policy": vp, "rng": rng})
            cr.attrs["viewer_policies"] = Attr(policies)
            mpv = "TLSv1"
            for b in res.blocks("viewer_certificate"):
                default_cert, _ = block_attr(
                    module, b, "cloudfront_default_certificate", False)
                mpv, _ = block_attr(module, b,
                                    "minimum_protocol_version",
                                    "TLSv1")
                if default_cert is True:
                    mpv = "TLSv1"   # default cert caps the policy
            cr.attrs["minimum_protocol_version"] = Attr(mpv)
            out.append(cr)

        elif t == "aws_redshift_cluster":
            _a(res, "encrypted", cr)
            _a(res, "cluster_subnet_group_name", cr, "subnet_group")
            out.append(cr)

        elif t == "aws_elasticache_replication_group":
            _a(res, "at_rest_encryption_enabled", cr)
            _a(res, "transit_encryption_enabled", cr)
            out.append(cr)

        elif t == "aws_lambda_function":
            mode = "PassThrough"
            for b in res.blocks("tracing_config"):
                mode, _ = block_attr(module, b, "mode", "PassThrough")
            cr.attrs["tracing_mode"] = Attr(mode)
            out.append(cr)

    # second pass: companion resources joined to their parent
    for res in module.resources:
        t = res.type
        if t == "aws_s3_bucket_public_access_block":
            target = _ref_target(res.raw.get("bucket"), "aws_s3_bucket")
            parent = buckets.get(target)
            if parent is not None:
                parent.attrs["public_access_block"] = Attr({
                    "block_public_acls": res.value("block_public_acls"),
                    "block_public_policy":
                        res.value("block_public_policy"),
                    "ignore_public_acls":
                        res.value("ignore_public_acls"),
                    "restrict_public_buckets":
                        res.value("restrict_public_buckets"),
                }, res.rng())
        elif t == "aws_s3_bucket_server_side_encryption_configuration":
            target = _ref_target(res.raw.get("bucket"), "aws_s3_bucket")
            parent = buckets.get(target)
            if parent is not None:
                parent.attrs["encryption_enabled"] = Attr(
                    True, res.rng())
                kms = _sse_kms_key_from_rules(module,
                                              res.blocks("rule"))
                if kms is not None:
                    parent.attrs["sse_kms_key_id"] = Attr(
                        kms, res.rng())
        elif t == "aws_s3_bucket_versioning":
            target = _ref_target(res.raw.get("bucket"), "aws_s3_bucket")
            parent = buckets.get(target)
            if parent is not None:
                v, rng = _block_val(module, res,
                                    "versioning_configuration", "status")
                enabled = UNKNOWN if isinstance(v, Unknown) else \
                    (v == "Enabled")
                parent.attrs["versioning_enabled"] = Attr(
                    enabled, rng or res.rng())
        elif t == "aws_s3_bucket_logging":
            target = _ref_target(res.raw.get("bucket"), "aws_s3_bucket")
            parent = buckets.get(target)
            if parent is not None:
                parent.attrs["logging_enabled"] = Attr(True, res.rng())
        elif t == "aws_s3_bucket_acl":
            target = _ref_target(res.raw.get("bucket"), "aws_s3_bucket")
            parent = buckets.get(target)
            if parent is not None and "acl" in res.attrs:
                parent.attrs["acl"] = Attr(res.value("acl"),
                                           res.rng("acl"))
        elif t == "aws_security_group_rule":
            rtype = res.value("type")
            target = _ref_target(res.raw.get("security_group_id"),
                                 "aws_security_group")
            parent = groups.get(target)
            if parent is None:
                parent = CloudResource("aws_security_group", res.name,
                                       rng=res.rng(), path=res.path)
                parent.attrs["description"] = Attr("rule-only group")
                parent.attrs["ingress"] = Attr([])
                parent.attrs["egress"] = Attr([])
                groups[res.name] = parent
                out.append(parent)
            cidrs = []
            for key in ("cidr_blocks", "ipv6_cidr_blocks"):
                v = res.value(key)
                if isinstance(v, list):
                    cidrs.extend(x for x in v
                                 if not isinstance(x, Unknown))
            desc = res.value("description") or ""
            rule = {"cidrs": cidrs,
                    "description": desc
                    if not isinstance(desc, Unknown) else "",
                    "rng": res.rng()}
            side = "egress" if rtype == "egress" else "ingress"
            parent.attrs[side].value.append(rule)

    return out


def _tf_providers():
    """Provider registry: (adapter, check list) pairs.  Each adapter
    yields CloudResources only for its own resource-type prefixes, so a
    provider's checks run (and count successes) only when the module
    actually uses that provider — absent state passes trivially, the
    way the reference's rego sees empty input documents."""
    from .azure import AZURE_CHECKS, adapt_azurerm
    from .gcp import GCP_CHECKS, adapt_google
    from .providers_extra import EXTRA_CHECKS, adapt_extra
    return [(adapt_terraform, AWS_CHECKS),
            (adapt_azurerm, AZURE_CHECKS),
            (adapt_google, GCP_CHECKS),
            (adapt_extra, EXTRA_CHECKS)]


def scan_terraform_module(files: dict[str, str]
                          ) -> dict[str, tuple[list, int]]:
    """files: path → text (one module).  → per-file (failures,
    successes); module-wide passes are attributed to the first file."""
    module = TfModule(files)
    provider_work = []
    for adapt, checks in _tf_providers():
        resources = adapt(module)
        if resources:
            provider_work.append((resources, checks))
    if not provider_work:
        return {}
    ignores = {path: ignored_ids_by_line(text)
               for path, text in files.items()}
    lines = {path: text.splitlines() for path, text in files.items()}
    by_file: dict[str, list] = {}
    successes = 0
    for resources, checks in provider_work:
        for check in checks:
            found = []
            for r in resources:
                for msg, rng in check.fn([r]):
                    if is_ignored(ignores.get(r.path, {}), check,
                                  rng[0]):
                        continue
                    found.append((r.path, msg, rng))
            if not found:
                successes += 1
                continue
            for path, msg, rng in found:
                by_file.setdefault(path, []).append(build_misconf(
                    check, "terraform", msg, rng, lines.get(path, [])))
    out = {}
    tf_paths = sorted(p for p in files if p.endswith((".tf",
                                                      ".tf.json")))
    first = tf_paths[0] if tf_paths else sorted(files)[0]
    for path in sorted(set(list(by_file) + [first])):
        out[path] = (by_file.get(path, []),
                     successes if path == first else 0)
    return out


def scan_terraform_files(all_files: dict[str, bytes]
                         ) -> list[T.Misconfiguration]:
    """Group .tf/.tfvars files by directory (module), scan each module,
    → per-file Misconfiguration records."""
    modules: dict[str, dict[str, str]] = {}
    for path, content in all_files.items():
        if not path.endswith((".tf", ".tfvars")):
            continue
        d = path.rsplit("/", 1)[0] if "/" in path else "."
        modules.setdefault(d, {})[path] = content.decode(
            "utf-8", errors="replace")
    records = []
    for d in sorted(modules):
        per_file = scan_terraform_module(modules[d])
        for path in sorted(per_file):
            failures, succ = per_file[path]
            if not failures and not succ:
                continue
            records.append(T.Misconfiguration(
                file_type="terraform", file_path=path,
                successes=succ,
                failures=sorted(failures,
                                key=lambda f: (f.id, f.message))))
    return records
