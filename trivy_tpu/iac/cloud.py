"""Adapted cloud state + AWS checks shared by the CloudFormation and
Terraform scanners.

The reference parses each IaC dialect into one typed cloud-state model
(pkg/iac/adapters → pkg/iac/providers) and evaluates the same rego
policies against it; this module is that shared half.  Resources are
normalized to Terraform resource-type names as the lingua franca, with
each attribute carrying its source range for cause metadata.  Check IDs
and severities follow the published AVD-AWS series (trivy-checks
avd.aquasec.com) so findings line up with the reference."""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field

from .core import Check


@dataclass
class Attr:
    value: object = None
    rng: tuple = (0, 0)


class Unknown:
    """A value the adapter could not resolve statically (cross-resource
    reference, runtime input).  Checks treat unknowns as passing, the
    same way the reference's rego sees undefined."""

    def __repr__(self):
        return "<unknown>"


UNKNOWN = Unknown()


@dataclass
class CloudResource:
    kind: str                 # terraform-style type, e.g. aws_s3_bucket
    name: str = ""
    attrs: dict = field(default_factory=dict)   # str -> Attr
    rng: tuple = (0, 0)
    path: str = ""            # source file (multi-file terraform modules)

    def get(self, key, default=None):
        a = self.attrs.get(key)
        if a is None or isinstance(a.value, Unknown):
            return default
        return a.value

    def val(self, key, default=None):
        """Raw attribute value — may be Unknown (missing → default)."""
        a = self.attrs.get(key)
        return default if a is None else a.value

    def attr_rng(self, key):
        a = self.attrs.get(key)
        return a.rng if a is not None and a.rng != (0, 0) else self.rng

    def known(self, key) -> bool:
        a = self.attrs.get(key)
        return a is not None and not isinstance(a.value, Unknown)

    def unknown(self, key) -> bool:
        a = self.attrs.get(key)
        return a is not None and isinstance(a.value, Unknown)


def sub_blocks(block, btype):
    """Nested blocks of `btype` inside an HCL block body."""
    return [b for b in block.body.blocks if b.type == btype]


def block_attr(module, block, key, default=None):
    """Evaluate one attribute of a nested block → (value, range).
    Unknown values pass through untouched so checks can treat them the
    way the reference's rego treats undefined — never firing."""
    attrs = module.eval_block_attrs(block)
    if key in attrs:
        return attrs[key]
    return default, (block.start, block.end)


AWS_CHECKS: list[Check] = []


def _aws(id_, title, severity, service, description="", resolution=""):
    def deco(fn):
        AWS_CHECKS.append(Check(
            id=id_, avd_id=id_, title=title, severity=severity,
            description=description, resolution=resolution,
            provider="AWS", service=service,
            namespace=f"builtin.aws.{service}.{id_}", fn=fn))
        return fn
    return deco


def _of(resources, kind):
    return [r for r in resources if r.kind == kind]


def _truthy(v):
    """Fires only on a KNOWN true — Unknown never satisfies a check."""
    if isinstance(v, Unknown):
        return False
    return v is True or v == "true" or v == "True" or v == 1


def _falsy(v):
    """Fires only on a KNOWN false/missing — Unknown passes, the way the
    reference's rego treats undefined values."""
    if isinstance(v, Unknown):
        return False
    return not _truthy(v)


# --- S3 -------------------------------------------------------------

def _pab_check(id_, title, description, resolution, pab_key, fragment):
    """The four S3 public-access-block checks share one body shape."""
    @_aws(id_, title, "HIGH", "s3", description, resolution)
    def check(resources):
        for r in _of(resources, "aws_s3_bucket"):
            if r.unknown("public_access_block"):
                continue
            pab = r.get("public_access_block")
            if pab is None:
                yield (f"Bucket '{r.name}' does not have a corresponding"
                       f" public access block.", r.rng)
            elif _falsy(pab.get(pab_key)):
                yield (f"Public access block for bucket '{r.name}' does "
                       f"not {fragment}",
                       r.attr_rng("public_access_block"))
    return check


_pab_check(
    "AVD-AWS-0086", "S3 Access block should block public ACLs",
    "S3 buckets should block public ACLs on buckets and any objects "
    "they contain.",
    "Enable blocking any PUT calls with a public ACL specified",
    "block_public_acls", "block public ACLs")
_pab_check(
    "AVD-AWS-0087", "S3 Access block should block public policy",
    "S3 bucket policy should have block public policy to prevent users "
    "from putting a policy that enable public access.",
    "Prevent policies that allow public access being PUT",
    "block_public_policy", "block public policies")
_pab_check(
    "AVD-AWS-0091", "S3 Access Block should Ignore Public Acl",
    "S3 buckets should ignore public ACLs on buckets and any objects "
    "they contain.",
    "Enable ignoring the application of public ACLs in PUT calls",
    "ignore_public_acls", "ignore public ACLs")
_pab_check(
    "AVD-AWS-0093", "S3 Access block should restrict public bucket to "
    "limit access",
    "S3 buckets should restrict public policies for the bucket.",
    "Limit the access to public buckets to only the owner or AWS "
    "Services (eg; CloudFront)",
    "restrict_public_buckets", "restrict public buckets")


@_aws("AVD-AWS-0088", "Unencrypted S3 bucket", "HIGH", "s3",
      "S3 Buckets should be encrypted to protect the data that is "
      "stored within them if access is compromised.",
      "Configure bucket encryption")
def _s3_encryption(resources):
    for r in _of(resources, "aws_s3_bucket"):
        if _falsy(r.val("encryption_enabled")):
            yield (f"Bucket '{r.name}' does not have encryption enabled",
                   r.attr_rng("encryption_enabled"))


@_aws("AVD-AWS-0089", "S3 Bucket Logging", "LOW", "s3",
      "Ensures S3 bucket logging is enabled for S3 buckets",
      "Add a logging block to the resource to enable access logging")
def _s3_logging(resources):
    for r in _of(resources, "aws_s3_bucket"):
        if _falsy(r.val("logging_enabled")) and \
                r.get("acl") != "log-delivery-write":
            yield (f"Bucket '{r.name}' does not have logging enabled",
                   r.rng)


@_aws("AVD-AWS-0090", "S3 Data should be versioned", "MEDIUM", "s3",
      "Versioning in Amazon S3 is a means of keeping multiple variants "
      "of an object in the same bucket.",
      "Enable versioning to protect against accidental/malicious "
      "removal or modification")
def _s3_versioning(resources):
    for r in _of(resources, "aws_s3_bucket"):
        if _falsy(r.val("versioning_enabled")):
            yield (f"Bucket '{r.name}' does not have versioning enabled",
                   r.rng)


@_aws("AVD-AWS-0092", "S3 Buckets not publicly accessible through ACL.",
      "HIGH", "s3",
      "Buckets should not have ACLs that allow public access",
      "Don't use canned ACLs or switch to private acl")
def _s3_public_acl(resources):
    for r in _of(resources, "aws_s3_bucket"):
        acl = r.get("acl")
        if acl in ("public-read", "public-read-write",
                   "website", "authenticated-read"):
            yield (f"Bucket '{r.name}' has a public ACL: '{acl}'.",
                   r.attr_rng("acl"))


# --- EC2 / VPC ------------------------------------------------------

def _cidr_public(c):
    c = str(c)
    return c in ("0.0.0.0/0", "::/0", "*")


@_aws("AVD-AWS-0107", "An ingress security group rule allows traffic "
      "from /0", "CRITICAL", "ec2",
      "Opening up ports to connect out to the public internet is "
      "generally to be avoided. You should restrict access to IP "
      "addresses or ranges that are explicitly required where possible.",
      "Set a more restrictive CIDR range")
def _sg_public_ingress(resources):
    for r in _of(resources, "aws_security_group"):
        for rule in r.get("ingress", []):
            for cidr in rule.get("cidrs", []):
                if _cidr_public(cidr):
                    yield (f"Security group rule allows ingress from "
                           f"public internet.", rule.get("rng", r.rng))


@_aws("AVD-AWS-0104", "An egress security group rule allows traffic "
      "to /0", "CRITICAL", "ec2",
      "Opening up ports to connect out to the public internet is "
      "generally to be avoided. You should restrict access to IP "
      "addresses or ranges that are explicitly required where possible.",
      "Set a more restrictive CIDR range")
def _sg_public_egress(resources):
    for r in _of(resources, "aws_security_group"):
        for rule in r.get("egress", []):
            for cidr in rule.get("cidrs", []):
                if _cidr_public(cidr):
                    yield (f"Security group rule allows egress to "
                           f"public internet.", rule.get("rng", r.rng))


@_aws("AVD-AWS-0099", "Missing description for security group.",
      "LOW", "ec2",
      "Security groups should include a description for auditing "
      "purposes.",
      "Add descriptions for all security groups")
def _sg_description(resources):
    for r in _of(resources, "aws_security_group"):
        if not r.get("description"):
            yield (f"Security group '{r.name}' does not have a "
                   f"description.", r.rng)


@_aws("AVD-AWS-0124", "Missing description for security group rule.",
      "LOW", "ec2",
      "Security group rules should include a description for auditing "
      "purposes.",
      "Add descriptions for all security groups rules")
def _sg_rule_description(resources):
    for r in _of(resources, "aws_security_group"):
        for key in ("ingress", "egress"):
            for rule in r.get(key, []):
                if not rule.get("description"):
                    yield ("Security group rule does not have a "
                           "description.", rule.get("rng", r.rng))


@_aws("AVD-AWS-0028", "aws_instance should activate session tokens "
      "for Instance Metadata Service.", "HIGH", "ec2",
      "IMDS v2 (Instance Metadata Service) introduced session "
      "authentication tokens which improve security when talking to "
      "IMDS.",
      "Enable HTTP token requirement for IMDS")
def _imds_tokens(resources):
    for r in _of(resources, "aws_instance"):
        if r.unknown("metadata_options"):
            continue
        mo = r.get("metadata_options")
        if mo is not None:
            tokens = mo.get("http_tokens")
            if isinstance(tokens, Unknown) or tokens == "required" or \
                    mo.get("http_endpoint") == "disabled":
                continue
        yield (f"Instance '{r.name}' does not require IMDS access "
               f"to require a token",
               r.attr_rng("metadata_options"))


@_aws("AVD-AWS-0131", "Instance with unencrypted block device.",
      "HIGH", "ec2",
      "Block devices should be encrypted to ensure sensitive data is "
      "held securely at rest.",
      "Turn on encryption for all block devices")
def _instance_block_device(resources):
    for r in _of(resources, "aws_instance"):
        rbd = r.get("root_block_device")
        if rbd is not None and _falsy(rbd.get("encrypted")):
            yield (f"Instance '{r.name}' root block device is not "
                   f"encrypted.", r.attr_rng("root_block_device"))
        for ebd in r.get("ebs_block_device", []):
            if _falsy(ebd.get("encrypted")):
                yield (f"Instance '{r.name}' EBS block device is not "
                       f"encrypted.", ebd.get("rng", r.rng))


@_aws("AVD-AWS-0026", "EBS volumes must be encrypted", "HIGH", "ebs",
      "By enabling encryption on EBS volumes you protect the volume, "
      "the disk I/O and any derived snapshots from compromise if "
      "intercepted.",
      "Enable encryption of EBS volumes")
def _ebs_encryption(resources):
    for r in _of(resources, "aws_ebs_volume"):
        if _falsy(r.val("encrypted")):
            yield (f"EBS volume '{r.name}' is not encrypted.", r.rng)


# --- RDS ------------------------------------------------------------

@_aws("AVD-AWS-0080", "RDS encryption has not been enabled at a DB "
      "Instance level.", "HIGH", "rds",
      "Encryption should be enabled for an RDS Database instances.",
      "Enable encryption for RDS instances")
def _rds_encryption(resources):
    for r in _of(resources, "aws_db_instance"):
        if _falsy(r.val("storage_encrypted")):
            yield (f"Instance '{r.name}' does not have storage "
                   f"encryption enabled.", r.rng)


@_aws("AVD-AWS-0077", "RDS Cluster and RDS instance should have backup "
      "retention longer than default 1 day", "MEDIUM", "rds",
      "RDS backup retention for clusters defaults to 1 day, this may "
      "not be enough to identify and respond to an issue.",
      "Explicitly set the retention period to greater than the default")
def _rds_backup_retention(resources):
    for kind in ("aws_db_instance", "aws_rds_cluster"):
        for r in _of(resources, kind):
            if r.known("replicate_source_db") or \
                    r.unknown("backup_retention_period"):
                continue
            period = r.get("backup_retention_period", 1)
            try:
                period = int(period)
            except (TypeError, ValueError):
                continue
            if period <= 1:
                yield (f"Instance '{r.name}' has very low backup "
                       f"retention period.",
                       r.attr_rng("backup_retention_period"))


@_aws("AVD-AWS-0180", "RDS Publicly Accessible", "HIGH", "rds",
      "Database resources should not publicly available. You should "
      "limit all access to the minimum that is required for your "
      "application to function.",
      "Set the database to not be publicly accessible")
def _rds_public(resources):
    for r in _of(resources, "aws_db_instance"):
        if _truthy(r.get("publicly_accessible")):
            yield (f"Instance '{r.name}' is exposed publicly.",
                   r.attr_rng("publicly_accessible"))


# --- CloudTrail / EFS / ELB ----------------------------------------

@_aws("AVD-AWS-0014", "Cloudtrail should be enabled in all regions "
      "when managing a trail", "MEDIUM", "cloudtrail",
      "When creating Cloudtrail in the AWS Management Console the trail "
      "is configured by default to be multi-region.",
      "Enable Cloudtrail in all regions")
def _trail_multiregion(resources):
    for r in _of(resources, "aws_cloudtrail"):
        if _falsy(r.val("is_multi_region_trail")):
            yield (f"Trail '{r.name}' is not enabled across all regions.",
                   r.rng)


@_aws("AVD-AWS-0016", "Cloudtrail log validation should be enabled to "
      "prevent tampering of log data", "HIGH", "cloudtrail",
      "Log validation should be activated on Cloudtrail logs to "
      "prevent the tampering of the underlying data in the S3 bucket.",
      "Turn on log validation for Cloudtrail")
def _trail_validation(resources):
    for r in _of(resources, "aws_cloudtrail"):
        if _falsy(r.val("enable_log_file_validation")):
            yield (f"Trail '{r.name}' does not have log validation "
                   f"enabled.", r.rng)


@_aws("AVD-AWS-0015", "Cloudtrail should be encrypted at rest to "
      "secure access to sensitive trail data", "HIGH", "cloudtrail",
      "Cloudtrail logs should be encrypted at rest to secure the "
      "sensitive data. Cloudtrail logs record all activity that occurs "
      "in the the account through API calls.",
      "Enable encryption at rest")
def _trail_cmk(resources):
    for r in _of(resources, "aws_cloudtrail"):
        if not r.unknown("kms_key_id") and \
                not r.get("kms_key_id"):
            yield (f"Trail '{r.name}' does not have a cmk set.", r.rng)


@_aws("AVD-AWS-0037", "EFS Encryption has not been enabled", "HIGH",
      "efs",
      "If your organization is subject to corporate or regulatory "
      "policies that require encryption of data and metadata at rest, "
      "we recommend creating a file system that is encrypted at rest.",
      "Enable encryption for EFS")
def _efs_encryption(resources):
    for r in _of(resources, "aws_efs_file_system"):
        if _falsy(r.val("encrypted")):
            yield (f"File system '{r.name}' is not encrypted.", r.rng)


@_aws("AVD-AWS-0053", "Load balancer is exposed to the internet.",
      "HIGH", "elb",
      "There are many scenarios in which you would want to expose a "
      "load balancer to the wider internet, but this check exists as a "
      "warning to prevent accidental exposure of internal assets.",
      "Switch to an internal load balancer or add a tfsec ignore")
def _elb_public(resources):
    for r in _of(resources, "aws_lb"):
        if r.get("load_balancer_type", "application") == "gateway":
            continue
        if _falsy(r.val("internal")):
            yield (f"Load balancer '{r.name}' is exposed publicly.",
                   r.rng)


@_aws("AVD-AWS-0052", "Load balancers should drop invalid headers",
      "HIGH", "elb",
      "Passing unknown or invalid headers through to the target poses "
      "a potential risk of compromise.",
      "Set drop_invalid_header_fields to true")
def _elb_invalid_headers(resources):
    for r in _of(resources, "aws_lb"):
        if r.get("load_balancer_type", "application") != "application":
            continue
        if _falsy(r.val("drop_invalid_header_fields")):
            yield (f"Application load balancer '{r.name}' is not set to "
                   f"drop invalid headers.", r.rng)


# --- IAM ------------------------------------------------------------

def _policy_docs(r):
    doc = r.get("policy_document")
    if isinstance(doc, str):
        try:
            doc = json.loads(doc)
        except Exception:
            return []
    return [doc] if isinstance(doc, dict) else []


@_aws("AVD-AWS-0057", "IAM policy should avoid use of wildcards and "
      "instead apply the principle of least privilege", "HIGH", "iam",
      "You should use the principle of least privilege when defining "
      "your IAM policies. This means you should specify each exact "
      "permission required without using wildcards, as this could "
      "cause the granting of access to certain undesired actions, "
      "resources and principals.",
      "Specify the exact permissions required, and to which resources "
      "they should apply instead of using wildcards.")
def _iam_wildcards(resources):
    for kind in ("aws_iam_policy", "aws_iam_role_policy",
                 "aws_iam_user_policy", "aws_iam_group_policy"):
        for r in _of(resources, kind):
            for doc in _policy_docs(r):
                stmts = doc.get("Statement", [])
                if isinstance(stmts, dict):
                    stmts = [stmts]
                for stmt in stmts:
                    if not isinstance(stmt, dict) or \
                            stmt.get("Effect", "Allow") != "Allow":
                        continue
                    actions = stmt.get("Action", [])
                    if isinstance(actions, str):
                        actions = [actions]
                    for a in actions:
                        if str(a).strip() == "*" or \
                                str(a).endswith(":*"):
                            yield (f"IAM policy document uses sensitive "
                                   f"action '{a}' on wildcarded resource"
                                   f" '{stmt.get('Resource', '*')}'",
                                   r.attr_rng("policy_document"))
                            break


# --- round-4 breadth: EKS / ECR / KMS / queues / caches / CDN -------

@_aws("AVD-AWS-0038", "EKS clusters should have control plane audit "
      "logging enabled", "MEDIUM", "eks",
      "Audit logs record API requests to the cluster control plane.",
      "Enable all control-plane log types in enabled_cluster_log_types")
def _eks_logging(resources):
    for r in _of(resources, "aws_eks_cluster"):
        if r.unknown("enabled_log_types"):
            continue
        logs = r.get("enabled_log_types") or []
        if any(not isinstance(x, str) for x in logs):
            continue   # an unresolved element could be "audit"
        if "audit" not in logs:
            yield (f"EKS cluster '{r.name}' has control plane audit "
                   f"logging disabled.", r.rng)


@_aws("AVD-AWS-0039", "EKS clusters should have secrets encryption "
      "enabled", "HIGH", "eks",
      "Secrets encryption protects Kubernetes secrets with a KMS key.",
      "Add an encryption_config block with a KMS key.")
def _eks_secrets(resources):
    for r in _of(resources, "aws_eks_cluster"):
        if _falsy(r.val("secrets_encrypted")):
            yield (f"EKS cluster '{r.name}' does not encrypt secrets.",
                   r.rng)


@_aws("AVD-AWS-0040", "EKS cluster endpoint should not be publicly "
      "accessible", "CRITICAL", "eks",
      "A public API endpoint exposes the control plane to the "
      "internet.",
      "Set endpoint_public_access = false or restrict the CIDRs.")
def _eks_public(resources):
    for r in _of(resources, "aws_eks_cluster"):
        if r.unknown("public_access_cidrs"):
            continue
        if _truthy(r.val("endpoint_public_access")) and \
                "0.0.0.0/0" in (r.get("public_access_cidrs") or
                                ["0.0.0.0/0"]):
            yield (f"EKS cluster '{r.name}' has a publicly accessible "
                   f"API endpoint.", r.attr_rng("endpoint_public_access"))


@_aws("AVD-AWS-0030", "ECR repositories should have image scanning "
      "enabled", "HIGH", "ecr",
      "Scan on push surfaces vulnerabilities before images deploy.",
      "Set image_scanning_configuration.scan_on_push = true.")
def _ecr_scanning(resources):
    for r in _of(resources, "aws_ecr_repository"):
        if _falsy(r.val("scan_on_push")):
            yield (f"ECR repository '{r.name}' does not scan images on "
                   f"push.", r.attr_rng("scan_on_push"))


@_aws("AVD-AWS-0031", "ECR repositories should have immutable tags",
      "HIGH", "ecr",
      "Mutable tags allow silently replacing a deployed image.",
      "Set image_tag_mutability = IMMUTABLE.")
def _ecr_immutable(resources):
    for r in _of(resources, "aws_ecr_repository"):
        if r.unknown("image_tag_mutability"):
            continue
        if r.get("image_tag_mutability", "MUTABLE") != "IMMUTABLE":
            yield (f"ECR repository '{r.name}' allows mutable tags.",
                   r.attr_rng("image_tag_mutability"))


@_aws("AVD-AWS-0065", "KMS keys should have rotation enabled", "MEDIUM",
      "kms",
      "Rotation bounds the blast radius of a compromised key.",
      "Set enable_key_rotation = true.")
def _kms_rotation(resources):
    for r in _of(resources, "aws_kms_key"):
        if r.get("key_usage", "ENCRYPT_DECRYPT") != "ENCRYPT_DECRYPT":
            continue  # signing keys cannot rotate
        if _falsy(r.val("enable_key_rotation")):
            yield (f"KMS key '{r.name}' does not have rotation "
                   f"enabled.", r.attr_rng("enable_key_rotation"))


@_aws("AVD-AWS-0096", "SQS queues should be encrypted", "HIGH", "sqs",
      "Queue messages may carry sensitive payloads.",
      "Set kms_master_key_id or sqs_managed_sse_enabled = true.")
def _sqs_encryption(resources):
    for r in _of(resources, "aws_sqs_queue"):
        if r.unknown("kms_master_key_id"):
            continue
        if not r.get("kms_master_key_id") and \
                _falsy(r.val("sqs_managed_sse_enabled")):
            yield (f"SQS queue '{r.name}' is not encrypted.", r.rng)


@_aws("AVD-AWS-0095", "SNS topics should be encrypted", "HIGH", "sns",
      "Topic messages may carry sensitive payloads.",
      "Set kms_master_key_id.")
def _sns_encryption(resources):
    for r in _of(resources, "aws_sns_topic"):
        if r.unknown("kms_master_key_id"):
            continue
        if not r.get("kms_master_key_id"):
            yield (f"SNS topic '{r.name}' is not encrypted.", r.rng)


@_aws("AVD-AWS-0024", "DynamoDB tables should have point-in-time "
      "recovery", "MEDIUM", "dynamodb",
      "PITR protects table data against accidental writes/deletes.",
      "Add a point_in_time_recovery block with enabled = true.")
def _dynamo_pitr(resources):
    for r in _of(resources, "aws_dynamodb_table"):
        if _falsy(r.val("pitr_enabled")):
            yield (f"DynamoDB table '{r.name}' does not have "
                   f"point-in-time recovery.", r.rng)


@_aws("AVD-AWS-0025", "DynamoDB tables should use customer-managed KMS "
      "keys", "LOW", "dynamodb",
      "Customer-managed keys allow rotation and revocation control.",
      "Add server_side_encryption with a kms_key_arn.")
def _dynamo_cmk(resources):
    for r in _of(resources, "aws_dynamodb_table"):
        if r.unknown("sse_kms_key"):
            continue
        if not r.get("sse_kms_key"):
            yield (f"DynamoDB table '{r.name}' is not encrypted with a "
                   f"customer-managed key.", r.rng)


@_aws("AVD-AWS-0010", "CloudFront distributions should have logging "
      "enabled", "MEDIUM", "cloudfront",
      "Access logs are the audit trail for content delivery.",
      "Add a logging_config block.")
def _cf_logging(resources):
    for r in _of(resources, "aws_cloudfront_distribution"):
        if _falsy(r.val("logging_enabled")):
            yield (f"CloudFront distribution '{r.name}' does not have "
                   f"logging enabled.", r.rng)


@_aws("AVD-AWS-0012", "CloudFront distributions should enforce HTTPS",
      "HIGH", "cloudfront",
      "allow-all viewer protocol policy serves content over plain "
      "HTTP.",
      "Set viewer_protocol_policy to redirect-to-https or https-only.")
def _cf_https(resources):
    for r in _of(resources, "aws_cloudfront_distribution"):
        for vp in r.get("viewer_policies", []):
            if vp.get("policy") == "allow-all":
                yield (f"CloudFront distribution '{r.name}' allows "
                       f"plain HTTP.", vp.get("rng", r.rng))


@_aws("AVD-AWS-0013", "CloudFront distributions should use a secure "
      "TLS policy", "HIGH", "cloudfront",
      "Old TLS protocol versions have known weaknesses.",
      "Set minimum_protocol_version to TLSv1.2_2021.")
def _cf_tls(resources):
    for r in _of(resources, "aws_cloudfront_distribution"):
        if r.unknown("minimum_protocol_version"):
            continue
        v = r.get("minimum_protocol_version", "TLSv1")
        if v not in ("TLSv1.2_2021",):
            yield (f"CloudFront distribution '{r.name}' allows TLS "
                   f"below the TLSv1.2_2021 policy.", r.rng)


@_aws("AVD-AWS-0083", "Redshift clusters should be encrypted", "HIGH",
      "redshift",
      "Warehouse data at rest should be encrypted.",
      "Set encrypted = true with a KMS key.")
def _redshift_encrypted(resources):
    for r in _of(resources, "aws_redshift_cluster"):
        if _falsy(r.val("encrypted")):
            yield (f"Redshift cluster '{r.name}' is not encrypted.",
                   r.rng)


@_aws("AVD-AWS-0084", "Redshift clusters should be deployed in a VPC",
      "HIGH", "redshift",
      "EC2-Classic deployment bypasses VPC network controls.",
      "Set cluster_subnet_group_name.")
def _redshift_vpc(resources):
    for r in _of(resources, "aws_redshift_cluster"):
        if r.unknown("subnet_group"):
            continue
        if not r.get("subnet_group"):
            yield (f"Redshift cluster '{r.name}' is not deployed in a "
                   f"VPC.", r.rng)


@_aws("AVD-AWS-0045", "ElastiCache replication groups should be "
      "encrypted at rest", "HIGH", "elasticache",
      "Cache contents may include session and credential data.",
      "Set at_rest_encryption_enabled = true.")
def _elasticache_rest(resources):
    for r in _of(resources, "aws_elasticache_replication_group"):
        if _falsy(r.val("at_rest_encryption_enabled")):
            yield (f"ElastiCache replication group '{r.name}' is not "
                   f"encrypted at rest.", r.rng)


@_aws("AVD-AWS-0046", "ElastiCache replication groups should encrypt "
      "traffic in transit", "HIGH", "elasticache",
      "Unencrypted cache traffic exposes payloads on the network.",
      "Set transit_encryption_enabled = true.")
def _elasticache_transit(resources):
    for r in _of(resources, "aws_elasticache_replication_group"):
        if _falsy(r.val("transit_encryption_enabled")):
            yield (f"ElastiCache replication group '{r.name}' does not "
                   f"encrypt traffic in transit.", r.rng)


@_aws("AVD-AWS-0066", "Lambda functions should have tracing enabled",
      "LOW", "lambda",
      "X-Ray tracing aids incident analysis of function behavior.",
      "Set tracing_config.mode to Active.")
def _lambda_tracing(resources):
    for r in _of(resources, "aws_lambda_function"):
        if r.unknown("tracing_mode"):
            continue
        if r.get("tracing_mode", "PassThrough") != "Active":
            yield (f"Lambda function '{r.name}' does not have tracing "
                   f"enabled.", r.rng)


@_aws("AVD-AWS-0017", "CloudWatch log groups should be encrypted with "
      "a customer-managed key", "LOW", "cloudwatch",
      "CloudWatch log data may contain sensitive information.",
      "Set kms_key_id on the log group.")
def _cloudwatch_cmk(resources):
    for r in _of(resources, "aws_cloudwatch_log_group"):
        if r.unknown("kms_key_id"):
            continue
        if not r.get("kms_key_id"):
            yield (f"Log group '{r.name}' is not encrypted with a "
                   f"customer-managed key.", r.rng)


_SECRET_ENV_RE = re.compile(
    r"(?i)(secret|password|passwd|token|api_?key|"
    r"access_?key(_?id)?|private_?key|credential)")


def _looks_secret_env(name: str) -> bool:
    return bool(_SECRET_ENV_RE.search(name))


@_aws("AVD-AWS-0036", "ECS task definitions should not hold plaintext "
      "secrets", "CRITICAL", "ecs",
      "Environment variables in task definitions are visible to "
      "anyone with read access to the task definition.",
      "Use SSM/Secrets Manager references instead of plaintext "
      "values.")
def _ecs_plain_secrets(resources):
    for r in _of(resources, "aws_ecs_task_definition"):
        if r.unknown("container_definitions"):
            continue
        raw = r.get("container_definitions")
        try:
            defs = json.loads(raw) if isinstance(raw, str) else raw
        except (TypeError, ValueError):
            continue
        for cdef in defs or []:
            if not isinstance(cdef, dict):
                continue
            for env in cdef.get("environment") or []:
                if isinstance(env, dict) and \
                        _looks_secret_env(str(env.get("name", ""))) \
                        and env.get("value"):
                    yield (f"Task definition '{r.name}' holds a "
                           f"plaintext secret in environment variable "
                           f"'{env.get('name')}'.",
                           r.attr_rng("container_definitions"))


@_aws("AVD-AWS-0054", "Load balancer listeners should not use plain "
      "HTTP", "CRITICAL", "elb",
      "Plain HTTP listeners expose traffic on the network.",
      "Use HTTPS (or redirect HTTP to HTTPS) on ALB listeners.")
def _elb_http_listener(resources):
    for r in _of(resources, "aws_lb_listener"):
        if r.unknown("protocol"):
            continue
        if r.get("protocol", "HTTP") != "HTTP":
            continue
        action = r.get("default_action") or {}
        atype = action.get("type")
        rproto = action.get("redirect_protocol")
        if isinstance(atype, Unknown) or isinstance(rproto, Unknown):
            continue  # unresolvable action: never fire
        if atype == "redirect" and str(rproto or "").upper() == "HTTPS":
            continue
        yield (f"Listener '{r.name}' uses plain HTTP.", r.rng)


@_aws("AVD-AWS-0132", "S3 encryption should use a customer-managed "
      "key", "HIGH", "s3",
      "CMKs give rotation and revocation control over bucket data.",
      "Set a KMS key in the bucket's server-side encryption "
      "configuration.")
def _s3_cmk(resources):
    for r in _of(resources, "aws_s3_bucket"):
        if r.unknown("sse_kms_key_id") or r.unknown("sse_algorithm"):
            continue
        if not _truthy(r.val("encryption_enabled")):
            continue  # AVD-AWS-0088 already covers no encryption
        # fire only when the adapter SAW the encryption config: an
        # explicit default-encryption rule without a KMS key, or a
        # live-walked algorithm that isn't aws:kms — a bare
        # "encryption on" marker stays silent
        explicit_no_key = ("sse_kms_key_id" in r.attrs
                           and not r.get("sse_kms_key_id"))
        algo = r.get("sse_algorithm")
        non_kms_algo = algo is not None and \
            "kms" not in str(algo).lower()
        if explicit_no_key or non_kms_algo:
            yield (f"Bucket '{r.name}' does not use a "
                   f"customer-managed key for encryption.", r.rng)


@_aws("AVD-AWS-0033", "ECR repositories should be encrypted with a "
      "customer-managed key", "LOW", "ecr",
      "Image layers may embed proprietary code and secrets.",
      "Set encryption_configuration with encryption_type = KMS.")
def _ecr_cmk(resources):
    for r in _of(resources, "aws_ecr_repository"):
        if r.unknown("encryption_type"):
            continue
        if r.attrs.get("encryption_type") is None:
            continue  # live walker doesn't fetch it; IaC adapters do
        if r.get("encryption_type", "AES256") != "KMS":
            yield (f"ECR repository '{r.name}' is not encrypted with "
                   f"a customer-managed key.", r.rng)


@_aws("AVD-AWS-0034", "ECS clusters should have container insights "
      "enabled", "LOW", "ecs",
      "Container insights surface resource and failure telemetry.",
      "Enable the containerInsights cluster setting.")
def _ecs_insights(resources):
    for r in _of(resources, "aws_ecs_cluster"):
        if r.unknown("container_insights"):
            continue
        if not _truthy(r.val("container_insights")):
            yield (f"ECS cluster '{r.name}' does not have container "
                   f"insights enabled.", r.rng)


@_aws("AVD-AWS-0001", "API Gateway stages should have access logging "
      "enabled", "MEDIUM", "api-gateway",
      "Stage access logs are the audit trail for API traffic.",
      "Configure access_log_settings on every stage.")
def _apigw_logging(resources):
    for r in _of(resources, "aws_api_gateway_stage"):
        if r.unknown("access_log_arn"):
            continue
        if not r.get("access_log_arn"):
            yield (f"API Gateway stage '{r.name}' does not have "
                   f"access logging enabled.", r.rng)


@_aws("AVD-AWS-0162", "CloudTrail trails should be integrated with "
      "CloudWatch Logs", "LOW", "cloudtrail",
      "CloudWatch integration enables near-real-time alerting on "
      "trail events.",
      "Set cloud_watch_logs_group_arn on the trail.")
def _trail_cloudwatch(resources):
    for r in _of(resources, "aws_cloudtrail"):
        if r.unknown("cloud_watch_logs_group_arn"):
            continue
        if not r.get("cloud_watch_logs_group_arn"):
            yield (f"Trail '{r.name}' is not integrated with "
                   f"CloudWatch Logs.", r.rng)


@_aws("AVD-AWS-0178", "VPCs should have flow logging enabled", "MEDIUM",
      "ec2",
      "Flow logs capture IP traffic metadata for forensics.",
      "Create a flow log for every VPC.")
def _vpc_flow_logs(resources):
    for r in _of(resources, "aws_vpc"):
        if _falsy(r.val("flow_logs_enabled")):
            yield (f"VPC '{r.name}' does not have flow logs enabled.",
                   r.rng)


@_aws("AVD-AWS-0173", "Default VPC security groups should restrict "
      "all traffic", "LOW", "ec2",
      "Rules on the default security group invite accidental "
      "exposure.",
      "Remove all rules from default security groups.")
def _default_sg(resources):
    for r in _of(resources, "aws_security_group"):
        if not _truthy(r.val("is_default")):
            continue  # set by the live walker / default-SG adapters
        if r.unknown("ingress") or r.unknown("egress"):
            continue
        if r.get("ingress") or r.get("egress"):
            yield ("Default security group has rules attached.",
                   r.rng)


# --- IAM account hygiene (CIS 1.x; reference trivy-aws iam checks) ---

def _pwpolicy(resources):
    for r in _of(resources, "aws_iam_password_policy"):
        yield r


@_aws("AVD-AWS-0056", "IAM password policy should prevent password "
      "reuse", "MEDIUM", "iam",
      "Reused passwords extend the life of a compromised credential.",
      "Set password_reuse_prevention to 5 or more.")
def _iam_pw_reuse(resources):
    for r in _pwpolicy(resources):
        if r.unknown("reuse_prevention"):
            continue
        if int(r.get("reuse_prevention") or 0) < 5:
            yield ("Password policy allows reusing recent passwords.",
                   r.rng)


@_aws("AVD-AWS-0058", "IAM password policy should require lowercase "
      "characters", "MEDIUM", "iam", "", "Require lowercase letters.")
def _iam_pw_lower(resources):
    for r in _pwpolicy(resources):
        if _falsy(r.val("require_lowercase")):
            yield ("Password policy does not require lowercase "
                   "characters.", r.rng)


@_aws("AVD-AWS-0059", "IAM password policy should require numbers",
      "MEDIUM", "iam", "", "Require numeric characters.")
def _iam_pw_numbers(resources):
    for r in _pwpolicy(resources):
        if _falsy(r.val("require_numbers")):
            yield ("Password policy does not require numbers.", r.rng)


@_aws("AVD-AWS-0060", "IAM password policy should require symbols",
      "MEDIUM", "iam", "", "Require symbol characters.")
def _iam_pw_symbols(resources):
    for r in _pwpolicy(resources):
        if _falsy(r.val("require_symbols")):
            yield ("Password policy does not require symbols.", r.rng)


@_aws("AVD-AWS-0061", "IAM password policy should require uppercase "
      "characters", "MEDIUM", "iam", "", "Require uppercase letters.")
def _iam_pw_upper(resources):
    for r in _pwpolicy(resources):
        if _falsy(r.val("require_uppercase")):
            yield ("Password policy does not require uppercase "
                   "characters.", r.rng)


@_aws("AVD-AWS-0062", "IAM password policy should expire passwords "
      "within 90 days", "MEDIUM", "iam", "",
      "Set max_password_age to 90 or less.")
def _iam_pw_age(resources):
    for r in _pwpolicy(resources):
        if r.unknown("max_age_days"):
            continue
        age = r.get("max_age_days")
        if not age or int(age) > 90:
            yield ("Password policy does not expire passwords within "
                   "90 days.", r.rng)


@_aws("AVD-AWS-0063", "IAM password policy should require a minimum "
      "length of 14", "MEDIUM", "iam", "",
      "Set minimum_password_length to 14 or more.")
def _iam_pw_length(resources):
    for r in _pwpolicy(resources):
        if r.unknown("minimum_length"):
            continue
        if int(r.get("minimum_length") or 0) < 14:
            yield ("Password policy minimum length is below 14.",
                   r.rng)


@_aws("AVD-AWS-0141", "The root account should have no access keys",
      "CRITICAL", "iam",
      "Root access keys grant unrestricted, unauditable API access.",
      "Delete all root access keys.")
def _iam_root_keys(resources):
    for r in _of(resources, "aws_iam_root"):
        if _truthy(r.val("access_keys_present")):
            yield ("The root account has active access keys.", r.rng)


@_aws("AVD-AWS-0142", "The root account should have MFA enabled",
      "CRITICAL", "iam",
      "A compromised root password alone must not grant access.",
      "Enable (hardware) MFA on the root account.")
def _iam_root_mfa(resources):
    for r in _of(resources, "aws_iam_root"):
        if r.unknown("mfa_enabled"):
            continue
        if _falsy(r.val("mfa_enabled")):
            yield ("The root account does not have MFA enabled.",
                   r.rng)


@_aws("AVD-AWS-0143", "IAM policies should be attached to groups or "
      "roles, not users", "LOW", "iam",
      "Per-user policies sprawl and escape review.",
      "Attach policies to groups/roles and add users to groups.")
def _iam_user_policies(resources):
    for r in _of(resources, "aws_iam_user"):
        if r.unknown("attached_policies"):
            continue
        if r.get("attached_policies"):
            yield (f"IAM user '{r.name}' has directly attached "
                   f"policies.", r.rng)


@_aws("AVD-AWS-0144", "Credentials unused for 90 days should be "
      "disabled", "MEDIUM", "iam",
      "Stale credentials widen the attack surface silently.",
      "Disable or remove unused passwords and access keys.")
def _iam_unused_creds(resources):
    for r in _of(resources, "aws_iam_user"):
        pw_days = r.get("password_last_used_days")
        if _truthy(r.val("has_console_password")) and \
                pw_days is not None and int(pw_days) > 90:
            yield (f"IAM user '{r.name}' has a console password "
                   f"unused for more than 90 days.", r.rng)
        for age in (r.get("key_unused_days") or []):
            if isinstance(age, int) and age > 90:
                yield (f"IAM user '{r.name}' has an access key unused "
                       f"for more than 90 days.", r.rng)
                break


@_aws("AVD-AWS-0145", "IAM users with console passwords should have "
      "MFA", "HIGH", "iam",
      "Console access without MFA is one phish away from takeover.",
      "Enable MFA for every console user.")
def _iam_user_mfa(resources):
    for r in _of(resources, "aws_iam_user"):
        if r.unknown("mfa_active"):
            continue
        if _truthy(r.val("has_console_password")) and \
                _falsy(r.val("mfa_active")):
            yield (f"IAM user '{r.name}' has console access without "
                   f"MFA.", r.rng)


@_aws("AVD-AWS-0146", "Access keys should be rotated every 90 days",
      "MEDIUM", "iam",
      "Long-lived keys accumulate exposure.",
      "Rotate access keys at least every 90 days.")
def _iam_key_rotation(resources):
    for r in _of(resources, "aws_iam_user"):
        for age in (r.get("access_key_ages_days") or []):
            if isinstance(age, int) and age > 90:
                yield (f"IAM user '{r.name}' has an access key older "
                       f"than 90 days.", r.rng)
                break


def run_aws_checks(resources, file_type, text):
    """→ (failures, successes) for adapted AWS resources."""
    from .core import run_checks

    def call(check):
        yield from check.fn(resources)

    return run_checks(AWS_CHECKS, file_type, text, call)
