"""Rego language lexer + parser (subset).

Parses the Rego dialect used by trivy-checks and user custom checks:
packages, imports (incl. rego.v1 / future.keywords no-ops), complete
rules, partial set/object rules (`deny[msg]`, `deny contains msg if`),
functions, `default`, `else`, `not`, `some .. in`, `every`, unification
and `:=` assignment, arrays/objects/sets, comprehensions, refs with
variable keys, arithmetic/comparison operators, and `# METADATA`
annotation blocks.

Reference counterpart: the OPA ast package consumed by
pkg/iac/rego/scanner.go:129 (NewScanner) and load.go; the metadata
conventions follow pkg/iac/rego/metadata.go.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

KEYWORDS = {
    "package", "import", "as", "default", "not", "some", "every", "in",
    "if", "contains", "else", "true", "false", "null", "with",
}

_TOKEN_RE = re.compile(r"""
    (?P<ws>[ \t\r]+)
  | (?P<comment>\#[^\n]*)
  | (?P<nl>\n)
  | (?P<raw>`[^`]*`)
  | (?P<str>"(?:\\.|[^"\\])*")
  | (?P<num>\d+(?:\.\d+)?(?:[eE][+-]?\d+)?)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<punct>:=|==|!=|<=|>=|\||&|[\[\]{}().,:;=<>+\-*/%])
""", re.VERBOSE)


@dataclass
class Token:
    kind: str   # ident kw str num punct nl
    val: object
    line: int


def tokenize(src: str):
    toks: list[Token] = []
    comments: list[tuple[int, str]] = []
    line = 1
    pos = 0
    n = len(src)
    while pos < n:
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise RegoSyntaxError(f"line {line}: bad character {src[pos]!r}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group()
        if kind == "ws":
            continue
        if kind == "comment":
            comments.append((line, text))
            continue
        if kind == "nl":
            toks.append(Token("nl", "\n", line))
            line += 1
            continue
        if kind == "raw":
            toks.append(Token("str", text[1:-1], line))
            line += text.count("\n")
            continue
        if kind == "str":
            toks.append(Token("str", _unescape(text[1:-1]), line))
            continue
        if kind == "num":
            v = float(text)
            if v.is_integer() and "." not in text and "e" not in text.lower():
                v = int(text)
            toks.append(Token("num", v, line))
            continue
        if kind == "ident":
            toks.append(Token("kw" if text in KEYWORDS else "ident",
                              text, line))
            continue
        toks.append(Token("punct", text, line))
    toks.append(Token("eof", None, line))
    return toks, comments


def _unescape(s: str) -> str:
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            mapped = {"n": "\n", "t": "\t", "r": "\r", '"': '"',
                      "\\": "\\", "/": "/"}.get(nxt)
            if mapped is not None:
                out.append(mapped)
                i += 2
                continue
            if nxt == "u" and i + 5 < len(s):
                out.append(chr(int(s[i + 2:i + 6], 16)))
                i += 6
                continue
        out.append(c)
        i += 1
    return "".join(out)


class RegoSyntaxError(Exception):
    pass


# ---- AST --------------------------------------------------------------
# Terms are tuples:
#   ('num', v) ('str', s) ('bool', b) ('null',) ('var', name)
#   ('ref', baseterm, [('dot', name) | ('idx', term), ...])
#   ('array', [t]) ('object', [(k, v)]) ('set', [t])
#   ('call', ref_term, [args])
#   ('bin', op, a, b)            op in + - * / % | & (set ops | & -)
#   ('cmp', op, a, b)            op in == != < <= > >=
#   ('in', x, coll) ('in2', k, v, coll)
#   ('acompr', head, body) ('scompr', head, body)
#   ('ocompr', k, v, body)
# Body exprs:
#   ('term', t) ('not', t) ('assign', target, t) ('unify', a, b)
#   ('some', [names]) ('somein', kvar_or_None, vvar, coll)
#   ('every', kvar_or_None, vvar, coll, body)
# each expr is (line, node, withs) where withs = [(ref_term, term), ...]


@dataclass
class Rule:
    name: str
    key: object = None          # partial set/object key term
    value: object = None        # value term (None => true)
    args: object = None         # function params (list of terms)
    body: list = field(default_factory=list)
    is_default: bool = False
    else_rules: list = field(default_factory=list)
    line: int = 0
    metadata: dict = field(default_factory=dict)
    is_partial_set: bool = False
    is_partial_obj: bool = False


@dataclass
class Module:
    package: tuple
    imports: list
    rules: list
    metadata: dict = field(default_factory=dict)
    path: str = ""

    def rules_named(self, name):
        return [r for r in self.rules if r.name == name]


class Parser:
    def __init__(self, src: str, path: str = ""):
        self.toks, self.comments = tokenize(src)
        self.i = 0
        self.path = path
        self.annotations = _parse_annotations(self.comments)

    # -- token helpers
    def peek(self, k=0):
        j = self.i
        seen = 0
        while j < len(self.toks):
            t = self.toks[j]
            if t.kind != "nl":
                if seen == k:
                    return t
                seen += 1
            j += 1
        return self.toks[-1]

    def peek_raw(self):
        return self.toks[self.i]

    def next(self):
        while self.toks[self.i].kind == "nl":
            self.i += 1
        t = self.toks[self.i]
        self.i += 1
        return t

    def skip_nl(self):
        while self.toks[self.i].kind == "nl":
            self.i += 1

    def expect(self, kind, val=None):
        t = self.next()
        if t.kind != kind or (val is not None and t.val != val):
            raise RegoSyntaxError(
                f"{self.path}:{t.line}: expected {val or kind}, "
                f"got {t.val!r}")
        return t

    def at_punct(self, val):
        t = self.peek()
        return t.kind == "punct" and t.val == val

    def at_kw(self, val):
        t = self.peek()
        return t.kind == "kw" and t.val == val

    def eat_punct(self, val):
        if self.at_punct(val):
            self.next()
            return True
        return False

    def eat_kw(self, val):
        if self.at_kw(val):
            self.next()
            return True
        return False

    # -- module
    def parse_module(self) -> Module:
        self.skip_nl()
        pkg_line = self.peek().line
        self.expect("kw", "package")
        pkg = self._parse_pkg_path()
        imports = []
        rules = []
        mod_meta = self._annotation_for(pkg_line, scope_default="package")
        while True:
            self.skip_nl()
            t = self.peek()
            if t.kind == "eof":
                break
            if t.kind == "kw" and t.val == "import":
                self.next()
                imports.append(self._parse_import())
                continue
            rules.append(self._parse_rule())
        m = Module(tuple(pkg), imports, rules, metadata=mod_meta or {},
                   path=self.path)
        return m

    def _parse_pkg_path(self):
        parts = [self.expect("ident").val]
        while self.eat_punct("."):
            t = self.next()
            if t.kind not in ("ident", "kw"):
                raise RegoSyntaxError(f"bad package path at line {t.line}")
            parts.append(t.val)
        return parts

    def _parse_import(self):
        # import data.lib.foo [as bar] / import rego.v1 / import input.x
        parts = [self.next().val]
        while self.eat_punct("."):
            t = self.next()
            parts.append(t.val)
        alias = None
        if self.eat_kw("as"):
            alias = self.expect("ident").val
        return (tuple(parts), alias)

    def _annotation_for(self, line, scope_default="rule"):
        best = None
        for ann in self.annotations:
            if ann["end_line"] < line and (
                    best is None or ann["end_line"] > best["end_line"]):
                # annotation must be adjacent (within 1 line of gap)
                if line - ann["end_line"] <= 1:
                    best = ann
        if best is None:
            return {}
        return best["data"]

    # -- rules
    def _parse_rule(self) -> Rule:
        line = self.peek().line
        meta = self._annotation_for(line)
        is_default = self.eat_kw("default")
        name_tok = self.next()
        if name_tok.kind not in ("ident", "kw"):
            raise RegoSyntaxError(
                f"{self.path}:{name_tok.line}: expected rule name, got "
                f"{name_tok.val!r}")
        name = name_tok.val
        rule = Rule(name=name, line=line, is_default=is_default,
                    metadata=meta)

        if self.at_punct("("):
            # function definition
            self.next()
            args = []
            if not self.at_punct(")"):
                while True:
                    args.append(self._parse_term())
                    if not self.eat_punct(","):
                        break
            self.expect("punct", ")")
            rule.args = args
        elif self.at_punct("["):
            # partial set rule deny[msg] or partial object rule a[k] = v
            self.next()
            rule.key = self._parse_term()
            self.expect("punct", "]")
            if self.at_punct("=") or self.at_punct(":="):
                self.next()
                rule.value = self._parse_term()
                rule.is_partial_obj = True
            else:
                rule.is_partial_set = True
        elif self.eat_kw("contains"):
            # deny contains msg if { ... }
            rule.key = self._parse_term()
            rule.is_partial_set = True

        if rule.args is not None or not (rule.is_partial_set or
                                         rule.is_partial_obj):
            if self.at_punct("=") or self.at_punct(":="):
                self.next()
                rule.value = self._parse_term()

        self.eat_kw("if")
        if self.at_punct("{"):
            rule.body = self._parse_body()
        elif not is_default and rule.value is None and not (
                rule.is_partial_set or rule.is_partial_obj):
            # bare `name if expr` single-expression body or `name := v`
            expr = self._parse_expr()
            rule.body = [expr]
        elif self.peek().kind != "eof" and \
                self.peek_raw().kind != "nl" and not self.at_kw("else"):
            # single-expression body after `if` on same line
            if not (self.at_kw("default") or self.at_punct("}")):
                t = self.peek()
                if t.kind in ("ident", "kw", "str", "num", "punct") and \
                        not self.at_punct("}"):
                    nxt = self.peek()
                    if not (nxt.kind == "kw" and nxt.val in
                            ("default", "package", "import")):
                        rule.body = [self._parse_expr()]

        while self.at_kw("else"):
            self.next()
            er = Rule(name=name, line=self.peek().line)
            if self.at_punct("=") or self.at_punct(":="):
                self.next()
                er.value = self._parse_term()
            self.eat_kw("if")
            if self.at_punct("{"):
                er.body = self._parse_body()
            rule.else_rules.append(er)
        return rule

    def _parse_body(self):
        self.expect("punct", "{")
        exprs = []
        while True:
            self.skip_nl()
            if self.at_punct("}"):
                self.next()
                break
            exprs.append(self._parse_expr())
            self.skip_nl()
            self.eat_punct(";")
        return exprs

    # -- expressions
    def _parse_expr(self):
        line = self.peek().line
        node = self._parse_expr_node()
        withs = []
        while self.at_kw("with"):
            self.next()
            target = self._parse_term()
            self.expect("kw", "as")
            val = self._parse_term()
            withs.append((target, val))
        return (line, node, withs)

    def _parse_expr_node(self):
        if self.eat_kw("not"):
            t = self._parse_term()
            return ("not", t)
        if self.at_kw("some"):
            self.next()
            # parse below `in` precedence so `some x in coll` keeps the
            # `in` for us to consume
            names = [self._parse_cmp()]
            while self.eat_punct(","):
                names.append(self._parse_cmp())
            if self.eat_kw("in"):
                coll = self._parse_term()
                if len(names) == 1:
                    return ("somein", None, names[0], coll)
                return ("somein", names[0], names[1], coll)
            out = []
            for nm in names:
                if nm[0] != "var":
                    raise RegoSyntaxError("some: expected variable")
                out.append(nm[1])
            return ("some", out)
        if self.at_kw("every"):
            self.next()
            v1 = self._parse_cmp()
            v2 = None
            if self.eat_punct(","):
                v2 = self._parse_cmp()
            self.expect("kw", "in")
            coll = self._parse_term()
            body = self._parse_body()
            if v2 is None:
                return ("every", None, v1, coll, body)
            return ("every", v1, v2, coll, body)

        t = self._parse_term()
        if self.at_punct(":="):
            self.next()
            rhs = self._parse_term()
            return ("assign", t, rhs)
        if self.at_punct("="):
            self.next()
            rhs = self._parse_term()
            return ("unify", t, rhs)
        return ("term", t)

    # -- terms (precedence: in < cmp < add < mul < unary < postfix)
    def _parse_term(self):
        return self._parse_in()

    def _parse_in(self):
        t = self._parse_cmp()
        if self.at_kw("in"):
            self.next()
            coll = self._parse_cmp()
            return ("in", t, coll)
        if self.at_punct(","):
            # `k, v in coll` only valid inside some/every which handle
            # commas themselves; here comma terminates the term.
            pass
        return t

    def _parse_cmp(self):
        t = self._parse_add()
        while self.peek().kind == "punct" and self.peek().val in (
                "==", "!=", "<", "<=", ">", ">="):
            op = self.next().val
            rhs = self._parse_add()
            t = ("cmp", op, t, rhs)
        return t

    def _parse_add(self):
        t = self._parse_mul()
        # NOTE: `|`/`&` set operators are intentionally not parsed as
        # binary ops — `|` would be ambiguous with the comprehension
        # separator; use union()/intersection() builtins instead.
        while self.peek().kind == "punct" and self.peek().val in (
                "+", "-"):
            op = self.next().val
            rhs = self._parse_mul()
            t = ("bin", op, t, rhs)
        return t

    def _parse_mul(self):
        t = self._parse_unary()
        while self.peek().kind == "punct" and self.peek().val in (
                "*", "/", "%"):
            op = self.next().val
            rhs = self._parse_unary()
            t = ("bin", op, t, rhs)
        return t

    def _parse_unary(self):
        if self.at_punct("-"):
            self.next()
            t = self._parse_unary()
            return ("bin", "-", ("num", 0), t)
        return self._parse_postfix()

    def _parse_postfix(self):
        t = self._parse_primary()
        while True:
            if self.at_punct("."):
                # only a ref/dot if followed by ident on same logical pos
                self.next()
                name_tok = self.next()
                if name_tok.kind not in ("ident", "kw"):
                    raise RegoSyntaxError(
                        f"{self.path}:{name_tok.line}: bad ref")
                t = _extend_ref(t, ("dot", name_tok.val))
            elif self._at_idx_bracket():
                self.next()
                idx = self._parse_term()
                self.expect("punct", "]")
                t = _extend_ref(t, ("idx", idx))
            elif self.at_punct("(") and _callable_ref(t):
                self.next()
                args = []
                if not self.at_punct(")"):
                    while True:
                        args.append(self._parse_term())
                        if not self.eat_punct(","):
                            break
                self.expect("punct", ")")
                t = ("call", t, args)
            else:
                return t

    def _at_idx_bracket(self):
        # `[` directly after the previous token (no newline) → index
        if not self.at_punct("["):
            return False
        return self.peek_raw().kind != "nl"

    def _parse_primary(self):
        t = self.next()
        if t.kind == "num":
            return ("num", t.val)
        if t.kind == "str":
            return ("str", t.val)
        if t.kind == "kw":
            if t.val == "true":
                return ("bool", True)
            if t.val == "false":
                return ("bool", False)
            if t.val == "null":
                return ("null",)
            if t.val == "in":  # allow use as var in odd spots? no
                raise RegoSyntaxError(f"line {t.line}: unexpected 'in'")
            # keywords like `contains`/`if` used as plain idents (e.g.
            # builtin `contains(...)`)
            return ("var", t.val)
        if t.kind == "ident":
            return ("var", t.val)
        if t.kind == "punct":
            if t.val == "(":
                inner = self._parse_term()
                self.expect("punct", ")")
                return inner
            if t.val == "[":
                return self._parse_array_or_compr()
            if t.val == "{":
                return self._parse_obj_set_or_compr()
        raise RegoSyntaxError(f"line {t.line}: unexpected {t.val!r}")

    def _parse_array_or_compr(self):
        self.skip_nl()
        if self.at_punct("]"):
            self.next()
            return ("array", [])
        first = self._parse_term()
        if self.at_punct("|"):
            self.next()
            body = self._parse_compr_body("]")
            return ("acompr", first, body)
        items = [first]
        while self.eat_punct(","):
            self.skip_nl()
            if self.at_punct("]"):
                break
            items.append(self._parse_term())
        self.skip_nl()
        self.expect("punct", "]")
        return ("array", items)

    def _parse_obj_set_or_compr(self):
        self.skip_nl()
        if self.at_punct("}"):
            self.next()
            return ("object", [])
        first = self._parse_term()
        if self.at_punct(":"):
            self.next()
            val = self._parse_term()
            if self.at_punct("|"):
                self.next()
                body = self._parse_compr_body("}")
                return ("ocompr", first, val, body)
            pairs = [(first, val)]
            while self.eat_punct(","):
                self.skip_nl()
                if self.at_punct("}"):
                    break
                k = self._parse_term()
                self.expect("punct", ":")
                v = self._parse_term()
                pairs.append((k, v))
            self.skip_nl()
            self.expect("punct", "}")
            return ("object", pairs)
        if self.at_punct("|"):
            self.next()
            body = self._parse_compr_body("}")
            return ("scompr", first, body)
        items = [first]
        while self.eat_punct(","):
            self.skip_nl()
            if self.at_punct("}"):
                break
            items.append(self._parse_term())
        self.skip_nl()
        self.expect("punct", "}")
        return ("set", items)

    def _parse_compr_body(self, closer):
        exprs = []
        while True:
            self.skip_nl()
            if self.at_punct(closer):
                self.next()
                break
            exprs.append(self._parse_expr())
            self.skip_nl()
            self.eat_punct(";")
        return exprs


def _extend_ref(t, op):
    if t[0] == "ref":
        return ("ref", t[1], t[2] + [op])
    return ("ref", t, [op])


def _callable_ref(t):
    if t[0] == "var":
        return True
    if t[0] == "ref" and all(op[0] == "dot" for op in t[2]):
        return True
    return False


def _parse_annotations(comments):
    """Collect `# METADATA` blocks → [{'end_line': n, 'data': {...}}]."""
    anns = []
    i = 0
    comments = sorted(comments)
    n = len(comments)
    while i < n:
        line, text = comments[i]
        if text.strip() == "# METADATA":
            yaml_lines = []
            last = line
            j = i + 1
            while j < n and comments[j][0] == last + 1:
                body = comments[j][1]
                if not body.startswith("#"):
                    break
                yaml_lines.append(body[1:].removeprefix(" "))
                last = comments[j][0]
                j += 1
            data = _load_yaml("\n".join(yaml_lines))
            if isinstance(data, dict):
                anns.append({"end_line": last, "data": data})
            i = j
        else:
            i += 1
    return anns


def _load_yaml(text):
    try:
        import yaml
        return yaml.safe_load(text)
    except Exception:
        return None


def parse_module(src: str, path: str = "") -> Module:
    return Parser(src, path).parse_module()
