"""Rego check engine (reference pkg/iac/rego).

Loads `.rego` modules (custom checks, shared libs, ignore policies),
retrieves static metadata (`# METADATA` annotations with a `custom`
block, or the legacy `__rego_metadata__` rule — reference
pkg/iac/rego/metadata.go), filters by input selectors, evaluates the
enforced rules (deny*/warn*/violation* — scanner.go:404 isEnforcedRule)
against parsed config documents, and converts results (string / cause
object with msg/startline/endline — result.go parseResult) into
DetectedMisconfiguration records.
"""

from __future__ import annotations

import os

from ... import types as T
from ..core import Check, build_misconf, ignored_ids_by_line, is_ignored
from .builtins import RSet, UNDEF, unfreeze
from .eval import Interpreter
from .parser import Module, RegoSyntaxError, parse_module

BUILTIN_NAMESPACES = {"builtin", "defsec", "appshield"}
DEFAULT_USER_NAMESPACES = {"user", "custom"}


def _enforced(name: str) -> bool:
    return name in ("deny", "warn", "violation") or \
        name.startswith(("deny_", "warn_", "violation_"))


class RegoError(Exception):
    pass


def load_modules_from_paths(paths) -> list[Module]:
    """Load .rego files/directories (skipping *_test.rego, like the
    reference's load.go)."""
    mods = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                for f in sorted(files):
                    if f.endswith(".rego") and not \
                            f.endswith("_test.rego"):
                        mods.append(_load_file(os.path.join(root, f)))
        elif p.endswith(".rego"):
            mods.append(_load_file(p))
    return mods


def _load_file(path) -> Module:
    with open(path, encoding="utf-8", errors="replace") as f:
        src = f.read()
    try:
        return parse_module(src, path=path)
    except RegoSyntaxError as e:
        raise RegoError(f"failed to parse {path}: {e}") from e


def load_data_from_paths(paths) -> dict:
    """Data documents from JSON/YAML files (reference dataDirs)."""
    import json
    data: dict = {}
    for p in paths or []:
        files = []
        if os.path.isdir(p):
            for root, _, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in sorted(names))
        else:
            files = [p]
        for fp in files:
            try:
                with open(fp, encoding="utf-8") as f:
                    if fp.endswith(".json"):
                        doc = json.load(f)
                    elif fp.endswith((".yaml", ".yml")):
                        import yaml
                        doc = yaml.safe_load(f)
                    else:
                        continue
            except Exception:
                continue
            if isinstance(doc, dict):
                _merge(data, doc)
    return data


def _merge(dst, src):
    for k, v in src.items():
        if isinstance(v, dict) and isinstance(dst.get(k), dict):
            _merge(dst[k], v)
        else:
            dst[k] = v


class StaticMetadata:
    def __init__(self):
        self.id = "N/A"
        self.avd_id = ""
        self.title = ""
        self.short_code = ""
        self.description = ""
        self.severity = "UNKNOWN"
        self.recommended_actions = ""
        self.url = ""
        self.selectors: list[str] = []
        self.provider = ""
        self.service = "general"


def retrieve_metadata(interp: Interpreter, mod: Module) -> StaticMetadata:
    """METADATA annotation first, legacy __rego_metadata__ rule second
    (reference MetadataRetriever.RetrieveMetadata)."""
    sm = StaticMetadata()
    meta = dict(mod.metadata or {})
    legacy = None
    if any(r.name == "__rego_metadata__" for r in mod.rules):
        v = interp.eval_rule(mod.package, "__rego_metadata__")
        if isinstance(v, dict):
            legacy = v
    custom = meta.get("custom") or {}
    src = {}
    if legacy:
        src.update(legacy)
    if custom:
        src.update(custom)
    sm.id = str(src.get("id", meta.get("id", sm.id)))
    sm.avd_id = str(src.get("avd_id", src.get("aliases", [""])[0]
                            if isinstance(src.get("aliases"), list)
                            else ""))
    sm.title = str(meta.get("title", src.get("title", "")))
    sm.description = str(meta.get("description",
                                  src.get("description", "")))
    sm.severity = str(src.get("severity", "UNKNOWN")).upper()
    sm.short_code = str(src.get("short_code", ""))
    sm.recommended_actions = str(
        src.get("recommended_actions", src.get("recommended_action", "")))
    urls = meta.get("related_resources") or []
    if urls and isinstance(urls, list):
        first = urls[0]
        sm.url = first.get("ref", "") if isinstance(first, dict) \
            else str(first)
    inp = src.get("input") or {}
    sels = inp.get("selector") or []
    for s in sels:
        if isinstance(s, dict) and "type" in s:
            t = str(s["type"])
            sm.selectors.append("cloud" if t == "defsec" else t)
    svc = src.get("service")
    if svc:
        sm.service = str(svc)
    prov = src.get("provider")
    if prov:
        sm.provider = str(prov)
    return sm


def _applicable(sm: StaticMetadata, file_type: str) -> bool:
    if not sm.selectors:
        return True
    aliases = {file_type}
    if file_type in ("yaml", "json", "kubernetes"):
        aliases.add("kubernetes")
    return bool(aliases & set(sm.selectors))


# process-wide rego evaluation trace sink (reference --trace /
# rego.WithTrace); set via set_rego_trace, consumed by every
# Interpreter this package builds
_TRACE_SINK = None


def set_rego_trace(sink) -> None:
    """sink(event, rule_path, depth) or None to disable."""
    global _TRACE_SINK
    _TRACE_SINK = sink


def rego_trace():
    return _TRACE_SINK


class RegoChecksScanner:
    """Holds user modules + data and scans parsed config docs."""

    def __init__(self, modules: list[Module], data: dict | None = None,
                 namespaces=None):
        self.all_modules = modules
        self.namespaces = set(namespaces or []) | DEFAULT_USER_NAMESPACES
        self.interp = Interpreter(modules, data=data,
                                  trace=_TRACE_SINK)

    @classmethod
    def from_paths(cls, check_paths, data_paths=None, namespaces=None):
        return cls(load_modules_from_paths(check_paths),
                   data=load_data_from_paths(data_paths),
                   namespaces=namespaces)

    def check_modules(self):
        for m in self.all_modules:
            if m.package and m.package[0] in self.namespaces:
                yield m

    def has_exceptions(self) -> bool:
        return any(
            m.package == ("namespace", "exceptions") or
            any(r.name == "exception" for r in m.rules)
            for m in self.all_modules)

    def _exc_interp(self, all_namespaces):
        """Interpreter with `data.namespaces` bound to every evaluated
        check namespace (the document the reference's scanner supplies
        to namespace-exception policies)."""
        key = tuple(sorted(all_namespaces))
        cache = getattr(self, "_exc_interps", None)
        if cache is None:
            cache = self._exc_interps = {}
        if key not in cache:
            cache[key] = Interpreter(
                self.all_modules,
                data={**self.interp.base_data,
                      "namespaces": list(key)})
        return cache[key]

    def is_namespace_ignored(self, namespace: str, input_doc,
                             all_namespaces) -> bool:
        """`data.namespace.exceptions.exception[_] == <ns>` evaluated
        with the input (reference exceptions.go isNamespaceIgnored)."""
        if not any(m.package == ("namespace", "exceptions")
                   for m in self.all_modules):
            return False
        try:
            val = self._exc_interp(all_namespaces).query(
                "namespace.exceptions.exception", input_doc=input_doc)
        except Exception:
            return False
        items = val.to_list() if isinstance(val, RSet) else \
            val if isinstance(val, list) else []
        return namespace in {str(x) for x in items}

    def is_rule_ignored(self, namespace: str, rule_name: str,
                        input_doc) -> bool:
        """`endswith(<ruleName>, data.<ns>.exception[_][_])` with the
        input (reference exceptions.go isRuleIgnored): the exception
        rule yields LISTS of rule-name suffixes; '' matches every
        rule."""
        pkg = tuple(namespace.split("."))
        if not any(m.package == pkg and
                   any(r.name == "exception" for r in m.rules)
                   for m in self.all_modules):
            return False
        try:
            val = self.interp.query(namespace + ".exception",
                                    input_doc=input_doc)
        except Exception:
            return False
        if val is UNDEF or val in (False, None):
            return False
        groups = val.to_list() if isinstance(val, RSet) else \
            val if isinstance(val, list) else [val]
        for group in groups:
            suffixes = group if isinstance(group, (list, tuple)) \
                else [group]
            for s in suffixes:
                if isinstance(s, str) and rule_name.endswith(s):
                    return True
        return False

    def is_ignored(self, namespace: str, rule_name: str, input_doc,
                   all_namespaces) -> bool:
        return self.is_namespace_ignored(
            namespace, input_doc, all_namespaces) or \
            self.is_rule_ignored(namespace, rule_name, input_doc)

    def scan_docs(self, file_type: str, path: str, docs,
                  text: str = "", extra_namespaces=None):
        """Evaluate every applicable module × enforced rule × doc.

        docs: list of parsed documents (each a plain JSON-like value).
        extra_namespaces: full namespace universe for data.namespaces
        (builtin + custom) when the caller knows it.
        → (failures, successes, exceptions) in the shared misconf
        shape."""
        failures: list[T.DetectedMisconfiguration] = []
        successes = 0
        exceptions = 0
        src_lines = text.splitlines() if text else []
        ignores = ignored_ids_by_line(text) if text else {}
        seen_pkgs = set()
        # loop-invariants: both scan every module, hoist out of the
        # per-doc-per-rule evaluation
        check_exceptions = self.has_exceptions()
        all_ns = extra_namespaces or \
            sorted(".".join(m.package) for m in self.check_modules())
        for mod in self.check_modules():
            # one evaluation per package: rules merge across modules
            # sharing a package (OPA compiles them into one document)
            if mod.package in seen_pkgs:
                continue
            seen_pkgs.add(mod.package)
            sm = self._package_metadata(mod)
            if not _applicable(sm, file_type):
                continue
            check = Check(
                id=sm.id, avd_id=sm.avd_id or sm.id,
                title=sm.title or sm.id,
                severity=sm.severity if sm.severity != "UNKNOWN"
                else "MEDIUM",
                description=sm.description,
                resolution=sm.recommended_actions,
                provider=sm.provider, service=sm.service,
                namespace=".".join(mod.package))
            rule_names = [n for n in self.interp.rule_names(mod.package)
                          if _enforced(n)]
            ns = ".".join(mod.package)
            module_failed = False
            module_excepted = False
            for doc in docs:
                for rname in rule_names:
                    # rego exceptions run for every namespace, custom
                    # ones included (reference scanner.go isIgnored)
                    if check_exceptions and \
                            self.is_ignored(ns, rname, doc, all_ns):
                        module_excepted = True
                        continue
                    for msg, rng in self._apply_rule(mod, rname, doc):
                        if is_ignored(ignores, check, rng[0]):
                            continue
                        module_failed = True
                        failures.append(build_misconf(
                            check, file_type, msg, rng, src_lines))
            if rule_names and not module_failed:
                if module_excepted:
                    exceptions += 1
                else:
                    successes += 1
        return failures, successes, exceptions

    def _package_metadata(self, mod: Module) -> StaticMetadata:
        """Metadata for a package: the annotated module wins when several
        modules share the package."""
        best = None
        for m in self.all_modules:
            if m.package != mod.package:
                continue
            sm = retrieve_metadata(self.interp, m)
            if sm.id != "N/A":
                return sm
            if best is None:
                best = sm
        return best or retrieve_metadata(self.interp, mod)

    def _apply_rule(self, mod: Module, rname: str, doc):
        path = ".".join(mod.package) + "." + rname
        try:
            val = self.interp.query(path, input_doc=doc)
        except Exception:
            return
        if val is UNDEF or val is False or val is None:
            return
        default_rng = _doc_range(doc)
        if isinstance(val, RSet):
            items = val.to_list()
        elif isinstance(val, list):
            items = val
        elif val is True:
            yield "Rego policy resulted in DENY", default_rng
            return
        else:
            items = [val]
        for item in items:
            yield _parse_result(item, default_rng)


def _doc_range(doc):
    if isinstance(doc, dict):
        md = doc.get("__defsec_metadata")
        if isinstance(md, dict):
            try:
                return (int(md.get("startline", 0)),
                        int(md.get("endline", 0)))
            except Exception:
                pass
    return (0, 0)


def _parse_result(item, default_rng):
    """String / cause-object / [obj, msg] array → (msg, range)
    (reference result.go parseResult)."""
    item = unfreeze(item)
    if isinstance(item, str):
        return item, default_rng
    if isinstance(item, list):
        msg = ""
        rng = default_rng
        for sub in item:
            if isinstance(sub, str):
                msg = sub
            elif isinstance(sub, dict):
                m, rng = _parse_cause(sub, default_rng)
                if m:
                    msg = m
        return msg or "Rego policy resulted in DENY", rng
    if isinstance(item, dict):
        msg, rng = _parse_cause(item, default_rng)
        return msg or "Rego policy resulted in DENY", rng
    return "Rego policy resulted in DENY", default_rng


def _parse_cause(cause, default_rng):
    msg = str(cause.get("msg", ""))
    start, end = default_rng
    if "startline" in cause:
        start = _int(cause["startline"])
    if "endline" in cause:
        end = _int(cause["endline"])
    md = cause.get("__defsec_metadata")
    if isinstance(md, dict):
        if "startline" in md:
            start = _int(md["startline"])
        if "endline" in md:
            end = _int(md["endline"])
    return msg, (start, max(start, end))


def _int(v):
    try:
        return int(float(v))
    except Exception:
        return 0
