"""Rego evaluator (subset) — generator-based backtracking interpreter.

Semantics follow OPA as exercised by the reference's check bundle and
custom-check docs (pkg/iac/rego/scanner.go runQuery): rules are virtual
documents under `data.<package>`, bodies are conjunctive queries over
possibly-unbound variables, `undefined` propagates as query failure.

Values are plain Python: dict / list / RSet / str / int / float / bool /
None. `undefined` is the UNDEF sentinel.
"""

from __future__ import annotations

from .parser import Module, Rule, parse_module
from . import builtins as B

UNDEF = B.UNDEF
RSet = B.RSet


class RegoEvalError(Exception):
    pass


class _Wildcard:
    _n = 0

    @classmethod
    def fresh(cls):
        cls._n += 1
        return f"$w{cls._n}"


class Env:
    """Immutable-ish binding environment (copy-on-bind)."""
    __slots__ = ("b",)

    def __init__(self, b=None):
        self.b = b or {}

    def get(self, name):
        return self.b.get(name, UNDEF)

    def bind(self, name, value):
        nb = dict(self.b)
        nb[name] = value
        return Env(nb)


class Interpreter:
    def __init__(self, modules: list[Module], data: dict | None = None,
                 trace=None):
        self.modules = modules
        self.base_data = data or {}
        self.pkg_index: dict[tuple, list[Module]] = {}
        for m in modules:
            self.pkg_index.setdefault(m.package, []).append(m)
        self.rule_cache: dict = {}
        self.input = UNDEF
        self.trace = trace
        self._depth = 0
        # query() mutates input/rule_cache for the whole evaluation —
        # a shared Interpreter (custom-checks scanner under --parallel
        # walks) must serialize queries
        import threading
        self._query_lock = threading.Lock()

    # -- public API ----------------------------------------------------
    def query(self, path: str, input_doc=UNDEF):
        """Evaluate `data.<path>` → value or UNDEF. Thread-safe: the
        evaluation state (input, rule cache) is per-query."""
        with self._query_lock:
            self.input = input_doc
            self.rule_cache = {}
            parts = tuple(path.split("."))
            try:
                return self._data_path(parts)
            finally:
                self.input = UNDEF

    def rule_names(self, pkg: tuple) -> list[str]:
        names = []
        for m in self.pkg_index.get(pkg, []):
            for r in m.rules:
                if r.name not in names:
                    names.append(r.name)
        return names

    # -- data document -------------------------------------------------
    def _data_path(self, parts: tuple):
        # walk down: packages win over base data at the same key
        for cut in range(len(parts), 0, -1):
            pkg = parts[:cut]
            if pkg in self.pkg_index:
                val = self._eval_rule_path(pkg, parts[cut:])
                if val is not UNDEF:
                    return val
        # base data fallback
        cur = self.base_data
        for p in parts:
            if isinstance(cur, dict) and p in cur:
                cur = cur[p]
            else:
                return UNDEF
        return cur

    def _eval_rule_path(self, pkg: tuple, rest: tuple):
        if not rest:
            # whole package document
            out = {}
            for name in self.rule_names(pkg):
                v = self.eval_rule(pkg, name)
                if v is not UNDEF:
                    out[name] = v
            return out
        head, tail = rest[0], rest[1:]
        v = self.eval_rule(pkg, head)
        for p in tail:
            if v is UNDEF:
                return UNDEF
            v = B.index_into(v, p)
        return v

    # -- rules ---------------------------------------------------------
    def eval_rule(self, pkg: tuple, name: str):
        key = (pkg, name)
        if key in self.rule_cache:
            return self.rule_cache[key]
        if self.trace is not None:
            self.trace("enter", ".".join(pkg + (name,)), self._depth)
        self._depth += 1
        try:
            return self._eval_rule_inner(key, pkg, name)
        finally:
            self._depth -= 1
            if self.trace is not None:
                self.trace("exit", ".".join(pkg + (name,)), self._depth)

    def _eval_rule_inner(self, key, pkg: tuple, name: str):
        # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
        self.rule_cache[key] = UNDEF  # cycle guard
        defs = []
        for m in self.pkg_index.get(pkg, []):
            for r in m.rules:
                if r.name == name:
                    defs.append((m, r))
        if not defs:
            return UNDEF
        if any(r.args is not None for _, r in defs):
            fn = _UserFunction(self, [(m, r) for m, r in defs
                                      if r.args is not None])
            # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
            self.rule_cache[key] = fn
            return fn

        partial_set = any(r.is_partial_set for _, r in defs)
        partial_obj = any(r.is_partial_obj for _, r in defs)
        default_val = UNDEF
        result = UNDEF
        if partial_set:
            result = RSet()
            for m, r in defs:
                if not r.is_partial_set:
                    continue
                for env in self.eval_body(r.body, Env(), m):
                    for v, env2 in self.eval_term(r.key, env, m):
                        if v is not UNDEF:
                            result.add(v)
        elif partial_obj:
            result = {}
            for m, r in defs:
                if not r.is_partial_obj:
                    continue
                for env in self.eval_body(r.body, Env(), m):
                    for k, env2 in self.eval_term(r.key, env, m):
                        for v, _ in self.eval_term(r.value, env2, m):
                            if k is not UNDEF and v is not UNDEF:
                                result[B.to_key(k)] = v
        else:
            for m, r in defs:
                if r.is_default:
                    for v, _ in self.eval_term(r.value, Env(), m):
                        default_val = v
                    continue
                got = self._eval_complete_def(m, r)
                if got is not UNDEF:
                    result = got
                    break
            if result is UNDEF:
                result = default_val
        # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
        self.rule_cache[key] = result
        return result

    def _eval_complete_def(self, m, r: Rule):
        for env in self.eval_body(r.body, Env(), m):
            if r.value is None:
                return True
            for v, _ in self.eval_term(r.value, env, m):
                return v
        for er in r.else_rules:
            for env in self.eval_body(er.body, Env(), m):
                if er.value is None:
                    return True
                for v, _ in self.eval_term(er.value, env, m):
                    return v
        return UNDEF

    # -- bodies --------------------------------------------------------
    def eval_body(self, body, env: Env, mod: Module):
        if not body:
            yield env
            return
        yield from self._eval_exprs(body, 0, env, mod)

    def _eval_exprs(self, body, i, env, mod):
        if i >= len(body):
            yield env
            return
        line, node, withs = body[i]
        if withs:
            import copy
            saved = self.input
            saved_data = self.base_data
            try:
                for tgt, val_t in withs:
                    if tgt == ("var", "input") or (
                            tgt[0] == "ref" and tgt[1] == ("var", "input")
                            and not tgt[2]):
                        for v, env in self.eval_term(val_t, env, mod):
                            # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
                            self.input = v
                            break
                    # `with input.x as v` partial override
                    elif tgt[0] == "ref" and tgt[1] == ("var", "input"):
                        base = copy.deepcopy(self.input) \
                            if isinstance(self.input, (dict, list)) else {}
                        # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
                        self.input = _override_path(
                            base, tgt[2], val_t, self, env, mod)
                    elif tgt[0] == "ref" and tgt[1] == ("var", "data"):
                        base = copy.deepcopy(self.base_data)
                        self.base_data = _override_path(
                            base, tgt[2], val_t, self, env, mod)
                    else:
                        raise RegoEvalError(
                            "unsupported with-modifier target")
                # materialize while the override is active; rule results
                # computed under `with` must not leak into the cache
                saved_cache = self.rule_cache
                # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
                self.rule_cache = {}
                solutions = list(self._eval_one(node, env, mod))
                # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
                self.rule_cache = saved_cache
            finally:
                # lint: allow(TPU106) reason=runs under the query lock taken by Interpreter.query — an interprocedural hold the intraprocedural rule cannot see
                self.input = saved
                self.base_data = saved_data
            for e2 in solutions:
                yield from self._eval_exprs(body, i + 1, e2, mod)
            return
        for e2 in self._eval_one(node, env, mod):
            yield from self._eval_exprs(body, i + 1, e2, mod)

    def _eval_one(self, node, env, mod):
        kind = node[0]
        if kind == "term":
            for v, e2 in self.eval_term(node[1], env, mod):
                if v is not UNDEF and v is not False:
                    yield e2
            return
        if kind == "not":
            for v, _ in self.eval_term(node[1], env, mod):
                if v is not UNDEF and v is not False:
                    return
            yield env
            return
        if kind == "assign":
            target, rhs = node[1], node[2]
            for v, e2 in self.eval_term(rhs, env, mod):
                if v is UNDEF:
                    continue
                e3 = _bind_pattern(target, v, e2)
                if e3 is not None:
                    yield e3
            return
        if kind == "unify":
            yield from self._unify(node[1], node[2], env, mod)
            return
        if kind == "some":
            # declares locals; nothing to do eagerly
            yield env
            return
        if kind == "somein":
            _, kvar, vvar, coll_t = node
            for coll, e2 in self.eval_term(coll_t, env, mod):
                for k, v in B.iter_collection(coll):
                    e3 = e2
                    if kvar is not None:
                        e3 = _bind_pattern(kvar, k, e3)
                        if e3 is None:
                            continue
                    e4 = _bind_pattern(vvar, v, e3)
                    if e4 is not None:
                        yield e4
            return
        if kind == "every":
            _, kvar, vvar, coll_t, body = node
            for coll, e2 in self.eval_term(coll_t, env, mod):
                ok = True
                for k, v in B.iter_collection(coll):
                    e3 = e2
                    if kvar is not None:
                        e3 = _bind_pattern(kvar, k, e3)
                    e3 = _bind_pattern(vvar, v, e3) if e3 else None
                    if e3 is None:
                        ok = False
                        break
                    if not any(True for _ in self.eval_body(body, e3, mod)):
                        ok = False
                        break
                if ok:
                    yield e2
                    return
            return
        raise RegoEvalError(f"unknown expr {kind}")

    def _unify(self, a, b, env, mod):
        # try evaluating both; bind whichever side is an unbound pattern
        a_ground = _is_ground(a, env)
        b_ground = _is_ground(b, env)
        if a_ground and b_ground:
            for va, e2 in self.eval_term(a, env, mod):
                for vb, e3 in self.eval_term(b, e2, mod):
                    if B.rego_eq(va, vb):
                        yield e3
            return
        if a_ground:
            for va, e2 in self.eval_term(a, env, mod):
                if va is UNDEF:
                    continue
                e3 = _bind_pattern(b, va, e2)
                if e3 is not None:
                    yield e3
            return
        if b_ground:
            for vb, e2 in self.eval_term(b, env, mod):
                if vb is UNDEF:
                    continue
                e3 = _bind_pattern(a, vb, e2)
                if e3 is not None:
                    yield e3
            return
        # both non-ground: iterate a's possibilities (ref enumeration)
        for va, e2 in self.eval_term(a, env, mod):
            if va is UNDEF:
                continue
            e3 = _bind_pattern(b, va, e2)
            if e3 is not None:
                yield e3

    # -- terms ---------------------------------------------------------
    def eval_term(self, t, env: Env, mod: Module):
        """Yield (value, env) pairs — multiple when unbound vars occur
        in ref indices (enumeration)."""
        kind = t[0]
        if kind == "num" or kind == "str" or kind == "bool":
            yield t[1], env
            return
        if kind == "null":
            yield None, env
            return
        if kind == "var":
            name = t[1]
            if name == "_":
                yield UNDEF, env  # bare wildcard as value: undefined
                return
            if name == "input":
                yield self.input, env
                return
            if name == "data":
                yield _DataDoc(self), env
                return
            v = env.get(name)
            if v is not UNDEF:
                yield v, env
                return
            # maybe a rule or import in this module's package
            v = self._resolve_name(name, mod)
            yield v, env
            return
        if kind == "ref":
            yield from self._eval_ref(t, env, mod)
            return
        if kind == "array":
            yield from self._eval_array(t[1], env, mod)
            return
        if kind == "object":
            yield from self._eval_object(t[1], env, mod)
            return
        if kind == "set":
            s = RSet()
            done = env
            ok = True
            for item in t[1]:
                got = next(self.eval_term(item, done, mod), None)
                if got is None or got[0] is UNDEF:
                    ok = False
                    break
                s.add(got[0])
                done = got[1]
            if ok:
                yield s, done
            return
        if kind == "cmp":
            _, op, a, b = t
            for va, e2 in self.eval_term(a, env, mod):
                for vb, e3 in self.eval_term(b, e2, mod):
                    if va is UNDEF or vb is UNDEF:
                        continue
                    yield B.compare(op, va, vb), e3
            return
        if kind == "bin":
            _, op, a, b = t
            for va, e2 in self.eval_term(a, env, mod):
                for vb, e3 in self.eval_term(b, e2, mod):
                    if va is UNDEF or vb is UNDEF:
                        continue
                    yield B.arith(op, va, vb), e3
            return
        if kind == "in":
            _, x, coll_t = t
            for vx, e2 in self.eval_term(x, env, mod):
                for coll, e3 in self.eval_term(coll_t, e2, mod):
                    yield B.member(vx, coll), e3
            return
        if kind == "call":
            yield from self._eval_call(t, env, mod)
            return
        if kind == "acompr":
            head, body = t[1], t[2]
            out = []
            for e2 in self.eval_body(body, env, mod):
                for v, _ in self.eval_term(head, e2, mod):
                    if v is not UNDEF:
                        out.append(v)
            yield out, env
            return
        if kind == "scompr":
            head, body = t[1], t[2]
            s = RSet()
            for e2 in self.eval_body(body, env, mod):
                for v, _ in self.eval_term(head, e2, mod):
                    if v is not UNDEF:
                        s.add(v)
            yield s, env
            return
        if kind == "ocompr":
            kt, vt, body = t[1], t[2], t[3]
            out = {}
            for e2 in self.eval_body(body, env, mod):
                for k, e3 in self.eval_term(kt, e2, mod):
                    for v, _ in self.eval_term(vt, e3, mod):
                        if k is not UNDEF and v is not UNDEF:
                            out[B.to_key(k)] = v
            yield out, env
            return
        raise RegoEvalError(f"unknown term {kind}")

    def _eval_array(self, items, env, mod):
        def rec(idx, acc, e):
            if idx == len(items):
                yield list(acc), e
                return
            for v, e2 in self.eval_term(items[idx], e, mod):
                if v is UNDEF:
                    continue
                yield from rec(idx + 1, acc + [v], e2)
        yield from rec(0, [], env)

    def _eval_object(self, pairs, env, mod):
        def rec(idx, acc, e):
            if idx == len(pairs):
                yield dict(acc), e
                return
            kt, vt = pairs[idx]
            for k, e2 in self.eval_term(kt, e, mod):
                for v, e3 in self.eval_term(vt, e2, mod):
                    if k is UNDEF or v is UNDEF:
                        continue
                    yield from rec(idx + 1, acc + [(B.to_key(k), v)], e3)
        yield from rec(0, [], env)

    def _resolve_name(self, name, mod: Module):
        if mod is None:
            return UNDEF
        for path, alias in mod.imports:
            nm = alias or path[-1]
            if nm == name:
                if path[0] == "data":
                    return self._data_path(path[1:])
                if path[0] == "input":
                    v = self.input
                    for p in path[1:]:
                        v = B.index_into(v, p)
                    return v
                return UNDEF
        # rule in same package
        if any(r.name == name for r in mod.rules):
            return self.eval_rule(mod.package, name)
        # builtin zero-ref (e.g. used as function elsewhere)
        return UNDEF

    def _eval_ref(self, t, env, mod):
        base, ops = t[1], t[2]
        # data refs resolved lazily to support packages at any depth
        if base == ("var", "data"):
            yield from self._eval_data_ref(ops, env, mod)
            return
        for v, e in self.eval_term(base, env, mod):
            yield from self._walk_ops(v, ops, 0, e, mod)

    def _walk_ops(self, v, ops, i, env, mod):
        if v is UNDEF:
            return
        if isinstance(v, _UserFunction) or callable(v):
            # ref into function result unsupported without call
            return
        if i == len(ops):
            yield v, env
            return
        op = ops[i]
        if op[0] == "dot":
            yield from self._walk_ops(B.index_into(v, op[1]), ops, i + 1,
                                      env, mod)
            return
        idx_t = op[1]
        if idx_t[0] == "var":
            name = idx_t[1]
            if name == "_":
                for k, item in B.iter_collection(v):
                    yield from self._walk_ops(item, ops, i + 1, env, mod)
                return
            bound = env.get(name)
            if bound is UNDEF and not self._is_defined_name(name, mod):
                # unbound variable: enumerate collection, binding it
                for k, item in B.iter_collection(v):
                    yield from self._walk_ops(item, ops, i + 1,
                                              env.bind(name, k), mod)
                return
        for idx_v, e2 in self.eval_term(idx_t, env, mod):
            if idx_v is UNDEF:
                continue
            yield from self._walk_ops(B.index_into(v, idx_v), ops, i + 1,
                                      e2, mod)

    def _is_defined_name(self, name, mod):
        if name in ("input", "data"):
            return True
        if mod is not None:
            if any(r.name == name for r in mod.rules):
                return True
            for path, alias in mod.imports:
                if (alias or path[-1]) == name:
                    return True
        return False

    def _eval_data_ref(self, ops, env, mod):
        # resolve leading dot-ops as a static data path, then dynamic
        static = []
        i = 0
        while i < len(ops) and ops[i][0] == "dot":
            static.append(ops[i][1])
            i += 1
        v = self._data_path(tuple(static))
        yield from self._walk_ops(v, ops, i, env, mod)

    # -- calls ---------------------------------------------------------
    def _eval_call(self, t, env, mod):
        fn_t, args = t[1], t[2]
        name = _ref_to_name(fn_t)
        # user function in same package or imported lib?
        target = self._lookup_function(name, fn_t, env, mod)
        if target is not None:
            yield from target.call(args, env, mod)
            return
        if name == "walk":
            for v, e in self.eval_term(args[0], env, mod):
                for path, val in B.walk_paths(v):
                    yield [path, val], e
            return
        bfn = B.BUILTINS.get(name)
        if bfn is None:
            raise RegoEvalError(f"unknown function {name}")
        def rec(idx, acc, e):
            if idx == len(args):
                try:
                    yield bfn(*acc), e
                except B.Halt:
                    raise
                except Exception:
                    yield UNDEF, e
                return
            for v, e2 in self.eval_term(args[idx], e, mod):
                yield from rec(idx + 1, acc + [v], e2)
        yield from rec(0, [], env)

    def _lookup_function(self, name, fn_t, env, mod):
        if fn_t[0] == "var":
            if mod is not None and any(
                    r.name == name and r.args is not None
                    for r in mod.rules):
                v = self.eval_rule(mod.package, name)
                if isinstance(v, _UserFunction):
                    return v
            if mod is not None:
                for path, alias in mod.imports:
                    if (alias or path[-1]) == name and path[0] == "data":
                        v = self._data_path(path[1:])
                        if isinstance(v, _UserFunction):
                            return v
            v = env.get(name)
            if isinstance(v, _UserFunction):
                return v
            return None
        # dotted: maybe data.lib.fn or imported-lib.fn
        if fn_t[0] == "ref":
            parts = _ref_parts(fn_t)
            if parts is None:
                return None
            if parts[0] == "data":
                v = self._data_path(tuple(parts[1:]))
                if isinstance(v, _UserFunction):
                    return v
                return None
            if mod is not None:
                for path, alias in mod.imports:
                    if (alias or path[-1]) == parts[0] and \
                            path[0] == "data":
                        v = self._data_path(tuple(path[1:]) +
                                            tuple(parts[1:]))
                        if isinstance(v, _UserFunction):
                            return v
        return None


class _UserFunction:
    def __init__(self, interp, defs):
        self.interp = interp
        self.defs = defs  # [(module, rule)]

    def call(self, arg_terms, env, call_mod):
        interp = self.interp
        # evaluate args in caller env
        def rec(idx, acc, e):
            if idx == len(arg_terms):
                yield acc, e
                return
            for v, e2 in interp.eval_term(arg_terms[idx], e, call_mod):
                if v is UNDEF:
                    continue
                yield from rec(idx + 1, acc + [v], e2)
        # each argument-enumeration solution is an independent call;
        # yield at most one value per solution but keep enumerating
        for argvals, env_out in rec(0, [], env):
            produced = False
            for m, r in self.defs:
                if len(r.args) != len(argvals):
                    continue
                fenv = Env()
                ok = True
                for pat, v in zip(r.args, argvals):
                    fenv = _bind_pattern(pat, v, fenv)
                    if fenv is None:
                        ok = False
                        break
                if not ok:
                    continue
                clauses = [(r.value, r.body)] + [
                    (er.value, er.body) for er in r.else_rules]
                for val_t, body in clauses:
                    for fe in interp.eval_body(body, fenv, m):
                        if val_t is None:
                            yield True, env_out
                            produced = True
                            break
                        for v, _ in interp.eval_term(val_t, fe, m):
                            yield v, env_out
                            produced = True
                            break
                        break
                    if produced:
                        break
                if produced:
                    break


class _DataDoc:
    """Placeholder for bare `data` references (rarely used directly)."""

    def __init__(self, interp):
        self.interp = interp


def _override_path(base, ops, val_t, interp, env, mod):
    """Set a dotted path inside a deep-copied document (with-modifier)."""
    if not isinstance(base, dict):
        base = {}
    cur = base
    for j, op in enumerate(ops):
        if op[0] != "dot":
            raise RegoEvalError("with: only dotted paths supported")
        k = op[1]
        if j == len(ops) - 1:
            for v, _ in interp.eval_term(val_t, env, mod):
                cur[k] = v
                break
        else:
            nxt = cur.get(k)
            if not isinstance(nxt, dict):
                nxt = {}
                cur[k] = nxt
            cur = nxt
    return base


def _bind_pattern(pat, value, env):
    """Bind pattern term to concrete value; None on mismatch."""
    if env is None:
        return None
    kind = pat[0]
    if kind == "var":
        name = pat[1]
        if name == "_":
            return env
        bound = env.get(name)
        if bound is UNDEF:
            return env.bind(name, value)
        return env if B.rego_eq(bound, value) else None
    if kind == "array":
        if not isinstance(value, list) or len(value) != len(pat[1]):
            return None
        for sub, v in zip(pat[1], value):
            env = _bind_pattern(sub, v, env)
            if env is None:
                return None
        return env
    if kind == "object":
        if not isinstance(value, dict):
            return None
        for kt, vt in pat[1]:
            if kt[0] != "str":
                return None
            if kt[1] not in value:
                return None
            env = _bind_pattern(vt, value[kt[1]], env)
            if env is None:
                return None
        return env
    if kind in ("num", "str", "bool"):
        return env if B.rego_eq(pat[1], value) else None
    if kind == "null":
        return env if value is None else None
    return None


def _is_ground(t, env):
    """True when the term contains no unbound variables (conservative:
    refs with variable indices count as ground — they enumerate)."""
    kind = t[0]
    if kind == "var":
        return t[1] in ("input", "data") or env.get(t[1]) is not UNDEF \
            or t[1] == "_" and False
    if kind == "array":
        return all(_is_ground(x, env) for x in t[1])
    if kind == "object":
        return all(_is_ground(k, env) and _is_ground(v, env)
                   for k, v in t[1])
    return True


def _ref_to_name(t):
    if t[0] == "var":
        return t[1]
    parts = _ref_parts(t)
    return ".".join(parts) if parts else "?"


def _ref_parts(t):
    if t[0] == "var":
        return [t[1]]
    if t[0] != "ref":
        return None
    base = _ref_parts(t[1])
    if base is None:
        return None
    for op in t[2]:
        if op[0] != "dot":
            return None
        base.append(op[1])
    return base
