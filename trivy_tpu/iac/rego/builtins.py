"""Rego builtin functions (subset used by trivy checks / custom checks).

Mirrors the OPA builtins the reference's bundle relies on (string ops,
aggregates, regex, object/array helpers, type checks, json codecs).
Functions raise or return UNDEF on type mismatch; the evaluator converts
exceptions to undefined (OPA's silent-failure semantics).
"""

from __future__ import annotations

import json
import re


class _Undef:
    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        return False


UNDEF = _Undef()


class Halt(Exception):
    pass


class RSet:
    """Rego set: equality-based membership over arbitrary JSON values."""
    __slots__ = ("items",)

    def __init__(self, items=None):
        self.items = []
        for it in items or []:
            self.add(it)

    def add(self, v):
        if not self.has(v):
            self.items.append(v)

    def has(self, v):
        return any(rego_eq(v, x) for x in self.items)

    def __iter__(self):
        return iter(self.items)

    def __len__(self):
        return len(self.items)

    def __repr__(self):
        return "{" + ", ".join(repr(x) for x in self.items) + "}"

    def __eq__(self, other):
        if not isinstance(other, RSet):
            return NotImplemented
        return len(self) == len(other) and all(other.has(x) for x in self)

    def __hash__(self):
        return len(self.items)

    def to_list(self):
        return sorted(self.items, key=_sort_key)


def _sort_key(v):
    # total order across types for deterministic iteration
    if v is None:
        return (0, "")
    if isinstance(v, bool):
        return (1, str(v))
    if isinstance(v, (int, float)):
        return (2, v)
    if isinstance(v, str):
        return (3, v)
    return (4, json.dumps(unfreeze(v), sort_keys=True, default=str))


def unfreeze(v):
    if isinstance(v, RSet):
        return [unfreeze(x) for x in v.to_list()]
    if isinstance(v, list):
        return [unfreeze(x) for x in v]
    if isinstance(v, dict):
        return {k: unfreeze(x) for k, x in v.items()}
    return v


def rego_eq(a, b):
    if isinstance(a, RSet) or isinstance(b, RSet):
        if not (isinstance(a, RSet) and isinstance(b, RSet)):
            return False
        return a == b
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, (int, float)) and isinstance(b, (int, float)):
        return a == b
    if type(a) is not type(b):
        return False
    if isinstance(a, list):
        return len(a) == len(b) and all(
            rego_eq(x, y) for x, y in zip(a, b))
    if isinstance(a, dict):
        return a.keys() == b.keys() and all(
            rego_eq(a[k], b[k]) for k in a)
    return a == b


def to_key(v):
    """Object keys in our model: strings/numbers/bools kept as-is;
    compound keys JSON-encoded."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return json.dumps(unfreeze(v), sort_keys=True)


def index_into(v, key):
    if v is UNDEF:
        return UNDEF
    if isinstance(v, dict):
        if key in v:
            return v[key]
        # numeric string keys from yaml etc.
        return UNDEF
    if isinstance(v, list):
        if isinstance(key, bool):
            return UNDEF
        if isinstance(key, (int, float)) and not isinstance(key, bool):
            i = int(key)
            if 0 <= i < len(v):
                return v[i]
        return UNDEF
    if isinstance(v, RSet):
        return key if v.has(key) else UNDEF
    return UNDEF


def iter_collection(v):
    """Yield (key, value) pairs for enumeration."""
    if isinstance(v, list):
        for i, x in enumerate(v):
            yield i, x
    elif isinstance(v, dict):
        for k, x in v.items():
            yield k, x
    elif isinstance(v, RSet):
        for x in v.to_list():
            yield x, x


def member(x, coll):
    if isinstance(coll, list):
        return any(rego_eq(x, y) for y in coll)
    if isinstance(coll, RSet):
        return coll.has(x)
    if isinstance(coll, dict):
        return any(rego_eq(x, y) for y in coll.values())
    if isinstance(coll, str) and isinstance(x, str):
        return x in coll
    return False


def compare(op, a, b):
    if op == "==":
        return rego_eq(a, b)
    if op == "!=":
        return not rego_eq(a, b)
    try:
        if op == "<":
            return _cmp_lt(a, b)
        if op == "<=":
            return rego_eq(a, b) or _cmp_lt(a, b)
        if op == ">":
            return _cmp_lt(b, a)
        if op == ">=":
            return rego_eq(a, b) or _cmp_lt(b, a)
    except TypeError:
        return UNDEF
    return UNDEF


def _cmp_lt(a, b):
    if isinstance(a, (int, float)) and isinstance(b, (int, float)) and \
            not isinstance(a, bool) and not isinstance(b, bool):
        return a < b
    if isinstance(a, str) and isinstance(b, str):
        return a < b
    return _sort_key(a) < _sort_key(b)


def arith(op, a, b):
    if isinstance(a, RSet) and isinstance(b, RSet):
        if op == "-":
            return RSet([x for x in a if not b.has(x)])
        return UNDEF
    if not isinstance(a, (int, float)) or not isinstance(b, (int, float)):
        return UNDEF
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        if b == 0:
            return UNDEF
        r = a / b
        return int(r) if isinstance(a, int) and isinstance(b, int) and \
            a % b == 0 else r
    if op == "%":
        if b == 0:
            return UNDEF
        return a % b
    return UNDEF


def walk_paths(v, path=None):
    path = path or []
    yield list(path), v
    if isinstance(v, dict):
        for k, x in v.items():
            yield from walk_paths(x, path + [k])
    elif isinstance(v, list):
        for i, x in enumerate(v):
            yield from walk_paths(x, path + [i])
    elif isinstance(v, RSet):
        for x in v.to_list():
            yield from walk_paths(x, path + [x])


# ---- builtin function table ------------------------------------------

def _count(x):
    if isinstance(x, (list, dict, RSet)):
        return len(x)
    if isinstance(x, str):
        return len(x)
    raise TypeError


def _sum(x):
    vals = list(x) if not isinstance(x, dict) else list(x.values())
    return sum(vals)


def _sprintf(fmt, args):
    if not isinstance(args, list):
        args = [args]
    out = []
    i = 0
    ai = 0
    while i < len(fmt):
        c = fmt[i]
        if c != "%":
            out.append(c)
            i += 1
            continue
        # parse verb (with optional width/precision flags)
        j = i + 1
        while j < len(fmt) and fmt[j] in "+-# 0123456789.":
            j += 1
        if j >= len(fmt):
            out.append(c)
            break
        verb = fmt[j]
        if verb == "%":
            out.append("%")
            i = j + 1
            continue
        a = args[ai] if ai < len(args) else ""
        ai += 1
        if verb in ("v", "s"):
            out.append(_gostr(a))
        elif verb == "d":
            out.append(str(int(a)))
        elif verb in ("f", "g", "e"):
            spec = fmt[i:j + 1].replace("%", "")
            try:
                out.append(("%" + spec) % float(a))
            except Exception:
                out.append(str(float(a)))
        elif verb == "q":
            out.append(json.dumps(str(a)))
        elif verb == "t":
            out.append("true" if a else "false")
        elif verb == "x":
            out.append(format(int(a), "x"))
        else:
            out.append(_gostr(a))
        i = j + 1
    return "".join(out)


def _gostr(a):
    if a is None:
        return "null"
    if isinstance(a, bool):
        return "true" if a else "false"
    if isinstance(a, float) and a.is_integer():
        return str(int(a))
    if isinstance(a, (dict, list, RSet)):
        return json.dumps(unfreeze(a), separators=(", ", ": "))
    return str(a)


def _format_int(x, base):
    return {2: "{:b}", 8: "{:o}", 10: "{:d}", 16: "{:x}"}[
        int(base)].format(int(x))


def _concat(sep, coll):
    items = coll.to_list() if isinstance(coll, RSet) else list(coll)
    return sep.join(str(x) for x in items)


def _object_get(obj, key, default):
    if isinstance(key, list):
        cur = obj
        for k in key:
            got = index_into(cur, k)
            if got is UNDEF:
                return default
            cur = got
        return cur
    got = index_into(obj, key)
    return default if got is UNDEF else got


def _union(x):
    out = RSet()
    for s in x:
        for v in s:
            out.add(v)
    return out


def _intersection(x):
    sets = list(x)
    if not sets:
        return RSet()
    out = RSet([v for v in sets[0]
                if all(s.has(v) for s in sets[1:])])
    return out


def _to_number(x):
    if isinstance(x, bool):
        return 1 if x else 0
    if isinstance(x, (int, float)):
        return x
    if x is None:
        return 0
    s = str(x).strip()
    v = float(s)
    return int(v) if v.is_integer() and "." not in s and \
        "e" not in s.lower() else v


def _type_name(x):
    if x is None:
        return "null"
    if isinstance(x, bool):
        return "boolean"
    if isinstance(x, (int, float)):
        return "number"
    if isinstance(x, str):
        return "string"
    if isinstance(x, list):
        return "array"
    if isinstance(x, dict):
        return "object"
    if isinstance(x, RSet):
        return "set"
    return "unknown"


def _regex_split(pat, s):
    return re.split(pat, s)


def _glob_match(pattern, delimiters, match):
    # translate glob to regex; ** crosses delimiters, * does not.
    # OPA semantics: null/unspecified delimiters default to ["."];
    # an EMPTY array means no delimiters (so * crosses everything).
    if isinstance(delimiters, RSet):
        delimiters = delimiters.to_list()
    if delimiters is None:
        delims = ["."]
    elif isinstance(delimiters, list):
        delims = [str(d) for d in delimiters]
    else:
        delims = ["."]
    d = "".join(re.escape(x) for x in delims)
    star = f"[^{d}]*" if d else ".*"
    qmark = f"[^{d}]" if d else "."
    rx = ""
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                rx += ".*"
                i += 2
                continue
            rx += star
        elif c == "?":
            rx += qmark
        elif c in ".^$+{}[]()|\\":
            rx += "\\" + c
        else:
            rx += c
        i += 1
    return re.fullmatch(rx, match) is not None


def _startswith(s, p):
    return isinstance(s, str) and s.startswith(p)


def _endswith(s, p):
    return isinstance(s, str) and s.endswith(p)


def _substring(s, start, length):
    start = int(start)
    if start < 0:
        raise TypeError
    if length < 0:
        return s[start:]
    return s[start:start + int(length)]


def _array_slice(arr, lo, hi):
    lo = max(0, int(lo))
    hi = min(len(arr), int(hi))
    return arr[lo:hi] if lo <= hi else []


def _json_unmarshal(s):
    return json.loads(s)


def _yaml_unmarshal(s):
    import yaml
    return yaml.safe_load(s)


def _base64_decode(s):
    import base64
    return base64.b64decode(s).decode("utf-8", "replace")


def _base64_encode(s):
    import base64
    return base64.b64encode(s.encode()).decode()


def _set_diff(a, b):
    return RSet([x for x in a if not b.has(x)])


def _numbers_range(a, b):
    a, b = int(a), int(b)
    return list(range(a, b + 1)) if a <= b else list(range(a, b - 1, -1))


def _cast_set(x):
    if isinstance(x, RSet):
        return x
    return RSet(list(x))


def _cast_array(x):
    if isinstance(x, RSet):
        return x.to_list()
    return list(x)


def _semver_compare(a, b):
    def parse(v):
        core = re.split(r"[-+]", v, 1)[0]
        return [int(p) for p in core.split(".")]
    pa, pb = parse(a), parse(b)
    return -1 if pa < pb else (1 if pa > pb else 0)


BUILTINS = {
    "count": _count,
    "sum": _sum,
    "product": lambda x: __import__("math").prod(
        list(x.values()) if isinstance(x, dict) else list(x)),
    "max": lambda x: max(x.to_list() if isinstance(x, RSet) else x),
    "min": lambda x: min(x.to_list() if isinstance(x, RSet) else x),
    "sort": lambda x: sorted(
        x.to_list() if isinstance(x, RSet) else x, key=_sort_key),
    "abs": abs,
    "ceil": lambda x: int(__import__("math").ceil(x)),
    "floor": lambda x: int(__import__("math").floor(x)),
    "round": lambda x: int(round(x)),
    "to_number": _to_number,
    "numbers.range": _numbers_range,

    "concat": _concat,
    "contains": lambda s, sub: isinstance(s, str) and sub in s,
    "startswith": _startswith,
    "endswith": _endswith,
    "format_int": _format_int,
    "indexof": lambda s, sub: s.find(sub),
    "lower": lambda s: s.lower(),
    "upper": lambda s: s.upper(),
    "replace": lambda s, old, new: s.replace(old, new),
    "split": lambda s, d: s.split(d),
    "sprintf": _sprintf,
    "substring": _substring,
    "trim": lambda s, cut: s.strip(cut),
    "trim_left": lambda s, cut: s.lstrip(cut),
    "trim_right": lambda s, cut: s.rstrip(cut),
    "trim_prefix": lambda s, p: s[len(p):] if s.startswith(p) else s,
    "trim_suffix": lambda s, p: s[:-len(p)] if p and s.endswith(p) else s,
    "trim_space": lambda s: s.strip(),
    "strings.reverse": lambda s: s[::-1],
    "strings.replace_n": lambda pats, s: _replace_n(pats, s),
    "strings.any_prefix_match": lambda s, ps: _any_affix(s, ps, True),
    "strings.any_suffix_match": lambda s, ps: _any_affix(s, ps, False),

    "re_match": lambda pat, s: re.search(pat, s) is not None,
    "regex.match": lambda pat, s: re.search(pat, s) is not None,
    "regex.is_valid": lambda pat: _re_valid(pat),
    "regex.split": _regex_split,
    "regex.replace": lambda s, pat, new: re.sub(pat, new, s),
    "regex.find_n": lambda pat, s, n: (
        re.findall(pat, s)[:None if n < 0 else int(n)]),
    "glob.match": _glob_match,

    "array.concat": lambda a, b: list(a) + list(b),
    "array.slice": _array_slice,
    "array.reverse": lambda a: list(reversed(a)),

    "object.get": _object_get,
    "object.keys": lambda o: RSet(list(o.keys())),
    "object.remove": lambda o, ks: {
        k: v for k, v in o.items()
        if not member(k, ks)},
    "object.filter": lambda o, ks: {
        k: v for k, v in o.items() if member(k, ks)},
    "object.union": lambda a, b: {**a, **b},
    "object.union_n": lambda arr: {
        k: v for o in arr for k, v in o.items()},

    "union": _union,
    "intersection": _intersection,
    "set_diff": _set_diff,
    "cast_set": _cast_set,
    "cast_array": _cast_array,

    "is_string": lambda x: isinstance(x, str),
    "is_number": lambda x: isinstance(x, (int, float)) and
    not isinstance(x, bool),
    "is_boolean": lambda x: isinstance(x, bool),
    "is_array": lambda x: isinstance(x, list),
    "is_object": lambda x: isinstance(x, dict),
    "is_set": lambda x: isinstance(x, RSet),
    "is_null": lambda x: x is None,
    "type_name": _type_name,

    "json.unmarshal": _json_unmarshal,
    "json.marshal": lambda x: json.dumps(
        unfreeze(x), separators=(",", ":")),
    "json.is_valid": lambda s: _json_valid(s),
    "yaml.unmarshal": _yaml_unmarshal,
    "yaml.marshal": lambda x: __import__("yaml").safe_dump(unfreeze(x)),
    "base64.decode": _base64_decode,
    "base64.encode": _base64_encode,

    "semver.compare": _semver_compare,
    "semver.is_valid": lambda v: bool(re.fullmatch(
        r"\d+\.\d+\.\d+(?:-[0-9A-Za-z.-]+)?(?:\+[0-9A-Za-z.-]+)?",
        str(v))),

    "print": lambda *a: True,
    "trace": lambda *a: True,
    "object.subset": lambda sup, sub: _subset(sup, sub),
}


def _replace_n(pats, s):
    for old, new in pats.items():
        s = s.replace(old, new)
    return s


def _any_affix(s, ps, prefix):
    items = ps.to_list() if isinstance(ps, RSet) else (
        ps if isinstance(ps, list) else [ps])
    if prefix:
        return any(s.startswith(p) for p in items)
    return any(s.endswith(p) for p in items)


def _re_valid(pat):
    try:
        re.compile(pat)
        return True
    except re.error:
        return False


def _json_valid(s):
    try:
        json.loads(s)
        return True
    except Exception:
        return False


def _subset(sup, sub):
    if isinstance(sup, dict) and isinstance(sub, dict):
        return all(k in sup and rego_eq(sup[k], v)
                   for k, v in sub.items())
    if isinstance(sup, RSet) and isinstance(sub, RSet):
        return all(sup.has(x) for x in sub)
    if isinstance(sup, list) and isinstance(sub, list):
        return all(member(x, sup) for x in sub)
    return False
