"""Helm chart scanner (reference pkg/iac/scanners/helm).

Renders a chart the way the reference drives helm's engine
(parser/parser.go RenderedChartFiles: load chart → render release with
merged values → split manifests), then runs the kubernetes checks over
each rendered manifest. The template engine is our Go text/template
subset (report/gotemplate.py) extended with the sprig/helm functions
charts rely on (include, tpl, toYaml, nindent, required, ...).

Charts are detected by a `Chart.yaml` (pkg/iac/detection helm type);
`.tgz` archives are unpacked in memory (parser_tar.go).
"""

from __future__ import annotations

import gzip
import hashlib
import io
import json
import re
import tarfile

import yaml

from ..report.gotemplate import Template, TemplateError, _go_str


class HelmRenderError(Exception):
    pass


# ---- value helpers ----------------------------------------------------

def _deep_merge(base: dict, over: dict) -> dict:
    """helm's coalesce: `over` wins, dicts merge recursively."""
    out = dict(base)
    for k, v in (over or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def _set_path(values: dict, dotted: str, value) -> None:
    """--set a.b.c=v (ValueOptions.MergeValues, vals.go)."""
    parts = dotted.split(".")
    cur = values
    for p in parts[:-1]:
        nxt = cur.get(p)
        if not isinstance(nxt, dict):
            nxt = {}
            cur[p] = nxt
        cur = nxt
    cur[parts[-1]] = value


def _parse_set_value(raw: str):
    if raw == "true":
        return True
    if raw == "false":
        return False
    if raw == "null":
        return None
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        pass
    return raw


# ---- chart model ------------------------------------------------------

class Chart:
    def __init__(self, metadata: dict, values: dict,
                 templates: dict[str, str], helpers: dict[str, str],
                 subcharts: list["Chart"], root: str = ""):
        self.metadata = metadata or {}
        self.values = values or {}
        self.templates = templates      # rel path (templates/x.yaml) → src
        self.helpers = helpers          # rel path (_*.tpl) → src
        self.subcharts = subcharts
        self.root = root

    @property
    def name(self) -> str:
        return str(self.metadata.get("name", "chart"))


def load_chart_dir(files: dict[str, bytes], prefix: str = "") -> Chart:
    """files: rel-path → bytes for one chart tree (paths relative to the
    chart root, e.g. 'Chart.yaml', 'templates/deploy.yaml',
    'charts/sub/Chart.yaml')."""
    meta = {}
    values: dict = {}
    templates: dict[str, str] = {}
    helpers: dict[str, str] = {}
    sub_files: dict[str, dict[str, bytes]] = {}
    for path, content in files.items():
        if path.startswith("charts/"):
            rest = path[len("charts/"):]
            if "/" in rest:
                subname, subpath = rest.split("/", 1)
                sub_files.setdefault(subname, {})[subpath] = content
            elif rest.endswith(".tgz"):
                try:
                    subc = load_chart_tgz(content)
                    sub_files.setdefault(
                        "\x00tgz:" + rest, {})["\x00chart"] = subc
                except Exception:
                    pass
            continue
        if path == "Chart.yaml":
            try:
                meta = yaml.safe_load(content) or {}
            except yaml.YAMLError:
                meta = {}
        elif path == "values.yaml":
            try:
                values = yaml.safe_load(content) or {}
            except yaml.YAMLError:
                values = {}
        elif path.startswith("templates/"):
            name = path.rsplit("/", 1)[-1]
            text = content.decode("utf-8", errors="replace")
            if name.startswith("_"):
                helpers[path] = text
            elif name == "NOTES.txt":
                continue
            elif path.startswith("templates/tests/"):
                continue
            elif name.endswith((".yaml", ".yml", ".tpl", ".json")):
                templates[path] = text
    subcharts = []
    for subname, sf in sub_files.items():
        if "\x00chart" in sf:
            subcharts.append(sf["\x00chart"])
        elif "Chart.yaml" in sf:
            subcharts.append(load_chart_dir(sf))
    return Chart(meta, values, templates, helpers, subcharts,
                 root=prefix)


def load_chart_tgz(data: bytes) -> Chart:
    """Helm package archives: one top-level dir per chart
    (parser_tar.go)."""
    buf = io.BytesIO(data)
    try:
        raw = gzip.decompress(data)
    except OSError:
        raw = data
    files: dict[str, bytes] = {}
    with tarfile.open(fileobj=io.BytesIO(raw), mode="r") as tf:
        for m in tf.getmembers():
            if not m.isfile():
                continue
            parts = m.name.split("/", 1)
            if len(parts) != 2:
                continue
            f = tf.extractfile(m)
            if f is not None:
                files[parts[1]] = f.read()
    return load_chart_dir(files)


# ---- helm/sprig function table ---------------------------------------

def _to_yaml(v) -> str:
    if v is None:
        return ""
    return yaml.safe_dump(v, default_flow_style=False,
                          sort_keys=False).rstrip("\n")


def _indent(n, s):
    pad = " " * int(n)
    return "\n".join(pad + line for line in _go_str(s).split("\n"))


def _helm_funcs(engine_ref: dict) -> dict:
    """Functions closing over the active Template (include/tpl need to
    call back into the engine)."""

    def include(name, data):
        tmpl = engine_ref.get("tmpl")
        return tmpl.execute_template(name, data)

    def tpl(text, ctx):
        tmpl = engine_ref.get("tmpl")
        sub = Template(_go_str(text), funcs=tmpl.funcs)
        sub.defines = tmpl.defines
        return sub.render(ctx)

    def required(msg, v):
        if v is None or v == "":
            raise HelmRenderError(_go_str(msg))
        return v

    def fail(msg):
        raise HelmRenderError(_go_str(msg))

    def semver_compare(constraint, version):
        # minimal: supports >=, >, <=, <, =, ^ and plain versions
        def parse(v):
            m = re.match(r"v?(\d+)(?:\.(\d+))?(?:\.(\d+))?", str(v))
            if not m:
                return (0, 0, 0)
            return tuple(int(g or 0) for g in m.groups())
        c = str(constraint).strip()
        m = re.match(r"(>=|<=|>|<|\^|=)?\s*(.+)", c)
        op, rhs = (m.group(1) or "="), m.group(2)
        a, b = parse(version), parse(rhs)
        return {">=": a >= b, "<=": a <= b, ">": a > b, "<": a < b,
                "=": a == b,
                "^": a >= b and a[0] == b[0]}.get(op, a == b)

    def merge(dst, *srcs):
        out = dict(dst or {})
        for s in srcs:
            out = _deep_merge(s or {}, out)   # dst wins in sprig merge
        return out

    def merge_overwrite(dst, *srcs):
        out = dict(dst or {})
        for s in srcs:
            out = _deep_merge(out, s or {})
        return out

    def _get(d, key):
        return (d or {}).get(key, "")

    def _set(d, key, val):
        d[key] = val
        return d

    def _unset(d, key):
        d.pop(key, None)
        return d

    def kind_is(kind, v):
        return {
            "map": isinstance(v, dict),
            "slice": isinstance(v, list),
            "string": isinstance(v, str),
            "bool": isinstance(v, bool),
            "int": isinstance(v, int) and not isinstance(v, bool),
            "int64": isinstance(v, int) and not isinstance(v, bool),
            "float64": isinstance(v, float),
            "invalid": v is None,
        }.get(kind, False)

    import base64
    return {
        "include": include,
        "tpl": tpl,
        "required": required,
        "fail": fail,
        "lookup": lambda *a: {},
        "toYaml": _to_yaml,
        "fromYaml": lambda s: yaml.safe_load(s) or {},
        "fromJson": lambda s: json.loads(s) if s else {},
        "toToml": _to_yaml,   # close enough for rendering side effects
        "indent": _indent,
        "nindent": lambda n, s: "\n" + _indent(n, s),
        "quote": lambda *a: " ".join(
            '"%s"' % _go_str(x).replace('"', '\\"') for x in a),
        "squote": lambda *a: " ".join(
            "'%s'" % _go_str(x) for x in a),
        "b64enc": lambda s: base64.b64encode(
            _go_str(s).encode()).decode(),
        "b64dec": lambda s: base64.b64decode(
            _go_str(s)).decode("utf-8", "replace"),
        "trimSuffix": lambda suf, s: _go_str(s)[:-len(suf)]
        if suf and _go_str(s).endswith(suf) else _go_str(s),
        "trimPrefix": lambda pre, s: _go_str(s)[len(pre):]
        if pre and _go_str(s).startswith(pre) else _go_str(s),
        "repeat": lambda n, s: _go_str(s) * int(n),
        "add1": lambda v: int(v) + 1,
        "sub1": lambda v: int(v) - 1,
        "mod": lambda a, b: int(a) % int(b),
        "div": lambda a, b: int(a) // int(b),
        "max": lambda *a: max(int(x) for x in a),
        "min": lambda *a: min(int(x) for x in a),
        "ceil": lambda v: -(-int(float(v)) // 1),
        "floor": lambda v: int(float(v)),
        "until": lambda n: list(range(int(n))),
        "untilStep": lambda a, b, s: list(range(int(a), int(b), int(s))),
        "get": _get,
        "set": _set,
        "unset": _unset,
        "hasKey": lambda d, k: k in (d or {}),
        "keys": lambda *ds: [k for d in ds for k in (d or {})],
        "pluck": lambda k, *ds: [d[k] for d in ds if k in (d or {})],
        "merge": merge,
        "mergeOverwrite": merge_overwrite,
        "deepCopy": lambda v: json.loads(json.dumps(v)),
        "dig": lambda *a: _dig(list(a)),
        "ternary": lambda t, f, c: t if c else f,
        "kindIs": kind_is,
        "kindOf": lambda v: (
            "map" if isinstance(v, dict) else
            "slice" if isinstance(v, list) else
            "bool" if isinstance(v, bool) else
            "int" if isinstance(v, int) else
            "float64" if isinstance(v, float) else
            "string" if isinstance(v, str) else "invalid"),
        "typeOf": lambda v: type(v).__name__,
        "typeIs": lambda t, v: type(v).__name__ == t,
        "semverCompare": semver_compare,
        "rest": lambda lst: (lst or [])[1:],
        "initial": lambda lst: (lst or [])[:-1],
        "append": lambda lst, v: list(lst or []) + [v],
        "prepend": lambda lst, v: [v] + list(lst or []),
        "concat": lambda *ls: [x for l in ls for x in (l or [])],
        "has": lambda v, lst: v in (lst or []),
        "without": lambda lst, *vs: [x for x in (lst or [])
                                     if x not in vs],
        "compact": lambda lst: [x for x in (lst or []) if x],
        "randAlphaNum": lambda n: hashlib.sha256(
            b"seed").hexdigest()[:int(n)],
        "randAlpha": lambda n: ("a" * int(n)),
        "uuidv4": lambda: "00000000-0000-4000-8000-000000000000",
        "snakecase": lambda s: re.sub(
            r"(?<=[a-z0-9])([A-Z])", r"_\1", _go_str(s)).lower(),
        "camelcase": lambda s: "".join(
            w.capitalize() for w in re.split(r"[_-]", _go_str(s))),
        "kebabcase": lambda s: re.sub(
            r"(?<=[a-z0-9])([A-Z])", r"-\1", _go_str(s)).lower(),
        "untitle": lambda s: _go_str(s)[:1].lower() + _go_str(s)[1:],
        "print": lambda *a: "".join(_go_str(x) for x in a),
        "println": lambda *a: "".join(_go_str(x) for x in a) + "\n",
    }


def _dig(args):
    # dig "a" "b" default dict
    *path, default, d = args
    cur = d
    for p in path:
        if not isinstance(cur, dict) or p not in cur:
            return default
        cur = cur[p]
    return cur


# ---- rendering --------------------------------------------------------

DEFAULT_KUBE_VERSION = "v1.30.0"


def render_chart(chart: Chart, values_override: dict | None = None,
                 release_name: str | None = None,
                 _parent_values: dict | None = None,
                 _path_prefix: str = "") -> dict[str, str]:
    """→ {'<chart>/templates/x.yaml': rendered_text}.

    Mirrors helm's engine semantics for the constructs the checks care
    about; unrenderable templates are skipped (the reference logs and
    continues on individual template errors)."""
    values = dict(chart.values)
    if _parent_values:
        values = _deep_merge(values, _parent_values)
    if values_override:
        values = _deep_merge(values, values_override)
    name = release_name or chart.name
    ctx_base = {
        "Values": values,
        "Chart": _cap_meta(chart.metadata),
        "Release": {
            "Name": name, "Namespace": "default", "Service": "Helm",
            "IsInstall": True, "IsUpgrade": False, "Revision": 1,
        },
        "Capabilities": {
            "KubeVersion": {
                "Version": DEFAULT_KUBE_VERSION,
                "Major": "1", "Minor": "30",
                "GitVersion": DEFAULT_KUBE_VERSION,
            },
            "APIVersions": ["v1", "apps/v1", "batch/v1",
                            "networking.k8s.io/v1"],
            "HelmVersion": {"Version": "v3.14.0"},
        },
    }
    out: dict[str, str] = {}
    prefix = _path_prefix or chart.name
    for tpath, src in sorted(chart.templates.items()):
        engine_ref: dict = {}
        funcs = _helm_funcs(engine_ref)
        try:
            tmpl = Template(src, funcs=funcs)
            engine_ref["tmpl"] = tmpl
            for hsrc in chart.helpers.values():
                tmpl.add_associated(hsrc)
            ctx = dict(ctx_base)
            ctx["Template"] = {"Name": f"{prefix}/{tpath}",
                               "BasePath": f"{prefix}/templates"}
            rendered = tmpl.render(ctx)
        except Exception:
            # individual template failures skip the file, like the
            # reference which surfaces render errors per chart file
            continue
        if rendered.strip():
            out[f"{prefix}/{tpath}"] = rendered
    # subcharts: values scoped under the subchart name + global
    for sub in chart.subcharts:
        sub_vals = values.get(sub.name) or {}
        if isinstance(values.get("global"), dict):
            sub_vals = _deep_merge(sub_vals,
                                   {"global": values["global"]})
        if _enabled(values, sub.name):
            out.update(render_chart(
                sub, values_override=sub_vals, release_name=name,
                _path_prefix=f"{prefix}/charts/{sub.name}"))
    return out


def _enabled(values: dict, sub_name: str) -> bool:
    v = values.get(sub_name)
    if isinstance(v, dict) and v.get("enabled") is False:
        return False
    return True


def _cap_meta(meta: dict) -> dict:
    out = {}
    for k, v in (meta or {}).items():
        out[k[:1].upper() + k[1:]] = v
    out.setdefault("Name", "chart")
    out.setdefault("Version", "0.1.0")
    out.setdefault("AppVersion", "")
    return out


# ---- scanning ---------------------------------------------------------

def scan_chart_files(files: dict[str, bytes],
                     values_override: dict | None = None):
    """files: chart-root-relative path → bytes.
    → [T.Misconfiguration] records (one per rendered file with
    findings), matching the terraform post-analyzer output shape."""
    chart = load_chart_dir(files)
    return scan_rendered_chart(chart, values_override=values_override)


# process-wide value overrides (reference --set / --values,
# pkg/fanal/analyzer/config ScannerOption HelmValueOverrides): applied
# on top of every scanned chart's values
_OVERRIDES: dict = {"sets": [], "files": []}


def set_helm_overrides(sets=None, values_files=None) -> None:
    """Loads --helm-values files EAGERLY: a typo'd or malformed file
    must fail the run, not silently render default values."""
    docs = []
    for vf in values_files or []:
        try:
            with open(vf) as f:
                docs.append(yaml.safe_load(f) or {})
        except (OSError, yaml.YAMLError) as e:
            raise HelmRenderError(f"--helm-values {vf}: {e}") from None
    _OVERRIDES["sets"] = list(sets or [])
    _OVERRIDES["files"] = docs


def _apply_overrides(base: dict | None) -> dict | None:
    if not _OVERRIDES["sets"] and not _OVERRIDES["files"]:
        return base
    merged = dict(base or {})
    for doc in _OVERRIDES["files"]:
        merged = _deep_merge(merged, doc)
    for raw in _OVERRIDES["sets"]:
        key, _, val = raw.partition("=")
        if key:
            _set_path(merged, key, _parse_set_value(val))
    return merged


def scan_rendered_chart(chart: Chart,
                        values_override: dict | None = None,
                        prefix: str = ""):
    from .. import types as T
    from .kubernetes import scan_kubernetes
    rendered = render_chart(
        chart, values_override=_apply_overrides(values_override))
    records = []
    for rpath, text in rendered.items():
        try:
            docs = [d for d in yaml.safe_load_all(text) if d is not None]
        except yaml.YAMLError:
            continue
        if not any(isinstance(d, dict) and d.get("kind") for d in docs):
            continue
        failures, successes = scan_kubernetes(rpath, text.encode(),
                                              docs=None)
        if not failures and not successes:
            continue
        for f in failures:
            f.type = "helm"
        # report chart-root-relative paths, the way the reference's
        # helm scanner does (helm_testchart.json.golden targets are
        # "templates/deployment.yaml", not "<chartname>/templates/…")
        rel = rpath[len(chart.name) + 1:] \
            if rpath.startswith(chart.name + "/") else rpath
        records.append(T.Misconfiguration(
            file_type="helm", file_path=prefix + rel,
            successes=successes, failures=failures))
    return records


def find_charts(files) -> dict[str, list[str]]:
    """Group walked file paths by chart root (dirs holding Chart.yaml).
    Nested roots under charts/ belong to the parent chart."""
    roots = []
    for path in files:
        if path.endswith("Chart.yaml"):
            root = path[:-len("Chart.yaml")].rstrip("/")
            roots.append(root)
    # keep only top-most roots (subcharts folded into parents)
    tops = []
    for r in sorted(roots, key=len):
        if not any(r != t and r.startswith(t + "/") for t in tops):
            tops.append(r)
    out: dict[str, list[str]] = {t: [] for t in tops}
    for path in files:
        for t in sorted(tops, key=len, reverse=True):
            if t == "" or path == t or path.startswith(t + "/"):
                out[t].append(path)
                break
    return out
