"""Terraform plan (tfplan JSON) scanner.

Reference: pkg/iac/scanners/terraformplan/tfjson — `terraform show -json
plan.out` output is converted back into synthetic HCL (parser.go ToFS,
resource_block.go ToHCL) and run through the terraform scanner, so plan
scanning reuses every terraform check unchanged.

We mirror that: planned resource values (`resource_changes[].change.
after`, falling back to configuration expression constants) become a
`main.tf` that feeds iac.terraform.scan_terraform_files.
"""

from __future__ import annotations

import json

from .. import types as T


def _is_map_list(v) -> bool:
    return isinstance(v, list) and bool(v) and \
        all(isinstance(x, dict) for x in v)


def _render_primitive(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return "null"
    if isinstance(v, str):
        # always a quoted string (escapes handled by the HCL lexer):
        # heredocs break inside single-line map/list values and terminate
        # early when the content contains a bare delimiter line; plan
        # values are literal, so interpolation markers must be escaped
        # ($${ / %%{ round-trip through the lexer back to ${ / %{)
        v = v.replace("${", "$${").replace("%{", "%%{")
        return json.dumps(v, ensure_ascii=False)
    if isinstance(v, (int, float)):
        return json.dumps(v)
    if isinstance(v, dict):
        return _render_map(v)
    if isinstance(v, list):
        return "[" + ", ".join(_render_primitive(x) for x in v) + "]"
    return json.dumps(str(v))


def _render_map(m: dict) -> str:
    inner = ", ".join(f"{json.dumps(k)} = {_render_primitive(v)}"
                      for k, v in sorted(m.items()))
    return "{ " + inner + " }"


def _render_body(attrs: dict, indent: str) -> list[str]:
    lines = []
    for name, value in sorted(attrs.items()):
        if value is None:
            continue
        if _is_map_list(value):
            # nested blocks: one block per element (plan JSON encodes
            # repeated blocks as arrays of objects)
            for elem in value:
                lines.append(f"{indent}{name} {{")
                lines.extend(_render_body(elem, indent + "  "))
                lines.append(f"{indent}}}")
        elif isinstance(value, dict):
            lines.append(f"{indent}{name} = {_render_map(value)}")
        else:
            lines.append(f"{indent}{name} = {_render_primitive(value)}")
    return lines


def plan_to_hcl(plan: dict) -> str:
    """Synthesize main.tf from a terraform plan JSON document."""
    out = []
    changes = {c.get("address"): c
               for c in plan.get("resource_changes", [])}
    conf_res = _configuration_constants(plan)
    for res in _walk_resources(
            plan.get("planned_values", {}).get("root_module", {})):
        if res.get("mode") not in (None, "managed"):
            continue
        rtype = res.get("type", "")
        rname = res.get("name", "")
        addr = res.get("address", f"{rtype}.{rname}")
        attrs: dict = {}
        change = changes.get(addr)
        if change:
            after = change.get("change", {}).get("after")
            if isinstance(after, dict):
                attrs.update(after)
        # configuration constants fill attributes the plan omits
        for k, v in conf_res.get(addr, {}).items():
            attrs.setdefault(k, v)
        out.append(f'resource "{rtype}" "{rname}" {{')
        out.extend(_render_body(attrs, "  "))
        out.append("}")
        out.append("")
    return "\n".join(out)


def _walk_resources(module: dict):
    yield from module.get("resources", []) or []
    for child in module.get("child_modules", []) or []:
        yield from _walk_resources(child)


def _configuration_constants(plan: dict) -> dict[str, dict]:
    """address → {attr: constant_value} from configuration expressions
    (parser.go unpackConfigurationValue keeps constant_value entries)."""
    out: dict[str, dict] = {}

    def walk(module: dict, prefix: str):
        for res in module.get("resources", []) or []:
            addr = (prefix + "." if prefix else "") + \
                res.get("address", "")
            consts = {}
            for attr, expr in (res.get("expressions") or {}).items():
                if isinstance(expr, dict) and "constant_value" in expr:
                    consts[attr] = expr["constant_value"]
            if consts:
                out[addr] = consts
        for name, call in (module.get("module_calls") or {}).items():
            walk(call.get("module", {}),
                 (prefix + "." if prefix else "") + f"module.{name}")

    walk(plan.get("configuration", {}).get("root_module", {}), "")
    return out


def looks_like_plan(doc) -> bool:
    return isinstance(doc, dict) and "format_version" in doc and (
        "planned_values" in doc or "resource_changes" in doc)


def scan_plan_file(path: str, content: bytes) -> list[T.Misconfiguration]:
    """→ Misconfiguration records; findings point at the plan file with
    line ranges in the synthesized HCL."""
    from .terraform import scan_terraform_files
    try:
        plan = json.loads(content.decode("utf-8", errors="replace"))
    except json.JSONDecodeError:
        return []
    if not looks_like_plan(plan):
        return []
    hcl = plan_to_hcl(plan)
    if not hcl.strip():
        return []
    records = scan_terraform_files({"main.tf": hcl.encode()})
    for rec in records:
        rec.file_type = "terraformplan"
        rec.file_path = path
        for f in rec.failures:
            f.type = "terraformplan"
    return records
