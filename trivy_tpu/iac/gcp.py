"""Google Cloud IaC support: terraform adapter + check set.

Reference counterparts: pkg/iac/providers/google/** (typed state:
sql/storage/gke/compute/dns/kms/bigquery/iam) and
pkg/iac/adapters/terraform/google/** (resource-type mapping, e.g.
sql/adapt.go google_sql_database_instance flags/backup/ip-config,
compute/instances.go shielded-VM + metadata-key semantics,
gke/adapt.go cluster defaults).  The check bodies live in the external
trivy-checks bundle; they are re-authored here from the typed state the
adapters produce, with IDs/severities following the published AVD-GCP
series (avd.aquasec.com; AVD-GCP-0007's metadata is pinned by the
reference's pkg/report/sarif_test.go:556-560).

Adapter defaults mirror the reference exactly where they matter for
check semantics: shielded-VM flags default false without a
shielded_instance_config block and integrity-monitoring/vTPM default
true inside one (instances.go:18-59); GKE clusters default
enable_shielded_nodes=true, legacy_endpoints=true, logging/monitoring
to the kubernetes services (gke/adapt.go:50-100); SQL Server
contained-db-auth and cross-db-ownership-chaining default true
(sql/adapt.go:36-44)."""

from __future__ import annotations

import re

from .cloud import (Attr, CloudResource, Unknown, block_attr,
                    sub_blocks)
from .core import Check

GCP_CHECKS: list[Check] = []


def _gcp(id_, title, severity, service, description="", resolution=""):
    def deco(fn):
        GCP_CHECKS.append(Check(
            id=id_, avd_id=id_, title=title, severity=severity,
            description=description, resolution=resolution,
            provider="Google", service=service,
            namespace=f"builtin.google.{service}.{id_}", fn=fn))
        return fn
    return deco


def _of(resources, kind):
    return [r for r in resources if r.kind == kind]


def _known_false(v):
    return not isinstance(v, Unknown) and \
        (v is False or v == "false" or v == "False" or v == 0 or v is None)


def _known_true(v):
    return not isinstance(v, Unknown) and \
        (v is True or v == "true" or v == "True" or v == 1)


# ---------------------------------------------------------------------
# Adapter: TfModule -> CloudResource list (google_* resource types)
# ---------------------------------------------------------------------

_sub_blocks = sub_blocks
_block_attr = block_attr


def _adapt_sql(module, res, cr):
    cr.attrs["database_version"] = Attr(res.value("database_version", ""))
    cr.attrs["is_replica"] = Attr("master_instance_name" in res.attrs)
    backups, ipv4, require_ssl = False, True, False
    backups_rng = ssl_rng = ipv4_rng = None
    networks = []
    flags = {}
    flag_rngs = {}
    for settings in res.blocks("settings"):
        for fb in _sub_blocks(settings, "database_flags"):
            name, _ = _block_attr(module, fb, "name")
            value, vrng = _block_attr(module, fb, "value")
            if isinstance(name, str):
                flags[name] = value
                flag_rngs[name] = vrng
        for bb in _sub_blocks(settings, "backup_configuration"):
            backups, backups_rng = _block_attr(module, bb, "enabled", False)
        for ib in _sub_blocks(settings, "ip_configuration"):
            ipv4, ipv4_rng = _block_attr(module, ib, "ipv4_enabled", True)
            require_ssl, ssl_rng = _block_attr(module, ib, "require_ssl",
                                               False)
            for nb in _sub_blocks(ib, "authorized_networks"):
                cidr, crng = _block_attr(module, nb, "value")
                networks.append({"cidr": cidr, "rng": crng})
    cr.attrs["backups_enabled"] = Attr(backups, backups_rng or cr.rng)
    cr.attrs["ipv4_enabled"] = Attr(ipv4, ipv4_rng or cr.rng)
    cr.attrs["require_ssl"] = Attr(require_ssl, ssl_rng or cr.rng)
    cr.attrs["authorized_networks"] = Attr(networks)
    cr.attrs["flags"] = Attr(flags)
    cr.attrs["flag_rngs"] = Attr(flag_rngs)


def _adapt_gke_node_config(module, block, cr):
    """node_config block → image_type / service_account / workload
    metadata / legacy endpoints attrs on cr."""
    it, it_rng = _block_attr(module, block, "image_type", "")
    cr.attrs["node_image_type"] = Attr(it, it_rng)
    sa, _ = _block_attr(module, block, "service_account", "")
    cr.attrs["node_service_account"] = Attr(sa)
    md, _ = _block_attr(module, block, "metadata", None)
    if isinstance(md, dict) and "disable-legacy-endpoints" in md:
        v = md["disable-legacy-endpoints"]
        cr.attrs["legacy_endpoints"] = Attr(
            not (_known_true(v)), cr.attr_rng("node_image_type"))
    for wb in _sub_blocks(block, "workload_metadata_config"):
        for key in ("node_metadata", "mode"):
            v, vrng = _block_attr(module, wb, key)
            if isinstance(v, str):
                cr.attrs["node_metadata"] = Attr(v, vrng)


def _adapt_gke(module, res, cr):
    cr.attrs["shielded_nodes"] = Attr(
        res.value("enable_shielded_nodes", True),
        res.rng("enable_shielded_nodes"))
    cr.attrs["legacy_abac"] = Attr(res.value("enable_legacy_abac", False),
                                   res.rng("enable_legacy_abac"))
    cr.attrs["datapath_provider"] = Attr(
        res.value("datapath_provider", "DATAPATH_PROVIDER_UNSPECIFIED"))
    cr.attrs["logging_service"] = Attr(
        res.value("logging_service", "logging.googleapis.com/kubernetes"),
        res.rng("logging_service"))
    cr.attrs["monitoring_service"] = Attr(
        res.value("monitoring_service",
                  "monitoring.googleapis.com/kubernetes"),
        res.rng("monitoring_service"))
    labels = res.value("resource_labels")
    cr.attrs["resource_labels"] = Attr(
        labels if isinstance(labels, (dict, Unknown)) else {},
        res.rng("resource_labels"))
    cr.attrs["autopilot"] = Attr(res.value("enable_autopilot", False))
    cr.attrs["ip_aliasing"] = Attr(False)
    cr.attrs["master_networks"] = Attr(False)
    cr.attrs["network_policy"] = Attr(False)
    cr.attrs["private_nodes"] = Attr(False)
    cr.attrs["issue_client_cert"] = Attr(False)
    cr.attrs["master_username"] = Attr("")
    cr.attrs["legacy_endpoints"] = Attr(True)
    cr.attrs["node_service_account"] = Attr("")
    for b in res.blocks("ip_allocation_policy"):
        cr.attrs["ip_aliasing"] = Attr(True, (b.start, b.end))
    for b in res.blocks("master_authorized_networks_config"):
        cr.attrs["master_networks"] = Attr(True, (b.start, b.end))
    for b in res.blocks("network_policy"):
        v, rng = _block_attr(module, b, "enabled", False)
        cr.attrs["network_policy"] = Attr(v, rng)
    for b in res.blocks("private_cluster_config"):
        v, rng = _block_attr(module, b, "enable_private_nodes", False)
        cr.attrs["private_nodes"] = Attr(v, rng)
    for b in res.blocks("master_auth"):
        u, urng = _block_attr(module, b, "username", "")
        cr.attrs["master_username"] = Attr(u, urng)
        for cb in _sub_blocks(b, "client_certificate_config"):
            v, vrng = _block_attr(module, cb, "issue_client_certificate",
                                  False)
            cr.attrs["issue_client_cert"] = Attr(v, vrng)
    for b in res.blocks("node_config"):
        _adapt_gke_node_config(module, b, cr)


def _adapt_instance(module, res, cr):
    ifaces = []
    for b in res.blocks("network_interface"):
        has_public = bool(_sub_blocks(b, "access_config"))
        ifaces.append({"public_ip": has_public, "rng": (b.start, b.end)})
    cr.attrs["interfaces"] = Attr(ifaces)
    cr.attrs["can_ip_forward"] = Attr(res.value("can_ip_forward", False),
                                      res.rng("can_ip_forward"))
    # shielded VM: absent block -> all false; inside a block IM/vTPM
    # default true, secure boot false (reference instances.go:18-59)
    secure_boot = integrity = vtpm = False
    sh_rng = cr.rng
    for b in res.blocks("shielded_instance_config"):
        sh_rng = (b.start, b.end)
        integrity, _ = _block_attr(module, b, "enable_integrity_monitoring",
                                   True)
        vtpm, _ = _block_attr(module, b, "enable_vtpm", True)
        secure_boot, _ = _block_attr(module, b, "enable_secure_boot", False)
    cr.attrs["secure_boot"] = Attr(secure_boot, sh_rng)
    cr.attrs["integrity_monitoring"] = Attr(integrity, sh_rng)
    cr.attrs["vtpm"] = Attr(vtpm, sh_rng)
    md = res.value("metadata")
    md = md if isinstance(md, dict) else {}

    def _md_bool(key, absent):
        v = md.get(key)
        if isinstance(v, Unknown):
            return v          # unresolvable: checks must not fire
        return _known_true(v) if key in md else absent

    cr.attrs["oslogin"] = Attr(_md_bool("enable-oslogin", True),
                               res.rng("metadata"))
    cr.attrs["block_project_ssh_keys"] = Attr(
        _md_bool("block-project-ssh-keys", False), res.rng("metadata"))
    cr.attrs["serial_port"] = Attr(
        _md_bool("serial-port-enable", False), res.rng("metadata"))
    # service account: empty email or *-compute@developer... is default
    sa_default, sa_email, sa_rng = None, "", cr.rng
    for b in res.blocks("service_account"):
        sa_rng = (b.start, b.end)
        sa_email, _ = _block_attr(module, b, "email", "")
        if not isinstance(sa_email, str):
            sa_default = False      # reference-style block ref: not default
        else:
            sa_default = (sa_email == "" or sa_email.endswith(
                "-compute@developer.gserviceaccount.com"))
    cr.attrs["sa_is_default"] = Attr(sa_default, sa_rng)
    disks = []
    for btype in ("boot_disk", "attached_disk"):
        for b in res.blocks(btype):
            raw, _ = _block_attr(module, b, "disk_encryption_key_raw")
            kms, _ = _block_attr(module, b, "kms_key_self_link", "")
            disks.append({
                "raw_key": bool(raw) and not isinstance(raw, Unknown),
                "kms_key": kms,   # may be Unknown: CMK check skips it
                "rng": (b.start, b.end)})
    cr.attrs["disks"] = Attr(disks)


def _adapt_firewall(module, res, cr):
    # ranges apply to the firewall as a whole; allow blocks only decide
    # whether any traffic is admitted at all
    ingress, egress = [], []
    if res.blocks("allow"):
        src = res.value("source_ranges")
        dst = res.value("destination_ranges")
        direction = res.value("direction", "INGRESS")
        if isinstance(direction, str) and direction.upper() == "EGRESS":
            for c in (dst if isinstance(dst, list) else []):
                if isinstance(c, str):
                    egress.append({"cidr": c,
                                   "rng": res.rng("destination_ranges")})
        else:
            for c in (src if isinstance(src, list) else []):
                if isinstance(c, str):
                    ingress.append({"cidr": c,
                                    "rng": res.rng("source_ranges")})
    cr.attrs["ingress"] = Attr(ingress)
    cr.attrs["egress"] = Attr(egress)


def _adapt_dns(module, res, cr):
    state, s_rng = "off", cr.rng
    algos = []
    for b in res.blocks("dnssec_config"):
        state, s_rng = _block_attr(module, b, "state", "off")
        for kb in _sub_blocks(b, "default_key_specs"):
            alg, arng = _block_attr(module, kb, "algorithm", "")
            algos.append({"algorithm": alg, "rng": arng})
    cr.attrs["dnssec_state"] = Attr(state, s_rng)
    cr.attrs["key_algorithms"] = Attr(algos)


_IMPERSONATION_ROLES = ("roles/iam.serviceAccountUser",
                        "roles/iam.serviceAccountTokenCreator")


def _adapt_iam(res, cr, level):
    cr.attrs["level"] = Attr(level)
    cr.attrs["role"] = Attr(res.value("role", ""), res.rng("role"))
    members = []
    m = res.value("member")
    if isinstance(m, str):
        members.append(m)
    ms = res.value("members")
    if isinstance(ms, list):
        members.extend(x for x in ms if isinstance(x, str))
    cr.attrs["members"] = Attr(
        members, res.rng("member") if "member" in res.attrs
        else res.rng("members"))


def adapt_google(module) -> list[CloudResource]:
    """Adapt one TfModule's google_* resources into CloudResources."""
    out: list[CloudResource] = []
    for res in module.resources:
        t = res.type
        if not t.startswith("google_"):
            continue
        cr = CloudResource(t, res.name, rng=res.rng(), path=res.path)
        if t == "google_sql_database_instance":
            _adapt_sql(module, res, cr)
        elif t == "google_storage_bucket":
            cr.attrs["uniform_access"] = Attr(
                res.value("uniform_bucket_level_access", False),
                res.rng("uniform_bucket_level_access"))
            kms = ""
            for b in res.blocks("encryption"):
                kms, _ = _block_attr(module, b, "default_kms_key_name", "")
            cr.attrs["kms_key"] = Attr(kms)
        elif t in ("google_storage_bucket_iam_member",
                   "google_storage_bucket_iam_binding"):
            cr.kind = "google_storage_bucket_iam"
            _adapt_iam(res, cr, "bucket")
        elif t == "google_container_cluster":
            _adapt_gke(module, res, cr)
        elif t == "google_container_node_pool":
            ar = au = False
            m_rng = cr.rng
            for b in res.blocks("management"):
                m_rng = (b.start, b.end)
                ar, _ = _block_attr(module, b, "auto_repair", False)
                au, _ = _block_attr(module, b, "auto_upgrade", False)
            cr.attrs["auto_repair"] = Attr(ar, m_rng)
            cr.attrs["auto_upgrade"] = Attr(au, m_rng)
            for b in res.blocks("node_config"):
                _adapt_gke_node_config(module, b, cr)
        elif t == "google_compute_instance":
            _adapt_instance(module, res, cr)
        elif t == "google_compute_disk":
            raw, kms = False, ""
            rng = cr.rng
            for b in res.blocks("disk_encryption_key"):
                rng = (b.start, b.end)
                rk, _ = _block_attr(module, b, "raw_key")
                raw = bool(rk) and not isinstance(rk, Unknown)
                kms, _ = _block_attr(module, b, "kms_key_self_link", "")
            cr.attrs["raw_key"] = Attr(raw, rng)
            cr.attrs["kms_key"] = Attr(kms, rng)
        elif t == "google_compute_firewall":
            _adapt_firewall(module, res, cr)
        elif t == "google_compute_subnetwork":
            cr.attrs["flow_logs"] = Attr(bool(res.blocks("log_config")))
            cr.attrs["purpose"] = Attr(res.value("purpose", ""))
        elif t == "google_compute_ssl_policy":
            cr.attrs["min_tls_version"] = Attr(
                res.value("min_tls_version", "TLS_1_0"),
                res.rng("min_tls_version"))
            cr.attrs["profile"] = Attr(res.value("profile", ""))
        elif t == "google_compute_project_metadata":
            md = res.value("metadata")
            md = md if isinstance(md, dict) else {}
            cr.attrs["oslogin"] = Attr(
                _known_true(md.get("enable-oslogin")), res.rng("metadata"))
        elif t == "google_dns_managed_zone":
            _adapt_dns(module, res, cr)
        elif t == "google_kms_crypto_key":
            period = res.value("rotation_period")
            seconds = None
            if isinstance(period, Unknown):
                seconds = period           # unknown passes the check
            elif isinstance(period, str) and period.endswith("s"):
                try:
                    seconds = int(float(period[:-1]))
                except ValueError:
                    seconds = None
            cr.attrs["rotation_seconds"] = Attr(
                seconds, res.rng("rotation_period"))
        elif t == "google_bigquery_dataset":
            groups = []
            for b in res.blocks("access"):
                g, grng = _block_attr(module, b, "special_group", "")
                if isinstance(g, str) and g:
                    groups.append({"group": g, "rng": grng})
            cr.attrs["special_groups"] = Attr(groups)
        elif t in ("google_project_iam_member", "google_project_iam_binding"):
            cr.kind = "google_iam_grant"
            _adapt_iam(res, cr, "project")
        elif t in ("google_folder_iam_member", "google_folder_iam_binding"):
            cr.kind = "google_iam_grant"
            _adapt_iam(res, cr, "folder")
        elif t in ("google_organization_iam_member",
                   "google_organization_iam_binding"):
            cr.kind = "google_iam_grant"
            _adapt_iam(res, cr, "organization")
        elif t == "google_project":
            cr.attrs["auto_create_network"] = Attr(
                res.value("auto_create_network", True),
                res.rng("auto_create_network"))
        else:
            continue
        out.append(cr)
    return out


# ---------------------------------------------------------------------
# Checks — Cloud SQL
# ---------------------------------------------------------------------

def _family(r):
    v = r.get("database_version", "")
    return v.split("_")[0] if isinstance(v, str) else ""


@_gcp("AVD-GCP-0003", "Cloud SQL instances should have automated backups "
      "enabled", "MEDIUM", "sql",
      "Without automated backups a database cannot be restored after "
      "data loss or corruption.", "Enable backup_configuration.")
def _sql_backups(resources):
    for r in _of(resources, "google_sql_database_instance"):
        if _known_true(r.get("is_replica")):
            continue
        if _known_false(r.val("backups_enabled")):
            yield (f"Database instance '{r.name}' does not have backups "
                   f"enabled.", r.attr_rng("backups_enabled"))


@_gcp("AVD-GCP-0017", "Cloud SQL instances should not be publicly "
      "accessible", "HIGH", "sql",
      "Publicly reachable database instances expose the attack surface "
      "to the entire internet.",
      "Disable public IPv4 or restrict authorized networks.")
def _sql_public(resources):
    for r in _of(resources, "google_sql_database_instance"):
        for n in r.get("authorized_networks", []):
            if n.get("cidr") in ("0.0.0.0/0", "::/0"):
                yield (f"Database instance '{r.name}' authorizes access "
                       f"from anywhere.", n["rng"])


@_gcp("AVD-GCP-0015", "Cloud SQL instances should require TLS for all "
      "connections", "HIGH", "sql",
      "Unencrypted connections expose data in transit.",
      "Set settings.ip_configuration.require_ssl = true.")
def _sql_tls(resources):
    for r in _of(resources, "google_sql_database_instance"):
        if _known_false(r.val("require_ssl")):
            yield (f"Database instance '{r.name}' does not require TLS for "
                   f"all connections.", r.attr_rng("require_ssl"))


def _pg_flag_check(id_, flag, title):
    @_gcp(id_, title, "MEDIUM", "sql",
          f"The {flag} flag aids audit and incident analysis on "
          f"PostgreSQL instances.", f"Set the {flag} database flag to on.")
    def check(resources):
        for r in _of(resources, "google_sql_database_instance"):
            if _family(r) != "POSTGRES":
                continue
            flags = r.get("flags", {})
            if isinstance(flags.get(flag), Unknown):
                continue
            if flags.get(flag) != "on":
                rng = r.get("flag_rngs", {}).get(flag, r.rng)
                yield (f"Database instance '{r.name}' is not configured "
                       f"to log {flag.replace('log_', '').replace('_', ' ')}"
                       f".", rng)
    return check


_pg_flag_check("AVD-GCP-0016", "log_checkpoints",
               "PostgreSQL instances should log checkpoints")
_pg_flag_check("AVD-GCP-0014", "log_connections",
               "PostgreSQL instances should log connections")
_pg_flag_check("AVD-GCP-0022", "log_disconnections",
               "PostgreSQL instances should log disconnections")
_pg_flag_check("AVD-GCP-0020", "log_lock_waits",
               "PostgreSQL instances should log lock waits")


@_gcp("AVD-GCP-0026", "MySQL instances should not allow local data "
      "loading", "HIGH", "sql",
      "local_infile allows reading files from the server host during "
      "LOAD DATA operations.", "Set the local_infile flag to off.")
def _sql_local_infile(resources):
    for r in _of(resources, "google_sql_database_instance"):
        if _family(r) != "MYSQL":
            continue
        v = r.get("flags", {}).get("local_infile")
        if not isinstance(v, Unknown) and v == "on":
            yield (f"Database instance '{r.name}' has local file reads "
                   f"enabled.",
                   r.get("flag_rngs", {}).get("local_infile", r.rng))


def _sqlserver_flag_check(id_, flag, title):
    @_gcp(id_, title, "MEDIUM", "sql",
          f"The '{flag}' flag should be disabled on SQL Server "
          f"instances.", f"Set the '{flag}' database flag to off.")
    def check(resources):
        for r in _of(resources, "google_sql_database_instance"):
            if _family(r) != "SQLSERVER":
                continue
            if isinstance(r.get("flags", {}).get(flag), Unknown):
                continue
            # reference default: enabled unless explicitly set off
            if r.get("flags", {}).get(flag) != "off":
                rng = r.get("flag_rngs", {}).get(flag, r.rng)
                yield (f"Database instance '{r.name}' does not disable "
                       f"'{flag}'.", rng)
    return check


_sqlserver_flag_check(
    "AVD-GCP-0023", "contained database authentication",
    "SQL Server instances should disable contained database "
    "authentication")
_sqlserver_flag_check(
    "AVD-GCP-0019", "cross db ownership chaining",
    "SQL Server instances should disable cross-database ownership "
    "chaining")


# ---------------------------------------------------------------------
# Checks — Cloud Storage
# ---------------------------------------------------------------------

_PUBLIC_MEMBERS = ("allUsers", "allAuthenticatedUsers")


@_gcp("AVD-GCP-0001", "Storage buckets should not be publicly accessible",
      "HIGH", "storage",
      "Granting allUsers or allAuthenticatedUsers exposes the bucket "
      "contents to everyone.", "Restrict IAM members to identities.")
def _storage_public(resources):
    for r in _of(resources, "google_storage_bucket_iam"):
        for m in r.get("members", []):
            if m in _PUBLIC_MEMBERS:
                yield (f"Bucket IAM grant '{r.name}' allows public access "
                       f"({m}).", r.attr_rng("members"))


@_gcp("AVD-GCP-0002", "Storage buckets should enable uniform bucket-level "
      "access", "MEDIUM", "storage",
      "Uniform bucket-level access disables per-object ACLs, leaving "
      "IAM as the single access-control plane.",
      "Set uniform_bucket_level_access = true.")
def _storage_ubla(resources):
    for r in _of(resources, "google_storage_bucket"):
        if _known_false(r.val("uniform_access")):
            yield (f"Bucket '{r.name}' does not enable uniform "
                   f"bucket-level access.", r.attr_rng("uniform_access"))


@_gcp("AVD-GCP-0066", "Storage buckets should be encrypted with "
      "customer-managed keys", "LOW", "storage",
      "Customer-managed KMS keys give control over key rotation and "
      "revocation.", "Set encryption.default_kms_key_name.")
def _storage_cmk(resources):
    for r in _of(resources, "google_storage_bucket"):
        if r.unknown("kms_key"):
            continue
        if not r.get("kms_key"):
            yield (f"Bucket '{r.name}' is not encrypted with a "
                   f"customer-managed key.", r.rng)


# ---------------------------------------------------------------------
# Checks — GKE
# ---------------------------------------------------------------------

@_gcp("AVD-GCP-0060", "GKE clusters should not use legacy ABAC",
      "HIGH", "gke",
      "Legacy ABAC grants broad, coarse permissions and predates RBAC.",
      "Set enable_legacy_abac = false.")
def _gke_abac(resources):
    for r in _of(resources, "google_container_cluster"):
        if _known_true(r.val("legacy_abac")):
            yield (f"Cluster '{r.name}' has legacy ABAC enabled.",
                   r.attr_rng("legacy_abac"))


@_gcp("AVD-GCP-0056", "GKE clusters should have a network policy enabled",
      "MEDIUM", "gke",
      "Without network policies any pod may talk to any other pod.",
      "Enable network_policy (or the ADVANCED_DATAPATH dataplane).")
def _gke_netpol(resources):
    for r in _of(resources, "google_container_cluster"):
        if r.get("datapath_provider") == "ADVANCED_DATAPATH":
            continue
        if _known_false(r.val("network_policy")):
            yield (f"Cluster '{r.name}' does not have a network policy "
                   f"enabled.", r.attr_rng("network_policy"))


@_gcp("AVD-GCP-0053", "GKE clusters should use private nodes",
      "MEDIUM", "gke",
      "Nodes with public IPs are directly reachable from the internet.",
      "Set private_cluster_config.enable_private_nodes = true.")
def _gke_private(resources):
    for r in _of(resources, "google_container_cluster"):
        if _known_false(r.val("private_nodes")):
            yield (f"Cluster '{r.name}' does not use private nodes.",
                   r.attr_rng("private_nodes"))


@_gcp("AVD-GCP-0051", "GKE clusters should enable master authorized "
      "networks", "MEDIUM", "gke",
      "Master authorized networks restrict control-plane access to "
      "known CIDR ranges.",
      "Add a master_authorized_networks_config block.")
def _gke_master_networks(resources):
    for r in _of(resources, "google_container_cluster"):
        if _known_false(r.val("master_networks")):
            yield (f"Cluster '{r.name}' does not enable master authorized "
                   f"networks.", r.attr_rng("master_networks"))


@_gcp("AVD-GCP-0054", "GKE clusters should have shielded nodes enabled",
      "HIGH", "gke",
      "Shielded nodes provide verifiable node identity and integrity.",
      "Keep enable_shielded_nodes = true.")
def _gke_shielded(resources):
    for r in _of(resources, "google_container_cluster"):
        if _known_false(r.val("shielded_nodes")):
            yield (f"Cluster '{r.name}' has shielded nodes disabled.",
                   r.attr_rng("shielded_nodes"))


@_gcp("AVD-GCP-0055", "GKE clusters should not use basic authentication",
      "HIGH", "gke",
      "Basic auth places a static username/password on the API server.",
      "Remove master_auth username/password.")
def _gke_basic_auth(resources):
    for r in _of(resources, "google_container_cluster"):
        u = r.get("master_username", "")
        if isinstance(u, str) and u:
            yield (f"Cluster '{r.name}' uses basic authentication.",
                   r.attr_rng("master_username"))


@_gcp("AVD-GCP-0052", "GKE clusters should not issue client certificates",
      "MEDIUM", "gke",
      "Client certificates cannot be revoked without rotating the "
      "cluster CA.",
      "Set client_certificate_config.issue_client_certificate = false.")
def _gke_client_cert(resources):
    for r in _of(resources, "google_container_cluster"):
        if _known_true(r.val("issue_client_cert")):
            yield (f"Cluster '{r.name}' issues a client certificate.",
                   r.attr_rng("issue_client_cert"))


@_gcp("AVD-GCP-0057", "GKE clusters should have IP aliasing enabled",
      "LOW", "gke",
      "IP aliasing (VPC-native networking) enables network policy "
      "enforcement and private access paths.",
      "Add an ip_allocation_policy block.")
def _gke_ip_alias(resources):
    for r in _of(resources, "google_container_cluster"):
        if _known_false(r.val("ip_aliasing")):
            yield (f"Cluster '{r.name}' does not have IP aliasing "
                   f"enabled.", r.attr_rng("ip_aliasing"))


@_gcp("AVD-GCP-0038", "GKE clusters should have logging enabled",
      "MEDIUM", "gke",
      "Disabling cluster logging removes the audit trail.",
      "Leave logging_service at its kubernetes default.")
def _gke_logging(resources):
    for r in _of(resources, "google_container_cluster"):
        if r.get("logging_service") == "none":
            yield (f"Cluster '{r.name}' has logging disabled.",
                   r.attr_rng("logging_service"))


@_gcp("AVD-GCP-0040", "GKE clusters should have monitoring enabled",
      "MEDIUM", "gke",
      "Disabling monitoring removes visibility into cluster health.",
      "Leave monitoring_service at its kubernetes default.")
def _gke_monitoring(resources):
    for r in _of(resources, "google_container_cluster"):
        if r.get("monitoring_service") == "none":
            yield (f"Cluster '{r.name}' has monitoring disabled.",
                   r.attr_rng("monitoring_service"))


@_gcp("AVD-GCP-0062", "GKE clusters should have resource labels",
      "LOW", "gke",
      "Resource labels support cost attribution and policy targeting.",
      "Set resource_labels.")
def _gke_labels(resources):
    for r in _of(resources, "google_container_cluster"):
        if r.unknown("resource_labels"):
            continue
        if not r.get("resource_labels"):
            yield (f"Cluster '{r.name}' does not set resource labels.",
                   r.attr_rng("resource_labels"))


@_gcp("AVD-GCP-0049", "GKE nodes should disable legacy metadata endpoints",
      "HIGH", "gke",
      "The v0.1/v1beta1 metadata endpoints expose instance metadata "
      "without requiring custom headers.",
      "Set node metadata disable-legacy-endpoints = true.")
def _gke_legacy_endpoints(resources):
    for r in resources:
        if r.kind not in ("google_container_cluster",
                          "google_container_node_pool"):
            continue
        if r.kind == "google_container_cluster" and \
                _known_true(r.val("autopilot")):
            continue
        v = r.val("legacy_endpoints")
        if v is None and r.kind == "google_container_node_pool":
            continue
        if not _known_false(v):
            yield (f"'{r.name}' does not disable legacy metadata "
                   f"endpoints.", r.attr_rng("legacy_endpoints"))


@_gcp("AVD-GCP-0050", "GKE nodes should conceal workload metadata",
      "HIGH", "gke",
      "Exposed node metadata lets workloads read node credentials.",
      "Set workload_metadata_config mode to GKE_METADATA (or SECURE).")
def _gke_node_metadata(resources):
    for r in resources:
        if r.kind not in ("google_container_cluster",
                          "google_container_node_pool"):
            continue
        v = r.get("node_metadata")
        if isinstance(v, str) and v.upper() in ("EXPOSE", "EXPOSED",
                                                "UNSPECIFIED"):
            yield (f"'{r.name}' exposes node metadata to workloads.",
                   r.attr_rng("node_metadata"))


@_gcp("AVD-GCP-0048", "GKE node pools should have auto-repair enabled",
      "LOW", "gke",
      "Auto-repair replaces unhealthy nodes automatically.",
      "Set management.auto_repair = true.")
def _gke_auto_repair(resources):
    for r in _of(resources, "google_container_node_pool"):
        if _known_false(r.val("auto_repair")):
            yield (f"Node pool '{r.name}' does not have auto-repair "
                   f"enabled.", r.attr_rng("auto_repair"))


@_gcp("AVD-GCP-0058", "GKE node pools should have auto-upgrade enabled",
      "LOW", "gke",
      "Auto-upgrade keeps node kubelets patched.",
      "Set management.auto_upgrade = true.")
def _gke_auto_upgrade(resources):
    for r in _of(resources, "google_container_node_pool"):
        if _known_false(r.val("auto_upgrade")):
            yield (f"Node pool '{r.name}' does not have auto-upgrade "
                   f"enabled.", r.attr_rng("auto_upgrade"))


@_gcp("AVD-GCP-0059", "GKE nodes should use the COS image type",
      "LOW", "gke",
      "Container-Optimized OS has a minimal, verified attack surface.",
      "Set node_config.image_type to a COS variant.")
def _gke_cos(resources):
    for r in resources:
        if r.kind not in ("google_container_cluster",
                          "google_container_node_pool"):
            continue
        it = r.get("node_image_type", "")
        if isinstance(it, str) and it and \
                not it.upper().startswith("COS"):
            yield (f"'{r.name}' does not use a Container-Optimized OS "
                   f"node image.", r.attr_rng("node_image_type"))


# ---------------------------------------------------------------------
# Checks — Compute
# ---------------------------------------------------------------------

@_gcp("AVD-GCP-0031", "Compute instances should not have public IP "
      "addresses", "HIGH", "compute",
      "Instances with external IPs are directly reachable from the "
      "internet.", "Remove the access_config block.")
def _inst_public_ip(resources):
    for r in _of(resources, "google_compute_instance"):
        for iface in r.get("interfaces", []):
            if iface["public_ip"]:
                yield (f"Instance '{r.name}' has a public IP allocated.",
                       iface["rng"])


@_gcp("AVD-GCP-0043", "Compute instances should not have IP forwarding "
      "enabled", "HIGH", "compute",
      "IP forwarding lets an instance spoof or route foreign traffic.",
      "Set can_ip_forward = false.")
def _inst_ip_forward(resources):
    for r in _of(resources, "google_compute_instance"):
        if _known_true(r.val("can_ip_forward")):
            yield (f"Instance '{r.name}' has IP forwarding allowed.",
                   r.attr_rng("can_ip_forward"))


@_gcp("AVD-GCP-0044", "Compute instances should not use the default "
      "service account", "HIGH", "compute",
      "The default service account carries project-editor privileges.",
      "Attach a minimally-scoped service account.")
def _inst_default_sa(resources):
    for r in _of(resources, "google_compute_instance"):
        if _known_true(r.val("sa_is_default")):
            yield (f"Instance '{r.name}' uses the default service "
                   f"account.", r.attr_rng("sa_is_default"))


@_gcp("AVD-GCP-0030", "Compute instances should block project-wide SSH "
      "keys", "MEDIUM", "compute",
      "Project-wide SSH keys grant every key holder access to every "
      "instance.", "Set metadata block-project-ssh-keys = true.")
def _inst_ssh_keys(resources):
    for r in _of(resources, "google_compute_instance"):
        if _known_false(r.val("block_project_ssh_keys")):
            yield (f"Instance '{r.name}' does not block project-wide SSH "
                   f"keys.", r.attr_rng("block_project_ssh_keys"))


@_gcp("AVD-GCP-0032", "Compute instances should disable serial port "
      "access", "MEDIUM", "compute",
      "The interactive serial console bypasses firewall rules.",
      "Remove metadata serial-port-enable.")
def _inst_serial(resources):
    for r in _of(resources, "google_compute_instance"):
        if _known_true(r.val("serial_port")):
            yield (f"Instance '{r.name}' enables serial port access.",
                   r.attr_rng("serial_port"))


@_gcp("AVD-GCP-0036", "Compute instances should not override OS Login",
      "MEDIUM", "compute",
      "Disabling OS Login re-enables static metadata SSH keys.",
      "Remove metadata enable-oslogin = false.")
def _inst_oslogin(resources):
    for r in _of(resources, "google_compute_instance"):
        if _known_false(r.val("oslogin")):
            yield (f"Instance '{r.name}' disables OS Login.",
                   r.attr_rng("oslogin"))


def _shield_check(id_, attr, what):
    @_gcp(id_, f"Compute instances should have Shielded VM {what} "
          f"enabled", "MEDIUM", "compute",
          f"Shielded VM {what} protects the boot chain and runtime "
          f"integrity of the instance.",
          f"Enable {attr} in shielded_instance_config.")
    def check(resources):
        for r in _of(resources, "google_compute_instance"):
            if _known_false(r.val(attr)):
                yield (f"Instance '{r.name}' does not have Shielded VM "
                       f"{what} enabled.", r.attr_rng(attr))
    return check


_shield_check("AVD-GCP-0067", "secure_boot", "secure boot")
_shield_check("AVD-GCP-0045", "integrity_monitoring",
              "integrity monitoring")
_shield_check("AVD-GCP-0068", "vtpm", "vTPM")


@_gcp("AVD-GCP-0037", "Compute disks should not embed plaintext "
      "encryption keys", "CRITICAL", "compute",
      "A raw key in the configuration leaks the disk key to anyone who "
      "can read state or source.", "Use a KMS key instead of a raw key.")
def _disk_raw_key(resources):
    for r in _of(resources, "google_compute_disk"):
        if _known_true(r.val("raw_key")):
            yield (f"Disk '{r.name}' specifies a plaintext encryption "
                   f"key.", r.attr_rng("raw_key"))
    for r in _of(resources, "google_compute_instance"):
        for d in r.get("disks", []):
            if d["raw_key"]:
                yield (f"Instance '{r.name}' attaches a disk with a "
                       f"plaintext encryption key.", d["rng"])


@_gcp("AVD-GCP-0034", "Compute disks should be encrypted with "
      "customer-managed keys", "LOW", "compute",
      "Customer-managed keys allow rotation and revocation control.",
      "Set disk_encryption_key.kms_key_self_link.")
def _disk_cmk(resources):
    for r in _of(resources, "google_compute_disk"):
        if r.unknown("kms_key"):
            continue
        if not r.get("kms_key") and not _known_true(r.val("raw_key")):
            yield (f"Disk '{r.name}' is not encrypted with a "
                   f"customer-managed key.", r.rng)


@_gcp("AVD-GCP-0033", "Instance disks should be encrypted with "
      "customer-managed keys", "LOW", "compute",
      "Customer-managed keys allow rotation and revocation control.",
      "Set kms_key_self_link on boot/attached disks.")
def _inst_disk_cmk(resources):
    for r in _of(resources, "google_compute_instance"):
        for d in r.get("disks", []):
            if isinstance(d["kms_key"], Unknown):
                continue
            if not d["kms_key"] and not d["raw_key"]:
                yield (f"Instance '{r.name}' has a disk without a "
                       f"customer-managed encryption key.", d["rng"])


@_gcp("AVD-GCP-0027", "Firewall rules should not permit public ingress",
      "HIGH", "compute",
      "An allow rule from 0.0.0.0/0 opens the port to the internet.",
      "Restrict source_ranges.")
def _fw_ingress(resources):
    for r in _of(resources, "google_compute_firewall"):
        for rule in r.get("ingress", []):
            if rule["cidr"] in ("0.0.0.0/0", "::/0", "0.0.0.0"):
                yield (f"Firewall '{r.name}' allows ingress from anywhere.",
                       rule["rng"])


@_gcp("AVD-GCP-0035", "Firewall rules should not permit public egress",
      "HIGH", "compute",
      "Unrestricted egress allows exfiltration to any destination.",
      "Restrict destination_ranges.")
def _fw_egress(resources):
    for r in _of(resources, "google_compute_firewall"):
        for rule in r.get("egress", []):
            if rule["cidr"] in ("0.0.0.0/0", "::/0", "0.0.0.0"):
                yield (f"Firewall '{r.name}' allows egress to anywhere.",
                       rule["rng"])


@_gcp("AVD-GCP-0029", "VPC subnetworks should have flow logs enabled",
      "LOW", "compute",
      "Flow logs record network traffic for audit and forensics.",
      "Add a log_config block.")
def _subnet_flow_logs(resources):
    for r in _of(resources, "google_compute_subnetwork"):
        purpose = r.get("purpose", "")
        if purpose in ("REGIONAL_MANAGED_PROXY",
                       "INTERNAL_HTTPS_LOAD_BALANCER"):
            continue
        if _known_false(r.val("flow_logs")):
            yield (f"Subnetwork '{r.name}' does not have flow logs "
                   f"enabled.", r.rng)


@_gcp("AVD-GCP-0039", "SSL policies should use a secure TLS version",
      "MEDIUM", "compute",
      "TLS versions below 1.2 have known weaknesses.",
      "Set min_tls_version = TLS_1_2.")
def _ssl_policy(resources):
    for r in _of(resources, "google_compute_ssl_policy"):
        v = r.get("min_tls_version", "TLS_1_0")
        if isinstance(v, str) and v != "TLS_1_2":
            yield (f"SSL policy '{r.name}' allows TLS versions below "
                   f"1.2.", r.attr_rng("min_tls_version"))


@_gcp("AVD-GCP-0042", "Projects should have OS Login enabled", "MEDIUM",
      "compute",
      "OS Login centralizes SSH access through IAM.",
      "Set project metadata enable-oslogin = true.")
def _project_oslogin(resources):
    for r in _of(resources, "google_compute_project_metadata"):
        if _known_false(r.val("oslogin")):
            yield ("Project metadata does not enable OS Login.",
                   r.attr_rng("oslogin"))


# ---------------------------------------------------------------------
# Checks — DNS / KMS / BigQuery / IAM
# ---------------------------------------------------------------------

@_gcp("AVD-GCP-0012", "Managed DNS zones should have DNSSEC enabled",
      "MEDIUM", "dns",
      "DNSSEC protects zone records from spoofing.",
      "Set dnssec_config.state = on.")
def _dns_dnssec(resources):
    for r in _of(resources, "google_dns_managed_zone"):
        state = r.get("dnssec_state", "off")
        if isinstance(state, str) and state != "on":
            yield (f"Managed zone '{r.name}' does not have DNSSEC "
                   f"enabled.", r.attr_rng("dnssec_state"))


@_gcp("AVD-GCP-0011", "Zone-signing keys should not use RSASHA1",
      "MEDIUM", "dns",
      "RSASHA1 is cryptographically weak for DNSSEC signing.",
      "Use RSASHA256 or an elliptic-curve algorithm.")
def _dns_rsasha1(resources):
    for r in _of(resources, "google_dns_managed_zone"):
        for spec in r.get("key_algorithms", []):
            if spec["algorithm"] == "rsasha1":
                yield (f"Managed zone '{r.name}' signs with RSASHA1.",
                       spec["rng"])


@_gcp("AVD-GCP-0065", "KMS keys should be rotated at least every 90 days",
      "HIGH", "kms",
      "Stale keys grow the blast radius of a key compromise.",
      "Set rotation_period to 7776000s or less.")
def _kms_rotation(resources):
    for r in _of(resources, "google_kms_crypto_key"):
        if r.unknown("rotation_seconds"):
            continue
        secs = r.get("rotation_seconds")
        if secs is None or secs > 7776000:
            yield (f"KMS key '{r.name}' is not rotated at least every "
                   f"90 days.", r.attr_rng("rotation_seconds"))


@_gcp("AVD-GCP-0046", "BigQuery datasets should not be publicly "
      "accessible", "CRITICAL", "bigquery",
      "allAuthenticatedUsers means every Google account holder.",
      "Restrict dataset access to specific identities.")
def _bq_public(resources):
    for r in _of(resources, "google_bigquery_dataset"):
        for g in r.get("special_groups", []):
            if g["group"] == "allAuthenticatedUsers":
                yield (f"Dataset '{r.name}' is accessible to all "
                       f"authenticated users.", g["rng"])


_PRIVILEGED_RE = re.compile(
    r"^roles/(owner|editor)$|(Admin|admin)$")


@_gcp("AVD-GCP-0007", "Service accounts should not have roles assigned "
      "with excessive privileges", "HIGH", "iam",
      "Service accounts should have a minimal set of permissions "
      "assigned in order to do their job. They should never have "
      "excessive access as if compromised, an attacker can escalate "
      "privileges and take over the entire account.",
      "Limit service account roles to minimal required access.")
def _iam_privileged_sa(resources):
    for r in _of(resources, "google_iam_grant"):
        role = r.get("role", "")
        if not (isinstance(role, str) and _PRIVILEGED_RE.search(role)):
            continue
        for m in r.get("members", []):
            if m.startswith("serviceAccount:"):
                yield ("Service account is granted a privileged role.",
                       r.attr_rng("members"))


def _impersonation_check(id_, level):
    @_gcp(id_, f"Service-account impersonation should not be granted at "
          f"the {level} level", "HIGH", "iam",
          "serviceAccountUser / serviceAccountTokenCreator at a "
          "hierarchy level allows impersonating every service account "
          "below it.", "Grant impersonation on specific accounts only.")
    def check(resources):
        for r in _of(resources, "google_iam_grant"):
            if r.get("level") != level:
                continue
            if r.get("role", "") in _IMPERSONATION_ROLES:
                yield (f"Impersonation role granted at {level} level.",
                       r.attr_rng("role"))
    return check


_impersonation_check("AVD-GCP-0005", "project")
_impersonation_check("AVD-GCP-0006", "folder")
_impersonation_check("AVD-GCP-0004", "organization")


@_gcp("AVD-GCP-0010", "Projects should not have the default network",
      "HIGH", "iam",
      "The auto-created default network ships permissive firewall "
      "rules.", "Set auto_create_network = false.")
def _project_default_network(resources):
    for r in _of(resources, "google_project"):
        if _known_true(r.val("auto_create_network")):
            yield (f"Project '{r.name}' creates the default network.",
                   r.attr_rng("auto_create_network"))
