"""Minor-cloud IaC support: DigitalOcean, Nifcloud, OpenStack, GitHub,
Oracle and CloudStack terraform adapters + check sets.

Reference counterparts: pkg/iac/providers/{digitalocean,nifcloud,
openstack,github,oracle,cloudstack}/** (typed state) and
pkg/iac/adapters/terraform/<provider>/** for the resource-type and
attribute mapping (e.g. nifcloud_db_instance publicly_accessible
defaults true and network_id defaults net-COMMON_PRIVATE per
rdb/db_instance.go; digitalocean_spaces_bucket acl defaults
public-read per spaces/adapt.go).  Check bodies are re-authored from
that typed state with IDs following the published AVD series."""

from __future__ import annotations

import re

from .cloud import (Attr, CloudResource, Unknown, block_attr,
                    sub_blocks)
from .core import Check

EXTRA_CHECKS: list[Check] = []


def _reg(provider, service):
    def make(id_, title, severity, description="", resolution=""):
        def deco(fn):
            EXTRA_CHECKS.append(Check(
                id=id_, avd_id=id_, title=title, severity=severity,
                description=description, resolution=resolution,
                provider=provider, service=service,
                namespace=f"builtin.{provider.lower()}.{service}.{id_}",
                fn=fn))
            return fn
        return deco
    return make


def _of(resources, kind):
    return [r for r in resources if r.kind == kind]


def _known(v):
    return not isinstance(v, Unknown)


def _public_cidr(c):
    return c in ("0.0.0.0/0", "::/0", "0.0.0.0")


# ---------------------------------------------------------------------
# Adapter
# ---------------------------------------------------------------------

_PREFIXES = ("digitalocean_", "nifcloud_", "openstack_", "github_",
             "opc_", "cloudstack_")


_sub_blocks = sub_blocks
_block_attr = block_attr


def _rule_cidrs(module, res, btype, key):
    out = []
    for b in res.blocks(btype):
        v, rng = _block_attr(module, b, key)
        if isinstance(v, list):
            out.extend({"cidr": c, "rng": rng} for c in v
                       if isinstance(c, str))
        elif isinstance(v, str):
            out.append({"cidr": v, "rng": rng})
    return out


def adapt_extra(module) -> list[CloudResource]:
    """Adapt minor-provider terraform resources into CloudResources."""
    out: list[CloudResource] = []
    for res in module.resources:
        t = res.type
        if not t.startswith(_PREFIXES):
            continue
        cr = CloudResource(t, res.name, rng=res.rng(), path=res.path)

        if t == "digitalocean_firewall":
            cr.attrs["inbound"] = Attr(
                _rule_cidrs(module, res, "inbound_rule",
                            "source_addresses"))
            cr.attrs["outbound"] = Attr(
                _rule_cidrs(module, res, "outbound_rule",
                            "destination_addresses"))
        elif t == "digitalocean_droplet":
            keys = res.value("ssh_keys")
            if not isinstance(keys, (list, Unknown)):
                keys = []
            cr.attrs["ssh_keys"] = Attr(keys, res.rng("ssh_keys"))
        elif t == "digitalocean_loadbalancer":
            rules = []
            for b in res.blocks("forwarding_rule"):
                proto, rng = _block_attr(module, b, "entry_protocol", "")
                rules.append({"entry_protocol":
                              proto.lower() if isinstance(proto, str)
                              else "", "rng": rng})
            cr.attrs["forwarding_rules"] = Attr(rules)
            cr.attrs["redirect_http_to_https"] = Attr(
                res.value("redirect_http_to_https", False))
        elif t == "digitalocean_kubernetes_cluster":
            cr.attrs["auto_upgrade"] = Attr(
                res.value("auto_upgrade", False), res.rng("auto_upgrade"))
            cr.attrs["surge_upgrade"] = Attr(
                res.value("surge_upgrade", False),
                res.rng("surge_upgrade"))
        elif t == "digitalocean_spaces_bucket":
            cr.attrs["acl"] = Attr(res.value("acl", "public-read"),
                                   res.rng("acl"))
            cr.attrs["force_destroy"] = Attr(
                res.value("force_destroy", False),
                res.rng("force_destroy"))
            versioning = False
            v_rng = cr.rng
            for b in res.blocks("versioning"):
                versioning, v_rng = _block_attr(module, b, "enabled",
                                                False)
            cr.attrs["versioning"] = Attr(versioning, v_rng)
        elif t == "digitalocean_spaces_bucket_object":
            cr.attrs["acl"] = Attr(res.value("acl", "private"),
                                   res.rng("acl"))

        elif t == "nifcloud_security_group":
            cr.attrs["description"] = Attr(
                res.value("description", ""), res.rng("description"))
        elif t == "nifcloud_security_group_rule":
            cr.attrs["cidr"] = Attr(res.value("cidr_ip", ""),
                                    res.rng("cidr_ip"))
            cr.attrs["type"] = Attr(res.value("type", "IN"))
        elif t == "nifcloud_instance":
            cr.attrs["security_group"] = Attr(
                res.value("security_group", ""),
                res.rng("security_group"))
            nets = []
            for b in res.blocks("network_interface"):
                nid, rng = _block_attr(module, b, "network_id", "")
                nets.append({"network_id": nid, "rng": rng})
            cr.attrs["interfaces"] = Attr(nets)
        elif t == "nifcloud_router":
            cr.attrs["security_group"] = Attr(
                res.value("security_group", ""),
                res.rng("security_group"))
        elif t == "nifcloud_vpn_gateway":
            cr.attrs["security_group"] = Attr(
                res.value("security_group", ""),
                res.rng("security_group"))
        elif t == "nifcloud_load_balancer":
            cr.attrs["port"] = Attr(res.value("load_balancer_port"),
                                    res.rng("load_balancer_port"))
            cr.attrs["ssl_policy"] = Attr(
                res.value("ssl_policy_id")
                or res.value("ssl_policy_name") or "")
        elif t == "nifcloud_elb":
            cr.attrs["protocol"] = Attr(res.value("protocol", ""),
                                        res.rng("protocol"))
            nets = []
            for b in res.blocks("network_interface"):
                nid, rng = _block_attr(module, b, "network_id", "")
                nets.append({"network_id": nid, "rng": rng})
            cr.attrs["interfaces"] = Attr(nets)
        elif t == "nifcloud_db_instance":
            cr.attrs["backup_retention"] = Attr(
                res.value("backup_retention_period", 0),
                res.rng("backup_retention_period"))
            # reference default: publicly accessible unless disabled
            cr.attrs["public"] = Attr(
                res.value("publicly_accessible", True),
                res.rng("publicly_accessible"))
            cr.attrs["network_id"] = Attr(
                res.value("network_id", "net-COMMON_PRIVATE"),
                res.rng("network_id"))
        elif t in ("nifcloud_db_security_group",
                   "nifcloud_nas_security_group"):
            cr.attrs["cidrs"] = Attr(
                _rule_cidrs(module, res, "rule", "cidr_ip"))
        elif t == "nifcloud_nas_instance":
            cr.attrs["network_id"] = Attr(
                res.value("network_id", "net-COMMON_PRIVATE"),
                res.rng("network_id"))
        elif t == "nifcloud_dns_record":
            cr.attrs["type"] = Attr(res.value("type", ""))
            cr.attrs["record"] = Attr(res.value("record", ""),
                                      res.rng("record"))

        elif t == "openstack_compute_instance_v2":
            cr.attrs["admin_pass"] = Attr(res.value("admin_pass", ""),
                                          res.rng("admin_pass"))
        elif t == "openstack_fw_rule_v1":
            cr.attrs["action"] = Attr(res.value("action", ""))
            cr.attrs["enabled"] = Attr(res.value("enabled", True))
            cr.attrs["source"] = Attr(
                res.value("source_ip_address", ""))
            cr.attrs["destination"] = Attr(
                res.value("destination_ip_address", ""))
        elif t == "openstack_networking_secgroup_v2":
            cr.attrs["description"] = Attr(
                res.value("description", ""), res.rng("description"))
        elif t == "openstack_networking_secgroup_rule_v2":
            cr.attrs["direction"] = Attr(res.value("direction", ""))
            cr.attrs["cidr"] = Attr(res.value("remote_ip_prefix", ""),
                                    res.rng("remote_ip_prefix"))

        elif t == "github_repository":
            private = res.value("private")
            visibility = res.value("visibility")
            if isinstance(visibility, Unknown) or \
                    isinstance(private, Unknown):
                public = visibility if isinstance(visibility, Unknown) \
                    else private
            elif isinstance(visibility, str) and visibility:
                public = visibility == "public"
            elif private is not None:
                public = not private
            else:
                public = True
            cr.attrs["public"] = Attr(
                public, res.rng("visibility")
                if "visibility" in res.attrs else res.rng("private"))
            cr.attrs["vulnerability_alerts"] = Attr(
                res.value("vulnerability_alerts", False),
                res.rng("vulnerability_alerts"))
            cr.attrs["archived"] = Attr(res.value("archived", False))
        elif t == "github_branch_protection":
            cr.attrs["require_signed_commits"] = Attr(
                res.value("require_signed_commits", False),
                res.rng("require_signed_commits"))
        elif t == "github_actions_environment_secret":
            cr.attrs["plaintext_value"] = Attr(
                res.value("plaintext_value", ""),
                res.rng("plaintext_value"))

        elif t == "opc_compute_ip_address_reservation":
            cr.attrs["pool"] = Attr(res.value("ip_address_pool", ""),
                                    res.rng("ip_address_pool"))
        elif t == "cloudstack_instance":
            cr.attrs["user_data"] = Attr(res.value("user_data", ""),
                                         res.rng("user_data"))
        else:
            continue
        out.append(cr)
    return out


# ---------------------------------------------------------------------
# DigitalOcean checks
# ---------------------------------------------------------------------

_dig_compute = _reg("DigitalOcean", "compute")
_dig_spaces = _reg("DigitalOcean", "spaces")


@_dig_compute("AVD-DIG-0001", "Firewalls should not permit public "
              "inbound traffic", "HIGH",
              "An inbound rule from 0.0.0.0/0 opens the port to the "
              "internet.", "Restrict source_addresses.")
def _dig_fw_in(resources):
    for r in _of(resources, "digitalocean_firewall"):
        for rule in r.get("inbound", []):
            if _public_cidr(rule["cidr"]):
                yield (f"Firewall '{r.name}' allows inbound access from "
                       f"anywhere.", rule["rng"])


@_dig_compute("AVD-DIG-0003", "Firewalls should not permit unrestricted "
              "outbound traffic", "HIGH",
              "Unrestricted egress allows exfiltration to any "
              "destination.", "Restrict destination_addresses.")
def _dig_fw_out(resources):
    for r in _of(resources, "digitalocean_firewall"):
        for rule in r.get("outbound", []):
            if _public_cidr(rule["cidr"]):
                yield (f"Firewall '{r.name}' allows outbound access to "
                       f"anywhere.", rule["rng"])


@_dig_compute("AVD-DIG-0002", "Load balancers should not forward plain "
              "HTTP", "HIGH",
              "HTTP forwarding rules carry traffic unencrypted.",
              "Use https/http2 entry protocols or redirect to HTTPS.")
def _dig_lb_http(resources):
    for r in _of(resources, "digitalocean_loadbalancer"):
        if r.get("redirect_http_to_https") is True:
            continue
        for rule in r.get("forwarding_rules", []):
            if rule["entry_protocol"] == "http":
                yield (f"Load balancer '{r.name}' accepts plain HTTP.",
                       rule["rng"])


@_dig_compute("AVD-DIG-0004", "Droplets should use SSH keys instead of "
              "passwords", "MEDIUM",
              "Password authentication is brute-forceable.",
              "Provision droplets with ssh_keys.")
def _dig_ssh(resources):
    for r in _of(resources, "digitalocean_droplet"):
        if r.unknown("ssh_keys"):
            continue
        if not r.get("ssh_keys"):
            yield (f"Droplet '{r.name}' does not specify SSH keys.",
                   r.rng)


@_dig_compute("AVD-DIG-0005", "Kubernetes clusters should enable surge "
              "upgrades", "MEDIUM",
              "Surge upgrades replace nodes before draining them, "
              "avoiding capacity loss during upgrades.",
              "Set surge_upgrade = true.")
def _dig_surge(resources):
    for r in _of(resources, "digitalocean_kubernetes_cluster"):
        if r.unknown("surge_upgrade"):
            continue
        if r.get("surge_upgrade") is not True:
            yield (f"Cluster '{r.name}' does not enable surge upgrades.",
                   r.attr_rng("surge_upgrade"))


@_dig_compute("AVD-DIG-0008", "Kubernetes clusters should enable "
              "auto-upgrade", "MEDIUM",
              "Auto-upgrade keeps the control plane patched.",
              "Set auto_upgrade = true.")
def _dig_auto_upgrade(resources):
    for r in _of(resources, "digitalocean_kubernetes_cluster"):
        if r.unknown("auto_upgrade"):
            continue
        if r.get("auto_upgrade") is not True:
            yield (f"Cluster '{r.name}' does not enable auto-upgrade.",
                   r.attr_rng("auto_upgrade"))


@_dig_spaces("AVD-DIG-0006", "Spaces buckets should not be publicly "
             "readable", "HIGH",
             "A public-read ACL exposes all objects.",
             "Set acl = private.")
def _dig_acl(resources):
    for r in _of(resources, "digitalocean_spaces_bucket"):
        if r.unknown("acl"):
            continue
        if r.get("acl", "public-read") == "public-read":
            yield (f"Spaces bucket '{r.name}' has a public-read ACL.",
                   r.attr_rng("acl"))
    for r in _of(resources, "digitalocean_spaces_bucket_object"):
        if r.unknown("acl"):
            continue
        if r.get("acl", "private") == "public-read":
            yield (f"Spaces bucket object '{r.name}' has a public-read "
                   f"ACL.", r.attr_rng("acl"))


@_dig_spaces("AVD-DIG-0007", "Spaces buckets should have versioning "
             "enabled", "MEDIUM",
             "Versioning protects objects from overwrite and deletion.",
             "Add a versioning block with enabled = true.")
def _dig_versioning(resources):
    for r in _of(resources, "digitalocean_spaces_bucket"):
        if r.unknown("versioning"):
            continue
        if r.get("versioning") is not True:
            yield (f"Spaces bucket '{r.name}' does not have versioning "
                   f"enabled.", r.attr_rng("versioning"))


@_dig_spaces("AVD-DIG-0009", "Spaces buckets should not enable "
             "force-destroy", "MEDIUM",
             "force_destroy deletes all objects on bucket removal.",
             "Leave force_destroy = false.")
def _dig_force_destroy(resources):
    for r in _of(resources, "digitalocean_spaces_bucket"):
        if r.get("force_destroy") is True:
            yield (f"Spaces bucket '{r.name}' enables force-destroy.",
                   r.attr_rng("force_destroy"))


# ---------------------------------------------------------------------
# Nifcloud checks
# ---------------------------------------------------------------------

_nif_computing = _reg("Nifcloud", "computing")
_nif_network = _reg("Nifcloud", "network")
_nif_rdb = _reg("Nifcloud", "rdb")
_nif_nas = _reg("Nifcloud", "nas")
_nif_dns = _reg("Nifcloud", "dns")

_COMMON_NETS = ("net-COMMON_GLOBAL", "net-COMMON_PRIVATE")


@_nif_computing("AVD-NIF-0001", "Security groups should not permit "
                "public ingress", "HIGH",
                "An IN rule from 0.0.0.0/0 opens the port to the "
                "internet.", "Restrict cidr_ip.")
def _nif_sg_public(resources):
    for r in _of(resources, "nifcloud_security_group_rule"):
        if r.get("type", "IN") == "IN" and _public_cidr(r.get("cidr", "")):
            yield (f"Security group rule '{r.name}' allows ingress from "
                   f"anywhere.", r.attr_rng("cidr"))


@_nif_computing("AVD-NIF-0002", "Security groups should have a "
                "description", "LOW",
                "Descriptions document rule intent for audits.",
                "Add a description.")
def _nif_sg_desc(resources):
    for r in _of(resources, "nifcloud_security_group"):
        if r.unknown("description"):
            continue
        if not r.get("description"):
            yield (f"Security group '{r.name}' has no description.",
                   r.rng)


@_nif_computing("AVD-NIF-0003", "Instances should have a security group",
                "MEDIUM",
                "An instance without a security group is unfiltered.",
                "Set security_group.")
def _nif_inst_sg(resources):
    for r in _of(resources, "nifcloud_instance"):
        if r.unknown("security_group"):
            continue
        if not r.get("security_group"):
            yield (f"Instance '{r.name}' does not set a security group.",
                   r.rng)


@_nif_computing("AVD-NIF-0004", "Instances should not sit on common "
                "networks", "LOW",
                "The shared COMMON networks are reachable by other "
                "tenants.", "Use a private LAN network_id.")
def _nif_inst_net(resources):
    for r in _of(resources, "nifcloud_instance"):
        for iface in r.get("interfaces", []):
            if iface["network_id"] in _COMMON_NETS:
                yield (f"Instance '{r.name}' uses the shared "
                       f"{iface['network_id']} network.", iface["rng"])


@_nif_network("AVD-NIF-0005", "Load balancers should use TLS", "MEDIUM",
              "Plain listeners carry traffic unencrypted.",
              "Terminate TLS (port 443 + ssl policy) on the listener.")
def _nif_lb_tls(resources):
    for r in _of(resources, "nifcloud_load_balancer"):
        if r.unknown("ssl_policy"):
            continue
        port = r.get("port")
        if port == 443 and not r.get("ssl_policy"):
            yield (f"Load balancer '{r.name}' serves 443 without a TLS "
                   f"policy.", r.attr_rng("port"))
        elif isinstance(port, int) and port not in (443,):
            yield (f"Load balancer '{r.name}' listens on plain port "
                   f"{port}.", r.attr_rng("port"))
    for r in _of(resources, "nifcloud_elb"):
        proto = r.get("protocol", "")
        if isinstance(proto, str) and proto.upper() in ("HTTP", "TCP"):
            yield (f"ELB '{r.name}' uses unencrypted protocol "
                   f"{proto}.", r.attr_rng("protocol"))


@_nif_network("AVD-NIF-0006", "Routers should have a security group",
              "MEDIUM",
              "An unfiltered router forwards any traffic.",
              "Set security_group.")
def _nif_router_sg(resources):
    for r in _of(resources, "nifcloud_router"):
        if r.unknown("security_group"):
            continue
        if not r.get("security_group"):
            yield (f"Router '{r.name}' does not set a security group.",
                   r.rng)


@_nif_network("AVD-NIF-0007", "VPN gateways should have a security group",
              "MEDIUM",
              "An unfiltered VPN gateway accepts any peer.",
              "Set security_group.")
def _nif_vpngw_sg(resources):
    for r in _of(resources, "nifcloud_vpn_gateway"):
        if r.unknown("security_group"):
            continue
        if not r.get("security_group"):
            yield (f"VPN gateway '{r.name}' does not set a security "
                   f"group.", r.rng)


@_nif_network("AVD-NIF-0008", "ELBs should not sit on common networks",
              "LOW",
              "The shared COMMON networks are reachable by other "
              "tenants.", "Use a private LAN network_id.")
def _nif_elb_net(resources):
    for r in _of(resources, "nifcloud_elb"):
        for iface in r.get("interfaces", []):
            if iface["network_id"] in _COMMON_NETS:
                yield (f"ELB '{r.name}' uses the shared "
                       f"{iface['network_id']} network.", iface["rng"])


@_nif_rdb("AVD-NIF-0009", "DB security groups should not permit public "
          "ingress", "HIGH",
          "A rule from 0.0.0.0/0 opens the database to the internet.",
          "Restrict cidr_ip.")
def _nif_dbsg_public(resources):
    for r in _of(resources, "nifcloud_db_security_group"):
        for rule in r.get("cidrs", []):
            if _public_cidr(rule["cidr"]):
                yield (f"DB security group '{r.name}' allows access from "
                       f"anywhere.", rule["rng"])


@_nif_rdb("AVD-NIF-0010", "DB instances should have backups enabled",
          "MEDIUM",
          "Without backup retention a database cannot be restored.",
          "Set backup_retention_period > 0.")
def _nif_db_backup(resources):
    for r in _of(resources, "nifcloud_db_instance"):
        if r.unknown("backup_retention"):
            continue
        ret = r.get("backup_retention", 0)
        if isinstance(ret, int) and ret <= 0:
            yield (f"DB instance '{r.name}' disables backups.",
                   r.attr_rng("backup_retention"))


@_nif_rdb("AVD-NIF-0011", "DB instances should not be publicly "
          "accessible", "HIGH",
          "Publicly reachable databases expose the attack surface to "
          "the internet.", "Set publicly_accessible = false.")
def _nif_db_public(resources):
    for r in _of(resources, "nifcloud_db_instance"):
        if r.unknown("public"):
            continue
        if r.get("public", True) is not False:
            yield (f"DB instance '{r.name}' is publicly accessible.",
                   r.attr_rng("public"))


@_nif_rdb("AVD-NIF-0012", "DB instances should not sit on common "
          "networks", "LOW",
          "The shared COMMON networks are reachable by other tenants.",
          "Use a private LAN network_id.")
def _nif_db_net(resources):
    for r in _of(resources, "nifcloud_db_instance"):
        if r.get("network_id") in _COMMON_NETS:
            yield (f"DB instance '{r.name}' uses a shared COMMON "
                   f"network.", r.attr_rng("network_id"))


@_nif_nas("AVD-NIF-0013", "NAS security groups should not permit public "
          "ingress", "HIGH",
          "A rule from 0.0.0.0/0 opens the share to the internet.",
          "Restrict cidr_ip.")
def _nif_nassg_public(resources):
    for r in _of(resources, "nifcloud_nas_security_group"):
        for rule in r.get("cidrs", []):
            if _public_cidr(rule["cidr"]):
                yield (f"NAS security group '{r.name}' allows access "
                       f"from anywhere.", rule["rng"])


@_nif_nas("AVD-NIF-0014", "NAS instances should not sit on common "
          "networks", "LOW",
          "The shared COMMON networks are reachable by other tenants.",
          "Use a private LAN network_id.")
def _nif_nas_net(resources):
    for r in _of(resources, "nifcloud_nas_instance"):
        if r.get("network_id") in _COMMON_NETS:
            yield (f"NAS instance '{r.name}' uses a shared COMMON "
                   f"network.", r.attr_rng("network_id"))


@_nif_dns("AVD-NIF-0015", "Zone-registration verify records should be "
          "removed", "MEDIUM",
          "The nifty-dns-verify TXT record is only needed during zone "
          "registration; leaving it allows re-verification hijack.",
          "Delete the verify record after registration.")
def _nif_dns_verify(resources):
    for r in _of(resources, "nifcloud_dns_record"):
        record = r.get("record", "")
        if r.get("type") == "TXT" and isinstance(record, str) and \
                record.startswith("nifty-dns-verify="):
            yield (f"DNS record '{r.name}' keeps the zone-registration "
                   f"verify token.", r.attr_rng("record"))


# ---------------------------------------------------------------------
# OpenStack checks
# ---------------------------------------------------------------------

_os_compute = _reg("OpenStack", "compute")
_os_network = _reg("OpenStack", "networking")


@_os_compute("AVD-OPNSTK-0001", "Instances should not have a plaintext "
             "admin password", "MEDIUM",
             "admin_pass stores the root password in state and source.",
             "Use key pairs instead of admin_pass.")
def _os_admin_pass(resources):
    for r in _of(resources, "openstack_compute_instance_v2"):
        if r.get("admin_pass"):
            yield (f"Instance '{r.name}' sets a plaintext admin "
                   f"password.", r.attr_rng("admin_pass"))


@_os_compute("AVD-OPNSTK-0002", "Firewall rules should not allow "
             "unrestricted traffic", "HIGH",
             "An allow rule without source and destination restrictions "
             "matches everything.", "Scope source/destination addresses.")
def _os_fw_rule(resources):
    for r in _of(resources, "openstack_fw_rule_v1"):
        if r.unknown("source") or r.unknown("destination"):
            continue
        if r.get("action") == "allow" and r.get("enabled", True) and \
                not r.get("source") and not r.get("destination"):
            yield (f"Firewall rule '{r.name}' allows unrestricted "
                   f"traffic.", r.rng)


@_os_network("AVD-OPNSTK-0003", "Security group rules should not permit "
             "public ingress", "HIGH",
             "An ingress rule from 0.0.0.0/0 opens the port to the "
             "internet.", "Restrict remote_ip_prefix.")
def _os_sg_ingress(resources):
    for r in _of(resources, "openstack_networking_secgroup_rule_v2"):
        if r.get("direction") == "ingress" and \
                _public_cidr(r.get("cidr", "")):
            yield (f"Security group rule '{r.name}' allows ingress from "
                   f"anywhere.", r.attr_rng("cidr"))


@_os_network("AVD-OPNSTK-0004", "Security group rules should not permit "
             "public egress", "HIGH",
             "An egress rule to 0.0.0.0/0 allows exfiltration "
             "anywhere.", "Restrict remote_ip_prefix.")
def _os_sg_egress(resources):
    for r in _of(resources, "openstack_networking_secgroup_rule_v2"):
        if r.get("direction") == "egress" and \
                _public_cidr(r.get("cidr", "")):
            yield (f"Security group rule '{r.name}' allows egress to "
                   f"anywhere.", r.attr_rng("cidr"))


@_os_network("AVD-OPNSTK-0005", "Security groups should have a "
             "description", "LOW",
             "Descriptions document rule intent for audits.",
             "Add a description.")
def _os_sg_desc(resources):
    for r in _of(resources, "openstack_networking_secgroup_v2"):
        if r.unknown("description"):
            continue
        if not r.get("description"):
            yield (f"Security group '{r.name}' has no description.",
                   r.rng)


# ---------------------------------------------------------------------
# GitHub checks
# ---------------------------------------------------------------------

_git_repos = _reg("GitHub", "repositories")
_git_branch = _reg("GitHub", "branch_protections")
_git_secrets = _reg("GitHub", "actions")


@_git_repos("AVD-GIT-0001", "Repositories should be private", "HIGH",
            "Public repositories expose source and history to everyone.",
            "Set visibility = private.")
def _git_private(resources):
    for r in _of(resources, "github_repository"):
        if r.get("public") is True:
            yield (f"Repository '{r.name}' is public.",
                   r.attr_rng("public"))


@_git_repos("AVD-GIT-0003", "Repositories should enable vulnerability "
            "alerts", "MEDIUM",
            "Vulnerability alerts surface known-vulnerable "
            "dependencies.", "Set vulnerability_alerts = true.")
def _git_vuln_alerts(resources):
    for r in _of(resources, "github_repository"):
        if r.get("archived") is True or r.unknown("vulnerability_alerts"):
            continue
        if r.get("vulnerability_alerts") is not True:
            yield (f"Repository '{r.name}' does not enable vulnerability "
                   f"alerts.", r.attr_rng("vulnerability_alerts"))


@_git_branch("AVD-GIT-0002", "Branch protections should require signed "
             "commits", "HIGH",
             "Signed commits authenticate the author of each change.",
             "Set require_signed_commits = true.")
def _git_signed(resources):
    for r in _of(resources, "github_branch_protection"):
        if r.unknown("require_signed_commits"):
            continue
        if r.get("require_signed_commits") is not True:
            yield (f"Branch protection '{r.name}' does not require "
                   f"signed commits.",
                   r.attr_rng("require_signed_commits"))


@_git_secrets("AVD-GIT-0004", "Actions secrets should not have plaintext "
              "values", "HIGH",
              "plaintext_value stores the secret unencrypted in state "
              "and source.", "Use encrypted_value.")
def _git_plaintext(resources):
    for r in _of(resources, "github_actions_environment_secret"):
        if r.get("plaintext_value"):
            yield (f"Actions secret '{r.name}' is set from a plaintext "
                   f"value.", r.attr_rng("plaintext_value"))


# ---------------------------------------------------------------------
# Oracle / CloudStack checks
# ---------------------------------------------------------------------

_oci_compute = _reg("Oracle", "compute")
_cs_compute = _reg("CloudStack", "compute")


@_oci_compute("AVD-OCI-0001", "Compute IP reservations should not use "
              "the public pool", "HIGH",
              "Addresses from the public-ippool are internet-reachable.",
              "Reserve from a private pool.")
def _oci_public_pool(resources):
    for r in _of(resources, "opc_compute_ip_address_reservation"):
        if r.get("pool") == "public-ippool":
            yield (f"IP reservation '{r.name}' draws from the public "
                   f"pool.", r.attr_rng("pool"))


_SENSITIVE_RE = re.compile(
    r"(?i)(password|passwd|secret|aws_access_key_id|aws_secret_access_key"
    r"|api[_-]?key|private[_-]?key|token)\s*[=:]")


@_cs_compute("AVD-CLDSTK-0001", "Instance user data should not contain "
             "sensitive information", "HIGH",
             "user_data is readable by anyone who can describe the "
             "instance.", "Deliver credentials via a secrets manager.")
def _cs_user_data(resources):
    import base64
    for r in _of(resources, "cloudstack_instance"):
        data = r.get("user_data", "")
        if not isinstance(data, str) or not data:
            continue
        decoded = data
        try:
            raw = base64.b64decode(data, validate=True)
            decoded = raw.decode("utf-8", errors="replace")
        except Exception:
            pass
        if _SENSITIVE_RE.search(decoded):
            yield (f"Instance '{r.name}' embeds sensitive data in "
                   f"user_data.", r.attr_rng("user_data"))
