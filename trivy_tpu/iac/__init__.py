"""IaC misconfiguration engine (reference pkg/iac, 47k LoC of Go).

The reference evaluates rego policies (trivy-checks) over typed cloud
state adapted from parsed IaC files (pkg/iac/{scanners,adapters,
providers,rego}).  This package is the native redesign: per-format
parsers that retain source positions, adapters into a lightweight
position-carrying cloud-state model, and Python check functions keyed by
the published AVD IDs so findings line up with the reference's output.

Scanners (reference pkg/iac/scanners/*):
  kubernetes  — manifest checks (KSV series)
  cloudformation — YAML/JSON templates with intrinsics (AVD-AWS series)
  terraform   — HCL2 parse + eval (AVD-AWS series, shared checks)
  dockerfile  — lives in trivy_tpu.misconf.dockerfile (DS series)
File-type detection mirrors pkg/iac/detection/detect.go.
"""

from . import detection  # noqa: F401
from .detection import detect_config_type  # noqa: F401
