"""CloudFormation template scanner (reference
pkg/iac/scanners/cloudformation + adapters/cloudformation).

Parses YAML (with intrinsic short forms) or JSON templates, resolves
Ref/Sub/Join against parameter defaults, adapts resources into the
shared cloud-state model, and runs the AVD-AWS checks."""

from __future__ import annotations

import json
import re

from .cloud import UNKNOWN, Attr, CloudResource, Unknown, run_aws_checks
from .yamlpos import PosDict, load_documents, value_range


def _params(template):
    out = {}
    params = template.get("Parameters")
    if isinstance(params, dict):
        for name, spec in params.items():
            if isinstance(spec, dict) and "Default" in spec:
                out[name] = spec["Default"]
    return out


_SUB_RE = re.compile(r"\$\{([^}]+)\}")


def _resolve(value, params, depth=0):
    """Evaluate CFN intrinsics to a concrete value or UNKNOWN."""
    if depth > 20:
        # self-referential parameter defaults (P: {Default: !Ref P})
        return UNKNOWN
    if isinstance(value, dict) and len(value) == 1:
        (key, arg), = value.items()
        if key == "Ref":
            if arg in params:
                return _resolve(params[arg], params, depth + 1)
            if isinstance(arg, str) and arg.startswith("AWS::"):
                return {"AWS::Region": "us-east-1",
                        "AWS::Partition": "aws",
                        "AWS::AccountId": "123456789012"}.get(arg, UNKNOWN)
            return UNKNOWN
        if key == "Fn::Sub":
            tmpl = arg[0] if isinstance(arg, list) and arg else arg
            if not isinstance(tmpl, str):
                return UNKNOWN
            ok = True

            def rep(m):
                nonlocal ok
                v = _resolve({"Ref": m.group(1)}, params, depth + 1)
                if isinstance(v, Unknown):
                    ok = False
                    return ""
                return str(v)
            out = _SUB_RE.sub(rep, tmpl)
            return out if ok else UNKNOWN
        if key == "Fn::Join":
            if isinstance(arg, list) and len(arg) == 2 and \
                    isinstance(arg[1], list):
                parts = [_resolve(p, params, depth + 1) for p in arg[1]]
                if all(not isinstance(p, Unknown) for p in parts):
                    return str(arg[0]).join(str(p) for p in parts)
            return UNKNOWN
        if key == "Condition" or key.startswith("Fn::"):
            # GetAtt/ImportValue/If/Select/FindInMap/... — not statically
            # resolvable here; unknown passes checks like rego undefined
            return UNKNOWN
    if isinstance(value, dict):
        return {k: _resolve(v, params, depth + 1)
                for k, v in value.items()}
    if isinstance(value, list):
        return [_resolve(v, params, depth + 1) for v in value]
    return value


_ACL_MAP = {
    "Private": "private", "PublicRead": "public-read",
    "PublicReadWrite": "public-read-write",
    "AuthenticatedRead": "authenticated-read",
    "LogDeliveryWrite": "log-delivery-write",
    "BucketOwnerRead": "bucket-owner-read",
    "BucketOwnerFullControl": "bucket-owner-full-control",
    "AwsExecRead": "aws-exec-read",
}


def _rng_of(resources_node, logical_id, props, key=None):
    if key is not None and isinstance(props, PosDict):
        r = value_range(props, key)
        if r != (0, 0):
            return r
    if isinstance(resources_node, PosDict):
        return value_range(resources_node, logical_id)
    return (0, 0)


def _sg_rules(props, key, params, res_rng):
    rules = []
    raw = props.get(key)
    if not isinstance(raw, list):
        return rules
    for i, rule in enumerate(raw):
        if not isinstance(rule, dict):
            continue
        rng = value_range(raw, i) if hasattr(raw, "item_lines") \
            else res_rng
        cidrs = []
        for ck in ("CidrIp", "CidrIpv6"):
            v = _resolve(rule.get(ck), params)
            if v is not None and not isinstance(v, Unknown):
                cidrs.append(v)
        rules.append({"cidrs": cidrs,
                      "description": _resolve(rule.get("Description"),
                                              params) or "",
                      "rng": rng})
    return rules


def adapt_cloudformation(template) -> list[CloudResource]:
    """CFN Resources → normalized CloudResource list."""
    params = _params(template)
    resources_node = template.get("Resources")
    if not isinstance(resources_node, dict):
        return []
    out = []
    for logical_id, body in resources_node.items():
        if not isinstance(body, dict):
            continue
        rtype = body.get("Type", "")
        props = body.get("Properties") or {}
        if not isinstance(props, dict):
            props = {}
        res_rng = _rng_of(resources_node, logical_id, None)

        def attr(key, norm=None, default_missing=None):
            """Adapt props[key] → Attr (resolved via intrinsics)."""
            if key not in props:
                return None
            v = _resolve(props[key], params)
            if norm is not None and not isinstance(v, Unknown):
                v = norm(v)
            return Attr(v, _rng_of(resources_node, logical_id, props, key))

        def put(res, name, a):
            if a is not None:
                res.attrs[name] = a

        if rtype == "AWS::S3::Bucket":
            r = CloudResource("aws_s3_bucket", logical_id, rng=res_rng)
            put(r, "acl", attr("AccessControl",
                               lambda v: _ACL_MAP.get(str(v), str(v))))
            if "BucketEncryption" in props:
                r.attrs["encryption_enabled"] = Attr(
                    True, _rng_of(resources_node, logical_id, props,
                                  "BucketEncryption"))
            vc = _resolve(props.get("VersioningConfiguration"), params)
            if isinstance(vc, dict):
                r.attrs["versioning_enabled"] = Attr(
                    vc.get("Status") == "Enabled",
                    _rng_of(resources_node, logical_id, props,
                            "VersioningConfiguration"))
            if "LoggingConfiguration" in props:
                r.attrs["logging_enabled"] = Attr(True)
            pab = _resolve(props.get("PublicAccessBlockConfiguration"),
                           params)
            pab_rng = _rng_of(resources_node, logical_id, props,
                              "PublicAccessBlockConfiguration")
            if isinstance(pab, dict):
                r.attrs["public_access_block"] = Attr({
                    "block_public_acls": pab.get("BlockPublicAcls"),
                    "block_public_policy": pab.get("BlockPublicPolicy"),
                    "ignore_public_acls": pab.get("IgnorePublicAcls"),
                    "restrict_public_buckets":
                        pab.get("RestrictPublicBuckets"),
                }, pab_rng)
            elif isinstance(pab, Unknown):
                r.attrs["public_access_block"] = Attr(UNKNOWN, pab_rng)
            out.append(r)

        elif rtype == "AWS::EC2::SecurityGroup":
            r = CloudResource("aws_security_group", logical_id,
                              rng=res_rng)
            put(r, "description", attr("GroupDescription"))
            r.attrs["ingress"] = Attr(_sg_rules(
                props, "SecurityGroupIngress", params, res_rng))
            r.attrs["egress"] = Attr(_sg_rules(
                props, "SecurityGroupEgress", params, res_rng))
            out.append(r)

        elif rtype == "AWS::EC2::Instance":
            r = CloudResource("aws_instance", logical_id, rng=res_rng)
            mo = _resolve(props.get("MetadataOptions"), params)
            mo_rng = _rng_of(resources_node, logical_id, props,
                             "MetadataOptions")
            if isinstance(mo, dict):
                r.attrs["metadata_options"] = Attr({
                    "http_tokens": mo.get("HttpTokens"),
                    "http_endpoint": mo.get("HttpEndpoint"),
                }, mo_rng)
            elif isinstance(mo, Unknown):
                r.attrs["metadata_options"] = Attr(UNKNOWN, mo_rng)
            bdm = _resolve(props.get("BlockDeviceMappings"), params)
            ebs_devices = []
            if isinstance(bdm, list):
                for m in bdm:
                    if isinstance(m, dict) and isinstance(
                            m.get("Ebs"), dict):
                        ebs_devices.append({
                            "encrypted": m["Ebs"].get("Encrypted"),
                            "rng": _rng_of(resources_node, logical_id,
                                           props, "BlockDeviceMappings")})
            if ebs_devices:
                # CFN has no root/extra split; treat first as root
                r.attrs["root_block_device"] = Attr(
                    ebs_devices[0],
                    _rng_of(resources_node, logical_id, props,
                            "BlockDeviceMappings"))
                r.attrs["ebs_block_device"] = Attr(ebs_devices[1:])
            out.append(r)

        elif rtype == "AWS::EC2::Volume":
            r = CloudResource("aws_ebs_volume", logical_id, rng=res_rng)
            put(r, "encrypted", attr("Encrypted"))
            out.append(r)

        elif rtype == "AWS::RDS::DBInstance":
            r = CloudResource("aws_db_instance", logical_id, rng=res_rng)
            put(r, "storage_encrypted", attr("StorageEncrypted"))
            put(r, "backup_retention_period",
                attr("BackupRetentionPeriod"))
            put(r, "publicly_accessible", attr("PubliclyAccessible"))
            put(r, "replicate_source_db",
                attr("SourceDBInstanceIdentifier"))
            out.append(r)

        elif rtype == "AWS::EFS::FileSystem":
            r = CloudResource("aws_efs_file_system", logical_id,
                              rng=res_rng)
            put(r, "encrypted", attr("Encrypted"))
            out.append(r)

        elif rtype == "AWS::CloudTrail::Trail":
            r = CloudResource("aws_cloudtrail", logical_id, rng=res_rng)
            put(r, "is_multi_region_trail", attr("IsMultiRegionTrail"))
            put(r, "enable_log_file_validation",
                attr("EnableLogFileValidation"))
            put(r, "kms_key_id", attr("KMSKeyId"))
            put(r, "cloud_watch_logs_group_arn",
                attr("CloudWatchLogsLogGroupArn"))
            out.append(r)

        elif rtype == "AWS::ElasticLoadBalancingV2::LoadBalancer":
            r = CloudResource("aws_lb", logical_id, rng=res_rng)
            scheme = _resolve(props.get("Scheme"), params)
            if scheme is not None:
                r.attrs["internal"] = Attr(
                    UNKNOWN if isinstance(scheme, Unknown)
                    else scheme == "internal",
                    _rng_of(resources_node, logical_id, props, "Scheme"))
            put(r, "load_balancer_type", attr("Type"))
            attrs_list = _resolve(props.get("LoadBalancerAttributes"),
                                  params)
            if isinstance(attrs_list, list):
                for a in attrs_list:
                    if isinstance(a, dict) and a.get("Key") == \
                            "routing.http.drop_invalid_header_fields." \
                            "enabled":
                        r.attrs["drop_invalid_header_fields"] = Attr(
                            str(a.get("Value")).lower() == "true")
            out.append(r)

        elif rtype in ("AWS::IAM::Policy", "AWS::IAM::ManagedPolicy"):
            r = CloudResource("aws_iam_policy", logical_id, rng=res_rng)
            put(r, "policy_document", attr("PolicyDocument"))
            out.append(r)

        elif rtype == "AWS::EKS::Cluster":
            r = CloudResource("aws_eks_cluster", logical_id,
                              rng=res_rng)
            logging = _resolve(props.get("Logging"), params)
            if isinstance(logging, Unknown):
                r.attrs["enabled_log_types"] = Attr(UNKNOWN)
            elif isinstance(logging, dict):
                types = []
                cl = logging.get("ClusterLogging")
                if isinstance(cl, dict):
                    for entry in cl.get("EnabledTypes") or []:
                        if isinstance(entry, dict):
                            # unresolved Type stays non-str so the
                            # audit check's element guard skips it
                            types.append(entry.get("Type"))
                r.attrs["enabled_log_types"] = Attr(types)
            enc = _resolve(props.get("EncryptionConfig"), params)
            if isinstance(enc, Unknown):
                r.attrs["secrets_encrypted"] = Attr(UNKNOWN)
            elif isinstance(enc, list):
                encrypted = any(
                    isinstance(e, dict) and
                    "SECRETS" in [str(x).upper() for x in
                                  (e.get("Resources") or [])]
                    for e in enc)
                r.attrs["secrets_encrypted"] = Attr(encrypted)
            vpc = _resolve(props.get("ResourcesVpcConfig"), params)
            # AWS default: the endpoint is public
            pub = True
            if isinstance(vpc, Unknown):
                pub = UNKNOWN
            elif isinstance(vpc, dict):
                pub = vpc.get("EndpointPublicAccess", True)
                cidrs = vpc.get("PublicAccessCidrs")
                if isinstance(cidrs, Unknown) or (
                        isinstance(cidrs, list) and
                        any(not isinstance(c, str) for c in cidrs)):
                    r.attrs["public_access_cidrs"] = Attr(UNKNOWN)
                elif isinstance(cidrs, list):
                    r.attrs["public_access_cidrs"] = Attr(cidrs)
            r.attrs["endpoint_public_access"] = Attr(pub)
            out.append(r)

        elif rtype == "AWS::ECR::Repository":
            r = CloudResource("aws_ecr_repository", logical_id,
                              rng=res_rng)
            scan_cfg = _resolve(props.get("ImageScanningConfiguration"),
                                params)
            if isinstance(scan_cfg, Unknown):
                r.attrs["scan_on_push"] = Attr(UNKNOWN)
            elif isinstance(scan_cfg, dict):
                # raw value: _truthy/_falsy handle string booleans
                r.attrs["scan_on_push"] = Attr(
                    scan_cfg.get("ScanOnPush"))
            put(r, "image_tag_mutability", attr("ImageTagMutability"))
            out.append(r)

        elif rtype == "AWS::KMS::Key":
            r = CloudResource("aws_kms_key", logical_id, rng=res_rng)
            put(r, "enable_key_rotation", attr("EnableKeyRotation"))
            put(r, "key_usage", attr("KeyUsage"))
            out.append(r)

        elif rtype == "AWS::SQS::Queue":
            r = CloudResource("aws_sqs_queue", logical_id, rng=res_rng)
            put(r, "kms_master_key_id", attr("KmsMasterKeyId"))
            put(r, "sqs_managed_sse_enabled", attr("SqsManagedSseEnabled"))
            out.append(r)

        elif rtype == "AWS::SNS::Topic":
            r = CloudResource("aws_sns_topic", logical_id, rng=res_rng)
            put(r, "kms_master_key_id", attr("KmsMasterKeyId"))
            out.append(r)

        elif rtype == "AWS::DynamoDB::Table":
            r = CloudResource("aws_dynamodb_table", logical_id,
                              rng=res_rng)
            pitr = _resolve(
                props.get("PointInTimeRecoverySpecification"), params)
            if isinstance(pitr, Unknown):
                r.attrs["pitr_enabled"] = Attr(UNKNOWN)
            else:
                r.attrs["pitr_enabled"] = Attr(
                    pitr.get("PointInTimeRecoveryEnabled")
                    if isinstance(pitr, dict) else False)
            sse = _resolve(props.get("SSESpecification"), params)
            if isinstance(sse, Unknown):
                r.attrs["sse_kms_key"] = Attr(UNKNOWN)
            else:
                r.attrs["sse_kms_key"] = Attr(
                    sse.get("KMSMasterKeyId", "")
                    if isinstance(sse, dict) else "")
            out.append(r)

        elif rtype == "AWS::Redshift::Cluster":
            r = CloudResource("aws_redshift_cluster", logical_id,
                              rng=res_rng)
            put(r, "encrypted", attr("Encrypted"))
            put(r, "subnet_group", attr("ClusterSubnetGroupName"))
            out.append(r)

        elif rtype == "AWS::ElastiCache::ReplicationGroup":
            r = CloudResource("aws_elasticache_replication_group",
                              logical_id, rng=res_rng)
            put(r, "at_rest_encryption_enabled",
                attr("AtRestEncryptionEnabled"))
            put(r, "transit_encryption_enabled",
                attr("TransitEncryptionEnabled"))
            out.append(r)

        elif rtype == "AWS::Lambda::Function":
            r = CloudResource("aws_lambda_function", logical_id,
                              rng=res_rng)
            tracing = _resolve(props.get("TracingConfig"), params)
            if isinstance(tracing, Unknown):
                r.attrs["tracing_mode"] = Attr(UNKNOWN)
            else:
                r.attrs["tracing_mode"] = Attr(
                    tracing.get("Mode", "PassThrough")
                    if isinstance(tracing, dict) else "PassThrough")
            out.append(r)

    return out


def scan_cloudformation(path: str, content: bytes, lines=None,
                        docs=None) -> tuple[list, int]:
    text = content.decode("utf-8", errors="replace")
    if docs is None:
        if path.endswith(".json"):
            try:
                template = json.loads(text)
            except Exception:
                return [], 0
            docs = [template]
        else:
            docs = load_documents(text)
    resources = []
    for doc in docs:
        if isinstance(doc, dict) and isinstance(doc.get("Resources"),
                                                dict):
            resources.extend(adapt_cloudformation(doc))
    if not resources:
        return [], 0
    return run_aws_checks(resources, "cloudformation", text)
