"""Extension module system (reference pkg/module — WASM analyzers and
post-scan hooks; module.go Register:411, PostScan:478).

The reference compiles modules to WASM and runs them under wazero; our
TPU-native analog loads Python modules from `<home>/modules/*.py`, which
is both the idiomatic extension mechanism for a Python host framework
and strictly more capable (modules may call into jax). The module API
mirrors the WASM one (examples/module/spring4shell):

    name = "spring4shell"            # module identity
    version = 1
    # per-file analyzer half (optional)
    required_files = [r"\\.jar$"]     # regexes over file paths
    def analyze(path, content): ...  # → dict merged as custom resource
    # post-scan half (optional)
    post_scan_spec = {"action": "update", "ids": ["CVE-2022-22965"]}
    def post_scan(results): ...      # → mutated results list

Actions: insert (add findings), update (modify the listed IDs), delete
(remove the listed IDs) — serialize.PostScanSpec.
"""

from __future__ import annotations

import importlib.util
import os
import re

from .log import logger


class LoadedModule:
    def __init__(self, pymod, path: str):
        self.pymod = pymod
        self.path = path
        self.name = getattr(pymod, "name", os.path.basename(path))
        self.version = getattr(pymod, "version", 1)
        pats = getattr(pymod, "required_files", [])
        self.required_res = [re.compile(p) for p in pats]
        self.analyze = getattr(pymod, "analyze", None)
        self.post_scan = getattr(pymod, "post_scan", None)
        self.post_scan_spec = getattr(pymod, "post_scan_spec", {}) or {}

    def required(self, path: str) -> bool:
        return any(r.search(path) for r in self.required_res)


_loaded: list[LoadedModule] = []


def modules_dir() -> str:
    base = os.environ.get("TRIVY_TPU_HOME") or \
        os.path.join(os.path.expanduser("~"), ".trivy-tpu")
    return os.path.join(base, "modules")


def load_modules(dir_: str | None = None) -> list[LoadedModule]:
    """Import every .py in the modules dir and register its hooks
    (reference module.go NewManager + Register)."""
    global _loaded
    _loaded = []
    root = dir_ or modules_dir()
    if not os.path.isdir(root):
        return _loaded
    for fname in sorted(os.listdir(root)):
        if not fname.endswith(".py") or fname.startswith("_"):
            continue
        fpath = os.path.join(root, fname)
        try:
            spec = importlib.util.spec_from_file_location(
                f"trivy_tpu_module_{fname[:-3]}", fpath)
            pymod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(pymod)
        except Exception as e:
            logger.warning("failed to load module %s: %s", fpath, e)
            continue
        m = LoadedModule(pymod, fpath)
        _loaded.append(m)
    _register_analyzers()
    return _loaded


def loaded_modules() -> list[LoadedModule]:
    return _loaded


def clear_modules() -> None:
    global _loaded
    _loaded = []
    _register_analyzers()


def _register_analyzers() -> None:
    """Expose module analyze() hooks through the fanal analyzer registry
    (the WASM modules register into the same registry — module.go:411)."""
    from .fanal.analyzers import set_module_analyzers
    set_module_analyzers([m for m in _loaded if m.analyze])


def apply_post_scan(results: list) -> list:
    """Run post-scan hooks over detection results (reference
    post.Scan called at pkg/scanner/local/scan.go:162)."""
    for m in _loaded:
        if m.post_scan is None:
            continue
        action = str(m.post_scan_spec.get("action", "")).lower()
        ids = set(m.post_scan_spec.get("ids", []))
        try:
            if action in ("update", "delete") and ids:
                relevant = _findings_with_ids(results, ids)
                out = m.post_scan(relevant)
                _apply_updates(results, out or [], ids,
                               delete=(action == "delete"))
            else:
                out = m.post_scan(results)
                if out is not None:
                    results = out
        except Exception as e:
            logger.warning("module %s post_scan failed: %s", m.name, e)
    return results


def _findings_with_ids(results, ids):
    out = []
    for res in results:
        vulns = [v for v in res.vulnerabilities
                 if v.vulnerability_id in ids]
        if vulns:
            out.append({"target": res.target, "vulnerabilities": vulns})
    return out


def _apply_updates(results, updated, ids, delete: bool):
    if delete:
        for res in results:
            res.vulnerabilities = [
                v for v in res.vulnerabilities
                if v.vulnerability_id not in ids]
        return
    # update: replace matching findings with the module's versions
    by_key = {}
    for entry in updated:
        for v in entry.get("vulnerabilities", []):
            by_key[(entry.get("target", ""), v.vulnerability_id,
                    v.pkg_name)] = v
    for res in results:
        res.vulnerabilities = [
            by_key.get((res.target, v.vulnerability_id, v.pkg_name), v)
            for v in res.vulnerabilities]
