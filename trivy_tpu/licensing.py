"""License scanning (reference pkg/licensing).

Round-1 scope matches the reference's default mode: package-declared
licenses are categorized and reported (scanLicenses,
pkg/scanner/local/scan.go:280); full-text file classification
(--license-full, google/licenseclassifier) is the expensive opt-in path
and lands later.

Category → severity mapping follows pkg/licensing/scanner.go:23."""

from __future__ import annotations

from . import types as T

CATEGORY_SEVERITY = {
    "forbidden": "CRITICAL",
    "restricted": "HIGH",
    "reciprocal": "MEDIUM",
    "notice": "LOW",
    "permissive": "LOW",
    "unencumbered": "LOW",
    "unknown": "UNKNOWN",
}

# Classification of common SPDX ids into google/licenseclassifier-style
# categories (pkg/licensing/category data).
_CATEGORIES = {
    "forbidden": {"AGPL-1.0", "AGPL-3.0", "AGPL-3.0-only",
                  "AGPL-3.0-or-later", "CC-BY-NC-1.0", "CC-BY-NC-2.0",
                  "CC-BY-NC-3.0", "CC-BY-NC-4.0", "CC-BY-NC-ND-4.0",
                  "CC-BY-NC-SA-4.0", "Commons-Clause", "WTFPL"},
    "restricted": {"GPL-1.0", "GPL-2.0", "GPL-2.0-only", "GPL-2.0+",
                   "GPL-2.0-or-later", "GPL-3.0", "GPL-3.0-only",
                   "GPL-3.0-or-later", "LGPL-2.0", "LGPL-2.1",
                   "LGPL-2.1-only", "LGPL-2.1-or-later", "LGPL-3.0",
                   "LGPL-3.0-only", "LGPL-3.0-or-later", "CC-BY-ND-4.0",
                   "CC-BY-SA-4.0", "NPL-1.0", "NPL-1.1", "OSL-3.0",
                   "QPL-1.0", "Sleepycat"},
    "reciprocal": {"MPL-1.0", "MPL-1.1", "MPL-2.0", "EPL-1.0", "EPL-2.0",
                   "CDDL-1.0", "CDDL-1.1", "CPL-1.0", "APSL-2.0",
                   "Ruby", "OSL-1.0", "IPL-1.0", "ErlPL-1.1"},
    "notice": {"Apache-2.0", "Apache-1.1", "Apache-1.0", "MIT", "BSD-2-Clause",
               "BSD-3-Clause", "BSD-4-Clause", "ISC", "Artistic-1.0",
               "Artistic-2.0", "Zlib", "PSF-2.0", "Python-2.0", "NCSA",
               "OpenSSL", "PHP-3.0", "PHP-3.01", "W3C", "X11", "Xnet",
               "AFL-3.0", "BSL-1.0", "CC-BY-4.0", "FTL", "LPL-1.02",
               "MS-PL", "Unicode-DFS-2015", "Unicode-DFS-2016",
               "UPL-1.0"},
    "unencumbered": {"CC0-1.0", "Unlicense", "0BSD", "blessing"},
    "permissive": set(),
}

_NORMALIZE = {
    "apache 2.0": "Apache-2.0", "apache2": "Apache-2.0",
    "apache-2": "Apache-2.0", "apache license 2.0": "Apache-2.0",
    "asl 2.0": "Apache-2.0", "apache software license": "Apache-2.0",
    "mit license": "MIT", "the mit license": "MIT",
    "bsd": "BSD-3-Clause", "new bsd license": "BSD-3-Clause",
    "bsd license": "BSD-3-Clause", "bsd-3": "BSD-3-Clause",
    "gplv2": "GPL-2.0", "gplv2+": "GPL-2.0-or-later",
    "gplv3": "GPL-3.0", "gplv3+": "GPL-3.0-or-later",
    "lgplv2": "LGPL-2.0", "lgplv2+": "LGPL-2.1-or-later",
    "lgplv3": "LGPL-3.0",
    "public domain": "Unlicense", "zlib/libpng license": "Zlib",
    "mpl 2.0": "MPL-2.0",
}


def normalize(name: str) -> str:
    return _NORMALIZE.get(name.strip().lower(), name.strip())


def categorize(name: str) -> str:
    n = normalize(name)
    for cat, names in _CATEGORIES.items():
        if n in names:
            return cat
    return "unknown"


def scan_license_name(name: str, categories: dict | None = None
                      ) -> tuple[str, str]:
    """→ (category, severity) for a RAW license name — the reference's
    licensing.Scanner.Scan does no normalization ("MIT License" is
    unknown, "MIT" is notice; scan.go:292)."""
    cat = _custom_category(name, categories)
    if cat is None:
        cat = "unknown"
        for c, names in _CATEGORIES.items():
            if name in names:
                cat = c
                break
    return cat, CATEGORY_SEVERITY.get(cat, "UNKNOWN")


def scan_packages(detail_packages: list, applications: list,
                  categories: dict | None = None) -> list[T.DetectedLicense]:
    """Declared-license scan over OS packages + applications.

    `categories` optionally overrides category membership per the
    --license-* flags (reference pkg/flag/license_flags.go)."""
    out: list[T.DetectedLicense] = []

    def _emit(pkg: T.Package, file_path: str = ""):
        for lic in pkg.licenses:
            cat, sev = scan_license_name(lic, categories)
            out.append(T.DetectedLicense(
                severity=sev, category=cat, pkg_name=pkg.name,
                file_path=file_path or pkg.file_path,
                name=lic, confidence=1.0,
            ))

    for pkg in detail_packages:
        _emit(pkg)
    for app in applications:
        for pkg in app.packages:
            _emit(pkg, app.file_path)
    return out


def _custom_category(name: str, categories: dict | None):
    if not categories:
        return None
    for cat, names in categories.items():
        if name in names:
            return cat
    return None


# ---- full-text classification (reference pkg/licensing/classifier.go
# via google/licenseclassifier; here: distinctive-phrase scoring) ------

# Distinctive phrases per license, drawn from the canonical public
# texts. A phrase "hits" when present in the normalized input; the
# confidence is the hit fraction. Phrases are chosen to be mutually
# discriminative (e.g. only Apache-2.0 contains "grant of patent
# license"; only GPL-3.0 has "basic permissions").
_CLASSIFY_PHRASES = {
    "MIT": [
        "permission is hereby granted free of charge",
        "to deal in the software without restriction",
        "the above copyright notice and this permission notice shall "
        "be included in all copies",
        "the software is provided as is without warranty of any kind",
    ],
    "Apache-2.0": [
        "apache license",
        "grant of patent license",
        "grant of copyright license",
        "unless required by applicable law or agreed to in writing",
        "limitations under the license",
    ],
    "GPL-3.0": [
        "gnu general public license",
        "version 3",
        "basic permissions",
        "protecting users legal rights from anti circumvention law",
        "conveying non source forms",
    ],
    "GPL-2.0": [
        "gnu general public license",
        "version 2",
        "the licenses for most software are designed to take away",
        "we protect your rights with two steps",
    ],
    "LGPL-2.1": [
        "gnu lesser general public license",
        "version 2 1",
        "when we speak of free software we are referring to freedom",
    ],
    "BSD-3-Clause": [
        "redistribution and use in source and binary forms",
        "redistributions of source code must retain the above "
        "copyright notice",
        "neither the name of",
        "this software is provided by the copyright holders and "
        "contributors as is",
    ],
    "BSD-2-Clause": [
        "redistribution and use in source and binary forms",
        "redistributions in binary form must reproduce the above "
        "copyright notice",
        "this software is provided by the copyright holders and "
        "contributors as is",
    ],
    "ISC": [
        "permission to use copy modify and or distribute this "
        "software for any purpose",
        "the software is provided as is and the author disclaims all "
        "warranties",
    ],
    "MPL-2.0": [
        "mozilla public license",
        "version 2 0",
        "means covered software of that particular contributor",
        "source code form",
    ],
    "Unlicense": [
        "this is free and unencumbered software released into the "
        "public domain",
        "anyone is free to copy modify publish use compile sell or "
        "distribute this software",
    ],
}

import re as _re

_NORM_RE = _re.compile(r"[^a-z0-9]+")


def _normalize_text(text: str) -> str:
    return " " + _NORM_RE.sub(" ", text.lower()).strip() + " "


def classify_text(text: str, confidence_level: float = 0.6):
    """→ (license name, confidence) of the best-scoring license, or
    None below the threshold (Classify's confidenceLevel gate,
    classifier.go:35-58)."""
    norm = _normalize_text(text)
    best = None
    for name, phrases in _CLASSIFY_PHRASES.items():
        hits = sum(1 for p in phrases if " " + p + " " in norm)
        conf = hits / len(phrases)
        # tie-break: BSD-3 over BSD-2 and GPL-3 over GPL-2 need full
        # distinctive coverage, so strictly-greater keeps the more
        # specific match when it scores higher
        if conf > confidence_level and \
                (best is None or conf > best[1]):
            best = (name, conf)
    return best


LICENSE_FILE_NAMES = {
    "license", "license.txt", "license.md", "licence", "licence.txt",
    "copying", "copying.txt", "notice", "copyright",
}


def classify_license_file(path: str, content: bytes,
                          confidence_level: float = 0.6
                          ) -> list[T.DetectedLicense]:
    """File-level classification for --license-full → DetectedLicense
    findings (reference pkg/fanal/analyzer/licensing → Classify)."""
    base = path.rsplit("/", 1)[-1].lower()
    if base not in LICENSE_FILE_NAMES:
        return []
    text = content.decode("utf-8", errors="replace")
    hit = classify_text(text, confidence_level)
    if hit is None:
        return []
    name, conf = hit
    cat = categorize(name)
    return [T.DetectedLicense(
        severity=CATEGORY_SEVERITY.get(cat, "UNKNOWN"),
        category=cat, file_path=path, name=name,
        confidence=round(conf, 2),
        link=f"https://spdx.org/licenses/{name}.html")]
