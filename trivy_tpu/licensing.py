"""License scanning (reference pkg/licensing).

Round-1 scope matches the reference's default mode: package-declared
licenses are categorized and reported (scanLicenses,
pkg/scanner/local/scan.go:280); full-text file classification
(--license-full, google/licenseclassifier) is the expensive opt-in path
and lands later.

Category → severity mapping follows pkg/licensing/scanner.go:23."""

from __future__ import annotations

from . import types as T

CATEGORY_SEVERITY = {
    "forbidden": "CRITICAL",
    "restricted": "HIGH",
    "reciprocal": "MEDIUM",
    "notice": "LOW",
    "permissive": "LOW",
    "unencumbered": "LOW",
    "unknown": "UNKNOWN",
}

# Classification of common SPDX ids into google/licenseclassifier-style
# categories (pkg/licensing/category data).
_CATEGORIES = {
    "forbidden": {"AGPL-1.0", "AGPL-3.0", "AGPL-3.0-only",
                  "AGPL-3.0-or-later", "CC-BY-NC-1.0", "CC-BY-NC-2.0",
                  "CC-BY-NC-3.0", "CC-BY-NC-4.0", "CC-BY-NC-ND-4.0",
                  "CC-BY-NC-SA-4.0", "Commons-Clause", "WTFPL"},
    "restricted": {"GPL-1.0", "GPL-2.0", "GPL-2.0-only", "GPL-2.0+",
                   "GPL-2.0-or-later", "GPL-3.0", "GPL-3.0-only",
                   "GPL-3.0-or-later", "LGPL-2.0", "LGPL-2.1",
                   "LGPL-2.1-only", "LGPL-2.1-or-later", "LGPL-3.0",
                   "LGPL-3.0-only", "LGPL-3.0-or-later", "CC-BY-ND-4.0",
                   "CC-BY-SA-4.0", "NPL-1.0", "NPL-1.1", "OSL-3.0",
                   "QPL-1.0", "Sleepycat"},
    "reciprocal": {"MPL-1.0", "MPL-1.1", "MPL-2.0", "EPL-1.0", "EPL-2.0",
                   "CDDL-1.0", "CDDL-1.1", "CPL-1.0", "APSL-2.0",
                   "Ruby", "OSL-1.0", "IPL-1.0", "ErlPL-1.1"},
    "notice": {"Apache-2.0", "Apache-1.1", "Apache-1.0", "MIT", "BSD-2-Clause",
               "BSD-3-Clause", "BSD-4-Clause", "ISC", "Artistic-1.0",
               "Artistic-2.0", "Zlib", "PSF-2.0", "Python-2.0", "NCSA",
               "OpenSSL", "PHP-3.0", "PHP-3.01", "W3C", "X11", "Xnet",
               "AFL-3.0", "BSL-1.0", "CC-BY-4.0", "FTL", "LPL-1.02",
               "MS-PL", "Unicode-DFS-2015", "Unicode-DFS-2016",
               "UPL-1.0"},
    "unencumbered": {"CC0-1.0", "Unlicense", "0BSD", "blessing"},
    "permissive": set(),
}

_NORMALIZE = {
    "apache 2.0": "Apache-2.0", "apache2": "Apache-2.0",
    "apache-2": "Apache-2.0", "apache license 2.0": "Apache-2.0",
    "asl 2.0": "Apache-2.0", "apache software license": "Apache-2.0",
    "mit license": "MIT", "the mit license": "MIT",
    "bsd": "BSD-3-Clause", "new bsd license": "BSD-3-Clause",
    "bsd license": "BSD-3-Clause", "bsd-3": "BSD-3-Clause",
    "gplv2": "GPL-2.0", "gplv2+": "GPL-2.0-or-later",
    "gplv3": "GPL-3.0", "gplv3+": "GPL-3.0-or-later",
    "lgplv2": "LGPL-2.0", "lgplv2+": "LGPL-2.1-or-later",
    "lgplv3": "LGPL-3.0",
    "public domain": "Unlicense", "zlib/libpng license": "Zlib",
    "mpl 2.0": "MPL-2.0",
}


def normalize(name: str) -> str:
    return _NORMALIZE.get(name.strip().lower(), name.strip())


def categorize(name: str) -> str:
    n = normalize(name)
    for cat, names in _CATEGORIES.items():
        if n in names:
            return cat
    return "unknown"


def scan_packages(detail_packages: list, applications: list,
                  categories: dict | None = None) -> list[T.DetectedLicense]:
    """Declared-license scan over OS packages + applications.

    `categories` optionally overrides category membership per the
    --license-* flags (reference pkg/flag/license_flags.go)."""
    out: list[T.DetectedLicense] = []

    def _emit(pkg: T.Package, file_path: str = ""):
        for lic in pkg.licenses:
            name = normalize(lic)
            cat = _custom_category(name, categories) or categorize(name)
            out.append(T.DetectedLicense(
                severity=CATEGORY_SEVERITY.get(cat, "UNKNOWN"),
                category=cat,
                pkg_name=pkg.name,
                file_path=file_path or pkg.file_path,
                name=name,
                link=f"https://spdx.org/licenses/{name}.html"
                if categorize(name) != "unknown" else "",
            ))

    for pkg in detail_packages:
        _emit(pkg)
    for app in applications:
        for pkg in app.packages:
            _emit(pkg, app.file_path)
    return out


def _custom_category(name: str, categories: dict | None):
    if not categories:
        return None
    for cat, names in categories.items():
        if name in names:
            return cat
    return None
