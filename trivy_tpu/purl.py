"""package-url construction (reference pkg/purl/purl.go): maps internal
package type + fields to pkg:<type>/<namespace>/<name>@<version>."""

from __future__ import annotations

from urllib.parse import quote

from . import types as T

_OS_DISTROS = {"alpine", "wolfi", "chainguard", "debian", "ubuntu",
               "redhat", "centos", "rocky", "alma", "amazon", "oracle",
               "fedora", "suse", "opensuse", "photon", "cbl-mariner"}

_TYPE_MAP = {
    "alpine": "apk", "wolfi": "apk", "chainguard": "apk",
    "debian": "deb", "ubuntu": "deb",
    "redhat": "rpm", "centos": "rpm", "rocky": "rpm", "alma": "rpm",
    "amazon": "rpm", "oracle": "rpm", "fedora": "rpm", "suse": "rpm",
    "opensuse": "rpm", "photon": "rpm", "cbl-mariner": "rpm",
    "python-pkg": "pypi", "pip": "pypi", "pipenv": "pypi", "poetry": "pypi",
    "npm": "npm", "node-pkg": "npm", "yarn": "npm", "pnpm": "npm",
    "gomod": "golang", "gobinary": "golang",
    "cargo": "cargo", "rustbinary": "cargo",
    "composer": "composer", "bundler": "gem", "gemspec": "gem",
    "jar": "maven", "pom": "maven", "gradle": "maven",
    "nuget": "nuget", "dotnet-core": "nuget", "packages-props": "nuget",
    "conan": "conan", "swift": "swift", "cocoapods": "cocoapods",
    "pub": "pub", "hex": "hex", "conda-pkg": "conda",
    "julia": "julia",
}


def purl_for_package(pkg_type: str, pkg: T.Package,
                     os_info: T.OS | None = None) -> str:
    ptype = _TYPE_MAP.get(pkg_type, "")
    if not ptype:
        return ""
    name = pkg.name
    namespace = ""
    if ptype == "deb":
        namespace = pkg_type  # debian/ubuntu
    elif ptype == "apk":
        namespace = "alpine" if pkg_type == "alpine" else pkg_type
    elif ptype == "rpm":
        namespace = pkg_type
    elif ptype in ("golang", "npm", "composer", "swift") and "/" in name:
        # swift names are repo URLs: host/org/repo → namespace host/org
        # (reference purl.go TypeSwift via swiftNamespace)
        namespace, name = name.rsplit("/", 1)
    elif ptype == "maven" and ":" in name:
        namespace, name = name.split(":", 1)
    if ptype == "pypi":
        # purl spec: PyPI names lowercase with '_' replaced by '-'
        # (reference purl.go purlType TypePyPi handling)
        name = name.lower().replace("_", "-")
    if ptype in ("deb", "rpm", "apk"):
        # OS purl versions carry epoch as a qualifier, not a prefix
        # (purl.go: version-release; e.g. openssl-libs@1.0.2k-16.el7
        # ?epoch=1 in centos-7.json.golden)
        version = pkg.version + (f"-{pkg.release}" if pkg.release else "")
    else:
        version = pkg.version
    parts = ["pkg:", ptype, "/"]
    if namespace:
        parts.append(quote(namespace, safe="/") + "/")
    parts.append(quote(name, safe=""))
    if version:
        parts.append("@" + quote(version, safe=""))
    # qualifiers in purl canonical (alphabetical) order:
    # arch < distro < epoch
    quals = []
    if pkg.arch:
        quals.append(f"arch={pkg.arch}")
    if os_info is not None and os_info.detected and os_info.name:
        if ptype == "apk":
            quals.append(f"distro={os_info.name}")
        else:
            quals.append(f"distro={os_info.family}-{os_info.name}")
    if pkg.epoch:
        quals.append(f"epoch={pkg.epoch}")
    if quals:
        parts.append("?" + "&".join(quals))
    return "".join(parts)
