"""package-url construction (reference pkg/purl/purl.go): maps internal
package type + fields to pkg:<type>/<namespace>/<name>@<version>."""

from __future__ import annotations

from urllib.parse import quote

from . import types as T

_OS_DISTROS = {"alpine", "wolfi", "chainguard", "debian", "ubuntu",
               "redhat", "centos", "rocky", "alma", "amazon", "oracle",
               "fedora", "suse", "opensuse", "photon", "cbl-mariner"}

_TYPE_MAP = {
    "alpine": "apk", "wolfi": "apk", "chainguard": "apk",
    "debian": "deb", "ubuntu": "deb",
    "redhat": "rpm", "centos": "rpm", "rocky": "rpm", "alma": "rpm",
    "amazon": "rpm", "oracle": "rpm", "fedora": "rpm", "suse": "rpm",
    "opensuse": "rpm", "photon": "rpm", "cbl-mariner": "rpm",
    "python-pkg": "pypi", "pip": "pypi", "pipenv": "pypi", "poetry": "pypi",
    "npm": "npm", "node-pkg": "npm", "yarn": "npm", "pnpm": "npm",
    "gomod": "golang", "gobinary": "golang",
    "cargo": "cargo", "rustbinary": "cargo",
    "composer": "composer", "bundler": "gem", "gemspec": "gem",
    "jar": "maven", "pom": "maven", "gradle": "maven",
    "nuget": "nuget", "dotnet-core": "nuget", "packages-props": "nuget",
    "conan": "conan", "swift": "swift", "cocoapods": "cocoapods",
    "pub": "pub", "hex": "hex", "conda-pkg": "conda",
    "julia": "julia",
}


def purl_for_package(pkg_type: str, pkg: T.Package) -> str:
    ptype = _TYPE_MAP.get(pkg_type, "")
    if not ptype:
        return ""
    name = pkg.name
    namespace = ""
    if ptype == "deb":
        namespace = pkg_type  # debian/ubuntu
    elif ptype == "apk":
        namespace = "alpine" if pkg_type == "alpine" else pkg_type
    elif ptype == "rpm":
        namespace = pkg_type
    elif ptype in ("golang", "npm", "composer") and "/" in name:
        namespace, name = name.rsplit("/", 1)
    elif ptype == "maven" and ":" in name:
        namespace, name = name.split(":", 1)
    version = pkg.format_version() or pkg.version
    parts = ["pkg:", ptype, "/"]
    if namespace:
        parts.append(quote(namespace, safe="/") + "/")
    parts.append(quote(name, safe=""))
    if version:
        parts.append("@" + quote(version, safe=""))
    quals = []
    if pkg.arch:
        quals.append(f"arch={pkg.arch}")
    if pkg.epoch:
        quals.append(f"epoch={pkg.epoch}")
    if quals:
        parts.append("?" + "&".join(quals))
    return "".join(parts)
