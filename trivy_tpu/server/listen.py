"""Scan server.

Mirrors pkg/rpc/server/listen.go: one HTTP mux exposing the Scanner and
Cache services plus /healthz and /version, with optional token auth
(Trivy-Token header) and a hot-swappable advisory table (the reference
drains in-flight requests around a DB reload, listen.go:129-192; here a
lock swap suffices because the table is immutable once built).

Routes speak both Twirp encodings (POST /twirp/<svc>/<Method>): JSON
bodies with proto field names, and application/protobuf binary for
drop-in Go clients (rpc/scanner/service.proto, rpc/cache/service.proto,
handwritten codec in protowire.py). Batches accumulate per request;
every Scan
request runs the batched device join over all its target's packages at
once (SURVEY.md §2.7 P4/P5)."""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import __version__, types as T
from ..fanal.cache import blob_from_json
from ..log import get as _get_logger
from ..obs import SLO, device_status, new_trace, span
from ..obs import cost as _cost
from ..obs.perf import debug_perf_payload, debug_profile_payload
from ..obs.recorder import (debug_incidents_payload,
                            debug_traces_payload)
from ..resilience import (AdmissionQueue, Deadline, GUARD, Shed,
                          failpoint)
from ..scanner import LocalScanner
# wire-header names live in the package __init__ so the CLIENT can
# import them without pulling in this module's server stack;
# re-exported here for the existing `listen.TOKEN_HEADER` readers
from . import (COST_HEADER, DB_VERSION_HEADER,  # noqa: F401
               DEADLINE_HEADER, PARENT_SPAN_HEADER, ROUTE_DESCRIPTORS,
               TENANT_HEADER, TOKEN_HEADER, TRACE_HEADER)

_log = _get_logger("server")


@dataclass
class MeshOptions:
    """Mesh-mode knobs (server flags --mesh-devices, --mesh-db-shards,
    --mesh-min-devices, --mesh-rebuild-cooldown-ms,
    --mesh-probe-timeout-ms, --mesh-hosts,
    --mesh-host-loss-window-ms, --table-device-budget-mb,
    --table-stream-slices). devices=0 keeps the single-chip path;
    the table-streaming knobs apply there too."""
    devices: int = 0          # mesh size; 0 = single-chip detect path
    db_shards: int = 1        # preferred db width (shrink re-fits it)
    min_devices: int = 1      # survivors below this → host join
    rebuild_cooldown_ms: float = 1000.0
    probe_timeout_ms: float = 5000.0
    # host fault domains: 0 = real per-device process_index (multi-
    # host jobs); > 1 = synthetic contiguous blocks for drills on a
    # single-process platform. Domains only engage when the mapping
    # actually spans ≥ 2 hosts — a single-host mesh keeps the plain
    # per-chip behavior.
    hosts: int = 0
    host_loss_window_ms: float = 250.0
    # graftstream: stream the advisory table through a double-buffered
    # resident slice pair once its per-device footprint exceeds the
    # budget (0 = auto off the graftprof hbm_bytes view; slices > 0
    # forces a slice count)
    table_device_budget_mb: float = 0.0
    table_stream_slices: int = 0
    # graftfeed: admission-aware slice prefetch — detectd peeks its
    # queue and warms the slices the next dispatch will touch
    # (--stream-prefetch / --no-stream-prefetch)
    stream_prefetch: bool = True


class ServerState:
    def __init__(self, table, cache_dir: str, token: str = "",
                 cache_backend: str = "fs", detect_opts=None,
                 admission=None, mesh_opts: MeshOptions | None = None,
                 memo_backend="", redetect_opts=None, sbom_opts=None):
        from ..detect.sched import SchedOptions
        from ..fanal.cache import open_cache
        # one backend-selection path (fanal.cache.open_cache) shared
        # with the CLI: fs | memory | redis:// | s3:// — the shared
        # backends are what make a replica fleet cache-coherent
        self.cache = open_cache(cache_backend, cache_dir)
        # graftmemo: content-addressed detection-result memo (same
        # backend grammar; "" = disabled). On a shared backend a blob
        # detected by any replica is a memo hit on all of them.
        from ..fleet.memo import open_memo
        self.memo = open_memo(memo_backend, cache_dir)
        self.token = token
        self._lock = threading.Lock()
        # server mode runs detectd by default: concurrent RPCs'
        # prepared batches coalesce into shared device dispatches
        # (detect/sched.py; --detect-* flags tune or disable it)
        self.detect_opts = detect_opts if detect_opts is not None \
            else SchedOptions()
        # graftbom: parse budgets/deadline for ScanSBOM document
        # decodes (SBOMOptions; None → defaults). Chaos drills tighten
        # the parse deadline the way they tighten ingest budgets.
        self.sbom_opts = sbom_opts
        # graftguard admission: bounded deadline-aware Scan queue
        # (--admit-* flags; unbounded by default). The breaker reference
        # picks the shed code — 503 while the device is down, 429 else
        self.admission = AdmissionQueue(admission,
                                        breaker=GUARD.breaker)
        self._table = table
        # advisory-DB version identity: the serving table's content
        # digest, stamped on every Scan response and in /healthz so a
        # mid-rollout fleet's skew is observable (the router counts
        # disagreements). Plain str attribute — handler reads need no
        # lock; swap_table re-stamps it when a new table installs.
        self.db_version = table.content_digest()
        # rolling-upgrade observability: the version this replica
        # served BEFORE its last hot swap, and when the swap landed —
        # /healthz surfaces both so an operator can tell which side of
        # a rolling fleet upgrade each replica is on (the skew counter
        # says the fleet disagrees; these say who moved, and when)
        self.db_previous_version = ""
        self.db_swapped_at = ""
        # graceful drain (SIGTERM/SIGINT): once draining, Scan sheds
        # 503 + Retry-After while in-flight requests finish through
        # the generation drain — a restart mid-load completes what the
        # admission queue holds instead of dropping it
        self._draining = False
        self.drain_retry_after_s = 5.0
        # meshguard: mesh mode shards the detect join over a device
        # mesh with per-device fault domains. Device loss shrinks the
        # mesh to the survivors (grow on readmission) through the
        # swap_table generation drain below, instead of dropping the
        # whole backend to the host fallback.
        self.mesh_guard = None
        self._mesh = None
        self._mesh_devices = []
        self._mesh_db_shards = 1
        # the latest breaker-recovery rebuild thread; close() joins it
        # (bounded) so a recovery swap can't outlive the server
        self._recover_thread: threading.Thread | None = None
        # graftstream: when mesh_opts carries streaming knobs (or just
        # defaults — the auto budget comes off graftprof's hbm view),
        # every detector this state builds may stream the advisory
        # table through a double-buffered resident slice pair instead
        # of holding it device-whole. plan_slices() decides per table;
        # a table within budget keeps the resident path unchanged.
        self.stream_opts = None
        if mesh_opts is not None:
            from ..parallel.stream import StreamOptions
            self.stream_opts = StreamOptions(
                device_budget_mb=mesh_opts.table_device_budget_mb,
                slices=mesh_opts.table_stream_slices,
                prefetch=mesh_opts.stream_prefetch)
        if mesh_opts is not None and mesh_opts.devices:
            import jax

            from ..parallel.mesh import mesh_from_devices
            from ..parallel.multihost import host_assignments
            from ..resilience import MeshGuard, MeshGuardOptions
            n = mesh_opts.devices
            devs = jax.devices()
            self._mesh_devices = list(devs if n < 0 else devs[:n])
            self._mesh_db_shards = mesh_opts.db_shards
            self._mesh = mesh_from_devices(self._mesh_devices,
                                           mesh_opts.db_shards)
            # host fault domains engage only when the mapping spans
            # ≥ 2 hosts — a single-host mesh must keep the prompt
            # per-chip shrink (no host-loss hold on every loss)
            host_of = host_assignments(self._mesh_devices,
                                       synthetic_hosts=mesh_opts.hosts)
            if len(set(host_of.values())) < 2:
                host_of = None
            self.mesh_guard = MeshGuard(
                [int(d.id) for d in self._mesh_devices],
                MeshGuardOptions(
                    min_devices=mesh_opts.min_devices,
                    rebuild_cooldown_ms=mesh_opts.rebuild_cooldown_ms,
                    probe_timeout_ms=mesh_opts.probe_timeout_ms,
                    host_loss_window_ms=mesh_opts.host_loss_window_ms),
                probe=self._mesh_probe, host_of=host_of)
        self._scanner = LocalScanner(self.cache, table,
                                     sched=self.detect_opts,
                                     mesh=self._mesh,
                                     mesh_guard=self.mesh_guard,
                                     memo=self.memo,
                                     stream=self.stream_opts)
        # redetectd: on a DB hot swap, sweep the memo's known blobs
        # through the pure detect path in the background so fresh
        # entries exist under the new db_version before users rescan
        self.redetect = None
        if self.memo is not None:
            from ..detect.redetect import RedetectDaemon
            self.redetect = RedetectDaemon(
                self.memo, self.cache, self.admission,
                self.scanner_with_version, redetect_opts,
                track=(self.request_started,
                       self.request_finished))
        self._inflight = 0
        self._closed = False
        # scanner generations: a request started under generation g
        # may hold that generation's scanner for its whole lifetime, so
        # a swapped-out scanner is closeable exactly when its
        # generation's active count drains — not on the GLOBAL count,
        # which under sustained traffic never reaches zero
        self._gen = 0
        self._gen_active = {0: 0}
        # breaker recovery (half-open probe succeeded): rebuild the
        # detector through the swap_table generation drain — a fresh
        # engine re-ships its device arrays onto the recovered backend
        # and no in-flight request is ever force-killed. The rebuild
        # runs on its own thread: listeners fire from whatever thread
        # recorded the probe's success, which must not absorb a
        # multi-second scanner build
        GUARD.breaker.on_recovery(self._recover)
        # meshguard rebuilds ride the same drain (they run on the
        # coordinator's maintenance thread, already off the hot path)
        if self.mesh_guard is not None:
            self.mesh_guard.on_rebuild(self._mesh_rebuild)

    def _mesh_probe(self, dev_id) -> None:
        """Readmission probe body (meshguard runs it under the
        device's own watch, after its failpoint site): one real tiny
        op on the lost device — a dead chip fails or wedges right
        here, a recovered one completes and closes its domain."""
        import jax
        import numpy as np
        dev = next(d for d in self._mesh_devices
                   if int(d.id) == int(dev_id))
        jax.device_put(np.zeros(8, np.int32), dev).block_until_ready()

    def _mesh_rebuild(self, active_ids, reason: str) -> None:
        """meshguard rebuild callback: re-mesh the survivors (largest
        valid dp×db factorization), re-shard the table, and swap the
        detector through the generation drain — in-flight scans finish
        on the old mesh while new requests land on the rebuilt one.
        Zero survivors swaps in the host-join degraded detector."""
        with self._lock:
            if self._closed:
                return
        from ..parallel.mesh import mesh_from_devices
        ids = {int(i) for i in active_ids}
        devs = [d for d in self._mesh_devices if int(d.id) in ids]
        mesh = mesh_from_devices(devs, self._mesh_db_shards) \
            if devs else "host"
        _log.warning("meshguard: %s rebuild → swapping %s-device mesh "
                     "via generation drain", reason,
                     len(devs) if devs else "host-join (0)")
        try:
            # _KEEP_TABLE: a DB hot swap racing this rebuild must not
            # be reverted to a snapshotted (stale) advisory table
            self.swap_table(ServerState._KEEP_TABLE, mesh=mesh)
        except Exception:
            _log.exception("meshguard: %s rebuild swap failed", reason)

    def _recover(self) -> None:
        with self._lock:
            if self._closed:
                return
        _log.warning("graftguard: device recovered; rebuilding "
                     "detector via swap_table")
        t = threading.Thread(target=self._recover_swap,
                             name="graftguard-recover", daemon=True)
        with self._lock:
            self._recover_thread = t
        t.start()

    def _recover_swap(self) -> None:
        try:
            # rebuild with whatever table/mesh are CURRENT at install
            # time — a hot swap racing the recovery must not be undone
            self.swap_table(ServerState._KEEP_TABLE)
        except Exception:
            _log.exception("graftguard: recovery swap failed")

    def request_started(self) -> int:
        """→ the scanner generation this request runs under; pass it
        back to request_finished."""
        with self._lock:
            self._inflight += 1
            self._gen_active[self._gen] += 1
            return self._gen

    def request_finished(self, gen: int | None = None) -> None:
        with self._lock:
            self._inflight -= 1
            g = self._gen if gen is None else gen
            self._gen_active[g] -= 1
            if g != self._gen and not self._gen_active[g]:
                del self._gen_active[g]

    @property
    def scanner(self) -> LocalScanner:
        with self._lock:
            return self._scanner

    def scanner_with_version(self) -> "tuple[LocalScanner, str]":
        """Scanner AND the digest of the table it serves, captured
        under one lock hold — a hot swap landing mid-scan must not
        stamp the NEW table's version on a result the OLD table
        produced (the router's skew accounting trusts the header)."""
        with self._lock:
            return self._scanner, self.db_version

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self, retry_after_s: float | None = None) -> None:
        """Stop admitting Scans: subsequent requests shed 503 +
        Retry-After while in-flight ones keep running."""
        with self._lock:
            if retry_after_s is not None:
                self.drain_retry_after_s = retry_after_s
            self._draining = True
        # a draining replica is leaving: its redetect sweep is work
        # for a process that won't serve the result — cancel it so the
        # drain window belongs entirely to in-flight user requests
        if self.redetect is not None:
            self.redetect.cancel()

    def drain(self, timeout_s: float) -> bool:
        """Wait (bounded) for every in-flight request to finish — the
        same generation counts the swap drain trusts. → True when the
        server went quiescent, False when the deadline expired with
        requests still running."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        while True:
            with self._lock:
                if self._inflight == 0:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def close(self) -> None:
        """Server shutdown: join the scanner's detectd + engine worker
        threads (idempotent)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            scanner = self._scanner
        GUARD.breaker.remove_recovery(self._recover)
        if self.redetect is not None:
            self.redetect.close()
        if self.mesh_guard is not None:
            self.mesh_guard.remove_rebuild(self._mesh_rebuild)
            self.mesh_guard.close()
        t = self._recover_thread
        if t is not None and t.is_alive():
            t.join(timeout=10.0)
        scanner.close()

    # "keep the current value" sentinels: a DB hot swap keeps the
    # mesh, a meshguard rebuild / breaker recovery keeps the table —
    # each must re-read the CURRENT other half at build time AND
    # re-check it at install time, or a swap racing a rebuild would
    # silently resurrect the half its caller never meant to change
    # (stale mesh with a lost device, or a stale advisory table)
    _KEEP_MESH = object()
    _KEEP_TABLE = object()

    def swap_table(self, table, mesh=_KEEP_MESH) -> None:
        """DB hot swap (reference listen.go dbWorker). Also the
        meshguard shrink/grow path: `mesh` swaps the detect mesh under
        the same generation drain."""
        keep_mesh = mesh is ServerState._KEEP_MESH
        keep_table = table is ServerState._KEEP_TABLE
        while True:
            with self._lock:
                build_mesh = self._mesh if keep_mesh else mesh
                build_table = self._table if keep_table else table
            # build (and, with --detect-warmup, XLA-warm) the new
            # scanner OUTSIDE the lock: construction can take seconds
            # and every handler blocks on request_started behind it
            new_scanner = LocalScanner(self.cache, build_table,
                                       sched=self.detect_opts,
                                       mesh=build_mesh,
                                       mesh_guard=self.mesh_guard,
                                       memo=self.memo,
                                       stream=self.stream_opts)
            # digest outside the lock too (first computation walks the
            # whole table); cached on the table object afterwards
            new_version = build_table.content_digest()
            with self._lock:
                # close() may have run while the scanner was building
                # (a meshguard rebuild races server shutdown):
                # installing now would strand a never-closed scanner
                # whose non-daemon workers hang process exit
                if self._closed:
                    outcome = "aborted"
                elif (keep_mesh and self._mesh is not build_mesh) or \
                        (keep_table and self._table
                         is not build_table):
                    # a concurrent swap changed the kept half
                    # mid-build: installing the snapshot would undo it
                    outcome = "stale"
                else:
                    outcome = "installed"
                    old_scanner = self._scanner
                    old_gen = self._gen
                    self._gen += 1
                    self._gen_active.setdefault(self._gen, 0)
                    if not self._gen_active[old_gen]:
                        del self._gen_active[old_gen]
                    self._scanner = new_scanner
                    self._table = build_table
                    self._mesh = build_mesh
                    version_changed = new_version != self.db_version
                    if version_changed:
                        # rolling-upgrade breadcrumbs for /healthz:
                        # what this replica served before, and when
                        # the swap landed
                        self.db_previous_version = self.db_version
                        self.db_swapped_at = time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                    self.db_version = new_version
            if outcome == "aborted":
                new_scanner.close()
                return
            if outcome == "stale":
                _log.warning("swap: mesh/table changed during scanner "
                             "build; rebuilding against fresh state")
                new_scanner.close()
                continue
            break
        # redetectd: a swap that changed the advisory-DB identity
        # kicks the background memo sweep — fresh entries publish
        # under the new db_version while old-version entries simply
        # stop being addressed (a mesh rebuild / breaker recovery
        # keeps the table, so it never sweeps)
        if version_changed and self.redetect is not None \
                and not self._draining:
            self.redetect.schedule(new_version)
            # begin_drain may have raced in between the check and the
            # schedule — its cancel would have found no sweep to stop.
            # Re-check and cancel so a draining replica never runs a
            # fresh sweep against its own in-flight user requests.
            with self._lock:
                draining = self._draining
            if draining:
                self.redetect.cancel()
        # the swapped-in table's object graph (~1M small objects for a
        # full trivy-db) is immutable; freezing it out of the cyclic
        # collector keeps gen2 passes from stalling in-flight scans.
        # unfreeze first: the PREVIOUS swap's frozen set must rejoin
        # the collector or every swap would leak one table's worth of
        # uncollectable objects. Freeze only in a quiescent window —
        # freezing while requests are in flight would pin their
        # transient buffers (and any cyclic garbage among them)
        # forever if no later swap unfreezes them.
        import gc
        gc.unfreeze()
        # the expensive full collect over the just-unfrozen table graph
        # runs OUTSIDE the lock — request_started/finished must never
        # block behind a multi-hundred-ms gen2 pass (healthz probes!)
        gc.collect()
        deadline = time.monotonic() + 2.0
        froze = False
        while time.monotonic() < deadline and \
                not (froze and old_scanner is None):
            with self._lock:
                drained = not self._gen_active.get(old_gen)
                if not froze and self._inflight == 0:
                    # young-gen sweep inside the window: requests that
                    # finished during the wait leave fresh cyclic
                    # garbage that must die before freeze pins it;
                    # gen-1 collects are cheap enough to hold the lock
                    gc.collect(1)
                    gc.freeze()
                    froze = True
            if drained and old_scanner is not None:
                # no request started before the swap is still running:
                # nothing can hold the old scanner, so its executors
                # join without breaking an in-flight detect (the
                # pre-close() leak: every swap stranded the old
                # engine's threads forever)
                old_scanner.close()
                old_scanner = None
            if not (froze and old_scanner is None):
                time.sleep(0.01)
        # old generation still busy (a long scan straddles the swap):
        # retire its scanner from a waiter thread once its LAST request
        # drains — never force-close, that would yank the executors out
        # from under the running detect. An un-frozen swap just means
        # gen2 GC passes stay slower until the next swap.
        if old_scanner is not None:
            self._close_when_idle(old_scanner, old_gen)

    def _close_when_idle(self, scanner: LocalScanner,
                         gen: int) -> None:
        def waiter():
            deadline = time.monotonic() + 600.0
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._gen_active.get(gen):
                        break
                time.sleep(0.05)
            else:
                _log.warning(
                    "swap: generation %d still busy after 600s; "
                    "leaking its scanner workers", gen)
                return
            scanner.close()
        # lint: allow(TPU112) reason=detached by design so the swap path never blocks; the waiter self-bounds at 600s and then deliberately leaks the busy generation
        threading.Thread(target=waiter, name="swap-close",
                         daemon=True).start()


def _result_to_json(res: T.Result) -> dict:
    return res.to_json()


class ScanServer(ThreadingHTTPServer):
    # graftfair: the TCP accept backlog must exceed any burst the
    # admission layer is meant to judge — with the http.server default
    # (5), a flooding tenant's connections die as kernel RSTs before
    # the quota layer can mint its well-formed 429 + Retry-After
    request_queue_size = 128


class Handler(BaseHTTPRequestHandler):
    state: ServerState = None  # set by serve()
    protocol_version = "HTTP/1.1"
    _trace_id = ""  # per-request; set by do_POST before dispatch
    _db_version = ""  # stamped on Scan responses only (X-Trivy-DB-Version)

    def log_message(self, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self._trace_id:
            self.send_header(TRACE_HEADER, self._trace_id)
        if self._db_version:
            self.send_header(DB_VERSION_HEADER, self._db_version)
        # graftcost: the per-request cost split rides every response
        # produced inside a request ledger (do_POST installs one);
        # GET surfaces have no ledger and stamp nothing
        led = _cost.active()
        if led is not None:
            self.send_header(COST_HEADER, led.header_json())
        self.end_headers()
        self.wfile.write(body)

    def _twirp_error(self, code: int, twirp_code: str, msg: str):
        self._json(code, {"code": twirp_code, "msg": msg})

    def do_GET(self):
        st = self.state
        # clear any trace id a previous POST on this keep-alive
        # connection stamped on the handler instance — a health probe
        # must not echo an unrelated scan's id
        self._trace_id = ""
        self._db_version = ""
        gen = st.request_started()
        try:
            self._do_get()
        finally:
            st.request_finished(gen)

    def _do_get(self):
        if self.path.startswith(("/debug/traces", "/debug/incidents",
                                 "/debug/perf", "/debug/profile",
                                 "/debug/costs")):
            # unlike /healthz//metrics (liveness/scrape surfaces), the
            # debug buffers carry scan detail — file paths in analyzer
            # spans, other tenants' trace ids — so a configured token
            # gates them exactly like the POST surface; /debug/profile
            # additionally COSTS (it runs the profiler against live
            # traffic), which is exactly what a token should gate
            if self.state.token and \
                    self.headers.get(TOKEN_HEADER) != self.state.token:
                return self._twirp_error(401, "unauthenticated",
                                         "invalid token")
            if self.path.startswith("/debug/traces"):
                return self._json(200, debug_traces_payload(self.path))
            if self.path.startswith("/debug/perf"):
                return self._json(200, debug_perf_payload())
            if self.path.startswith("/debug/profile"):
                code, payload = debug_profile_payload(self.path)
                return self._json(code, payload)
            if self.path.startswith("/debug/costs"):
                # graftcost: per-tenant totals + the conservation
                # reconciliation (replica-local; the fleet router
                # serves the fleet-wide variant from relayed headers)
                return self._json(200, _cost.debug_costs_payload())
            return self._json(200, debug_incidents_payload())
        if self.path == "/healthz":
            # plain `ok` stays the fast path for probes that ask for
            # it (kubelet-style `Accept: text/plain`); everything else
            # gets the device-backend status as JSON. Neither path
            # touches jax — the status is the cached view the detect
            # engine stamps on its dispatch path (obs.device).
            accept = self.headers.get("Accept") or ""
            if "text/plain" in accept:
                body = b"ok"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                from ..fanal.pipeline import INGEST
                resilience = {
                    **GUARD.status(),
                    "admission": self.state.admission.snapshot(),
                    # fanald: per-stage ingest breaker states, partial-
                    # scan and budget-trip counters — the degradation
                    # contract's observable face (a scan that returned
                    # an annotated partial shows up here, never as a
                    # 5xx)
                    "ingest": INGEST.status(),
                }
                # meshguard: per-device breaker states, lost set, and
                # the shrink/grow rebuild counters
                if self.state.mesh_guard is not None:
                    resilience["mesh"] = self.state.mesh_guard.status()
                payload = {
                    "status": "draining" if self.state.draining
                    else "ok",
                    # advisory-DB identity: replicas of one fleet must
                    # agree, or bit-identical failover is a lie — the
                    # router's probe reads this field. The previous
                    # version + swap timestamp make a rolling upgrade
                    # observable per replica.
                    "db_version": self.state.db_version,
                    "db_previous_version":
                        self.state.db_previous_version,
                    "db_swapped_at": self.state.db_swapped_at,
                    "device": device_status(),
                    # graftguard: breaker state, watchdog last-probe
                    # age, shed/fallback counters, admission snapshot
                    "resilience": resilience,
                    # graftwatch: per-objective burn rates over the
                    # sliding windows (export() also refreshes the
                    # burn-rate gauges, so /healthz and /metrics agree)
                    "slo": SLO.export(),
                    # graftcost: per-tenant scan counts + headline cost
                    # split (bounded rows — the top-K clamp already
                    # ran). graftfair adds the `qos` view: per-tenant
                    # admission quota state and tenant-labelled SLO
                    # burn rates, bounded by the same clamp
                    "tenants": {
                        **_cost.TENANTS.healthz_block(),
                        "qos": {
                            "quotas": resilience["admission"].get(
                                "tenant_quotas"),
                            "admission": resilience["admission"].get(
                                "tenants", {}),
                            "burn_rates": SLO.tenant_burn_rates(),
                        },
                    },
                }
                # graftstream: slice plan + resident set when the
                # serving detector streams its advisory table (the
                # single-chip StreamingDetector exposes status();
                # resident detectors have nothing to report)
                stream_status = getattr(
                    self.state.scanner.detector, "status", None)
                if callable(stream_status):
                    payload["stream"] = stream_status()
                # graftmemo: backend + known-blob count, and the
                # redetectd sweep's progress (phase, done/total,
                # target db_version)
                if self.state.memo is not None:
                    memo = self.state.memo.status()
                    if self.state.redetect is not None:
                        memo["sweep"] = self.state.redetect.status()
                    payload["memo"] = memo
                self._json(200, payload)
        elif self.path == "/version":
            self._json(200, {"Version": __version__})
        elif self.path == "/metrics":
            from ..metrics import METRICS
            # burn-rate gauges are window functions of the SLO event
            # store — recompute at scrape time so they are current
            SLO.export()
            body = METRICS.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._twirp_error(404, "not_found", self.path)

    def _proto(self, code: int, payload: dict, desc: str):
        from .protowire import encode_msg
        body = encode_msg(payload, desc)
        self.send_response(code)
        self.send_header("Content-Type", "application/protobuf")
        self.send_header("Content-Length", str(len(body)))
        if self._trace_id:
            self.send_header(TRACE_HEADER, self._trace_id)
        if self._db_version:
            self.send_header(DB_VERSION_HEADER, self._db_version)
        led = _cost.active()
        if led is not None:
            self.send_header(COST_HEADER, led.header_json())
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, payload: dict, desc: str):
        """Encode the response in the request's encoding (Twirp
        requires responses to match the request content type)."""
        if self._is_proto:
            return self._proto(200, payload, desc)
        return self._json(200, payload)

    # request-message descriptor per route (binary Twirp); the map
    # itself lives in the package __init__ so the fleet router shares
    # it without importing this module's server stack
    _ROUTES = ROUTE_DESCRIPTORS

    def do_POST(self):
        st = self.state
        self._db_version = ""
        gen = st.request_started()
        # per-RPC trace stamp: reuse the client's id when forwarded,
        # mint one otherwise; every span/log line below inherits it.
        # The forwarded parent-span id (router hop or client span)
        # parents this fragment's root, so obs.collect stitches one
        # tree across processes
        tid = self.headers.get(TRACE_HEADER) or ""
        parent = self.headers.get(PARENT_SPAN_HEADER) or ""
        # graftcost: one request-scoped ledger per RPC, keyed by the
        # relayed tenant header (client --tenant; router forwards it;
        # absent → "default"). Every seam below — admission queue,
        # detectd apportionment, fanald ingest, secrets, memo — charges
        # this ledger through the contextvar; settle folds it into the
        # tenant aggregate once the response is on the wire.
        # graftfair: the raw header is attacker-controlled, so it is
        # syntactically clamped HERE — before it can mint a ledger,
        # quota state, or a metric label anywhere downstream
        tenant = _cost.normalize_tenant(self.headers.get(TENANT_HEADER))
        try:
            with new_trace(tid or None, parent_id=parent or None) as tid:
                self._trace_id = tid
                with _cost.request_ledger(tenant) as led:
                    try:
                        with span("server.rpc", route=self.path,
                                  tenant=tenant):
                            self._do_post(st)
                    finally:
                        _cost.TENANTS.settle(led, led.outcome)
        finally:
            st.request_finished(gen)

    def _do_post(self, st):
        if st.token and self.headers.get(TOKEN_HEADER) != st.token:
            return self._twirp_error(401, "unauthenticated", "invalid token")
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        self._is_proto = ctype in ("application/protobuf",
                                   "application/x-protobuf")
        route = self.path
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if self._is_proto:
                from .protowire import decode_msg
                desc = self._ROUTES.get(route)
                if desc is None:
                    return self._twirp_error(404, "bad_route", route)
                req = decode_msg(body, desc)
            else:
                req = json.loads(body or b"{}")
        except (ValueError, json.JSONDecodeError):
            return self._twirp_error(400, "malformed", "bad body")

        try:
            if route == "/twirp/trivy.scanner.v1.Scanner/Scan":
                return self._scan_admitted(req)
            if route == "/twirp/trivy.scanner.v1.Scanner/ScanSBOM":
                return self._scan_admitted(req, sbom=True)
            if route == "/twirp/trivy.cache.v1.Cache/PutArtifact":
                st.cache.put_artifact(req.get("artifact_id", ""),
                                      req.get("artifact_info") or {})
                return self._reply({}, "Empty")
            if route == "/twirp/trivy.cache.v1.Cache/PutBlob":
                blob_j = req.get("blob_info") or {}
                if self._is_proto:
                    from .convert import proto_blob_to_json
                    blob_j = proto_blob_to_json(blob_j)
                blob = blob_from_json(blob_j)
                st.cache.put_blob(req.get("diff_id", ""), blob)
                return self._reply({}, "Empty")
            if route == "/twirp/trivy.cache.v1.Cache/MissingBlobs":
                missing_artifact, missing = st.cache.missing_blobs(
                    req.get("artifact_id", ""), req.get("blob_ids") or [])
                return self._reply({
                    "missing_artifact": missing_artifact,
                    "missing_blob_ids": missing,
                }, "MissingBlobsResponse")
            if route == "/twirp/trivy.cache.v1.Cache/DeleteBlobs":
                return self._reply({}, "Empty")
            return self._twirp_error(404, "bad_route", route)
        except KeyError as e:
            return self._twirp_error(400, "invalid_argument", str(e))
        except Exception as e:  # noqa: BLE001 — server must not die
            return self._twirp_error(500, "internal", f"{type(e).__name__}: {e}")

    def _shed_response(self, s: Shed):
        """429/503 + Retry-After: the admission queue rejected the
        scan. Twirp-style JSON body so clients surface a reason."""
        body = json.dumps({
            "code": "resource_exhausted" if s.http_code == 429
            else "unavailable",
            "msg": f"scan shed: {s.reason}",
        }).encode()
        self.send_response(s.http_code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Retry-After",
                         str(max(1, int(s.retry_after_s + 0.999))))
        self.send_header("Content-Length", str(len(body)))
        if self._trace_id:
            self.send_header(TRACE_HEADER, self._trace_id)
        # a shed response still tells the tenant what it cost them:
        # pure queue ms (the router's fleet aggregator sums it across
        # the failover hops that eventually served the scan)
        led = _cost.active()
        if led is not None:
            self.send_header(COST_HEADER, led.header_json())
        self.end_headers()
        self.wfile.write(body)

    def _scan_admitted(self, req: dict, sbom: bool = False):
        """Scan behind graftguard admission: bounded concurrency,
        bounded queue time, per-request deadline from
        X-Trivy-Deadline-Ms — a handler thread is never parked past
        the point its client has given up. ScanSBOM (`sbom=True`)
        shares every seam — admission, shed accounting, rpc.scan
        failpoint, SLO, cost settle — and differs only in the decode
        step ahead of the Scan tail."""
        st = self.state
        if st.draining:
            # graceful drain: no NEW scans once the shutdown signal
            # landed — shed exactly like admission overload so clients
            # back off to another replica (or retry after the restart)
            from ..metrics import METRICS
            s = Shed("server draining", 503, st.drain_retry_after_s)
            METRICS.inc("trivy_tpu_requests_shed_total")
            led = _cost.active()
            SLO.observe_scan(
                0.0, "shed",
                tenant=_cost.TENANTS.resolve(led.tenant)
                if led is not None else None)
            if led is not None:
                led.outcome = "shed"
            _log.warning("scan shed (draining): 503 Retry-After=%ds",
                         int(s.retry_after_s))
            return self._shed_response(s)
        deadline = None
        hdr = self.headers.get(DEADLINE_HEADER)
        if hdr:
            try:
                deadline = Deadline(max(float(hdr), 0.0) / 1e3)
            except ValueError:
                deadline = None  # unparseable header: no deadline
        led = _cost.active()
        # graftfair: quota state keys on the CLAMPED aggregator label,
        # never the raw header — a cardinality bomb of distinct names
        # folds into "other" and shares ONE bucket. System work (no
        # ledger installed) passes tenant=None and is quota-exempt
        qlabel = (_cost.TENANTS.resolve(led.tenant)
                  if led is not None else None)
        # graftcost: time parked in the admission queue is queue ms —
        # kept distinct from service ms so a tenant whose scans are
        # QUEUED reads differently from one whose scans are SLOW.
        # Charged on the shed path too (the wait really happened)
        t_adm = time.perf_counter()
        try:
            st.admission.admit(deadline, tenant=qlabel)
        except Shed as s:
            _cost.charge_queue_ms(
                (time.perf_counter() - t_adm) * 1e3, ledger=led)
            if led is not None:
                led.outcome = "shed"
            _log.warning("scan shed (%s): %d Retry-After=%ds",
                         s.reason, s.http_code, int(s.retry_after_s))
            # shed-aware SLO accounting: a 429/503 is load the
            # deployment refused on purpose — availability's
            # denominator grows, its error count does not
            SLO.observe_scan(0.0, "shed", tenant=qlabel)
            return self._shed_response(s)
        _cost.charge_queue_ms((time.perf_counter() - t_adm) * 1e3,
                              ledger=led)
        try:
            failpoint("rpc.scan")
            if sbom:
                return self._scan_sbom(req)
            return self._scan(req)
        except KeyError:
            raise   # 400 invalid_argument: the client's error
        except Exception:
            if led is not None:
                led.outcome = "error"
            SLO.observe_scan(
                0.0, "error",
                tenant=_cost.TENANTS.resolve(led.tenant)
                if led is not None else None)
            raise
        finally:
            st.admission.release(tenant=qlabel)

    def _scan_sbom(self, req: dict):
        """graftbom ingress: one supervised decode into a content-
        addressed blob, then the UNCHANGED Scan tail. inspect() never
        raises for the document's fault — hostile input lands as an
        annotated partial result, not a 5xx, and not a breaker
        charge. The client-stamped artifact_id only steered router
        affinity; the blob identity is the server-computed document
        digest either way (the two agree for honest clients)."""
        import base64

        from ..sbom.artifact import SBOMArtifact
        raw = req.get("document") or b""
        if isinstance(raw, str):
            # JSON-mode bodies carry the document base64-encoded;
            # fall back to literal text for hand-rolled callers
            try:
                raw = base64.b64decode(raw, validate=True)
            except (ValueError, TypeError):
                raw = raw.encode()
        ref = SBOMArtifact(raw, self.state.cache,
                           name=req.get("target", ""),
                           opts=self.state.sbom_opts).inspect()
        return self._scan({
            "target": req.get("target", "") or ref.name,
            "artifact_id": ref.id,
            "blob_ids": ref.blob_ids,
            "options": req.get("options") or {},
        })

    def _scan(self, req: dict):
        import time

        from ..metrics import METRICS
        opts_j = req.get("options") or {}
        opts = T.ScanOptions(
            scanners=tuple(opts_j.get("scanners") or ("vuln",)),
            pkg_types=tuple(opts_j.get("vuln_type") or ("os", "library")),
            list_all_packages=bool(opts_j.get("list_all_packages")),
        )
        t0 = time.perf_counter()
        # scanner + db version captured together: the header must name
        # the table that produced THIS answer, even when a hot swap
        # lands mid-scan (the reply helpers stamp it)
        scanner, self._db_version = self.state.scanner_with_version()
        results, os_info = scanner.scan(
            req.get("target", ""), req.get("artifact_id", ""),
            req.get("blob_ids") or [], opts)
        elapsed = time.perf_counter() - t0
        METRICS.inc("trivy_tpu_scans_total")
        METRICS.inc("trivy_tpu_scan_seconds_total", elapsed)
        METRICS.observe("trivy_tpu_scan_latency_seconds", elapsed)
        led = _cost.active()
        if led is not None:
            led.outcome = "ok"
        # per-tenant burn window keyed by the CLAMPED label — raw
        # header values never become metric labels
        SLO.observe_scan(
            elapsed, "ok",
            tenant=_cost.TENANTS.resolve(led.tenant)
            if led is not None else None)
        _log.debug("scan %s: %d results in %.1fms",
                   req.get("target", ""), len(results), elapsed * 1e3)
        if self._is_proto:
            from .convert import results_to_proto
            return self._proto(200, results_to_proto(results, os_info),
                               "ScanResponse")
        self._json(200, {
            "os": {"family": os_info.family, "name": os_info.name,
                   "eosl": os_info.eosl},
            "results": [_result_to_json(r) for r in results],
        })


def drain_then_shutdown(httpd, state: ServerState,
                        grace_s: float = 10.0) -> None:
    """Graceful shutdown: stop admitting Scans (503 + Retry-After),
    wait (bounded) for in-flight requests to finish through the
    generation counts, then stop the accept loop. serve() wires
    SIGTERM/SIGINT here — a restart mid-load completes what the
    admission queue holds instead of dropping it. Runs off the signal
    handler on its own thread; callers under test drive it directly."""
    _log.warning("drain: admission stopped; waiting up to %.1fs for "
                 "%d in-flight request(s)", grace_s, state.inflight)
    state.begin_drain()
    if not state.drain(grace_s):
        _log.warning("drain: grace period expired with %d request(s) "
                     "still in flight; shutting down anyway",
                     state.inflight)
    httpd.shutdown()


def install_drain_handlers(httpd, state, grace_s: float) -> bool:
    """SIGTERM/SIGINT → graceful drain (main thread only — background
    servers in tests own their shutdown). → True when installed."""
    import signal

    def _on_signal(signum, frame):
        # the handler must return immediately; the drain wait runs on
        # its own thread and ends by stopping the accept loop
        # lint: allow(TPU112) reason=signal-time drain thread; the process is exiting and the drain ends by stopping the accept loop the main thread sits in
        threading.Thread(target=drain_then_shutdown,
                         args=(httpd, state, grace_s),
                         name="graceful-drain", daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)
        return True
    except ValueError:
        return False   # not the main thread


def serve(host: str, port: int, table, cache_dir: str, token: str = "",
          ready_event: threading.Event | None = None,
          cache_backend: str = "fs", trace_path: str = "",
          detect_opts=None, admission=None, mesh_opts=None,
          drain_grace_s: float = 10.0, memo_backend="",
          redetect_opts=None, sbom_opts=None):
    """`trace_path` arms graftscope recording for the server's
    lifetime and dumps the Chrome trace-event JSON there on shutdown
    (the CLI's `server --trace FILE`). `detect_opts` (SchedOptions)
    tunes detectd — coalesce wait, in-flight pair bound, warmup;
    `admission` (AdmissionOptions) bounds the graftguard scan queue;
    `mesh_opts` (MeshOptions) shards detection over a device mesh with
    meshguard per-device fault domains; `drain_grace_s` bounds the
    SIGTERM/SIGINT graceful drain (--drain-grace-ms)."""
    if trace_path:
        from ..obs import COLLECTOR
        COLLECTOR.enable()
    state = ServerState(table, cache_dir, token, cache_backend,
                        detect_opts=detect_opts, admission=admission,
                        mesh_opts=mesh_opts, memo_backend=memo_backend,
                        redetect_opts=redetect_opts,
                        sbom_opts=sbom_opts)
    # per-server Handler subclass: `state` must not live on the shared
    # base class, or two in-process replicas (the fleet tests/bench)
    # would serve each other's caches and scanners
    handler = type("Handler", (Handler,), {"state": state})
    httpd = ScanServer((host, port), handler)
    install_drain_handlers(httpd, state, drain_grace_s)
    if ready_event is not None:
        ready_event.set()
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        state.close()
        if trace_path:
            from ..obs import COLLECTOR, write_chrome_trace
            COLLECTOR.disable()
            write_chrome_trace(trace_path)
            _log.warning("graftscope trace written to %s", trace_path)
    return httpd


def serve_background(host: str, port: int, table, cache_dir: str,
                     token: str = "", cache_backend: str = "fs",
                     detect_opts=None, admission=None, mesh_opts=None,
                     memo_backend="", redetect_opts=None,
                     sbom_opts=None):
    """Start in a daemon thread; returns (httpd, state) once listening.
    Callers own shutdown: `httpd.shutdown()` then `state.close()` (the
    detect engine's worker threads are non-daemon). `cache_backend`
    picks the fanal cache (fs | memory | redis:// | s3://) — fleet
    tests and the bench point several replicas at one shared
    redis/s3 URL."""
    state = ServerState(table, cache_dir, token, cache_backend,
                        detect_opts=detect_opts,
                        admission=admission,
                        mesh_opts=mesh_opts,
                        memo_backend=memo_backend,
                        redetect_opts=redetect_opts,
                        sbom_opts=sbom_opts)
    handler = type("Handler", (Handler,), {"state": state})
    httpd = ScanServer((host, port), handler)
    # lint: allow(TPU112) reason=serve loop exits when the caller runs httpd.shutdown() (documented caller-owned shutdown contract)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, state
