"""Scan server.

Mirrors pkg/rpc/server/listen.go: one HTTP mux exposing the Scanner and
Cache services plus /healthz and /version, with optional token auth
(Trivy-Token header) and a hot-swappable advisory table (the reference
drains in-flight requests around a DB reload, listen.go:129-192; here a
lock swap suffices because the table is immutable once built).

Routes speak Twirp's JSON encoding (POST /twirp/<svc>/<Method> with JSON
bodies using proto field names — rpc/scanner/service.proto,
rpc/cache/service.proto). The protobuf-binary encoding for drop-in Go
clients is a later round. Batches accumulate per request; every Scan
request runs the batched device join over all its target's packages at
once (SURVEY.md §2.7 P4/P5)."""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import __version__, types as T
from ..fanal.cache import FSCache, blob_from_json
from ..scanner import LocalScanner

TOKEN_HEADER = "Trivy-Token"


class ServerState:
    def __init__(self, table, cache_dir: str, token: str = "",
                 cache_backend: str = "fs"):
        if cache_backend.startswith("redis://"):
            from ..fanal.redis_cache import RedisCache
            self.cache = RedisCache(cache_backend)
        else:
            self.cache = FSCache(cache_dir)
        self.token = token
        self._lock = threading.Lock()
        self._scanner = LocalScanner(self.cache, table)

    @property
    def scanner(self) -> LocalScanner:
        with self._lock:
            return self._scanner

    def swap_table(self, table) -> None:
        """DB hot swap (reference listen.go dbWorker)."""
        with self._lock:
            self._scanner = LocalScanner(self.cache, table)


def _result_to_json(res: T.Result) -> dict:
    return res.to_json()


class Handler(BaseHTTPRequestHandler):
    state: ServerState = None  # set by serve()
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _twirp_error(self, code: int, twirp_code: str, msg: str):
        self._json(code, {"code": twirp_code, "msg": msg})

    def do_GET(self):
        if self.path == "/healthz":
            body = b"ok"
            self.send_response(200)
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        elif self.path == "/version":
            self._json(200, {"Version": __version__})
        else:
            self._twirp_error(404, "not_found", self.path)

    def do_POST(self):
        st = self.state
        if st.token and self.headers.get(TOKEN_HEADER) != st.token:
            return self._twirp_error(401, "unauthenticated", "invalid token")
        try:
            length = int(self.headers.get("Content-Length", "0"))
            req = json.loads(self.rfile.read(length) or b"{}")
        except (ValueError, json.JSONDecodeError):
            return self._twirp_error(400, "malformed", "bad JSON body")

        route = self.path
        try:
            if route == "/twirp/trivy.scanner.v1.Scanner/Scan":
                return self._scan(req)
            if route == "/twirp/trivy.cache.v1.Cache/PutArtifact":
                st.cache.put_artifact(req.get("artifact_id", ""),
                                      req.get("artifact_info") or {})
                return self._json(200, {})
            if route == "/twirp/trivy.cache.v1.Cache/PutBlob":
                blob = blob_from_json(req.get("blob_info") or {})
                st.cache.put_blob(req.get("diff_id", ""), blob)
                return self._json(200, {})
            if route == "/twirp/trivy.cache.v1.Cache/MissingBlobs":
                missing_artifact, missing = st.cache.missing_blobs(
                    req.get("artifact_id", ""), req.get("blob_ids") or [])
                return self._json(200, {
                    "missing_artifact": missing_artifact,
                    "missing_blob_ids": missing,
                })
            if route == "/twirp/trivy.cache.v1.Cache/DeleteBlobs":
                return self._json(200, {})
            return self._twirp_error(404, "bad_route", route)
        except KeyError as e:
            return self._twirp_error(400, "invalid_argument", str(e))
        except Exception as e:  # noqa: BLE001 — server must not die
            return self._twirp_error(500, "internal", f"{type(e).__name__}: {e}")

    def _scan(self, req: dict):
        opts_j = req.get("options") or {}
        opts = T.ScanOptions(
            scanners=tuple(opts_j.get("scanners") or ("vuln",)),
            pkg_types=tuple(opts_j.get("vuln_type") or ("os", "library")),
            list_all_packages=bool(opts_j.get("list_all_packages")),
        )
        results, os_info = self.state.scanner.scan(
            req.get("target", ""), req.get("artifact_id", ""),
            req.get("blob_ids") or [], opts)
        self._json(200, {
            "os": {"family": os_info.family, "name": os_info.name,
                   "eosl": os_info.eosl},
            "results": [_result_to_json(r) for r in results],
        })


def serve(host: str, port: int, table, cache_dir: str, token: str = "",
          ready_event: threading.Event | None = None,
          cache_backend: str = "fs"):
    Handler.state = ServerState(table, cache_dir, token, cache_backend)
    httpd = ThreadingHTTPServer((host, port), Handler)
    if ready_event is not None:
        ready_event.set()
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
    return httpd


def serve_background(host: str, port: int, table, cache_dir: str,
                     token: str = ""):
    """Start in a daemon thread; returns (httpd, state) once listening."""
    Handler.state = ServerState(table, cache_dir, token)
    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, Handler.state
