"""Scan server.

Mirrors pkg/rpc/server/listen.go: one HTTP mux exposing the Scanner and
Cache services plus /healthz and /version, with optional token auth
(Trivy-Token header) and a hot-swappable advisory table (the reference
drains in-flight requests around a DB reload, listen.go:129-192; here a
lock swap suffices because the table is immutable once built).

Routes speak both Twirp encodings (POST /twirp/<svc>/<Method>): JSON
bodies with proto field names, and application/protobuf binary for
drop-in Go clients (rpc/scanner/service.proto, rpc/cache/service.proto,
handwritten codec in protowire.py). Batches accumulate per request;
every Scan
request runs the batched device join over all its target's packages at
once (SURVEY.md §2.7 P4/P5)."""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .. import __version__, types as T
from ..fanal.cache import FSCache, blob_from_json
from ..log import get as _get_logger
from ..obs import device_status, new_trace, span
from ..scanner import LocalScanner

TOKEN_HEADER = "Trivy-Token"
# per-RPC trace id: honored when the client sends one, generated
# otherwise; echoed on every response and stamped on every span and
# log line the request produces (graftscope propagation)
TRACE_HEADER = "X-Trivy-Trace-Id"

_log = _get_logger("server")


class ServerState:
    def __init__(self, table, cache_dir: str, token: str = "",
                 cache_backend: str = "fs"):
        if cache_backend.startswith("redis://"):
            from ..fanal.redis_cache import RedisCache
            self.cache = RedisCache(cache_backend)
        elif cache_backend.startswith("s3://"):
            from ..fanal.s3_cache import S3Cache
            self.cache = S3Cache(cache_backend)
        else:
            self.cache = FSCache(cache_dir)
        self.token = token
        self._lock = threading.Lock()
        self._scanner = LocalScanner(self.cache, table)
        self._inflight = 0

    def request_started(self) -> None:
        with self._lock:
            self._inflight += 1

    def request_finished(self) -> None:
        with self._lock:
            self._inflight -= 1

    @property
    def scanner(self) -> LocalScanner:
        with self._lock:
            return self._scanner

    def swap_table(self, table) -> None:
        """DB hot swap (reference listen.go dbWorker)."""
        with self._lock:
            self._scanner = LocalScanner(self.cache, table)
        # the swapped-in table's object graph (~1M small objects for a
        # full trivy-db) is immutable; freezing it out of the cyclic
        # collector keeps gen2 passes from stalling in-flight scans.
        # unfreeze first: the PREVIOUS swap's frozen set must rejoin
        # the collector or every swap would leak one table's worth of
        # uncollectable objects. Freeze only in a quiescent window —
        # freezing while requests are in flight would pin their
        # transient buffers (and any cyclic garbage among them)
        # forever if no later swap unfreezes them.
        import gc
        gc.unfreeze()
        # the expensive full collect over the just-unfrozen table graph
        # runs OUTSIDE the lock — request_started/finished must never
        # block behind a multi-hundred-ms gen2 pass (healthz probes!)
        gc.collect()
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    # young-gen sweep inside the window: requests that
                    # finished during the wait leave fresh cyclic
                    # garbage that must die before freeze pins it;
                    # gen-1 collects are cheap enough to hold the lock
                    gc.collect(1)
                    gc.freeze()
                    return
            time.sleep(0.01)
        # never went quiescent: skip the freeze; gen2 passes just get
        # slower until the next swap — correctness is unaffected


def _result_to_json(res: T.Result) -> dict:
    return res.to_json()


class Handler(BaseHTTPRequestHandler):
    state: ServerState = None  # set by serve()
    protocol_version = "HTTP/1.1"
    _trace_id = ""  # per-request; set by do_POST before dispatch

    def log_message(self, *args):
        pass

    def _json(self, code: int, payload: dict):
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if self._trace_id:
            self.send_header(TRACE_HEADER, self._trace_id)
        self.end_headers()
        self.wfile.write(body)

    def _twirp_error(self, code: int, twirp_code: str, msg: str):
        self._json(code, {"code": twirp_code, "msg": msg})

    def do_GET(self):
        st = self.state
        # clear any trace id a previous POST on this keep-alive
        # connection stamped on the handler instance — a health probe
        # must not echo an unrelated scan's id
        self._trace_id = ""
        st.request_started()
        try:
            self._do_get()
        finally:
            st.request_finished()

    def _do_get(self):
        if self.path == "/healthz":
            # plain `ok` stays the fast path for probes that ask for
            # it (kubelet-style `Accept: text/plain`); everything else
            # gets the device-backend status as JSON. Neither path
            # touches jax — the status is the cached view the detect
            # engine stamps on its dispatch path (obs.device).
            accept = self.headers.get("Accept") or ""
            if "text/plain" in accept:
                body = b"ok"
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._json(200, {"status": "ok",
                                 "device": device_status()})
        elif self.path == "/version":
            self._json(200, {"Version": __version__})
        elif self.path == "/metrics":
            from ..metrics import METRICS
            body = METRICS.render().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
        else:
            self._twirp_error(404, "not_found", self.path)

    def _proto(self, code: int, payload: dict, desc: str):
        from .protowire import encode_msg
        body = encode_msg(payload, desc)
        self.send_response(code)
        self.send_header("Content-Type", "application/protobuf")
        self.send_header("Content-Length", str(len(body)))
        if self._trace_id:
            self.send_header(TRACE_HEADER, self._trace_id)
        self.end_headers()
        self.wfile.write(body)

    def _reply(self, payload: dict, desc: str):
        """Encode the response in the request's encoding (Twirp
        requires responses to match the request content type)."""
        if self._is_proto:
            return self._proto(200, payload, desc)
        return self._json(200, payload)

    # request-message descriptor per route (binary Twirp)
    _ROUTES = {
        "/twirp/trivy.scanner.v1.Scanner/Scan": "ScanRequest",
        "/twirp/trivy.cache.v1.Cache/PutArtifact": "PutArtifactRequest",
        "/twirp/trivy.cache.v1.Cache/PutBlob": "PutBlobRequest",
        "/twirp/trivy.cache.v1.Cache/MissingBlobs":
            "MissingBlobsRequest",
        "/twirp/trivy.cache.v1.Cache/DeleteBlobs": "DeleteBlobsRequest",
    }

    def do_POST(self):
        st = self.state
        st.request_started()
        # per-RPC trace stamp: reuse the client's id when forwarded,
        # mint one otherwise; every span/log line below inherits it
        tid = self.headers.get(TRACE_HEADER) or ""
        try:
            with new_trace(tid or None) as tid:
                self._trace_id = tid
                with span("server.rpc", route=self.path):
                    self._do_post(st)
        finally:
            st.request_finished()

    def _do_post(self, st):
        if st.token and self.headers.get(TOKEN_HEADER) != st.token:
            return self._twirp_error(401, "unauthenticated", "invalid token")
        ctype = (self.headers.get("Content-Type") or "").split(";")[0]
        self._is_proto = ctype in ("application/protobuf",
                                   "application/x-protobuf")
        route = self.path
        try:
            length = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(length)
            if self._is_proto:
                from .protowire import decode_msg
                desc = self._ROUTES.get(route)
                if desc is None:
                    return self._twirp_error(404, "bad_route", route)
                req = decode_msg(body, desc)
            else:
                req = json.loads(body or b"{}")
        except (ValueError, json.JSONDecodeError):
            return self._twirp_error(400, "malformed", "bad body")

        try:
            if route == "/twirp/trivy.scanner.v1.Scanner/Scan":
                return self._scan(req)
            if route == "/twirp/trivy.cache.v1.Cache/PutArtifact":
                st.cache.put_artifact(req.get("artifact_id", ""),
                                      req.get("artifact_info") or {})
                return self._reply({}, "Empty")
            if route == "/twirp/trivy.cache.v1.Cache/PutBlob":
                blob_j = req.get("blob_info") or {}
                if self._is_proto:
                    from .convert import proto_blob_to_json
                    blob_j = proto_blob_to_json(blob_j)
                blob = blob_from_json(blob_j)
                st.cache.put_blob(req.get("diff_id", ""), blob)
                return self._reply({}, "Empty")
            if route == "/twirp/trivy.cache.v1.Cache/MissingBlobs":
                missing_artifact, missing = st.cache.missing_blobs(
                    req.get("artifact_id", ""), req.get("blob_ids") or [])
                return self._reply({
                    "missing_artifact": missing_artifact,
                    "missing_blob_ids": missing,
                }, "MissingBlobsResponse")
            if route == "/twirp/trivy.cache.v1.Cache/DeleteBlobs":
                return self._reply({}, "Empty")
            return self._twirp_error(404, "bad_route", route)
        except KeyError as e:
            return self._twirp_error(400, "invalid_argument", str(e))
        except Exception as e:  # noqa: BLE001 — server must not die
            return self._twirp_error(500, "internal", f"{type(e).__name__}: {e}")

    def _scan(self, req: dict):
        import time

        from ..metrics import METRICS
        opts_j = req.get("options") or {}
        opts = T.ScanOptions(
            scanners=tuple(opts_j.get("scanners") or ("vuln",)),
            pkg_types=tuple(opts_j.get("vuln_type") or ("os", "library")),
            list_all_packages=bool(opts_j.get("list_all_packages")),
        )
        t0 = time.perf_counter()
        results, os_info = self.state.scanner.scan(
            req.get("target", ""), req.get("artifact_id", ""),
            req.get("blob_ids") or [], opts)
        elapsed = time.perf_counter() - t0
        METRICS.inc("trivy_tpu_scans_total")
        METRICS.inc("trivy_tpu_scan_seconds_total", elapsed)
        METRICS.observe("trivy_tpu_scan_latency_seconds", elapsed)
        _log.debug("scan %s: %d results in %.1fms",
                   req.get("target", ""), len(results), elapsed * 1e3)
        if self._is_proto:
            from .convert import results_to_proto
            return self._proto(200, results_to_proto(results, os_info),
                               "ScanResponse")
        self._json(200, {
            "os": {"family": os_info.family, "name": os_info.name,
                   "eosl": os_info.eosl},
            "results": [_result_to_json(r) for r in results],
        })


def serve(host: str, port: int, table, cache_dir: str, token: str = "",
          ready_event: threading.Event | None = None,
          cache_backend: str = "fs", trace_path: str = ""):
    """`trace_path` arms graftscope recording for the server's
    lifetime and dumps the Chrome trace-event JSON there on shutdown
    (the CLI's `server --trace FILE`)."""
    if trace_path:
        from ..obs import COLLECTOR
        COLLECTOR.enable()
    Handler.state = ServerState(table, cache_dir, token, cache_backend)
    httpd = ThreadingHTTPServer((host, port), Handler)
    if ready_event is not None:
        ready_event.set()
    try:
        httpd.serve_forever()
    finally:
        httpd.server_close()
        if trace_path:
            from ..obs import COLLECTOR, write_chrome_trace
            COLLECTOR.disable()
            write_chrome_trace(trace_path)
            _log.warning("graftscope trace written to %s", trace_path)
    return httpd


def serve_background(host: str, port: int, table, cache_dir: str,
                     token: str = ""):
    """Start in a daemon thread; returns (httpd, state) once listening."""
    Handler.state = ServerState(table, cache_dir, token)
    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, Handler.state
