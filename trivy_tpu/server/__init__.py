"""Client/server mode (reference rpc/ + pkg/rpc): a Twirp-shaped HTTP
boundary between analysis (client side) and batched TPU detection
(server side).

The wire-header names live HERE, not in listen.py: the client must be
importable without dragging in the server stack (listen → scanner →
detect engine → graftguard watchdog thread) — a remote-scan client
process has no device to supervise."""

TOKEN_HEADER = "Trivy-Token"
# per-RPC trace id: honored when the client sends one, generated
# otherwise; echoed on every response and stamped on every span and
# log line the request produces (graftscope propagation)
TRACE_HEADER = "X-Trivy-Trace-Id"
# graftwatch cross-process parentage: the forwarding span's id (the
# client's client.scan, or the router's per-hop router.forward), so
# the receiver's root span links under it and obs.collect can stitch
# one tree across processes with no shared clock
PARENT_SPAN_HEADER = "X-Trivy-Parent-Span"
# stamped by the router on relayed responses: which replica actually
# answered (failovers make the ring owner a guess, not an answer)
REPLICA_HEADER = "X-Trivy-Replica"
# graftguard per-request deadline: milliseconds the client is willing
# to wait, queue time included — the admission queue never parks a
# handler thread past it (the client stamps its own timeout here)
DEADLINE_HEADER = "X-Trivy-Deadline-Ms"
# advisory-DB version identity: the serving AdvisoryTable's content
# digest (table.content_digest), stamped on every Scan response and
# exposed in /healthz — the router compares it across replicas and
# counts trivy_tpu_fleet_db_version_skew_total when a mid-rollout
# fleet answers from different databases
DB_VERSION_HEADER = "X-Trivy-DB-Version"
# graftcost tenant identity: who this scan is billed to (client
# --tenant; the router forwards it verbatim; absent = "default").
# The FULL id always rides this header and the cost response — only
# the metric label space is clamped to top-K-plus-"other"
TENANT_HEADER = "X-Trivy-Tenant"
# graftcost per-request cost split: compact JSON (tenant, queue_ms,
# service_ms, device_ms, transfer_bytes, host_ms, avoided_ms, hops)
# stamped on every Scan response; the router sums it across failover
# hops so the client sees ONE document covering everything its
# request cost, wherever it ran
COST_HEADER = "X-Trivy-Cost"

# request-message descriptor per Twirp route (binary encoding) —
# shared by the server handler and the graftfleet router, which must
# stay importable without the server stack (listen → scanner → jax)
ROUTE_DESCRIPTORS = {
    "/twirp/trivy.scanner.v1.Scanner/Scan": "ScanRequest",
    # graftbom: the document rides the request body; the server runs
    # the supervised decode and the unchanged detect path behind it
    "/twirp/trivy.scanner.v1.Scanner/ScanSBOM": "ScanSBOMRequest",
    "/twirp/trivy.cache.v1.Cache/PutArtifact": "PutArtifactRequest",
    "/twirp/trivy.cache.v1.Cache/PutBlob": "PutBlobRequest",
    "/twirp/trivy.cache.v1.Cache/MissingBlobs": "MissingBlobsRequest",
    "/twirp/trivy.cache.v1.Cache/DeleteBlobs": "DeleteBlobsRequest",
}
