"""Client/server mode (reference rpc/ + pkg/rpc): a Twirp-shaped HTTP
boundary between analysis (client side) and batched TPU detection
(server side)."""
