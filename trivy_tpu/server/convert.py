"""struct ⇄ proto-dict converters (reference pkg/rpc/convert.go).

Two directions are needed for wire compat with binary Twirp clients:
- incoming `PutBlobRequest.blob_info` proto dicts → the Go-JSON shape
  our cache layer stores (ConvertFromRPC* family);
- outgoing scan results (our dataclasses) → `ScanResponse` proto dicts
  (ConvertToRPC* family).
"""

from __future__ import annotations

from .. import types as T
from .protowire import SEVERITY_NAMES


def _sev_enum(name: str) -> int:
    try:
        return SEVERITY_NAMES.index((name or "UNKNOWN").upper())
    except ValueError:
        return 0


# ---- incoming: proto BlobInfo → Go-JSON (cache shape) -----------------

def _pkg_json(p: dict) -> dict:
    out = {
        "ID": p.get("id", ""), "Name": p.get("name", ""),
        "Version": p.get("version", ""), "Release": p.get("release", ""),
        "Epoch": p.get("epoch", 0), "Arch": p.get("arch", ""),
        "SrcName": p.get("src_name", ""),
        "SrcVersion": p.get("src_version", ""),
        "SrcRelease": p.get("src_release", ""),
        "SrcEpoch": p.get("src_epoch", 0),
        "Licenses": p.get("licenses", []),
        "FilePath": p.get("file_path", ""),
        "DependsOn": p.get("depends_on", []),
        "Digest": p.get("digest", ""),
        "Dev": p.get("dev", False),
        "Indirect": p.get("indirect", False),
    }
    ident = p.get("identifier")
    if ident:
        # reference pkg/rpc/convert.go:239 maps BomRef<->BOMRef; UID is not
        # carried on the wire (proto PkgIdentifier has only purl+bom_ref)
        out["Identifier"] = {"PURL": ident.get("purl", ""),
                             "BOMRef": ident.get("bom_ref", "")}
    locs = p.get("locations")
    if locs:
        out["Locations"] = [{"StartLine": l.get("start_line", 0),
                             "EndLine": l.get("end_line", 0)}
                            for l in locs]
    layer = p.get("layer")
    if layer:
        out["Layer"] = _layer_json(layer)
    return out


def _layer_json(l: dict) -> dict:
    return {"Digest": l.get("digest", ""),
            "DiffID": l.get("diff_id", ""),
            "CreatedBy": l.get("created_by", "")}


def _cause_json(c: dict) -> dict:
    out = {"Resource": c.get("resource", ""),
           "Provider": c.get("provider", ""),
           "Service": c.get("service", ""),
           "StartLine": c.get("start_line", 0),
           "EndLine": c.get("end_line", 0)}
    code = c.get("code")
    if code:
        out["Code"] = {"Lines": [{
            "Number": ln.get("number", 0),
            "Content": ln.get("content", ""),
            "IsCause": ln.get("is_cause", False),
            "Annotation": ln.get("annotation", ""),
            "Truncated": ln.get("truncated", False),
            "Highlighted": ln.get("highlighted", ""),
            "FirstCause": ln.get("first_cause", False),
            "LastCause": ln.get("last_cause", False),
        } for ln in code.get("lines", [])]}
    return out


def proto_blob_to_json(b: dict) -> dict:
    """proto BlobInfo dict → the Go-JSON dict blob_from_json reads."""
    os_p = b.get("os") or {}
    out = {
        "SchemaVersion": b.get("schema_version", 2),
        "Digest": b.get("digest", ""),
        "DiffID": b.get("diff_id", ""),
        "OpaqueDirs": b.get("opaque_dirs", []),
        "WhiteoutFiles": b.get("whiteout_files", []),
        "OS": {"Family": os_p.get("family", ""),
               "Name": os_p.get("name", ""),
               "EOSL": os_p.get("eosl", False),
               "extended": os_p.get("extended", False)},
    }
    repo = b.get("repository")
    if repo:
        out["Repository"] = {"Family": repo.get("family", ""),
                             "Release": repo.get("release", "")}
    out["PackageInfos"] = [{
        "FilePath": pi.get("file_path", ""),
        "Packages": [_pkg_json(p) for p in pi.get("packages", [])],
    } for pi in b.get("package_infos", [])]
    out["Applications"] = [{
        "Type": a.get("type", ""),
        "FilePath": a.get("file_path", ""),
        "Packages": [_pkg_json(p) for p in a.get("libraries", [])],
    } for a in b.get("applications", [])]
    out["Misconfigurations"] = [{
        "FileType": m.get("file_type", ""),
        "FilePath": m.get("file_path", ""),
        "Successes": len(m.get("successes", [])),
        "Exceptions": len(m.get("exceptions", [])),
        "Failures": [_misconf_result_json(m, r)
                     for r in m.get("failures", [])],
    } for m in b.get("misconfigurations", [])]
    out["Secrets"] = [{
        "FilePath": s.get("filepath", ""),
        "Findings": [{
            "RuleID": f.get("rule_id", ""),
            "Category": f.get("category", ""),
            "Severity": f.get("severity", ""),
            "Title": f.get("title", ""),
            "StartLine": f.get("start_line", 0),
            "EndLine": f.get("end_line", 0),
            "Match": f.get("match", ""),
        } for f in s.get("findings", [])],
    } for s in b.get("secrets", [])]
    return out


def _misconf_result_json(m: dict, r: dict) -> dict:
    pm = r.get("policy_metadata") or {}
    return {
        "Type": pm.get("type", m.get("file_type", "")),
        "ID": pm.get("id", ""),
        "AVDID": pm.get("adv_id", ""),
        "Title": pm.get("title", ""),
        "Description": pm.get("description", ""),
        "Message": r.get("message", ""),
        "Namespace": r.get("namespace", ""),
        "Resolution": pm.get("recommended_actions", ""),
        "Severity": pm.get("severity", "UNKNOWN"),
        "References": pm.get("references", []),
        "Status": "FAIL",
        "CauseMetadata": _cause_json(r.get("cause_metadata") or {}),
    }


# ---- outgoing: our dataclasses → proto ScanResponse -------------------

def _layer_proto(layer) -> dict:
    if layer is None:
        return {}
    return {"digest": layer.digest, "diff_id": layer.diff_id,
            "created_by": layer.created_by}


def _vuln_proto(v: T.DetectedVulnerability) -> dict:
    det = v.vulnerability  # embedded details (FillInfo)
    out = {
        "vulnerability_id": v.vulnerability_id,
        "vendor_ids": list(v.vendor_ids or []),
        "pkg_name": v.pkg_name,
        "pkg_id": v.pkg_id,
        "pkg_path": v.pkg_path,
        "installed_version": v.installed_version,
        "fixed_version": v.fixed_version,
        "title": det.title,
        "description": det.description,
        "severity": _sev_enum(v.severity),
        "severity_source": v.severity_source,
        "primary_url": v.primary_url,
        "references": list(det.references or []),
        "cwe_ids": list(det.cwe_ids or []),
        "layer": _layer_proto(v.layer),
    }
    if v.pkg_identifier and (v.pkg_identifier.purl
                             or v.pkg_identifier.bom_ref):
        out["pkg_identifier"] = {"purl": v.pkg_identifier.purl,
                                 "bom_ref": v.pkg_identifier.bom_ref}
    if det.cvss:
        cvss = {}
        for src, c in det.cvss.items():
            cvss[src] = {
                "v2_vector": getattr(c, "v2_vector", "") or
                (c.get("V2Vector", "") if isinstance(c, dict) else ""),
                "v3_vector": getattr(c, "v3_vector", "") or
                (c.get("V3Vector", "") if isinstance(c, dict) else ""),
                "v2_score": getattr(c, "v2_score", 0) or
                (c.get("V2Score", 0) if isinstance(c, dict) else 0),
                "v3_score": getattr(c, "v3_score", 0) or
                (c.get("V3Score", 0) if isinstance(c, dict) else 0),
            }
        out["cvss"] = cvss
    if det.vendor_severity:
        out["vendor_severity"] = {
            src: (_sev_enum(sev) if isinstance(sev, str)
                  else int(sev))
            for src, sev in det.vendor_severity.items()}
    if v.data_source is not None:
        ds = v.data_source
        out["data_source"] = {"id": ds.id, "name": ds.name,
                              "url": ds.url}
    if det.published_date:
        out["published_date"] = det.published_date
    if det.last_modified_date:
        out["last_modified_date"] = det.last_modified_date
    return out


def _misconf_proto(m) -> dict:
    cm = m.cause_metadata
    cause = {}
    if cm is not None:
        cause = {
            "resource": getattr(cm, "resource", ""),
            "provider": cm.provider, "service": cm.service,
            "start_line": cm.start_line, "end_line": cm.end_line,
        }
        if cm.code and cm.code.lines:
            cause["code"] = {"lines": [{
                "number": ln.number, "content": ln.content,
                "is_cause": ln.is_cause, "annotation": ln.annotation,
                "truncated": ln.truncated,
                "highlighted": ln.highlighted,
                "first_cause": ln.first_cause,
                "last_cause": ln.last_cause,
            } for ln in cm.code.lines]}
    return {
        "type": m.type, "id": m.id, "avd_id": m.avd_id,
        "title": m.title, "description": m.description,
        "message": m.message, "namespace": m.namespace,
        "query": m.query, "resolution": m.resolution,
        "severity": _sev_enum(m.severity),
        "primary_url": m.primary_url,
        "references": list(m.references or []),
        "status": m.status, "layer": _layer_proto(m.layer),
        "cause_metadata": cause,
    }


def _secret_proto(s) -> dict:
    return {
        "rule_id": s.rule_id, "category": s.category,
        "severity": s.severity, "title": s.title,
        "start_line": s.start_line, "end_line": s.end_line,
        "match": s.match, "layer": _layer_proto(s.layer),
    }


def _pkg_proto(p: T.Package) -> dict:
    return {
        "id": p.id, "name": p.name, "version": p.version,
        "release": p.release, "epoch": p.epoch, "arch": p.arch,
        "src_name": p.src_name, "src_version": p.src_version,
        "src_release": p.src_release, "src_epoch": p.src_epoch,
        "licenses": list(p.licenses or []),
        "file_path": p.file_path,
        "depends_on": list(p.depends_on or []),
        "digest": p.digest, "dev": p.dev, "indirect": p.indirect,
        "layer": _layer_proto(p.layer),
        "identifier": {"purl": p.identifier.purl,
                       "bom_ref": p.identifier.bom_ref}
        if p.identifier and (p.identifier.purl or p.identifier.bom_ref)
        else None,
    }


def results_to_proto(results: list[T.Result], os_info: T.OS) -> dict:
    out_results = []
    for r in results:
        pr = {
            "target": r.target, "class": r.clazz, "type": r.type,
            "vulnerabilities": [_vuln_proto(v)
                                for v in r.vulnerabilities],
            "misconfigurations": [_misconf_proto(m)
                                  for m in r.misconfigurations],
            "secrets": [_secret_proto(s) for s in r.secrets],
            "packages": [_pkg_proto(p) for p in r.packages],
        }
        out_results.append(pr)
    return {
        "os": {"family": os_info.family, "name": os_info.name,
               "eosl": os_info.eosl},
        "results": out_results,
    }
