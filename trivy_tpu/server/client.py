"""Remote scan client (reference pkg/rpc/client + pkg/cache/remote.go):
the client analyzes locally, pushes blobs to the server's cache, and
asks the server — which owns the device-resident advisory table — to
detect. Transient failures retry through the shared graftguard
RetryPolicy (full jitter, budget-capped — resilience/retry.py replaced
the bespoke fixed-backoff loop this module used to carry); 429/503
sheds from the server's admission queue are retried honoring their
Retry-After hint. Each RPC carries an X-Trivy-Deadline-Ms stamp of the
client's own timeout so the server never queues it past that."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

from .. import types as T
from ..obs import current_span_id, current_trace_id, ensure_trace, span
from ..report.writer import report_from_json
from . import (COST_HEADER, DEADLINE_HEADER, PARENT_SPAN_HEADER,
               TENANT_HEADER, TOKEN_HEADER, TRACE_HEADER)

# one policy shape for every RPC; _Base accepts an override for tests.
# Built lazily (like oci.py / db/download.py): a pure client process
# has no device to supervise, and a module-level resilience import
# would spawn the GUARD watchdog thread as a side effect
DEFAULT_RETRY = None
_retry_after_hint = None


def _retry_hint():
    global _retry_after_hint
    if _retry_after_hint is None:
        from ..resilience.retry import http_should_retry
        # admission sheds (429/503) retry honoring the server's
        # Retry-After; other HTTP errors are terminal Twirp responses
        _retry_after_hint = http_should_retry((429, 503))
    return _retry_after_hint


def _default_retry():
    global DEFAULT_RETRY
    if DEFAULT_RETRY is None:
        from ..resilience import RetryPolicy
        DEFAULT_RETRY = RetryPolicy(attempts=3, base_delay_s=0.2,
                                    max_delay_s=2.0, budget_s=15.0)
    return DEFAULT_RETRY


class TwirpError(RuntimeError):
    def __init__(self, code: str, msg: str):
        super().__init__(f"{code}: {msg}")
        self.code = code


class _Base:
    def __init__(self, base_url: str, token: str = "", timeout: float = 60,
                 retry=None, tenant: str = ""):
        # fleet awareness: a comma-separated URL list fails over
        # client-side — point at several routers (or at the replicas
        # directly in a routerless deployment) and the client walks
        # past an unreachable one, remembering the base that answered
        # so steady-state traffic doesn't re-probe a dead endpoint
        self.bases = [u.strip().rstrip("/")
                      for u in base_url.split(",") if u.strip()]
        if not self.bases:
            raise ValueError("empty server url")
        self._base_idx = 0
        self.token = token
        self.timeout = timeout
        self.retry = retry  # None → the shared lazy DEFAULT_RETRY
        # graftcost tenant identity (--tenant): stamped on every RPC
        # as X-Trivy-Tenant; the router relays it per hop and the
        # replica's cost ledger attributes under it. Empty → the
        # server's "default" tenant. The LAST response's parsed
        # X-Trivy-Cost doc (merged across failover hops when a router
        # answered) is kept for callers that want the bill.
        self.tenant = tenant
        self.last_cost: dict | None = None

    @property
    def base_url(self) -> str:
        """The currently-preferred endpoint (first of `bases` until a
        failover promotes another)."""
        return self.bases[self._base_idx % len(self.bases)]

    def _call(self, service: str, method: str, payload: dict) -> dict:
        body = json.dumps(payload).encode()
        # forward the active graftscope trace id so client and server
        # spans/logs correlate (the server mints one when absent), and
        # the active span id so the server fragment's root parents
        # under this call (graftwatch cross-process assembly)
        tid = current_trace_id()
        psid = current_span_id()
        headers = {
            "Content-Type": "application/json",
            DEADLINE_HEADER: str(int(self.timeout * 1e3)),
            **({TRACE_HEADER: tid} if tid else {}),
            **({PARENT_SPAN_HEADER: psid} if tid and psid else {}),
            **({TOKEN_HEADER: self.token} if self.token else {}),
            **({TENANT_HEADER: self.tenant} if self.tenant else {}),
        }
        policy = self.retry or _default_retry()

        def attempt() -> dict:
            # one pass over the base list: a connection error moves to
            # the NEXT base immediately (failover before backoff — a
            # dead endpoint must cost one connect, not a retry
            # budget); only a whole failed walk is retried by the
            # policy. HTTPErrors propagate: the endpoint answered, so
            # 429/503 retry per the hint and the rest are terminal.
            last: Exception | None = None
            for hop in range(len(self.bases)):
                idx = (self._base_idx + hop) % len(self.bases)
                url = f"{self.bases[idx]}/twirp/{service}/{method}"
                req = urllib.request.Request(url, data=body,
                                             method="POST",
                                             headers=headers)
                try:
                    with urllib.request.urlopen(
                            req, timeout=self.timeout) as r:
                        result = json.loads(r.read() or b"{}")
                        hdrs = getattr(r, "headers", None)
                        raw_cost = hdrs.get(COST_HEADER) if hdrs else None
                except urllib.error.HTTPError:
                    raise
                except urllib.error.URLError as e:
                    last = e
                    continue   # unreachable: try the next base
                self._base_idx = idx
                if raw_cost:
                    from ..obs.cost import parse_cost_header
                    self.last_cost = parse_cost_header(raw_cost)
                return result
            raise last

        try:
            return policy.call(attempt, should_retry=_retry_hint())
        except urllib.error.HTTPError as e:
            # the endpoint ANSWERED: a Twirp error is terminal, not a
            # reason to re-run a scan against another base
            detail = e.read().decode(errors="replace")
            try:
                j = json.loads(detail)
                if not isinstance(j, dict):   # valid-but-non-object
                    raise ValueError("non-object error body")
                raise TwirpError(j.get("code", str(e.code)),
                                 j.get("msg", detail)) from None
            except (ValueError, json.JSONDecodeError):
                raise TwirpError(str(e.code), detail) from None
        except urllib.error.URLError as e:
            raise TwirpError("unavailable", str(e)) from None


class RemoteCache(_Base):
    """cache.ArtifactCache over the wire — the client half of the split
    that makes client/server mode work (SURVEY.md §1)."""

    SERVICE = "trivy.cache.v1.Cache"

    def missing_blobs(self, artifact_id: str, blob_ids: list):
        r = self._call(self.SERVICE, "MissingBlobs",
                       {"artifact_id": artifact_id, "blob_ids": blob_ids})
        return bool(r.get("missing_artifact")), r.get("missing_blob_ids") or []

    def put_artifact(self, artifact_id: str, info: dict):
        self._call(self.SERVICE, "PutArtifact",
                   {"artifact_id": artifact_id, "artifact_info": info})

    def put_blob(self, blob_id: str, blob: T.BlobInfo):
        self._call(self.SERVICE, "PutBlob",
                   {"diff_id": blob_id, "blob_info": blob.to_json()})

    def get_blob(self, blob_id: str):
        return None  # client mode holds no local blobs (run.go:352-353)

    def get_artifact(self, artifact_id: str):
        return None


class RemoteScanner(_Base):
    """scanner.Driver over the wire (pkg/rpc/client/client.go:67)."""

    SERVICE = "trivy.scanner.v1.Scanner"

    def scan(self, target: str, artifact_id: str, blob_ids: list,
             options: T.ScanOptions | None = None):
        options = options or T.ScanOptions()
        with ensure_trace(), span("client.scan", target=target):
            return self._scan(target, artifact_id, blob_ids, options)

    def _scan(self, target, artifact_id, blob_ids, options):
        r = self._call(self.SERVICE, "Scan", {
            "target": target,
            "artifact_id": artifact_id,
            "blob_ids": blob_ids,
            "options": self._options_json(options),
        })
        return self._decode_response(r)

    def scan_sbom(self, target: str, raw: bytes,
                  options: T.ScanOptions | None = None):
        """graftbom client half: ship the raw document, let the server
        run the supervised decode against ITS cache + memo. The client
        stamps the artifact kind (a cheap local sniff — the server
        re-detects authoritatively) and the document digest as
        artifact_id, which is what the fleet router keys affinity on:
        duplicate documents land on the same replica's memo."""
        import base64

        from ..sbom.artifact import doc_digest
        options = options or T.ScanOptions()
        kind = ""
        if b'"bomFormat"' in raw and b"CycloneDX" in raw:
            kind = "cyclonedx"
        elif b"spdxVersion" in raw or b"SPDXVersion:" in raw:
            kind = "spdx"
        with ensure_trace(), span("client.scan_sbom", target=target,
                                  kind=kind):
            r = self._call(self.SERVICE, "ScanSBOM", {
                "target": target,
                "artifact_id": doc_digest(raw),
                "kind": kind,
                "document": base64.b64encode(raw).decode(),
                "options": self._options_json(options),
            })
            return self._decode_response(r)

    @staticmethod
    def _options_json(options) -> dict:
        return {
            "scanners": list(options.scanners),
            "vuln_type": list(options.pkg_types),
            "list_all_packages": options.list_all_packages,
        }

    def _decode_response(self, r: dict):
        os_j = r.get("os") or {}
        os_info = T.OS(family=os_j.get("family", ""),
                       name=os_j.get("name", ""),
                       eosl=bool(os_j.get("eosl")))
        report = report_from_json({"Results": r.get("results") or []})
        return report.results, os_info
